"""Gluon LSTM language-model training throughput (tokens/sec) on one TPU
chip — the BASELINE.md north-star's second metric (the reference repo
publishes no LSTM tokens/sec figure, so this sets the number to beat).

Model: medium LM (wikitext-2-scale vocab, 650-d embedding + 2x650 LSTM +
tied-size decoder), truncated-BPTT with zero initial state per step (the
standard throughput-benchmark setup). The whole step — embedding, fused
lax.scan LSTM, decoder, softmax CE, backward, SGD update — is ONE XLA
program via parallel.TrainStep, bf16 compute over fp32 master weights.

Usage: python bench_lstm.py [batch] [bptt]
Prints one JSON line: {"metric": "lstm_lm_train_tokens_per_sec", ...}
"""
import json
import sys
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon import HybridBlock, nn, rnn
from mxnet_tpu.parallel import TrainStep

VOCAB = 33278      # wikitext-2
EMSIZE = 650
NHID = 650
NLAYERS = 2


class LMModel(HybridBlock):
    def __init__(self, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embed = nn.Embedding(VOCAB, EMSIZE)
            self.lstm = rnn.LSTM(NHID, num_layers=NLAYERS, layout="NTC")
            self.decoder = nn.Dense(VOCAB, flatten=False)

    def hybrid_forward(self, F, x):
        h = self.lstm(self.embed(x))
        out = self.decoder(h)                # (B, T, V)
        return out.reshape((-1, VOCAB))      # (B*T, V)


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 512
    bptt = int(sys.argv[2]) if len(sys.argv) > 2 else 35
    steps = 30

    mx.random.seed(0)
    net = LMModel()
    net.initialize(mx.init.Xavier())
    step = TrainStep(net, loss="softmax_ce", optimizer="sgd",
                     optimizer_params={"momentum": 0.9}, lr=0.1,
                     compute_dtype="bfloat16")

    rng = np.random.RandomState(0)
    xs = [mx.nd.array(rng.randint(0, VOCAB, (batch, bptt)), dtype="int32")
          for _ in range(4)]
    ys = [mx.nd.array(rng.randint(0, VOCAB, (batch * bptt,)),
                      dtype="int32") for _ in range(4)]

    loss = None
    for i in range(3):                     # warmup/compile
        loss = step(xs[i % 4], ys[i % 4])
    float(loss.asnumpy())                  # arm real sync (see bench.py)

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for i in range(steps):
            loss = step(xs[i % 4], ys[i % 4])
        loss.wait_to_read()
        best = min(best, time.perf_counter() - t0)
    tok_s = batch * bptt * steps / best
    dev = getattr(loss.data, "device", None) or "cpu"
    print(json.dumps({
        "metric": "lstm_lm_train_tokens_per_sec",
        "value": round(tok_s, 1),
        "unit": "tokens/sec",
        "batch": batch, "bptt": bptt,
        "vocab": VOCAB, "emsize": EMSIZE, "nhid": NHID,
        "nlayers": NLAYERS,
        "step_time_s": round(best / steps, 5),
        "device": str(dev),
    }))


if __name__ == "__main__":
    main()
