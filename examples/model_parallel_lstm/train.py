"""Model-parallel LSTM language model.

TPU-native rebuild of the reference's model-parallel LSTM
(reference: example/model-parallel/lstm/lstm.py:65-100 — layers pinned to
different GPUs via group2ctx + _CrossDeviceCopy). On TPU the idiomatic
form is sharding, not placement: the mesh has a 'model' axis, the LSTM
gate weights shard over it (param_spec_fn), and XLA inserts the
collectives group2ctx's cross-device copies did by hand.

Run: python train.py --num-epoch 3      (8 virtual devices when no TPU)
"""
import argparse
import os

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"

import numpy as np


def make_data(num_seq=256, seq_len=32, vocab=32, seed=0):
    """Synthetic next-token task: token t+1 = (token t * 3 + 1) mod vocab,
    fully learnable by a small LSTM."""
    rng = np.random.RandomState(seed)
    starts = rng.randint(0, vocab, num_seq)
    seqs = np.zeros((num_seq, seq_len + 1), np.int64)
    seqs[:, 0] = starts
    for t in range(seq_len):
        seqs[:, t + 1] = (seqs[:, t] * 3 + 1) % vocab
    return seqs[:, :-1], seqs[:, 1:]


def build_net(vocab, hidden, num_layers):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import HybridBlock, nn, rnn

    class LM(HybridBlock):
        def __init__(self, **kw):
            super().__init__(**kw)
            with self.name_scope():
                self.embed = nn.Embedding(vocab, hidden)
                self.lstm = rnn.LSTM(hidden, num_layers=num_layers,
                                     layout="NTC")
                self.out = nn.Dense(vocab, flatten=False)

        def forward(self, x):
            h = self.embed(x)
            h = self.lstm(h)
            return self.out(h)

    net = LM(prefix="mp_lstm_")
    net.initialize(mx.init.Xavier())
    return net


def train(num_epoch=3, batch_size=32, hidden=64, num_layers=2, vocab=32,
          lr=0.01, log=print):
    import jax
    from jax.sharding import PartitionSpec as P
    import mxnet_tpu as mx
    from mxnet_tpu.parallel import TrainStep, make_mesh

    x, y = make_data(vocab=vocab)
    net = build_net(vocab, hidden, num_layers)

    n_dev = len(jax.devices())
    model_par = 4 if n_dev >= 8 else max(1, n_dev // 2)
    mesh = make_mesh({"data": n_dev // model_par, "model": model_par})

    def spec_fn(p):
        # LSTM gate weights are (4*hidden, in): shard the gate dim over
        # the model axis — the TP analog of the reference putting each
        # layer on its own GPU (lstm.py:65-100)
        if ("lstm" in p.name and p.name.endswith("weight")
                and len(p.shape) == 2 and p.shape[0] % model_par == 0):
            return P("model", None)
        return P()

    def seq_ce(logits, labels):
        import jax.numpy as jnp
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logp, labels.astype(jnp.int32)[..., None], axis=-1)
        return -jnp.mean(picked)

    step = TrainStep(net, loss=seq_ce, optimizer="adam", lr=lr, mesh=mesh,
                     param_spec_fn=spec_fn)
    n = len(x)
    losses = []
    for epoch in range(num_epoch):
        order = np.random.RandomState(epoch).permutation(n)
        total, nb = 0.0, 0
        for lo in range(0, n - batch_size + 1, batch_size):
            idx = order[lo:lo + batch_size]
            loss = step(x[idx], y[idx])
            total += float(loss.asscalar())
            nb += 1
        losses.append(total / nb)
        log(f"epoch {epoch}: loss={losses[-1]:.4f} "
            f"(mesh data={n_dev // model_par} x model={model_par})")
    return losses


def main():
    parser = argparse.ArgumentParser(
        description="model-parallel LSTM LM (sharded gate weights)")
    parser.add_argument("--num-epoch", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=32)
    parser.add_argument("--hidden", type=int, default=64)
    parser.add_argument("--num-layers", type=int, default=2)
    parser.add_argument("--lr", type=float, default=0.01)
    args = parser.parse_args()
    train(args.num_epoch, args.batch_size, args.hidden, args.num_layers,
          lr=args.lr)


if __name__ == "__main__":
    main()
