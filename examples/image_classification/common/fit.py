"""The shared training driver: argparse → Module.fit.

Capability rebuild of the reference's example/image-classification/common/
fit.py:141 (``fit(args, network, data_loader)``): wires the kvstore, LR
schedule, initializer, checkpointing and monitoring around Module.fit. On
TPU the device list collapses into the GSPMD mesh — ``--gpus 0,1,..`` is
kept as a flag and maps to "shard the batch this many ways".
"""
from __future__ import annotations

import argparse
import logging
import os
import re
import time

import mxnet_tpu as mx


def add_fit_args(parser: argparse.ArgumentParser):
    """(reference: common/fit.py:58 add_fit_args)"""
    train = parser.add_argument_group("Training", "model training")
    train.add_argument("--network", type=str, help="the neural network to use")
    train.add_argument("--num-layers", type=int,
                       help="number of layers in the neural network")
    train.add_argument("--gpus", type=str, default=None,
                       help="devices to run on; e.g. '0,1'. On TPU this "
                       "selects how many mesh devices shard the batch")
    train.add_argument("--kv-store", type=str, default="device",
                       help="key-value store type")
    train.add_argument("--num-epochs", type=int, default=100)
    train.add_argument("--lr", type=float, default=0.1)
    train.add_argument("--lr-factor", type=float, default=0.1,
                       help="reduce the lr by this factor at each step")
    train.add_argument("--lr-step-epochs", type=str, default="30,60",
                       help="epochs at which the lr decays")
    train.add_argument("--optimizer", type=str, default="sgd")
    train.add_argument("--mom", type=float, default=0.9)
    train.add_argument("--wd", type=float, default=1e-4)
    train.add_argument("--batch-size", type=int, default=128)
    train.add_argument("--disp-batches", type=int, default=20,
                       help="show progress every N batches")
    train.add_argument("--model-prefix", type=str,
                       help="checkpoint prefix (save + resume)")
    train.add_argument("--load-epoch", type=int,
                       help="load the model saved at this epoch")
    train.add_argument("--top-k", type=int, default=0,
                       help="also report top-k accuracy")
    train.add_argument("--dtype", type=str, default="float32",
                       help="compute precision: float32 or bfloat16")
    train.add_argument("--monitor", type=int, default=0,
                       help="log network statistics every N batches")
    train.add_argument("--test-io", type=int, default=0,
                       help="only test the data pipeline speed")
    return train


def _get_lr_scheduler(args, kv, epoch_size):
    """(reference: common/fit.py:30 _get_lr_scheduler)"""
    if not args.lr_factor or args.lr_factor >= 1:
        return args.lr, None
    begin_epoch = args.load_epoch or 0
    step_epochs = [int(l) for l in args.lr_step_epochs.split(",") if l]
    lr = args.lr
    for s in step_epochs:
        if begin_epoch >= s:
            lr *= args.lr_factor
    if lr != args.lr:
        logging.info("Adjust learning rate to %e for epoch %d", lr,
                     begin_epoch)
    steps = [epoch_size * (x - begin_epoch) for x in step_epochs
             if x - begin_epoch > 0]
    if not steps:
        return lr, None
    return lr, mx.lr_scheduler.MultiFactorScheduler(step=steps,
                                                    factor=args.lr_factor)


def _load_model(args, rank=0):
    if args.load_epoch is None or args.model_prefix is None:
        return None, None, None
    model_prefix = args.model_prefix
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, args.load_epoch)
    logging.info("Loaded model %s_%04d.params", model_prefix,
                 args.load_epoch)
    return sym, arg_params, aux_params


def _save_model(args, rank=0):
    if args.model_prefix is None:
        return None
    dst_dir = os.path.dirname(args.model_prefix)
    if dst_dir and not os.path.isdir(dst_dir):
        os.makedirs(dst_dir, exist_ok=True)
    return mx.callback.do_checkpoint(
        args.model_prefix if rank == 0
        else "%s-%d" % (args.model_prefix, rank))


def _benchmark(args, network, train):
    """Timed steady-state loop over the symbolic Module path — the
    north-star measurement (BASELINE.json drives this file). Prints ONE
    bench.py-style JSON line. Async dispatch with a single sync, like
    bench.py phase A: the donated fused-step params chain the steps."""
    import json

    import jax

    devs = mx.cpu() if args.gpus is None or args.gpus == "" else [
        mx.gpu(int(i)) for i in args.gpus.split(",")]
    compute_dtype = "bfloat16" if args.dtype == "bfloat16" else None
    model = mx.mod.Module(context=devs, symbol=network, fused=True,
                          compute_dtype=compute_dtype)
    model.bind(train.provide_data, train.provide_label)
    model.init_params(mx.init.Xavier(rnd_type="gaussian",
                                     factor_type="in", magnitude=2))
    optimizer_params = {"learning_rate": args.lr, "wd": args.wd}
    if args.optimizer in ("sgd", "nag", "signum", "lbsgd"):
        optimizer_params["momentum"] = args.mom
    model.init_optimizer(kvstore=None, optimizer=args.optimizer,
                         optimizer_params=optimizer_params)
    assert model._fused is not None

    batches = []
    for batch in train:
        batches.append(batch)
        if len(batches) >= 4:
            break
    train.reset()

    steps = getattr(args, "benchmark_steps", 30)
    for _ in range(3):  # compile + warmup
        for b in batches[:1]:
            model.forward(b, is_train=True)
            model.backward()
            model.update()
    jax.block_until_ready(model._fused._pvals)

    best = float("inf")
    for _ in range(3):
        t0 = time.time()
        for i in range(steps):
            model.forward(batches[i % len(batches)], is_train=True)
            model.backward()
            model.update()
        jax.block_until_ready(model._fused._pvals)
        best = min(best, time.time() - t0)
    img_s = args.batch_size * steps / best
    # single source of truth for the reference number (cited in bench.py /
    # BASELINE.md: 181.53 img/s, 1x P100, docs/faq/perf.md:176-185)
    baseline = None
    try:
        import sys as _sys
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        _sys.path.insert(0, root)
        from bench import BASELINE_IMG_S as baseline
    except Exception:
        pass
    print(json.dumps({
        "metric": "module_fit_train_throughput",
        "value": round(img_s, 2),
        "unit": "images/sec",
        "vs_baseline": round(img_s / baseline, 3) if baseline else None,
        "network": args.network,
        "batch": args.batch_size,
        "steps": steps,
        "step_time_s": round(best / steps, 5),
        "path": "Module(fused) symbolic graph + functional optimizer "
                f"[{args.optimizer}, dtype={args.dtype}]",
    }))
    return model


def fit(args, network, data_loader, **kwargs):
    """Train ``network`` (a Symbol) on the iterators from ``data_loader``
    (reference: common/fit.py:141)."""
    if getattr(args, "benchmark", 0):
        train, _ = data_loader(args, None)
        return _benchmark(args, network, train)

    kv = mx.kvstore.create(args.kv_store)

    head = "%(asctime)-15s Node[" + str(kv.rank) + "] %(message)s"
    logging.basicConfig(level=logging.INFO, format=head)
    logging.info("start with arguments %s", args)

    train, val = data_loader(args, kv)

    if args.test_io:
        tic = time.time()
        for i, batch in enumerate(train):
            for j in batch.data:
                j.wait_to_read()
            if (i + 1) % args.disp_batches == 0:
                logging.info("Batch [%d]\tSpeed: %.2f samples/sec", i,
                             args.disp_batches * args.batch_size /
                             (time.time() - tic))
                tic = time.time()
        return

    sym, arg_params, aux_params = _load_model(args, kv.rank)
    if sym is not None:
        assert sym.tojson() == network.tojson()
    # caller-provided warm-start params (fine_tune.py) take precedence
    # over checkpoint loading; both can't be active at once. Always pop:
    # leftovers would collide with the explicit keywords at model.fit.
    caller_arg = kwargs.pop("arg_params", None)
    caller_aux = kwargs.pop("aux_params", None)
    if caller_arg is not None or caller_aux is not None:
        assert arg_params is None and aux_params is None, \
            "pass either --load-epoch or explicit arg/aux_params, not both"
        arg_params, aux_params = caller_arg, caller_aux

    checkpoint = _save_model(args, kv.rank)

    devs = mx.cpu() if args.gpus is None or args.gpus == "" else [
        mx.gpu(int(i)) for i in args.gpus.split(",")]

    epoch_size = args.num_examples // args.batch_size
    lr, lr_scheduler = _get_lr_scheduler(args, kv, epoch_size)

    model = mx.mod.Module(context=devs, symbol=network)

    optimizer_params = {
        "learning_rate": lr,
        "wd": args.wd,
        "lr_scheduler": lr_scheduler}
    if args.optimizer in ("sgd", "nag", "signum", "lbsgd"):
        optimizer_params["momentum"] = args.mom
    # bf16 compute with fp32 master weights (the reference's fp16 path
    # uses multi_precision the same way, fit.py dtype handling)
    if args.dtype == "bfloat16":
        optimizer_params["multi_precision"] = True

    initializer = mx.init.Xavier(rnd_type="gaussian", factor_type="in",
                                 magnitude=2)

    eval_metrics = ["accuracy"]
    if args.top_k > 0:
        eval_metrics.append(mx.metric.create("top_k_accuracy",
                                             top_k=args.top_k))

    batch_end_callbacks = [mx.callback.Speedometer(args.batch_size,
                                                   args.disp_batches)]

    monitor = mx.mon.Monitor(args.monitor, pattern=".*") \
        if args.monitor > 0 else None

    model.fit(train,
              begin_epoch=args.load_epoch if args.load_epoch else 0,
              num_epoch=args.num_epochs,
              eval_data=val,
              eval_metric=eval_metrics,
              kvstore=kv,
              optimizer=args.optimizer,
              optimizer_params=optimizer_params,
              initializer=initializer,
              arg_params=arg_params,
              aux_params=aux_params,
              batch_end_callback=batch_end_callbacks,
              epoch_end_callback=checkpoint,
              allow_missing=True,
              monitor=monitor,
              **kwargs)
    return model
