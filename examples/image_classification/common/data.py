"""Data iterators for the image-classification examples.

Capability analog of the reference's example/image-classification/common/
data.py (get_rec_iter over ImageRecordIter) with an added synthetic mode so
the examples run hermetically (no dataset download; the image lives on a
zero-egress TPU host).
"""
from __future__ import annotations

import argparse
import gzip
import os
import struct

import numpy as np

import mxnet_tpu as mx


def add_data_args(parser: argparse.ArgumentParser):
    data = parser.add_argument_group("Data", "the input images")
    data.add_argument("--data-train", type=str, help="the training data (.rec)")
    data.add_argument("--data-val", type=str, help="the validation data (.rec)")
    data.add_argument("--rgb-mean", type=str, default="123.68,116.779,103.939",
                      help="a tuple of size 3 for the mean rgb")
    data.add_argument("--image-shape", type=str, default="3,224,224",
                      help="the image shape feed into the network")
    data.add_argument("--num-classes", type=int, default=1000,
                      help="the number of classes")
    data.add_argument("--num-examples", type=int, default=1281167,
                      help="the number of training examples")
    data.add_argument("--data-nthreads", type=int, default=4,
                      help="number of decode workers")
    data.add_argument("--benchmark", type=int, default=0,
                      help="if 1, run on synthetic data of --image-shape")
    return data


def add_aug_args(parser: argparse.ArgumentParser):
    aug = parser.add_argument_group("Augmentation", "image augmentations")
    aug.add_argument("--random-crop", type=int, default=1)
    aug.add_argument("--random-mirror", type=int, default=1)
    aug.add_argument("--max-random-scale", type=float, default=1)
    aug.add_argument("--min-random-scale", type=float, default=1)
    return aug


class SyntheticDataIter(mx.io.DataIter):
    """Deterministic learnable synthetic classification batches.

    Each class is a fixed random prototype; samples are prototype + noise,
    so small trainings genuinely converge (used by the train tests). With
    ``learnable=False`` it is pure random data like the reference's
    --benchmark mode.
    """

    def __init__(self, num_classes, data_shape, num_batches=100,
                 dtype="float32", label_name="softmax_label",
                 learnable=False, noise=0.3, seed=0, proto_seed=42):
        super().__init__()
        self.batch_size = data_shape[0]
        self.cur_batch = 0
        self.num_batches = num_batches
        rng = np.random.RandomState(seed)
        if learnable:
            # distinct batches drawn from per-class prototypes so the
            # training signal is real (not one memorized batch); the
            # prototypes are seeded separately so train/val iterators with
            # different sample seeds describe the SAME task
            n = self.batch_size * num_batches
            label = rng.randint(0, num_classes, (n,))
            protos = np.random.RandomState(proto_seed).randn(
                num_classes, *data_shape[1:])
            data = protos[label] + noise * rng.randn(n, *data_shape[1:])
            self.data = [mx.nd.array(
                data[i * self.batch_size:(i + 1) * self.batch_size]
                .astype(dtype)) for i in range(num_batches)]
            self.label = [mx.nd.array(
                label[i * self.batch_size:(i + 1) * self.batch_size]
                .astype(np.float32)) for i in range(num_batches)]
        else:
            # pure-throughput mode: one random batch repeated (reference
            # --benchmark semantics; data content is irrelevant)
            label = rng.randint(0, num_classes, (self.batch_size,))
            data = rng.uniform(-1, 1, data_shape)
            self.data = [mx.nd.array(data.astype(dtype))]
            self.label = [mx.nd.array(label.astype(np.float32))]
        self.data_shape = data_shape
        self.label_name = label_name

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", self.data_shape)]

    @property
    def provide_label(self):
        return [mx.io.DataDesc(self.label_name, (self.batch_size,))]

    def reset(self):
        self.cur_batch = 0

    def next(self):
        if self.cur_batch >= self.num_batches:
            raise StopIteration
        i = self.cur_batch % len(self.data)
        self.cur_batch += 1
        return mx.io.DataBatch(data=[self.data[i]], label=[self.label[i]],
                               pad=0, index=None)


def _read_mnist_images(path):
    with gzip.open(path, "rb") as f:
        _, n, rows, cols = struct.unpack(">IIII", f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(n, 1, rows, cols).astype(np.float32) / 255.0


def _read_mnist_labels(path):
    with gzip.open(path, "rb") as f:
        struct.unpack(">II", f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8).astype(np.float32)


def get_mnist_iter(args, kv=None):
    """MNIST train/val iterators.

    Looks for the idx-ubyte files under --data-dir (reference
    train_mnist.py downloads them; this host has no egress, so absent
    files fall back to a learnable synthetic set of the same shape).
    """
    data_dir = getattr(args, "data_dir", "data/mnist")
    names = ["train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz",
             "t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"]
    paths = [os.path.join(data_dir, n) for n in names]
    if all(os.path.exists(p) for p in paths):
        tx, ty = _read_mnist_images(paths[0]), _read_mnist_labels(paths[1])
        vx, vy = _read_mnist_images(paths[2]), _read_mnist_labels(paths[3])
        train = mx.io.NDArrayIter(tx, ty, args.batch_size, shuffle=True)
        val = mx.io.NDArrayIter(vx, vy, args.batch_size)
        return train, val
    shape = (args.batch_size, 1, 28, 28)
    train = SyntheticDataIter(10, shape, num_batches=60, learnable=True,
                              noise=0.5, seed=0)
    val = SyntheticDataIter(10, shape, num_batches=10, learnable=True,
                            noise=0.5, seed=0)
    return train, val


def get_rec_iter(args, kv=None):
    """RecordIO train/val iterators (reference common/data.py:109
    get_rec_iter → ImageRecordIter); --benchmark 1 → synthetic."""
    image_shape = tuple(int(x) for x in args.image_shape.split(","))
    if args.benchmark:
        shape = (args.batch_size,) + image_shape
        train = SyntheticDataIter(args.num_classes, shape,
                                  num_batches=getattr(args, "num_batches", 50))
        return train, None
    rank, nworker = (kv.rank, kv.num_workers) if kv else (0, 1)
    mean = [float(x) for x in args.rgb_mean.split(",")]
    train = mx.io.ImageRecordIter(
        path_imgrec=args.data_train,
        batch_size=args.batch_size,
        data_shape=image_shape,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        rand_crop=bool(args.random_crop),
        rand_mirror=bool(args.random_mirror),
        shuffle=True,
        num_parts=nworker, part_index=rank,
        preprocess_threads=args.data_nthreads)
    if not args.data_val:
        return train, None
    val = mx.io.ImageRecordIter(
        path_imgrec=args.data_val,
        batch_size=args.batch_size,
        data_shape=image_shape,
        mean_r=mean[0], mean_g=mean[1], mean_b=mean[2],
        rand_crop=False, rand_mirror=False, shuffle=False,
        num_parts=nworker, part_index=rank,
        preprocess_threads=args.data_nthreads)
    return train, val
