"""Train CIFAR-10 (reference: example/image-classification/train_cifar10.py).

    # real data (RecordIO built with tools/im2rec.py)
    python train_cifar10.py --data-train cifar10_train.rec \\
        --data-val cifar10_val.rec

    # synthetic benchmark mode (no dataset needed)
    python train_cifar10.py --benchmark 1 --num-epochs 1
"""
import argparse
import importlib
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

from common import data, fit


def main():
    parser = argparse.ArgumentParser(
        description="train cifar10",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_aug_args(parser)
    parser.set_defaults(
        network="resnet", num_layers=110,
        num_classes=10, num_examples=50000,
        image_shape="3,32,32",
        batch_size=128, num_epochs=300,
        lr=0.05, lr_step_epochs="200,250", wd=1e-4)
    args = parser.parse_args()

    net = importlib.import_module("symbols." + args.network).get_symbol(
        num_classes=args.num_classes, num_layers=args.num_layers,
        image_shape=args.image_shape)

    fit.fit(args, net, data.get_rec_iter)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
