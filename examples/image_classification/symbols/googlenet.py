"""GoogLeNet / Inception-v1 (Szegedy et al. 2014).

Symbolic analog of the reference example's googlenet
(/root/reference/example/image-classification/symbols/googlenet.py),
generated from the paper's inception-module table (without the training-
time auxiliary heads, like the reference example).
"""
import mxnet_tpu as mx


def _conv(x, nf, kernel, stride=(1, 1), pad=(0, 0), name=""):
    x = mx.sym.Convolution(x, num_filter=nf, kernel=kernel, stride=stride,
                           pad=pad, name=f"{name}_conv")
    return mx.sym.Activation(x, act_type="relu", name=f"{name}_relu")


def _inception(x, c1, c3r, c3, c5r, c5, cp, name):
    b1 = _conv(x, c1, (1, 1), name=f"{name}_1x1")
    b3 = _conv(x, c3r, (1, 1), name=f"{name}_3x3r")
    b3 = _conv(b3, c3, (3, 3), pad=(1, 1), name=f"{name}_3x3")
    b5 = _conv(x, c5r, (1, 1), name=f"{name}_5x5r")
    b5 = _conv(b5, c5, (5, 5), pad=(2, 2), name=f"{name}_5x5")
    bp = mx.sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type="max")
    bp = _conv(bp, cp, (1, 1), name=f"{name}_proj")
    return mx.sym.concat(b1, b3, b5, bp, dim=1)


# (c1, c3reduce, c3, c5reduce, c5, pool_proj) per module, from the paper
_MODULES = {
    "3a": (64, 96, 128, 16, 32, 32), "3b": (128, 128, 192, 32, 96, 64),
    "4a": (192, 96, 208, 16, 48, 64), "4b": (160, 112, 224, 24, 64, 64),
    "4c": (128, 128, 256, 24, 64, 64), "4d": (112, 144, 288, 32, 64, 64),
    "4e": (256, 160, 320, 32, 128, 128),
    "5a": (256, 160, 320, 32, 128, 128),
    "5b": (384, 192, 384, 48, 128, 128),
}


def get_symbol(num_classes=1000, **kwargs):
    x = mx.sym.Variable("data")
    x = _conv(x, 64, (7, 7), (2, 2), (3, 3), name="conv1")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    x = _conv(x, 64, (1, 1), name="conv2r")
    x = _conv(x, 192, (3, 3), pad=(1, 1), name="conv2")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    for mod in ("3a", "3b"):
        x = _inception(x, *_MODULES[mod], name=f"incep{mod}")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    for mod in ("4a", "4b", "4c", "4d", "4e"):
        x = _inception(x, *_MODULES[mod], name=f"incep{mod}")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    for mod in ("5a", "5b"):
        x = _inception(x, *_MODULES[mod], name=f"incep{mod}")
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg", kernel=(7, 7))
    x = mx.sym.Flatten(x)
    x = mx.sym.Dropout(x, p=0.4)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(x, name="softmax")
