"""ResNeXt (Xie et al. 2016): resnet bottlenecks with grouped 3x3 convs.

Symbolic analog of the reference example's resnext
(/root/reference/example/image-classification/symbols/resnext.py); the
cardinality-grouped conv lowers to one XLA grouped convolution
(feature_group_count), which the MXU handles natively — no per-branch
splitting like the original paper's figure.
"""
import mxnet_tpu as mx


def _bn(x, name):
    return mx.sym.BatchNorm(x, fix_gamma=False, eps=2e-5, momentum=0.9,
                            name=name)


def residual_unit(data, num_filter, stride, dim_match, name,
                  num_group=32, bottle_neck=True):
    if bottle_neck:
        mid = num_filter // 2
        x = mx.sym.Convolution(data, num_filter=mid, kernel=(1, 1),
                               no_bias=True, name=name + "_conv1")
        x = _bn(x, name + "_bn1")
        x = mx.sym.Activation(x, act_type="relu")
        x = mx.sym.Convolution(x, num_filter=mid, kernel=(3, 3),
                               stride=stride, pad=(1, 1),
                               num_group=num_group, no_bias=True,
                               name=name + "_conv2")
        x = _bn(x, name + "_bn2")
        x = mx.sym.Activation(x, act_type="relu")
        x = mx.sym.Convolution(x, num_filter=num_filter, kernel=(1, 1),
                               no_bias=True, name=name + "_conv3")
        x = _bn(x, name + "_bn3")
    else:
        x = mx.sym.Convolution(data, num_filter=num_filter, kernel=(3, 3),
                               stride=stride, pad=(1, 1), no_bias=True,
                               name=name + "_conv1")
        x = _bn(x, name + "_bn1")
        x = mx.sym.Activation(x, act_type="relu")
        x = mx.sym.Convolution(x, num_filter=num_filter, kernel=(3, 3),
                               pad=(1, 1), no_bias=True,
                               name=name + "_conv2")
        x = _bn(x, name + "_bn2")
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(data, num_filter=num_filter,
                                      kernel=(1, 1), stride=stride,
                                      no_bias=True, name=name + "_sc")
        shortcut = _bn(shortcut, name + "_sc_bn")
    return mx.sym.Activation(x + shortcut, act_type="relu")


def get_symbol(num_classes=1000, num_layers=101, num_group=32, **kwargs):
    units = {50: (3, 4, 6, 3), 101: (3, 4, 23, 3),
             152: (3, 8, 36, 3)}[num_layers]
    filters = (256, 512, 1024, 2048)
    x = mx.sym.Variable("data")
    x = mx.sym.Convolution(x, num_filter=64, kernel=(7, 7), stride=(2, 2),
                           pad=(3, 3), no_bias=True, name="conv0")
    x = _bn(x, "bn0")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    for si, (n, nf) in enumerate(zip(units, filters)):
        for ui in range(n):
            stride = (1, 1) if si == 0 or ui > 0 else (2, 2)
            x = residual_unit(x, nf, stride, ui > 0,
                              f"stage{si + 1}_unit{ui + 1}",
                              num_group=num_group)
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg", kernel=(7, 7))
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(x, name="softmax")
