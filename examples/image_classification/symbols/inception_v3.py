"""Inception-v3 (Szegedy et al. 2015), 299x299 input.

Symbolic analog of the reference example's inception-v3
(/root/reference/example/image-classification/symbols/inception-v3.py),
generated from branch specs (mirrors the gluon model_zoo Inception3
factorizations: A/B/C/D/E blocks with 7x1/1x7 and 3x1/1x3 splits).
"""
import mxnet_tpu as mx


def _conv(x, nf, kernel, stride=(1, 1), pad=(0, 0), name=""):
    x = mx.sym.Convolution(x, num_filter=nf, kernel=kernel, stride=stride,
                           pad=pad, no_bias=True, name=name + "_conv")
    x = mx.sym.BatchNorm(x, eps=0.001, name=name + "_bn")
    return mx.sym.Activation(x, act_type="relu")


def _branch(x, name, *convs, pool=None):
    out = x
    if pool == "avg":
        out = mx.sym.Pooling(out, kernel=(3, 3), stride=(1, 1),
                             pad=(1, 1), pool_type="avg")
    elif pool == "max":
        out = mx.sym.Pooling(out, kernel=(3, 3), stride=(2, 2),
                             pool_type="max")
    for i, (nf, k, s, p) in enumerate(convs):
        out = _conv(out, nf, k, s, p, name=f"{name}_{i}")
    return out


def _block_a(x, pool_features, name):
    return mx.sym.concat(
        _branch(x, name + "_b0", (64, (1, 1), (1, 1), (0, 0))),
        _branch(x, name + "_b1", (48, (1, 1), (1, 1), (0, 0)),
                (64, (5, 5), (1, 1), (2, 2))),
        _branch(x, name + "_b2", (64, (1, 1), (1, 1), (0, 0)),
                (96, (3, 3), (1, 1), (1, 1)),
                (96, (3, 3), (1, 1), (1, 1))),
        _branch(x, name + "_b3", (pool_features, (1, 1), (1, 1), (0, 0)),
                pool="avg"), dim=1)


def _block_b(x, name):
    return mx.sym.concat(
        _branch(x, name + "_b0", (384, (3, 3), (2, 2), (0, 0))),
        _branch(x, name + "_b1", (64, (1, 1), (1, 1), (0, 0)),
                (96, (3, 3), (1, 1), (1, 1)),
                (96, (3, 3), (2, 2), (0, 0))),
        _branch(x, name + "_b2", pool="max"), dim=1)


def _block_c(x, c7, name):
    return mx.sym.concat(
        _branch(x, name + "_b0", (192, (1, 1), (1, 1), (0, 0))),
        _branch(x, name + "_b1", (c7, (1, 1), (1, 1), (0, 0)),
                (c7, (1, 7), (1, 1), (0, 3)),
                (192, (7, 1), (1, 1), (3, 0))),
        _branch(x, name + "_b2", (c7, (1, 1), (1, 1), (0, 0)),
                (c7, (7, 1), (1, 1), (3, 0)),
                (c7, (1, 7), (1, 1), (0, 3)),
                (c7, (7, 1), (1, 1), (3, 0)),
                (192, (1, 7), (1, 1), (0, 3))),
        _branch(x, name + "_b3", (192, (1, 1), (1, 1), (0, 0)),
                pool="avg"), dim=1)


def _block_d(x, name):
    return mx.sym.concat(
        _branch(x, name + "_b0", (192, (1, 1), (1, 1), (0, 0)),
                (320, (3, 3), (2, 2), (0, 0))),
        _branch(x, name + "_b1", (192, (1, 1), (1, 1), (0, 0)),
                (192, (1, 7), (1, 1), (0, 3)),
                (192, (7, 1), (1, 1), (3, 0)),
                (192, (3, 3), (2, 2), (0, 0))),
        _branch(x, name + "_b2", pool="max"), dim=1)


def _block_e(x, name):
    def split(y, nf, name):
        a = _conv(y, nf, (1, 3), (1, 1), (0, 1), name=name + "_a")
        b = _conv(y, nf, (3, 1), (1, 1), (1, 0), name=name + "_b")
        return mx.sym.concat(a, b, dim=1)

    b1 = _conv(x, 384, (1, 1), name=name + "_b1")
    b2 = _conv(x, 448, (1, 1), name=name + "_b2_0")
    b2 = _conv(b2, 384, (3, 3), (1, 1), (1, 1), name=name + "_b2_1")
    b3 = mx.sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type="avg")
    b3 = _conv(b3, 192, (1, 1), name=name + "_b3")
    return mx.sym.concat(
        _branch(x, name + "_b0", (320, (1, 1), (1, 1), (0, 0))),
        split(b1, 384, name + "_s1"), split(b2, 384, name + "_s2"),
        b3, dim=1)


def get_symbol(num_classes=1000, **kwargs):
    x = mx.sym.Variable("data")
    x = _conv(x, 32, (3, 3), (2, 2), name="stem0")
    x = _conv(x, 32, (3, 3), name="stem1")
    x = _conv(x, 64, (3, 3), pad=(1, 1), name="stem2")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, 80, (1, 1), name="stem3")
    x = _conv(x, 192, (3, 3), name="stem4")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _block_a(x, 32, "mixed0")
    x = _block_a(x, 64, "mixed1")
    x = _block_a(x, 64, "mixed2")
    x = _block_b(x, "mixed3")
    x = _block_c(x, 128, "mixed4")
    x = _block_c(x, 160, "mixed5")
    x = _block_c(x, 160, "mixed6")
    x = _block_c(x, 192, "mixed7")
    x = _block_d(x, "mixed8")
    x = _block_e(x, "mixed9")
    x = _block_e(x, "mixed10")
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg", kernel=(8, 8))
    x = mx.sym.Flatten(x)
    x = mx.sym.Dropout(x, p=0.5)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(x, name="softmax")
