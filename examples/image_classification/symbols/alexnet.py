"""AlexNet (Krizhevsky et al. 2012), single-tower variant.

Symbolic analog of the reference example's alexnet
(/root/reference/example/image-classification/symbols/alexnet.py) —
re-expressed compactly; architecture from the paper: 5 convs (LRN after
conv1/conv2), 3 FC layers with dropout.
"""
import mxnet_tpu as mx


def _conv(x, name, num_filter, kernel, stride=(1, 1), pad=(0, 0)):
    x = mx.sym.Convolution(x, num_filter=num_filter, kernel=kernel,
                           stride=stride, pad=pad, name=name)
    return mx.sym.Activation(x, act_type="relu", name=name + "_relu")


def get_symbol(num_classes=1000, dtype="float32", **kwargs):
    data = mx.sym.Variable("data")
    x = _conv(data, "conv1", 96, (11, 11), (4, 4))
    x = mx.sym.LRN(x, alpha=1e-4, beta=0.75, knorm=2, nsize=5)
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, "conv2", 256, (5, 5), pad=(2, 2))
    x = mx.sym.LRN(x, alpha=1e-4, beta=0.75, knorm=2, nsize=5)
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = _conv(x, "conv3", 384, (3, 3), pad=(1, 1))
    x = _conv(x, "conv4", 384, (3, 3), pad=(1, 1))
    x = _conv(x, "conv5", 256, (3, 3), pad=(1, 1))
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pool_type="max")
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=4096, name="fc6")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Dropout(x, p=0.5)
    x = mx.sym.FullyConnected(x, num_hidden=4096, name="fc7")
    x = mx.sym.Activation(x, act_type="relu")
    x = mx.sym.Dropout(x, p=0.5)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc8")
    return mx.sym.SoftmaxOutput(x, name="softmax")
