"""ResNet symbol (capability analog of example/image-classification/symbols/
resnet.py — He et al., "Identity Mappings in Deep Residual Networks").

Built from scratch as mx.sym graph composition; pre-activation (v2) units.
"""
import mxnet_tpu as mx


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True, bn_mom=0.9):
    """A pre-activation residual unit."""
    bn1 = mx.sym.BatchNorm(data=data, fix_gamma=False, eps=2e-5,
                           momentum=bn_mom, name=name + "_bn1")
    act1 = mx.sym.Activation(data=bn1, act_type="relu",
                             name=name + "_relu1")
    if bottle_neck:
        conv1 = mx.sym.Convolution(data=act1, num_filter=num_filter // 4,
                                   kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                   no_bias=True, name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                               momentum=bn_mom, name=name + "_bn2")
        act2 = mx.sym.Activation(data=bn2, act_type="relu",
                                 name=name + "_relu2")
        conv2 = mx.sym.Convolution(data=act2, num_filter=num_filter // 4,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, name=name + "_conv2")
        bn3 = mx.sym.BatchNorm(data=conv2, fix_gamma=False, eps=2e-5,
                               momentum=bn_mom, name=name + "_bn3")
        act3 = mx.sym.Activation(data=bn3, act_type="relu",
                                 name=name + "_relu3")
        conv3 = mx.sym.Convolution(data=act3, num_filter=num_filter,
                                   kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                   no_bias=True, name=name + "_conv3")
        body = conv3
    else:
        conv1 = mx.sym.Convolution(data=act1, num_filter=num_filter,
                                   kernel=(3, 3), stride=stride, pad=(1, 1),
                                   no_bias=True, name=name + "_conv1")
        bn2 = mx.sym.BatchNorm(data=conv1, fix_gamma=False, eps=2e-5,
                               momentum=bn_mom, name=name + "_bn2")
        act2 = mx.sym.Activation(data=bn2, act_type="relu",
                                 name=name + "_relu2")
        conv2 = mx.sym.Convolution(data=act2, num_filter=num_filter,
                                   kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                                   no_bias=True, name=name + "_conv2")
        body = conv2
    if dim_match:
        shortcut = data
    else:
        shortcut = mx.sym.Convolution(data=act1, num_filter=num_filter,
                                      kernel=(1, 1), stride=stride,
                                      no_bias=True, name=name + "_sc")
    return body + shortcut


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, bn_mom=0.9, stem="std"):
    data = mx.sym.Variable("data")
    nchannel, height, _ = image_shape
    data = mx.sym.BatchNorm(data=data, fix_gamma=True, eps=2e-5,
                            momentum=bn_mom, name="bn_data")
    if height <= 32:  # cifar-style stem
        body = mx.sym.Convolution(data=data, num_filter=filter_list[0],
                                  kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                                  no_bias=True, name="conv0")
    else:  # imagenet stem
        if stem == "s2d":
            # exact space-to-depth rewrite of the 7x7/s2/p3 stem —
            # identical math and the identical (O,C,7,7) weight
            # (checkpoint-compatible); quadruples the MXU contraction
            # depth (ops/nn.py conv_s2d_stem)
            w0 = mx.sym.Variable("conv0_weight",
                                 shape=(filter_list[0], nchannel, 7, 7))
            body = mx.sym.conv_s2d_stem(data=data, weight=w0,
                                        name="conv0")
        else:
            body = mx.sym.Convolution(data=data,
                                      num_filter=filter_list[0],
                                      kernel=(7, 7), stride=(2, 2),
                                      pad=(3, 3), no_bias=True,
                                      name="conv0")
        body = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                                momentum=bn_mom, name="bn0")
        body = mx.sym.Activation(data=body, act_type="relu", name="relu0")
        body = mx.sym.Pooling(data=body, kernel=(3, 3), stride=(2, 2),
                              pad=(1, 1), pool_type="max")

    for i in range(num_stages):
        stride = (1, 1) if i == 0 and height > 32 else (2, 2) if i > 0 \
            else (1, 1)
        body = residual_unit(body, filter_list[i + 1], stride, False,
                             name="stage%d_unit%d" % (i + 1, 1),
                             bottle_neck=bottle_neck, bn_mom=bn_mom)
        for j in range(units[i] - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 name="stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck=bottle_neck, bn_mom=bn_mom)
    bn1 = mx.sym.BatchNorm(data=body, fix_gamma=False, eps=2e-5,
                           momentum=bn_mom, name="bn1")
    relu1 = mx.sym.Activation(data=bn1, act_type="relu", name="relu1")
    pool1 = mx.sym.Pooling(data=relu1, global_pool=True, kernel=(7, 7),
                           pool_type="avg", name="pool1")
    flat = mx.sym.Flatten(data=pool1)
    fc1 = mx.sym.FullyConnected(data=flat, num_hidden=num_classes,
                                name="fc1")
    return mx.sym.SoftmaxOutput(data=fc1, name="softmax")


def get_symbol(num_classes, num_layers, image_shape, **kwargs):
    """(reference: symbols/resnet.py get_symbol — unit counts per depth)"""
    image_shape = [int(x) for x in image_shape.split(",")] \
        if isinstance(image_shape, str) else list(image_shape)
    _, height, _ = image_shape
    if height <= 32:  # cifar
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            filter_list = [16, 64, 128, 256]
            bottle_neck = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            filter_list = [16, 16, 32, 64]
            bottle_neck = False
        else:
            raise ValueError(f"no experiments done on num_layers "
                             f"{num_layers}")
        units = per_unit * num_stages
    else:  # imagenet
        num_stages = 4
        if num_layers >= 50:
            filter_list = [64, 256, 512, 1024, 2048]
            bottle_neck = True
        else:
            filter_list = [64, 64, 128, 256, 512]
            bottle_neck = False
        units_map = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3],
                     200: [3, 24, 36, 3]}
        if num_layers not in units_map:
            raise ValueError(f"no experiments done on num_layers "
                             f"{num_layers}")
        units = units_map[num_layers]
    return resnet(units=units, num_stages=num_stages,
                  filter_list=filter_list, num_classes=num_classes,
                  image_shape=image_shape, bottle_neck=bottle_neck,
                  stem=kwargs.get("stem", "std"))
