"""VGG 11/13/16/19 (Simonyan & Zisserman 2014).

Symbolic analog of the reference example's vgg
(/root/reference/example/image-classification/symbols/vgg.py), generated
from the per-stage filter spec instead of unrolled blocks.
"""
import mxnet_tpu as mx

_SPEC = {11: (1, 1, 2, 2, 2), 13: (2, 2, 2, 2, 2),
         16: (2, 2, 3, 3, 3), 19: (2, 2, 4, 4, 4)}
_FILTERS = (64, 128, 256, 512, 512)


def get_symbol(num_classes=1000, num_layers=16, batch_norm=False, **kwargs):
    assert num_layers in _SPEC, f"vgg-{num_layers} not defined"
    x = mx.sym.Variable("data")
    for si, (reps, nf) in enumerate(zip(_SPEC[num_layers], _FILTERS)):
        for ri in range(reps):
            x = mx.sym.Convolution(x, num_filter=nf, kernel=(3, 3),
                                   pad=(1, 1),
                                   name=f"conv{si + 1}_{ri + 1}")
            if batch_norm:
                x = mx.sym.BatchNorm(x, name=f"bn{si + 1}_{ri + 1}")
            x = mx.sym.Activation(x, act_type="relu")
        x = mx.sym.Pooling(x, kernel=(2, 2), stride=(2, 2),
                           pool_type="max")
    x = mx.sym.Flatten(x)
    for i, fc in enumerate((4096, 4096)):
        x = mx.sym.FullyConnected(x, num_hidden=fc, name=f"fc{i + 6}")
        x = mx.sym.Activation(x, act_type="relu")
        x = mx.sym.Dropout(x, p=0.5)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc8")
    return mx.sym.SoftmaxOutput(x, name="softmax")
