"""Inception-BN / Inception-v2 (Ioffe & Szegedy 2015): GoogLeNet with
BatchNorm after every conv and 5x5 branches factored into double 3x3.

Symbolic analog of the reference example's inception-bn
(/root/reference/example/image-classification/symbols/inception-bn.py) —
the model behind the reference's published 152 img/s K80 training number
and 0.7245 top-1 (BASELINE.md).
"""
import mxnet_tpu as mx


def _conv(x, nf, kernel, stride=(1, 1), pad=(0, 0), name=""):
    x = mx.sym.Convolution(x, num_filter=nf, kernel=kernel, stride=stride,
                           pad=pad, no_bias=True, name=f"{name}_conv")
    x = mx.sym.BatchNorm(x, fix_gamma=False, name=f"{name}_bn")
    return mx.sym.Activation(x, act_type="relu", name=f"{name}_relu")


def _inception(x, c1, c3r, c3, cd3r, cd3, cp, pool, name):
    branches = []
    if c1 > 0:
        branches.append(_conv(x, c1, (1, 1), name=f"{name}_1x1"))
    b3 = _conv(x, c3r, (1, 1), name=f"{name}_3x3r")
    branches.append(_conv(b3, c3, (3, 3), pad=(1, 1), name=f"{name}_3x3"))
    bd = _conv(x, cd3r, (1, 1), name=f"{name}_d3x3r")
    bd = _conv(bd, cd3, (3, 3), pad=(1, 1), name=f"{name}_d3x3a")
    branches.append(_conv(bd, cd3, (3, 3), pad=(1, 1),
                          name=f"{name}_d3x3b"))
    bp = mx.sym.Pooling(x, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                        pool_type=pool)
    if cp > 0:
        bp = _conv(bp, cp, (1, 1), name=f"{name}_proj")
    branches.append(bp)
    return mx.sym.concat(*branches, dim=1)


def _inception_stride(x, c3r, c3, cd3r, cd3, name):
    b3 = _conv(x, c3r, (1, 1), name=f"{name}_3x3r")
    b3 = _conv(b3, c3, (3, 3), (2, 2), (1, 1), name=f"{name}_3x3")
    bd = _conv(x, cd3r, (1, 1), name=f"{name}_d3x3r")
    bd = _conv(bd, cd3, (3, 3), pad=(1, 1), name=f"{name}_d3x3a")
    bd = _conv(bd, cd3, (3, 3), (2, 2), (1, 1), name=f"{name}_d3x3b")
    bp = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                        pool_type="max")
    return mx.sym.concat(b3, bd, bp, dim=1)


def get_symbol(num_classes=1000, **kwargs):
    x = mx.sym.Variable("data")
    x = _conv(x, 64, (7, 7), (2, 2), (3, 3), name="conv1")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    x = _conv(x, 64, (1, 1), name="conv2r")
    x = _conv(x, 192, (3, 3), pad=(1, 1), name="conv2")
    x = mx.sym.Pooling(x, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                       pool_type="max")
    x = _inception(x, 64, 64, 64, 64, 96, 32, "avg", "3a")
    x = _inception(x, 64, 64, 96, 64, 96, 64, "avg", "3b")
    x = _inception_stride(x, 128, 160, 64, 96, "3c")
    x = _inception(x, 224, 64, 96, 96, 128, 128, "avg", "4a")
    x = _inception(x, 192, 96, 128, 96, 128, 128, "avg", "4b")
    x = _inception(x, 160, 128, 160, 128, 160, 128, "avg", "4c")
    x = _inception(x, 96, 128, 192, 160, 192, 128, "avg", "4d")
    x = _inception_stride(x, 128, 192, 192, 256, "4e")
    x = _inception(x, 352, 192, 320, 160, 224, 128, "avg", "5a")
    x = _inception(x, 352, 192, 320, 192, 224, 128, "max", "5b")
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg", kernel=(7, 7))
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(x, name="softmax")
