"""MobileNet v1 (Howard et al. 2017): depthwise-separable convolutions.

Symbolic analog of the reference example's mobilenet
(/root/reference/example/image-classification/symbols/mobilenet.py).
Depthwise convs lower to one XLA grouped convolution
(feature_group_count=channels); on TPU they are bandwidth-bound, not
MXU-bound — the framework keeps them fused with the following pointwise
conv's normalization chain.
"""
import mxnet_tpu as mx

# (stride, out_channels) for each depthwise-separable block after the stem
_BLOCKS = [(1, 64), (2, 128), (1, 128), (2, 256), (1, 256), (2, 512),
           (1, 512), (1, 512), (1, 512), (1, 512), (1, 512), (2, 1024),
           (1, 1024)]


def _conv_bn(x, nf, kernel, stride, pad, name, num_group=1):
    x = mx.sym.Convolution(x, num_filter=nf, kernel=kernel, stride=stride,
                           pad=pad, num_group=num_group, no_bias=True,
                           name=f"{name}_conv")
    x = mx.sym.BatchNorm(x, fix_gamma=False, name=f"{name}_bn")
    return mx.sym.Activation(x, act_type="relu", name=f"{name}_relu")


def get_symbol(num_classes=1000, multiplier=1.0, **kwargs):
    def ch(c):
        return max(8, int(c * multiplier))

    x = mx.sym.Variable("data")
    x = _conv_bn(x, ch(32), (3, 3), (2, 2), (1, 1), "conv1")
    cin = ch(32)
    for i, (stride, cout) in enumerate(_BLOCKS):
        x = _conv_bn(x, cin, (3, 3), (stride, stride), (1, 1),
                     f"dw{i + 1}", num_group=cin)      # depthwise
        x = _conv_bn(x, ch(cout), (1, 1), (1, 1), (0, 0),
                     f"pw{i + 1}")                     # pointwise
        cin = ch(cout)
    x = mx.sym.Pooling(x, global_pool=True, pool_type="avg", kernel=(7, 7))
    x = mx.sym.Flatten(x)
    x = mx.sym.FullyConnected(x, num_hidden=num_classes, name="fc1")
    return mx.sym.SoftmaxOutput(x, name="softmax")
