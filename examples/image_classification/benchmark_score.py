"""Inference throughput benchmark — the analog of the reference's
example/image-classification/benchmark_score.py (which produced the
docs/faq/perf.md scoring tables: ResNet-50 713 img/s on 1x P100 @ batch 32).

Scores the jitted symbolic forward on one TPU chip in bf16; batches are
device-resident and dispatch is async with one trailing sync, matching the
training bench's methodology.

Usage: python benchmark_score.py [--networks resnet-50,inception-v3,...]
                                 [--batch-sizes 1,32,128] [--dtype bfloat16]
Prints one JSON line per (network, batch).
"""
import argparse
import json
import time

import numpy as np

import mxnet_tpu as mx

from symbols import alexnet as _alexnet
from symbols import googlenet as _googlenet
from symbols import inception_bn as _incbn
from symbols import inception_v3 as _inc3
from symbols import mobilenet as _mobilenet
from symbols import resnet as _resnet
from symbols import resnext as _resnext
from symbols import vgg as _vgg


def get_network(name):
    """Returns (symbol, image_shape)."""
    if name == "alexnet":
        return _alexnet.get_symbol(1000), (3, 224, 224)
    if name == "googlenet":
        return _googlenet.get_symbol(1000), (3, 224, 224)
    if name == "inception-bn":
        return _incbn.get_symbol(1000), (3, 224, 224)
    if name == "mobilenet":
        return _mobilenet.get_symbol(1000), (3, 224, 224)
    if name.startswith("vgg-"):
        parts = name.split("-")
        if len(parts) == 2 and parts[1].isdigit():
            return _vgg.get_symbol(1000, int(parts[1])), (3, 224, 224)
        if len(parts) == 3 and parts[1].isdigit() and parts[2] == "bn":
            return _vgg.get_symbol(1000, int(parts[1]),
                                   batch_norm=True), (3, 224, 224)
        raise ValueError(f"unknown network {name}")
    if name == "inception-v3":
        return _inc3.get_symbol(1000), (3, 299, 299)
    if name.startswith("resnext-"):
        return _resnext.get_symbol(
            1000, int(name.split("-")[1])), (3, 224, 224)
    if name.startswith("resnet-"):
        num_layers = int(name.split("-")[1])
        return _resnet.get_symbol(1000, num_layers, "3,224,224"), \
            (3, 224, 224)
    raise ValueError(f"unknown network {name}")


def score(network, batch, dtype="bfloat16", steps=30):
    sym, image_shape = get_network(network)
    # score mode: strip the training head's label dependency
    mod = mx.mod.Module(symbol=sym, context=mx.gpu(0),
                        label_names=("softmax_label",))
    data_shape = (batch,) + image_shape
    mod.bind(data_shapes=[("data", data_shape)],
             label_shapes=[("softmax_label", (batch,))],
             for_training=False)
    mod.init_params(mx.init.Xavier())

    rng = np.random.RandomState(0)
    batches = [
        mx.io.DataBatch([mx.nd.array(
            rng.rand(*data_shape).astype(np.float32).astype(dtype))], [])
        for _ in range(4)
    ]
    # warmup/compile — the asnumpy also performs the process's first
    # device->host transfer, which this environment's tunneled runtime
    # needs before block_until_ready actually blocks (verified: without
    # it, waits no-op and "throughput" exceeds the chip's peak FLOPs)
    for b in batches[:2]:
        mod.forward(b, is_train=False)
    mod.get_outputs()[0].asnumpy()

    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        acc = None
        for i in range(steps):
            mod.forward(batches[i % 4], is_train=False)
            # chain every output into one scalar: the final wait then
            # provably covers ALL forwards, with a single 4-byte fetch
            # instead of per-step tunnel round trips
            s = mod.get_outputs()[0].sum()
            acc = s if acc is None else acc + s
        acc.wait_to_read()
        best = min(best, time.perf_counter() - t0)
    img_s = batch * steps / best
    print(json.dumps({"network": network, "batch": batch,
                      "dtype": dtype, "img_s": round(img_s, 1),
                      "ms_per_batch": round(1000 * best / steps, 3)}),
          flush=True)
    return img_s


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--networks", type=str,
                    default="alexnet,resnet-50,resnet-152,inception-v3")
    ap.add_argument("--batch-sizes", type=str, default="32,128")
    ap.add_argument("--dtype", type=str, default="bfloat16")
    args = ap.parse_args()
    for net in args.networks.split(","):
        for b in args.batch_sizes.split(","):
            score(net, int(b), args.dtype)
