"""Train MLP/LeNet on MNIST (reference: example/image-classification/
train_mnist.py).

    python train_mnist.py --network mlp
    python train_mnist.py --network lenet --num-epochs 5

Without the MNIST idx files under --data-dir the script trains on a
learnable synthetic set of the same shape (this host has no egress).
"""
import argparse
import importlib
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

import mxnet_tpu as mx
from common import data, fit


def main():
    parser = argparse.ArgumentParser(
        description="train mnist",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    parser.add_argument("--num-classes", type=int, default=10)
    parser.add_argument("--num-examples", type=int, default=60000)
    parser.add_argument("--add_stn", action="store_true")
    parser.add_argument("--data-dir", type=str, default="data/mnist")
    fit.add_fit_args(parser)
    parser.set_defaults(network="mlp", num_epochs=10,
                        lr=0.05, lr_step_epochs="10", batch_size=64,
                        disp_batches=100)
    args = parser.parse_args()

    net = importlib.import_module("symbols." + args.network).get_symbol(
        num_classes=args.num_classes)

    fit.fit(args, net, data.get_mnist_iter)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
