"""Train ImageNet-scale image classification (reference:
example/image-classification/train_imagenet.py:58).

    # real data (RecordIO built with tools/im2rec.py)
    python train_imagenet.py --network resnet --num-layers 50 \
        --data-train train.rec --data-val val.rec

    # synthetic benchmark mode (no dataset needed)
    python train_imagenet.py --network resnet --num-layers 50 \
        --benchmark 1 --num-epochs 1 --dtype bfloat16
"""
import argparse
import importlib
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

from common import data, fit


def main():
    parser = argparse.ArgumentParser(
        description="train imagenet-1k",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_aug_args(parser)
    parser.set_defaults(
        network="resnet", num_layers=50,
        num_classes=1000, num_examples=1281167,
        image_shape="3,224,224",
        batch_size=128, num_epochs=80,
        lr=0.1, lr_step_epochs="30,60,80", wd=1e-4)
    args = parser.parse_args()

    net = importlib.import_module("symbols." + args.network).get_symbol(
        num_classes=args.num_classes, num_layers=args.num_layers,
        image_shape=args.image_shape)

    fit.fit(args, net, data.get_rec_iter)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
