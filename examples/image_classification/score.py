"""Score a trained checkpoint on a validation set — the analog of the
reference's example/image-classification/score.py.

Usage:
  python score.py --model-prefix ckpt/r50 --load-epoch 90 \\
      --data-val val.rec --batch-size 128 [--metrics acc,top_k_accuracy_5]
"""
import argparse

import mxnet_tpu as mx


def score(model_prefix, load_epoch, data_val, image_shape=(3, 224, 224),
          batch_size=128, rgb_mean=(123.68, 116.779, 103.939),
          metrics=("acc",), data_nthreads=4, max_num_batches=None):
    sym, arg_params, aux_params = mx.model.load_checkpoint(
        model_prefix, load_epoch)
    val = mx.io.ImageRecordIter(
        path_imgrec=data_val, data_shape=image_shape,
        batch_size=batch_size, rand_crop=False, rand_mirror=False,
        mean_r=rgb_mean[0], mean_g=rgb_mean[1], mean_b=rgb_mean[2],
        preprocess_threads=data_nthreads)
    if max_num_batches:
        val = mx.io.ResizeIter(val, max_num_batches)
    mod = mx.mod.Module(symbol=sym, context=mx.gpu(0))
    mod.bind(data_shapes=val.provide_data,
             label_shapes=val.provide_label, for_training=False)
    mod.set_params(arg_params, aux_params)

    def make_metric(m):
        # "top_k_accuracy_5" -> top_k_accuracy with top_k=5
        if m.startswith("top_k_accuracy"):
            suffix = m[len("top_k_accuracy"):].lstrip("_")
            return mx.metric.create("top_k_accuracy",
                                    top_k=int(suffix) if suffix else 5)
        return mx.metric.create(m)

    composite = mx.metric.CompositeEvalMetric(
        [make_metric(m) for m in metrics])
    mod.score(val, composite)  # ONE inference pass for all metrics
    return [m.get() for m in composite.metrics]


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-prefix", required=True)
    ap.add_argument("--load-epoch", type=int, required=True)
    ap.add_argument("--data-val", required=True)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--metrics", default="acc")
    ap.add_argument("--data-nthreads", type=int, default=4)
    ap.add_argument("--max-num-batches", type=int, default=None)
    args = ap.parse_args()
    res = score(args.model_prefix, args.load_epoch, args.data_val,
                tuple(int(x) for x in args.image_shape.split(",")),
                args.batch_size, metrics=args.metrics.split(","),
                data_nthreads=args.data_nthreads,
                max_num_batches=args.max_num_batches)
    for name, value in res:
        print(name, value)
