"""Fine-tune a pretrained checkpoint on a new dataset — the analog of the
reference's example/image-classification/fine-tune.py.

Replaces the classifier head (everything after --layer-before-fullc) with a
fresh FC for the new class count, then trains with the standard fit driver;
backbone weights come from the checkpoint (convert reference checkpoints
with tools/convert_params.py first if needed).

    python fine_tune.py --pretrained-model ckpt/r50 --load-epoch 90 \\
        --data-train caltech_train.rec --num-classes 256 \\
        --num-examples 15240
"""
import argparse
import logging
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))

from common import data, fit

import mxnet_tpu as mx


def get_fine_tune_model(symbol, arg_params, num_classes,
                        layer_name="flatten0"):
    """Cut the graph after ``layer_name`` and attach a fresh classifier
    (reference: fine-tune.py get_fine_tune_model)."""
    all_layers = symbol.get_internals()
    net = all_layers[layer_name + "_output"]
    net = mx.sym.FullyConnected(net, num_hidden=num_classes, name="fc_new")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    # keep only params the cut graph still uses: drops the old classifier
    # head, and makes a wrong --layer-before-fullc fail loudly below
    # instead of silently carrying orphaned weights
    keep = set(net.list_arguments())
    new_args = {k: v for k, v in arg_params.items()
                if k in keep and not k.startswith("fc_new")}
    if not new_args:
        raise ValueError(
            f"no checkpoint params survive the cut at {layer_name!r}; "
            "check --layer-before-fullc")
    return net, new_args


def main():
    parser = argparse.ArgumentParser(
        description="fine-tune a pretrained model",
        formatter_class=argparse.ArgumentDefaultsHelpFormatter)
    fit.add_fit_args(parser)
    data.add_data_args(parser)
    data.add_aug_args(parser)
    parser.add_argument("--pretrained-model", required=True,
                        help="checkpoint prefix to start from")
    parser.add_argument("--layer-before-fullc", default="flatten0",
                        help="name of the layer before the classifier")
    parser.set_defaults(
        network=None, image_shape="3,224,224", num_epochs=30,
        lr=0.01, lr_step_epochs="20", wd=1e-4, batch_size=128)
    args = parser.parse_args()

    sym, arg_params, aux_params = mx.model.load_checkpoint(
        args.pretrained_model, args.load_epoch or 0)
    net, new_args = get_fine_tune_model(
        sym, arg_params, args.num_classes, args.layer_before_fullc)
    # fit must not try to reload the checkpoint on top of the edited graph
    args.load_epoch = None
    fit.fit(args, net, data.get_rec_iter,
            arg_params=new_args, aux_params=aux_params)


if __name__ == "__main__":
    logging.basicConfig(level=logging.INFO)
    main()
