"""Wide & Deep learning with a sparse wide component (BASELINE config 5).

TPU-native rebuild of the reference example
(reference: example/sparse/wide_deep/train.py, model.py): the "wide" half is
a linear model over one-hot categorical features stored as CSR whose weight
receives a row_sparse gradient (lazy_update); the "deep" half is embeddings +
an MLP trained densely through Gluon.

Run: python wide_deep.py --num-epoch 5   (synthetic census-like data)
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import HybridBlock, nn
from mxnet_tpu.ndarray import sparse


N_FIELDS = 3          # categorical fields
N_CATS = 50           # categories per field
N_CONT = 8            # continuous features
WIDE_DIM = N_FIELDS * N_CATS


def make_synthetic(num_rows=2000, seed=0):
    """Label depends on a sparse linear signal over the one-hot categoricals
    plus a nonlinear function of the continuous features."""
    rng = np.random.RandomState(seed)
    cats = rng.randint(0, N_CATS, size=(num_rows, N_FIELDS))
    cont = rng.randn(num_rows, N_CONT).astype(np.float32)
    w_wide = rng.randn(WIDE_DIM)
    offsets = np.arange(N_FIELDS) * N_CATS
    wide_ids = cats + offsets  # (num_rows, N_FIELDS) global one-hot columns
    signal = w_wide[wide_ids].sum(axis=1) + np.tanh(cont[:, :2]).sum(axis=1)
    label = (signal > 0).astype(np.float32)
    return cats.astype(np.int64), wide_ids.astype(np.int64), cont, label


def batch_csr(wide_ids_batch):
    """One-hot CSR for the wide part: one 1.0 per (row, field)."""
    bsz = wide_ids_batch.shape[0]
    indices = np.sort(wide_ids_batch, axis=1).reshape(-1)
    indptr = np.arange(bsz + 1) * N_FIELDS
    values = np.ones(bsz * N_FIELDS, np.float32)
    return sparse.csr_matrix((values, indices, indptr), shape=(bsz, WIDE_DIM))


class DeepNet(HybridBlock):
    """Embeddings per categorical field + MLP over [embeddings, continuous]
    (reference: wide_deep/model.py deep component)."""

    def __init__(self, embed_dim=8, hidden=32, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.embeddings = []
            for i in range(N_FIELDS):
                emb = nn.Embedding(N_CATS, embed_dim)
                setattr(self, f"embed{i}", emb)
                self.embeddings.append(emb)
            self.fc1 = nn.Dense(hidden, activation="relu")
            self.fc2 = nn.Dense(1)

    def forward(self, cats, cont):
        embs = [emb(cats[:, i]) for i, emb in enumerate(self.embeddings)]
        h = nd.concat(*embs, cont, dim=1)
        return self.fc2(self.fc1(h))


def train(num_epoch=5, batch_size=64, lr=0.02, wide_lr=0.2, log=print):
    cats, wide_ids, cont, label = make_synthetic()
    n = len(label)

    deep = DeepNet()
    deep.initialize(mx.init.Xavier())
    trainer = mx.gluon.Trainer(deep.collect_params(), "adam",
                               {"learning_rate": lr})

    # the wide weight trains with lazy row-sparse adam updates
    w_wide = nd.zeros((WIDE_DIM, 1))
    wide_opt = mx.optimizer.Adam(learning_rate=wide_lr, lazy_update=True)
    wide_state = wide_opt.create_state(0, w_wide)

    acc = 0.0
    for epoch in range(num_epoch):
        order = np.random.permutation(n)
        total_loss, correct = 0.0, 0
        for lo in range(0, n - batch_size + 1, batch_size):
            idx = order[lo:lo + batch_size]
            csr = batch_csr(wide_ids[idx])
            cat_nd = nd.array(cats[idx], dtype="int32")
            cont_nd = nd.array(cont[idx])
            y = nd.array(label[idx]).reshape((-1, 1))

            w_wide.attach_grad(stype="row_sparse")
            with mx.autograd.record():
                wide_logit = sparse.dot(csr, w_wide)
                deep_logit = deep(cat_nd, cont_nd)
                logits = wide_logit + deep_logit
                loss = (logits.relu() - logits * y +
                        (1 + (-logits.abs()).exp()).log()).mean()
            loss.backward()
            trainer.step(1)
            wide_opt.update(0, w_wide, w_wide.grad, wide_state)

            pred = (logits.asnumpy() > 0).astype(np.float32)
            correct += int((pred == label[idx].reshape(-1, 1)).sum())
            total_loss += float(loss.asscalar())
        nbatches = (n // batch_size)
        acc = correct / (nbatches * batch_size)
        log(f"epoch {epoch}: loss={total_loss / nbatches:.4f} "
            f"accuracy={acc:.4f}")
    return acc


def main():
    parser = argparse.ArgumentParser(description="wide & deep with sparse wide")
    parser.add_argument("--num-epoch", type=int, default=5)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--lr", type=float, default=0.02)
    parser.add_argument("--wide-lr", type=float, default=0.2)
    args = parser.parse_args()
    train(args.num_epoch, args.batch_size, args.lr, args.wide_lr)


if __name__ == "__main__":
    main()
