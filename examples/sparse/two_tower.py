"""DLRM-style two-tower recommender on SparseEmbedding (round 13).

Each tower is a ``SparseEmbedding`` table (users / items) whose gradient
rides the fused train step's row-sparse path: only the rows touched by
the batch are gathered, deduplicated, and lazily updated (sparse/
rowsparse.py + the lazy optimizer rules in parallel/functional_opt.py —
the reference's ``row_sparse`` + ``lazy_update`` economics, PAPER.md
L3/L6). The towers concatenate into a small MLP and a binary
click/no-click head — the minimal shape of the reference's
example/sparse recommenders and the DLRM family.

The script exercises the full round-13 surface end to end:

- training through ``fit()`` with the r9 async data pipeline wrapping
  the host iterator and a ``CheckpointManager`` snapshotting the tables
  + lazy optimizer state every epoch (kill the process mid-run and rerun
  with the same workdir: ``auto_resume`` picks up at the last epoch);
- ``sparse_report()`` telemetry after training (touched rows, dedup
  ratio, gather/scatter bytes);
- serving through ``Predictor``/``DynamicBatcher`` on integer id
  inputs (graph passes no-fire on embedding graphs — counted skips,
  not crashes).

Run: python two_tower.py                (synthetic, a few seconds)
     python two_tower.py --mini         (CI-sized: tiny vocab, 1 epoch)
"""
import argparse
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.data.pipeline import DataPipeline


def build_sym(n_users, n_items, embed_dim, hidden):
    user = mx.sym.Variable("user")
    item = mx.sym.Variable("item")
    u = mx.sym.SparseEmbedding(data=user, input_dim=n_users,
                               output_dim=embed_dim, name="user_emb")
    i = mx.sym.SparseEmbedding(data=item, input_dim=n_items,
                               output_dim=embed_dim, name="item_emb")
    x = mx.sym.Concat(mx.sym.Flatten(u), mx.sym.Flatten(i), dim=1)
    h = mx.sym.FullyConnected(x, num_hidden=hidden, name="fc1")
    h = mx.sym.Activation(h, act_type="relu")
    o = mx.sym.FullyConnected(h, num_hidden=2, name="fc2")
    return mx.sym.SoftmaxOutput(o, name="softmax")


def make_synthetic(n_users, n_items, num_rows, embed_dim=4, seed=0):
    """Clicks from a planted low-rank affinity: label = [u_vec·i_vec > 0]
    for random per-id vectors — learnable by exactly this model."""
    rng = np.random.RandomState(seed)
    uvec = rng.randn(n_users, embed_dim).astype(np.float32)
    ivec = rng.randn(n_items, embed_dim).astype(np.float32)
    users = rng.randint(0, n_users, size=(num_rows, 1)).astype(np.int32)
    items = rng.randint(0, n_items, size=(num_rows, 1)).astype(np.int32)
    score = (uvec[users[:, 0]] * ivec[items[:, 0]]).sum(axis=1)
    label = (score > 0).astype(np.float32)
    return users, items, label


def train(workdir, n_users=200, n_items=100, embed_dim=8, hidden=16,
          num_rows=2048, batch_size=64, num_epoch=3, pipeline_workers=2,
          quiet=False):
    users, items, label = make_synthetic(n_users, n_items, num_rows)
    base_iter = mx.io.NDArrayIter(
        data={"user": users, "item": items}, label={"softmax_label": label},
        batch_size=batch_size, shuffle=False)
    train_iter = DataPipeline(base_iter, num_workers=pipeline_workers,
                              name="two_tower")

    mod = mx.mod.Module(
        symbol=build_sym(n_users, n_items, embed_dim, hidden),
        data_names=("user", "item"), label_names=("softmax_label",),
        context=mx.cpu())
    manager = mx.CheckpointManager(os.path.join(workdir, "ckpt"))
    mod.fit(train_iter, num_epoch=num_epoch, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.init.Xavier(), eval_metric="acc",
            checkpoint_manager=manager, auto_resume=True,
            batch_end_callback=None if quiet else
            mx.callback.Speedometer(batch_size, 16))

    base_iter.reset()
    acc = mod.score(base_iter, "acc")[0][1]
    return mod, acc


def serve(mod, n_requests=32, seed=1):
    """The r7/r12 serving path on integer ids: Predictor buckets the
    batch, DynamicBatcher coalesces concurrent requests."""
    arg_params, aux_params = mod.get_params()
    pred = mx.serving.Predictor(
        mod.symbol, arg_params, aux_params,
        data_names=("user", "item"),
        data_shapes={"user": (1,), "item": (1,)}, buckets=(8, 32))
    rng = np.random.RandomState(seed)
    req = {"user": rng.randint(0, 10, size=(n_requests, 1), dtype=np.int32),
           "item": rng.randint(0, 10, size=(n_requests, 1), dtype=np.int32)}
    direct = pred.predict(req)
    batcher = mx.serving.DynamicBatcher(pred, name="two_tower").start()
    try:
        # concurrent few-row requests, the shape the batcher exists to
        # coalesce (one big request would exceed max_batch by design)
        futs = [batcher.submit({k: v[i:i + 4] for k, v in req.items()})
                for i in range(0, n_requests, 4)]
        batched = np.concatenate([f.result() for f in futs], axis=0)
    finally:
        batcher.stop()
    np.testing.assert_allclose(direct, batched, rtol=1e-5, atol=1e-6)
    return direct


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mini", action="store_true",
                    help="CI-sized run (tiny vocab, 1 epoch)")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint directory (default: temp; pass the "
                         "same dir twice to exercise auto-resume)")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="two_tower_")
    kw = dict(workdir=workdir)
    if args.mini:
        kw.update(n_users=40, n_items=24, embed_dim=4, hidden=8,
                  num_rows=256, batch_size=32, num_epoch=1,
                  pipeline_workers=1, quiet=True)
    mod, acc = train(**kw)
    scores = serve(mod, n_requests=16 if args.mini else 64)
    report = mx.sparse.sparse_report()
    print(f"train acc: {acc:.3f}  serving rows: {scores.shape[0]}")
    print("sparse_report:", report)
    return {"acc": acc, "scores": scores, "sparse": report}


if __name__ == "__main__":
    main()
