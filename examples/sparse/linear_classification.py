"""Sparse linear classification over LibSVM data (BASELINE config 5).

TPU-native rebuild of the reference example
(reference: example/sparse/linear_classification/train.py): a logistic
regression whose weight gradient is row_sparse — only the feature rows a
batch touches are updated (lazy_update) and only those rows are pulled from
the kvstore (row_sparse_pull), the sharded-embedding training pattern.

Run: python linear_classification.py --num-epoch 5
(Synthetic separable LibSVM data is generated on first use.)
"""
import argparse
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ndarray import sparse


def make_synthetic_libsvm(path, num_rows=2000, num_features=1000,
                          nnz_per_row=12, seed=0):
    """Separable data: label = sign of a sparse ground-truth weight dotted
    with the sample's features."""
    rng = np.random.RandomState(seed)
    w_true = rng.randn(num_features)
    with open(path, "w") as f:
        for _ in range(num_rows):
            cols = np.sort(rng.choice(num_features, nnz_per_row, replace=False))
            vals = rng.rand(nnz_per_row) + 0.1
            label = int(w_true[cols] @ vals > 0)
            feats = " ".join(f"{c}:{v:.4f}" for c, v in zip(cols, vals))
            f.write(f"{label} {feats}\n")


def train(data_path=None, num_features=1000, batch_size=64, num_epoch=5,
          lr=0.5, kvstore="local", log=print):
    if data_path is None:
        data_path = os.path.join(tempfile.gettempdir(),
                                 "mxtpu_linear_classification.libsvm")
        if not os.path.exists(data_path):
            make_synthetic_libsvm(data_path, num_features=num_features)

    train_iter = mx.io.LibSVMIter(data_libsvm=data_path,
                                  data_shape=(num_features,),
                                  batch_size=batch_size)

    bias = nd.zeros((1,))
    bias.attach_grad()

    kv = mx.kv.create(kvstore)
    kv.init("weight", nd.zeros((num_features, 1)))
    optimizer = mx.optimizer.SGD(learning_rate=lr, momentum=0.9)
    kv.set_optimizer(optimizer)
    bias_updater = mx.optimizer.get_updater(
        mx.optimizer.SGD(learning_rate=lr))

    metric = mx.metric.Accuracy()
    acc = 0.0
    for epoch in range(num_epoch):
        train_iter.reset()
        metric.reset()
        total_loss, nbatch = 0.0, 0
        for batch in train_iter:
            csr = batch.data[0]
            label = batch.label[0]
            # pull only the rows this batch touches (reference:
            # kvstore.py row_sparse_pull / kvstore_dist.h:259-288); the
            # dense view has non-touched rows zero, which is fine — the
            # csr dot only ever reads the touched rows
            w_rows = sparse.zeros("row_sparse", (num_features, 1))
            kv.row_sparse_pull("weight", out=w_rows, row_ids=csr.indices)
            w_dense = w_rows.todense()
            w_dense.attach_grad(stype="row_sparse")
            with mx.autograd.record():
                logits = sparse.dot(csr, w_dense) + bias
                y = label.reshape((-1, 1))
                # numerically-stable sigmoid BCE
                loss = (logits.relu() - logits * y +
                        (1 + (-logits.abs()).exp()).log()).mean()
            loss.backward()
            # push the row_sparse gradient; the kvstore-side optimizer
            # applies the lazy row update ("update_on_kvstore")
            kv.push("weight", w_dense.grad)
            bias_updater(1, bias.grad, bias)
            pred = (logits > 0).astype("float32").reshape((-1,))
            metric.update([label], [pred])
            total_loss += float(loss.asscalar())
            nbatch += 1
        acc = metric.get()[1]
        log(f"epoch {epoch}: loss={total_loss / nbatch:.4f} accuracy={acc:.4f}")
    return acc


def main():
    parser = argparse.ArgumentParser(
        description="sparse linear classification (LibSVM, row_sparse grads)")
    parser.add_argument("--data", default=None, help="LibSVM file "
                        "(synthetic data generated if omitted)")
    parser.add_argument("--num-features", type=int, default=1000)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--num-epoch", type=int, default=5)
    parser.add_argument("--lr", type=float, default=0.5)
    parser.add_argument("--kv-store", default="local")
    args = parser.parse_args()
    train(args.data, args.num_features, args.batch_size, args.num_epoch,
          args.lr, args.kv_store)


if __name__ == "__main__":
    main()
