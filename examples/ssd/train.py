"""Single-shot detection (SSD) training example (BASELINE config 4).

TPU-native rebuild of the reference SSD example (reference: example/ssd/
train.py, symbol/symbol_builder.py): a small multi-scale SSD over synthetic
"find the colored square" data — conv backbone, per-scale class/box heads,
MultiBoxPrior anchors, MultiBoxTarget training targets (with hard-negative
mining) and MultiBoxDetection + NMS inference.

Run: python train.py --num-epoch 3
"""
import argparse

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.gluon import HybridBlock, nn


NUM_CLASSES = 2        # square classes (background handled separately)
IMG_SIZE = 32


def make_batch(batch_size, rng):
    """Images with one axis-aligned colored square; label rows
    [cls, xmin, ymin, xmax, ymax] normalized to [0,1]."""
    imgs = rng.rand(batch_size, 3, IMG_SIZE, IMG_SIZE).astype(np.float32) * 0.1
    labels = np.full((batch_size, 1, 5), -1.0, np.float32)
    for i in range(batch_size):
        cls = rng.randint(NUM_CLASSES)
        size = rng.randint(10, 20)
        x0 = rng.randint(0, IMG_SIZE - size)
        y0 = rng.randint(0, IMG_SIZE - size)
        imgs[i, cls, y0:y0 + size, x0:x0 + size] = 1.0
        labels[i, 0] = [cls, x0 / IMG_SIZE, y0 / IMG_SIZE,
                        (x0 + size) / IMG_SIZE, (y0 + size) / IMG_SIZE]
    return nd.array(imgs), nd.array(labels)


class TinySSD(HybridBlock):
    """Two-scale SSD head (reference: example/ssd/symbol/symbol_builder.py
    get_symbol_train — backbone + multi-scale cls/loc conv heads)."""

    SIZES = [(0.3, 0.45), (0.6, 0.8)]
    RATIOS = (1.0, 2.0, 0.5)
    K = 4  # anchors per location: len(sizes) - 1 + len(ratios)

    def __init__(self, num_classes=NUM_CLASSES, **kwargs):
        super().__init__(**kwargs)
        self.num_classes = num_classes
        with self.name_scope():
            self.stem = nn.HybridSequential()
            for filters in (16, 32):
                self.stem.add(nn.Conv2D(filters, 3, padding=1),
                              nn.BatchNorm(), nn.Activation("relu"),
                              nn.MaxPool2D(2))
            self.down = nn.HybridSequential()
            self.down.add(nn.Conv2D(64, 3, padding=1), nn.BatchNorm(),
                          nn.Activation("relu"), nn.MaxPool2D(2))
            self.cls_heads = []
            self.loc_heads = []
            for i in range(2):
                c = nn.Conv2D(self.K * (num_classes + 1), 3, padding=1)
                l = nn.Conv2D(self.K * 4, 3, padding=1)
                setattr(self, f"cls{i}", c)
                setattr(self, f"loc{i}", l)
                self.cls_heads.append(c)
                self.loc_heads.append(l)

    def forward(self, x):
        feats = [self.stem(x)]
        feats.append(self.down(feats[0]))
        cls_preds, loc_preds, anchors = [], [], []
        for i, f in enumerate(feats):
            cp = self.cls_heads[i](f)      # (B, K*(C+1), H, W)
            lp = self.loc_heads[i](f)      # (B, K*4, H, W)
            b = cp.shape[0]
            hw = cp.shape[2] * cp.shape[3]
            cls_preds.append(
                cp.transpose((0, 2, 3, 1)).reshape(
                    (b, hw * self.K, self.num_classes + 1)))
            loc_preds.append(
                lp.transpose((0, 2, 3, 1)).reshape((b, hw * self.K * 4)))
            anchors.append(nd.MultiBoxPrior(
                f, sizes=self.SIZES[i], ratios=self.RATIOS))
        cls_pred = nd.concat(*cls_preds, dim=1)       # (B, A, C+1)
        loc_pred = nd.concat(*loc_preds, dim=1)       # (B, A*4)
        anchor = nd.concat(*anchors, dim=1)           # (1, A, 4)
        return cls_pred, loc_pred, anchor


def ssd_losses(cls_pred, loc_pred, cls_target, loc_target, loc_mask):
    """Masked softmax CE (ignore_label=-1) + smooth-L1 on positives
    (reference: MultiBoxTarget outputs feeding SoftmaxOutput + smooth_l1
    in symbol_builder.py)."""
    logp = cls_pred.log_softmax(axis=-1)
    valid = (cls_target >= 0).astype("float32")
    tgt = cls_target.clip(0, None)
    ce = -nd.pick(logp, tgt, axis=-1) * valid
    cls_loss = ce.sum() / valid.sum().clip(1.0, None)
    diff = (loc_pred - loc_target) * loc_mask
    ad = diff.abs()
    smooth = nd.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5)
    loc_loss = smooth.sum() / loc_mask.sum().clip(1.0, None)
    return cls_loss + loc_loss, cls_loss, loc_loss


def evaluate(net, rng, n=32):
    """Mean IoU of the top detection vs ground truth + class accuracy."""
    imgs, labels = make_batch(n, rng)
    cls_pred, loc_pred, anchor = net(imgs)
    cls_prob = cls_pred.softmax(axis=-1).transpose((0, 2, 1))
    dets = nd.MultiBoxDetection(cls_prob, loc_pred, anchor,
                                nms_threshold=0.45, threshold=0.01)
    dets = dets.asnumpy()
    gt = labels.asnumpy()
    ious, correct = [], 0
    for i in range(n):
        rows = dets[i]
        rows = rows[rows[:, 0] >= 0]
        if not len(rows):
            ious.append(0.0)
            continue
        best = rows[np.argmax(rows[:, 1])]
        g = gt[i, 0]
        ix0, iy0 = max(best[2], g[1]), max(best[3], g[2])
        ix1, iy1 = min(best[4], g[3]), min(best[5], g[4])
        inter = max(ix1 - ix0, 0) * max(iy1 - iy0, 0)
        area = ((best[4] - best[2]) * (best[5] - best[3])
                + (g[3] - g[1]) * (g[4] - g[2]) - inter)
        ious.append(inter / max(area, 1e-9))
        correct += int(best[0] == g[0])
    return float(np.mean(ious)), correct / n


def train(num_epoch=3, batch_size=16, steps_per_epoch=60, lr=0.05,
          seed=0, log=print):
    rng = np.random.RandomState(seed)
    mx.random.seed(seed)
    net = TinySSD()
    net.initialize(mx.init.Xavier())
    trainer = mx.gluon.Trainer(net.collect_params(), "sgd",
                               {"learning_rate": lr, "momentum": 0.9})
    mean_iou, cls_acc = 0.0, 0.0
    for epoch in range(num_epoch):
        total, total_cls, total_loc = 0.0, 0.0, 0.0
        for _ in range(steps_per_epoch):
            imgs, labels = make_batch(batch_size, rng)
            with mx.autograd.record():
                cls_pred, loc_pred, anchor = net(imgs)
                # MultiBoxTarget wants (B, C+1, A) predictions for mining
                cls_pred_t = cls_pred.transpose((0, 2, 1))
                loc_t, loc_m, cls_t = nd.MultiBoxTarget(
                    anchor, labels, cls_pred_t,
                    overlap_threshold=0.5, negative_mining_ratio=3.0,
                    negative_mining_thresh=0.5)
                loss, cls_l, loc_l = ssd_losses(cls_pred, loc_pred,
                                                cls_t, loc_t, loc_m)
            loss.backward()
            trainer.step(1)
            total += float(loss.asscalar())
            total_cls += float(cls_l.asscalar())
            total_loc += float(loc_l.asscalar())
        mean_iou, cls_acc = evaluate(net, rng)
        log(f"epoch {epoch}: loss={total / steps_per_epoch:.4f} "
            f"(cls={total_cls / steps_per_epoch:.4f} "
            f"loc={total_loc / steps_per_epoch:.4f}) "
            f"val_iou={mean_iou:.3f} val_cls_acc={cls_acc:.3f}")
    return mean_iou, cls_acc


def main():
    parser = argparse.ArgumentParser(description="tiny SSD on synthetic data")
    parser.add_argument("--num-epoch", type=int, default=3)
    parser.add_argument("--batch-size", type=int, default=16)
    parser.add_argument("--steps-per-epoch", type=int, default=60)
    parser.add_argument("--lr", type=float, default=0.05)
    args = parser.parse_args()
    train(args.num_epoch, args.batch_size, args.steps_per_epoch, args.lr)


if __name__ == "__main__":
    main()
