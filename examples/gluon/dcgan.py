"""DCGAN (Radford et al. 2015) — the analog of the reference's
example/gluon/dcgan.py: 64x64 generator from Conv2DTranspose stacks, conv
discriminator, alternating Trainer updates under autograd.

With no dataset available the default mode trains against low-frequency
procedural images so the script runs end to end; point --data at a folder
of jpg/png images for real use.

  python dcgan.py --epochs 1 --batch-size 16
  python dcgan.py --data /path/to/images --epochs 25
"""
import argparse
import logging
import time

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def build_generator(ngf=64, nc=3, nz=100):
    net = nn.HybridSequential(prefix="gen_")
    with net.name_scope():
        # nz -> (ngf*8) 4x4
        net.add(nn.Conv2DTranspose(ngf * 8, 4, 1, 0, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                # -> (ngf*4) 8x8
                nn.Conv2DTranspose(ngf * 4, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                # -> (ngf*2) 16x16
                nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                # -> (ngf) 32x32
                nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.Activation("relu"),
                # -> (nc) 64x64
                nn.Conv2DTranspose(nc, 4, 2, 1, use_bias=False),
                nn.Activation("tanh"))
    return net


def build_discriminator(ndf=64):
    net = nn.HybridSequential(prefix="disc_")
    with net.name_scope():
        net.add(nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
                nn.LeakyReLU(0.2),
                nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(ndf * 8, 4, 2, 1, use_bias=False),
                nn.BatchNorm(), nn.LeakyReLU(0.2),
                nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return net


def synthetic_batches(batch_size, n):
    """Low-frequency 64x64 images in [-1, 1]."""
    rng = np.random.RandomState(0)
    for _ in range(n):
        base = rng.rand(batch_size, 3, 8, 8).astype(np.float32)
        img = base.repeat(8, axis=2).repeat(8, axis=3) * 2 - 1
        yield mx.nd.array(img)


def folder_batches(path, batch_size, n):
    """Batches from an image folder (resized/cropped to 64x64, [-1, 1])."""
    import os
    from mxnet_tpu import image as mx_image
    files = sorted(
        os.path.join(path, f) for f in os.listdir(path)
        if f.lower().endswith((".jpg", ".jpeg", ".png")))
    if not files:
        raise ValueError(f"no images found under {path}")
    i = 0
    for _ in range(n):
        imgs = []
        while len(imgs) < batch_size:
            arr = mx_image.imread(files[i % len(files)]).asnumpy()
            i += 1
            arr = np.asarray(mx_image.imresize(
                mx.nd.array(arr), 64, 64).asnumpy(), np.float32)
            imgs.append(arr.transpose(2, 0, 1) / 127.5 - 1.0)
        yield mx.nd.array(np.stack(imgs))


def train(epochs=1, batch_size=16, nz=100, lr=0.0002, beta1=0.5,
          batches_per_epoch=20, data=None):
    gen = build_generator(nz=nz)
    disc = build_discriminator()
    gen.initialize(mx.init.Normal(0.02))
    disc.initialize(mx.init.Normal(0.02))
    g_tr = gluon.Trainer(gen.collect_params(), "adam",
                         {"learning_rate": lr, "beta1": beta1})
    d_tr = gluon.Trainer(disc.collect_params(), "adam",
                         {"learning_rate": lr, "beta1": beta1})
    loss_fn = gluon.loss.SigmoidBinaryCrossEntropyLoss()

    real_label = mx.nd.ones((batch_size,))
    fake_label = mx.nd.zeros((batch_size,))
    d_loss = g_loss = None
    for epoch in range(epochs):
        tic = time.time()
        batches = (folder_batches(data, batch_size, batches_per_epoch)
                   if data else
                   synthetic_batches(batch_size, batches_per_epoch))
        for real in batches:
            noise = mx.nd.random.normal(shape=(batch_size, nz, 1, 1))
            # -- discriminator: max log D(x) + log(1 - D(G(z))) ----------
            with autograd.record():
                out_real = disc(real).reshape((-1,))
                err_real = loss_fn(out_real, real_label)
                fake = gen(noise)
                out_fake = disc(fake.detach()).reshape((-1,))
                err_fake = loss_fn(out_fake, fake_label)
                d_loss = err_real + err_fake
            d_loss.backward()
            d_tr.step(batch_size)
            # -- generator: max log D(G(z)) ------------------------------
            with autograd.record():
                out = disc(fake).reshape((-1,))
                g_loss = loss_fn(out, real_label)
            g_loss.backward()
            g_tr.step(batch_size)
        logging.info("epoch %d: d_loss %.3f g_loss %.3f (%.1fs)",
                     epoch, float(d_loss.mean().asscalar()),
                     float(g_loss.mean().asscalar()), time.time() - tic)
    return gen, disc, float(d_loss.mean().asscalar()), \
        float(g_loss.mean().asscalar())


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--nz", type=int, default=100)
    ap.add_argument("--lr", type=float, default=0.0002)
    ap.add_argument("--data", type=str, default=None,
                    help="image folder; default: synthetic images")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    train(args.epochs, args.batch_size, args.nz, args.lr,
          data=args.data)
