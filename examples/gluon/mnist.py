"""Gluon MNIST — the analog of the reference's example/gluon/mnist.py:
a minimal imperative training loop (record/backward/Trainer.step).

Uses the gluon MNIST dataset when present on disk; otherwise the
--synthetic mode (default on this zero-egress host) trains on a
learnable synthetic digit distribution so the script runs end to end.
"""
import argparse
import logging

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon import nn


def build_net():
    net = nn.HybridSequential(prefix="mlp_")
    with net.name_scope():
        net.add(nn.Dense(128, activation="relu"),
                nn.Dense(64, activation="relu"),
                nn.Dense(10))
    return net


def synthetic_loader(batch_size, n_batches, seed=0):
    # class prototypes are FIXED across epochs (only the sampling noise
    # varies with seed) — the task must stay the same task every epoch
    protos = np.random.RandomState(0).rand(10, 28 * 28).astype(np.float32)
    rng = np.random.RandomState(seed + 1)
    for _ in range(n_batches):
        y = rng.randint(0, 10, batch_size)
        x = protos[y] + 0.3 * rng.randn(batch_size, 28 * 28).astype(
            np.float32)
        yield mx.nd.array(x.reshape(batch_size, 1, 28, 28)), mx.nd.array(y)


def train(epochs=5, batch_size=64, lr=0.1, hybridize=True, n_batches=50):
    net = build_net()
    net.initialize(mx.init.Xavier())
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": lr})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = mx.metric.Accuracy()
    for epoch in range(epochs):
        metric.reset()
        for x, y in synthetic_loader(batch_size, n_batches, seed=epoch):
            with autograd.record():
                out = net(x)
                loss = loss_fn(out, y)
            loss.backward()
            trainer.step(batch_size)
            metric.update([y], [out])
        name, acc = metric.get()
        logging.info("epoch %d: train %s=%.4f", epoch, name, acc)
    return net, acc


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=5)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--no-hybridize", action="store_true")
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)
    _, acc = train(args.epochs, args.batch_size, args.lr,
                   hybridize=not args.no_hybridize)
    assert acc > 0.9, f"did not converge: {acc}"
