"""Tiny character-level transformer LM, trained then SERVED (round 16).

The round-16 shape end to end: the same ``TransformerLMSpec`` drives
both halves. Training builds the full-sequence symbol
(``serving.decode.build_symbol`` — Embedding + learned positions +
pre-LN ``CausalSelfAttention`` blocks + tied-shape head) and runs it
through ``fit()`` with the r9 async data pipeline and a
``CheckpointManager`` snapshotting every epoch (kill the run and rerun
with the same workdir: ``auto_resume`` resumes at the last epoch).
Serving lifts the fitted params into a ``DecodePredictor`` — per-bucket
prefill programs plus ONE single-token decode program whose KV-cache is
donated device state — and streams generations through the continuous
batcher (``DecodeBatcher``), requests joining and leaving the in-flight
decode batch per token.

The corpus is a planted-structure toy (a few sentences repeated): big
enough that next-char accuracy well above chance proves the causal
blocks learn, small enough to fit in a docstring. ``--mini`` is the
CI-sized run the tier-1 suite executes.

Run: python tiny_lm.py                  (a few epochs, then streams)
     python tiny_lm.py --mini           (CI-sized: 1 epoch, tiny model)
"""
import argparse
import os
import tempfile

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.data.pipeline import DataPipeline
from mxnet_tpu.serving.decode import (
    TransformerLMSpec, DecodeBatcher, DecodePredictor, build_symbol)

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "pack my box with five dozen liquor jugs. "
    "how vexingly quick daft zebras jump! "
) * 12


def make_dataset(text, seq_len):
    """Sliding next-char windows: data[i] = chars [i, i+S), label[i] =
    chars [i+1, i+S+1) — the standard LM shift."""
    chars = sorted(set(text))
    stoi = {c: i for i, c in enumerate(chars)}
    ids = np.array([stoi[c] for c in text], dtype=np.int32)
    n = len(ids) - seq_len - 1
    data = np.stack([ids[i:i + seq_len] for i in range(n)])
    label = np.stack([ids[i + 1:i + seq_len + 1] for i in range(n)])
    return data, label.astype(np.float32), chars, stoi


def train(workdir, spec, seq_len, batch_size=32, num_epoch=4,
          pipeline_workers=2, quiet=False):
    data, label, chars, stoi = make_dataset(CORPUS, seq_len)
    base_iter = mx.io.NDArrayIter(
        data={"data": data}, label={"softmax_label": label},
        batch_size=batch_size, shuffle=False)
    train_iter = DataPipeline(base_iter, num_workers=pipeline_workers,
                              name="tiny_lm")

    mod = mx.mod.Module(symbol=build_symbol(spec, seq_len),
                        data_names=("data",),
                        label_names=("softmax_label",),
                        context=mx.cpu())
    manager = mx.CheckpointManager(os.path.join(workdir, "ckpt"))
    metric = mx.metric.Accuracy(axis=2, name="next_char_acc")
    mod.fit(train_iter, num_epoch=num_epoch, optimizer="adam",
            optimizer_params={"learning_rate": 0.003},
            initializer=mx.init.Xavier(), eval_metric=metric,
            checkpoint_manager=manager, auto_resume=True,
            batch_end_callback=None if quiet else
            mx.callback.Speedometer(batch_size, 16))

    base_iter.reset()
    acc = mod.score(base_iter, metric)[0][1]
    return mod, acc, chars, stoi


def generate(mod, spec, chars, stoi, prompts, max_new_tokens=24,
             slots=4):
    """Stream continuations for every prompt through the continuous
    batcher; returns {prompt: generated_text}."""
    eng = DecodePredictor.from_module(mod, spec, slots=slots)
    out = {}
    with DecodeBatcher(eng, name="tiny_lm") as bat:
        futs = {p: bat.submit(
            np.array([stoi[c] for c in p], dtype=np.int32),
            max_new_tokens=max_new_tokens) for p in prompts}
        for p, f in futs.items():
            out[p] = "".join(chars[t] for t in f.result(timeout=120))
    return out, eng.report()


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mini", action="store_true",
                    help="CI-sized run (tiny model, 1 epoch)")
    ap.add_argument("--workdir", default=None,
                    help="checkpoint directory (default: temp; pass the "
                         "same dir twice to exercise auto-resume)")
    args = ap.parse_args(argv)

    workdir = args.workdir or tempfile.mkdtemp(prefix="tiny_lm_")
    vocab = len(sorted(set(CORPUS)))
    if args.mini:
        spec = TransformerLMSpec(vocab_size=vocab, num_embed=32,
                                 num_heads=2, num_layers=2, max_seq=32,
                                 name="tinylm")
        fit_kw = dict(seq_len=16, batch_size=32, num_epoch=1,
                      pipeline_workers=1, quiet=True)
    else:
        spec = TransformerLMSpec(vocab_size=vocab, num_embed=64,
                                 num_heads=4, num_layers=2, max_seq=64,
                                 name="tinylm")
        fit_kw = dict(seq_len=32, batch_size=32, num_epoch=4)
    mod, acc, chars, stoi = train(workdir, spec, **fit_kw)

    prompts = ["the quick", "pack my"] if args.mini else \
        ["the quick brown ", "pack my box ", "how vexingly "]
    texts, report = generate(mod, spec, chars, stoi, prompts,
                             max_new_tokens=8 if args.mini else 24)
    print(f"next-char acc: {acc:.3f}  (chance: {1 / vocab:.3f})")
    for p, t in texts.items():
        print(f"  {p!r} -> {t!r}")
    print(f"decode report: programs={report['retraces']} "
          f"tokens={report['tokens']} "
          f"kv_cache_bytes={report['kv_cache_bytes']}")
    return {"acc": acc, "texts": texts, "report": report}


if __name__ == "__main__":
    main()
