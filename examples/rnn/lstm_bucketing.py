"""LSTM language model with bucketing — the analog of the reference's
example/rnn/bucketing/lstm_bucketing.py: variable-length sentences padded
into length buckets, one compiled executor per bucket (BucketingModule),
trained with Module.fit.

On TPU each bucket is one static-shape XLA program — bucketing is exactly
the right batching strategy for a compiler that wants static shapes (the
reference used it to avoid cudnn re-planning; here it avoids re-tracing).

With no dataset on disk the default synthetic mode generates a
Markov-chain corpus cut into random-length sentences; point --data at a
whitespace-tokenized text file (one sentence per line) for real use.
"""
import argparse
import logging

import numpy as np

import mxnet_tpu as mx


def tokenize(path, vocab=None):
    sentences, vocab = [], dict(vocab or {"<pad>": 0})
    with open(path) as f:
        for line in f:
            words = line.split()
            if not words:
                continue
            for w in words:
                vocab.setdefault(w, len(vocab))
            sentences.append([vocab[w] for w in words])
    return sentences, vocab


def synthetic_corpus(n_sentences=2000, vocab_size=200, seed=0):
    rng = np.random.RandomState(seed)
    trans = rng.dirichlet(np.ones(vocab_size) * 0.05, size=vocab_size)
    sentences = []
    for _ in range(n_sentences):
        length = rng.randint(5, 40)
        s, state = [], rng.randint(vocab_size)
        for _ in range(length):
            state = rng.choice(vocab_size, p=trans[state])
            s.append(state + 1)           # 0 is the pad id
        sentences.append(s)
    return sentences, vocab_size + 1


def sym_gen_factory(vocab_size, num_embed, num_hidden, num_layers,
                    batch_size):
    # the legacy cell API (reference: example/rnn/lstm_bucketing.py uses
    # mx.rnn cells): ONE FusedRNNCell shared across buckets — every
    # bucket's symbol reuses the same flat lstm_parameters variable
    # forget_bias=0: the synthetic corpus is order-1 Markov — biasing
    # the gates toward remembering only slows early convergence here
    cell = mx.rnn.FusedRNNCell(num_hidden, num_layers=num_layers,
                               mode="lstm", forget_bias=0.0,
                               prefix="lstm_")

    def sym_gen(seq_len):
        data = mx.sym.Variable("data")
        label = mx.sym.Variable("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab_size,
                                 output_dim=num_embed, name="embed")
        out, _ = cell.unroll(seq_len, embed, layout="NTC",
                             merge_outputs=True)
        pred = mx.sym.Reshape(out, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab_size,
                                     name="pred")
        label = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label, name="softmax")
        return pred, ("data",), ("softmax_label",)
    return sym_gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=str, default=None)
    ap.add_argument("--buckets", type=str, default="10,20,30,40")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--num-embed", type=int, default=128)
    ap.add_argument("--num-hidden", type=int, default=128)
    ap.add_argument("--num-layers", type=int, default=2)
    ap.add_argument("--num-epochs", type=int, default=5)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()
    logging.basicConfig(level=logging.INFO)

    if args.data:
        sentences, vocab = tokenize(args.data)
        vocab_size = len(vocab)
    else:
        sentences, vocab_size = synthetic_corpus()
    buckets = [int(b) for b in args.buckets.split(",")]

    # the iterator derives next-token labels by shifting inside each
    # padded bucket buffer (reference rnn/io.py semantics)
    train = mx.rnn.BucketSentenceIter(
        sentences, args.batch_size, buckets=buckets, invalid_label=0)

    mod = mx.mod.BucketingModule(
        sym_gen_factory(vocab_size, args.num_embed, args.num_hidden,
                        args.num_layers, args.batch_size),
        default_bucket_key=train.default_bucket_key,
        context=mx.gpu(0))
    mod.fit(train, num_epoch=args.num_epochs,
            eval_metric=mx.metric.Perplexity(ignore_label=0),
            optimizer="adam",
            optimizer_params={"learning_rate": args.lr},
            initializer=mx.init.Xavier(),
            batch_end_callback=mx.callback.Speedometer(
                args.batch_size, 50))
    return mod


if __name__ == "__main__":
    main()
