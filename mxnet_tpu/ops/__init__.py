"""Functional operator library (single source of truth for nd/sym/jit).

Importing this package registers the full op surface. Pallas kernels for the
ops XLA can't fuse well live in ``mxnet_tpu.ops.pallas_kernels``.
"""
from .registry import (OpDef, register_op, get_op, has_op, list_ops, alias,
                       parse_attr)

from . import elemwise  # noqa: F401
from . import reduce  # noqa: F401
from . import shape_ops  # noqa: F401
from . import creation  # noqa: F401
from . import nn  # noqa: F401
from . import random_ops  # noqa: F401
from . import linalg  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import contrib  # noqa: F401
from . import surface  # noqa: F401
from . import pallas_fused  # noqa: F401

__all__ = ["OpDef", "register_op", "get_op", "has_op", "list_ops", "alias",
           "parse_attr"]
