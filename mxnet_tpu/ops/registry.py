"""Operator registry.

TPU-native rebuild of the reference's NNVM op registry
(reference: include/mxnet/op_attr_types.h, src/operator/ — ~300
``NNVM_REGISTER_OP`` sites). Each op here is a *pure function over jax arrays*
``fn(*arrays, **attrs) -> array | tuple``; XLA replaces FCompute kernels,
shape/dtype inference, memory planning and fusion. The registry feeds three
consumers:

- ``mxnet_tpu.ndarray``: eager NDArray wrappers (analog of the generated
  functions in python/mxnet/ndarray/register.py:29-156),
- ``mxnet_tpu.symbol``: lazy graph nodes with the same names,
- ``jit``/hybridize: traced directly.
"""
from __future__ import annotations

import ast
from typing import Callable, Dict, Optional, Sequence

__all__ = ["OpDef", "register_op", "get_op", "list_ops", "alias", "parse_attr"]

_OPS: Dict[str, "OpDef"] = {}


class OpDef:
    __slots__ = ("name", "fn", "aliases", "no_grad", "num_outputs", "attr_types")

    def __init__(self, name: str, fn: Callable, aliases=(), no_grad=False,
                 num_outputs: int = 1, attr_types: Optional[dict] = None):
        self.name = name
        self.fn = fn
        self.aliases = tuple(aliases)
        self.no_grad = no_grad
        self.num_outputs = num_outputs
        self.attr_types = attr_types or {}

    def __call__(self, *args, **kwargs):
        return self.fn(*args, **kwargs)

    def __repr__(self):
        return f"OpDef({self.name})"


def register_op(name: str, aliases: Sequence[str] = (), no_grad: bool = False,
                num_outputs: int = 1):
    """Register an operator implementation under its MXNet name(s)."""

    def _reg(fn):
        opdef = OpDef(name, fn, aliases, no_grad, num_outputs)
        _OPS[name] = opdef
        for a in aliases:
            _OPS[a] = opdef
        return fn

    return _reg


def alias(existing: str, *names: str):
    opdef = _OPS[existing]
    for n in names:
        _OPS[n] = opdef


def get_op(name: str) -> OpDef:
    try:
        return _OPS[name]
    except KeyError:
        raise KeyError(f"Operator '{name}' is not registered "
                       f"({len(set(id(o) for o in _OPS.values()))} ops known)") from None


def has_op(name: str) -> bool:
    return name in _OPS


def list_ops():
    """All registered op names (analog of MXListAllOpNames, c_api.cc)."""
    return sorted(_OPS)


def parse_attr(value):
    """Parse a string-typed attribute as it appears in Symbol JSON.

    The reference stores all graph attrs as strings (dmlc::Parameter
    serialization); e.g. kernel="(3, 3)", no_bias="True", num_hidden="64".
    """
    if not isinstance(value, str):
        return value
    v = value.strip()
    low = v.lower()
    if low in ("true", "false"):
        return low == "true"
    if low in ("none", "null"):
        return None
    try:
        return ast.literal_eval(v)
    except (ValueError, SyntaxError):
        return value
