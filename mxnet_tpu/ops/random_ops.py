"""Random sampling operators.

Reference surface: src/operator/random/sample_op.cc (uniform/normal/gamma/
exponential/poisson/negative_binomial), multisample_op.cc, shuffle_op.cc,
unique_sample_op.cc. Eager calls draw from the global key
(mxnet_tpu.random); under jit pass ``key=`` explicitly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op
from ..dtype import resolve_dtype
from ..random import next_key


def _shape(shape):
    if shape is None:
        return ()
    if isinstance(shape, int):
        return (shape,)
    return tuple(shape)


@register_op("_random_uniform", aliases=["random_uniform", "uniform"], no_grad=True)
def random_uniform(low=0.0, high=1.0, shape=None, ctx=None, dtype="float32",
                   key=None, **kw):
    key = key if key is not None else next_key()
    return jax.random.uniform(key, _shape(shape), resolve_dtype(dtype), low, high)


@register_op("_random_normal", aliases=["random_normal", "normal"], no_grad=True)
def random_normal(loc=0.0, scale=1.0, shape=None, ctx=None, dtype="float32",
                  key=None, **kw):
    key = key if key is not None else next_key()
    return loc + scale * jax.random.normal(key, _shape(shape), resolve_dtype(dtype))


@register_op("_random_gamma", aliases=["random_gamma"], no_grad=True)
def random_gamma(alpha=1.0, beta=1.0, shape=None, ctx=None, dtype="float32",
                 key=None, **kw):
    key = key if key is not None else next_key()
    return jax.random.gamma(key, alpha, _shape(shape), resolve_dtype(dtype)) * beta


@register_op("_random_exponential", aliases=["random_exponential"], no_grad=True)
def random_exponential(lam=1.0, shape=None, ctx=None, dtype="float32", key=None, **kw):
    key = key if key is not None else next_key()
    return jax.random.exponential(key, _shape(shape), resolve_dtype(dtype)) / lam


@register_op("_random_poisson", aliases=["random_poisson"], no_grad=True)
def random_poisson(lam=1.0, shape=None, ctx=None, dtype="float32", key=None, **kw):
    key = key if key is not None else next_key()
    return jax.random.poisson(key, lam, _shape(shape)).astype(resolve_dtype(dtype))


@register_op("_random_negative_binomial", aliases=["random_negative_binomial"],
             no_grad=True)
def random_negative_binomial(k=1, p=1.0, shape=None, ctx=None, dtype="float32",
                             key=None, **kw):
    key = key if key is not None else next_key()
    k1, k2 = jax.random.split(key)
    lam = jax.random.gamma(k1, k, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(resolve_dtype(dtype))


@register_op("_random_generalized_negative_binomial",
             aliases=["random_generalized_negative_binomial"], no_grad=True)
def random_gen_neg_binomial(mu=1.0, alpha=1.0, shape=None, ctx=None,
                            dtype="float32", key=None, **kw):
    key = key if key is not None else next_key()
    k1, k2 = jax.random.split(key)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, _shape(shape)) * (1 - p) / p
    return jax.random.poisson(k2, lam, _shape(shape)).astype(resolve_dtype(dtype))


@register_op("_sample_multinomial", aliases=["sample_multinomial", "multinomial"],
             no_grad=True)
def sample_multinomial(data, shape=None, get_prob=False, dtype="int32", key=None, **kw):
    key = key if key is not None else next_key()
    n = 1 if not shape else (shape if isinstance(shape, int) else int(jnp.prod(jnp.asarray(shape))))
    logits = jnp.log(jnp.maximum(data, 1e-37))
    samples = jax.random.categorical(key, logits, axis=-1,
                                     shape=(n,) + data.shape[:-1])
    samples = jnp.moveaxis(samples, 0, -1)
    if n == 1 and not shape:
        samples = samples[..., 0]
    samples = samples.astype(resolve_dtype(dtype))
    if get_prob:
        logp = jnp.take_along_axis(
            jax.nn.log_softmax(logits), samples.astype(jnp.int32)[..., None], -1)
        return samples, logp[..., 0]
    return samples


@register_op("_shuffle", aliases=["shuffle"], no_grad=True)
def shuffle(data, key=None, **kw):
    key = key if key is not None else next_key()
    return jax.random.permutation(key, data, axis=0)


@register_op("_sample_unique_zipfian", no_grad=True)
def sample_unique_zipfian(range_max=1, shape=None, key=None, **kw):
    key = key if key is not None else next_key()
    n = shape[0] if isinstance(shape, (tuple, list)) else int(shape)
    u = jax.random.uniform(key, (n,))
    s = jnp.exp(u * jnp.log(float(range_max) + 1.0)) - 1.0
    return s.astype(jnp.int64) % range_max
