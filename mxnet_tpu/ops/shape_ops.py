"""Shape manipulation, indexing, joining and misc tensor ops.

Reference surface: src/operator/tensor/matrix_op.cc (reshape/transpose/slice/
clip/repeat/tile/flip/...), indexing_op.cc (take/one_hot/gather_nd/scatter_nd),
concat.cc, slice_channel.cc, stack, pad.cc, cast, depth-space ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op, alias
from ..dtype import resolve_dtype


@register_op("Reshape", aliases=["reshape"])
def reshape(data, shape=None, reverse=False, **kw):
    """MXNet reshape incl. special codes 0 (copy dim), -1 (infer), -2 (copy
    rest), -3 (merge two dims), -4 (split dim) — reference: matrix_op.cc
    ReshapeShape."""
    if shape is None:
        return data
    shape = tuple(shape)
    src = list(data.shape)
    if reverse:
        src = src[::-1]
        shape = tuple(reversed(shape))
    out = []
    src_i = 0
    i = 0
    while i < len(shape):
        s = shape[i]
        if s == 0:
            out.append(src[src_i]); src_i += 1
        elif s == -1:
            out.append(-1); src_i += 1
        elif s == -2:
            out.extend(src[src_i:]); src_i = len(src)
        elif s == -3:
            out.append(src[src_i] * src[src_i + 1]); src_i += 2
        elif s == -4:
            a, b = shape[i + 1], shape[i + 2]
            dim = src[src_i]
            if a == -1:
                a = dim // b
            if b == -1:
                b = dim // a
            out.extend([a, b]); src_i += 1; i += 2
        else:
            out.append(s); src_i += 1
        i += 1
    if reverse:
        out = out[::-1]
    return jnp.reshape(data, tuple(out))


@register_op("Flatten", aliases=["flatten"])
def flatten(data, **kw):
    return jnp.reshape(data, (data.shape[0], -1))


@register_op("transpose")
def transpose(data, axes=None, **kw):
    axes = tuple(axes) if axes else None
    return jnp.transpose(data, axes)


@register_op("expand_dims")
def expand_dims(data, axis=0, **kw):
    return jnp.expand_dims(data, axis)


@register_op("squeeze")
def squeeze(data, axis=None, **kw):
    return jnp.squeeze(data, axis if axis is None else tuple(
        axis if isinstance(axis, (tuple, list)) else (axis,)))


@register_op("SwapAxis", aliases=["swapaxes"])
def swapaxes(data, dim1=0, dim2=0, **kw):
    return jnp.swapaxes(data, dim1, dim2)


@register_op("slice", aliases=["crop"])
def slice_op(data, begin=(), end=(), step=(), **kw):
    """Reference: matrix_op.cc Slice; begin/end entries may be None."""
    slices = []
    step = step or (None,) * len(begin)
    for b, e, s in zip(begin, end, step):
        slices.append(slice(b, e, s))
    return data[tuple(slices)]


@register_op("slice_axis")
def slice_axis(data, axis=0, begin=0, end=None, **kw):
    axis = axis % data.ndim
    sl = [slice(None)] * data.ndim
    sl[axis] = slice(begin, end)
    return data[tuple(sl)]


@register_op("slice_like")
def slice_like(data, shape_like, axes=(), **kw):
    axes = tuple(axes) if axes else tuple(range(shape_like.ndim))
    sl = [slice(None)] * data.ndim
    for a in axes:
        sl[a % data.ndim] = slice(0, shape_like.shape[a % shape_like.ndim])
    return data[tuple(sl)]


@register_op("clip")
def clip(data, a_min=None, a_max=None, **kw):
    return jnp.clip(data, a_min, a_max)


@register_op("take")
def take(a, indices, axis=0, mode="clip", **kw):
    idx = indices.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, a.shape[axis] - 1)
    elif mode == "wrap":
        idx = idx % a.shape[axis]
    return jnp.take(a, idx, axis=axis)


@register_op("batch_take")
def batch_take(a, indices, **kw):
    idx = indices.astype(jnp.int32).reshape(-1)
    return jnp.take_along_axis(a, idx[:, None], axis=1)[:, 0]


@register_op("Embedding")
def embedding(data, weight, input_dim=None, output_dim=None, dtype=None,
              sparse_grad=False, **kw):
    """Reference: src/operator/tensor/indexing_op.cc Embedding. On TPU this is
    a gather that XLA lowers natively; sparse_grad is advisory."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register_op("one_hot", no_grad=True)
def one_hot(indices, depth=None, on_value=1.0, off_value=0.0, dtype="float32", **kw):
    oh = jax.nn.one_hot(indices.astype(jnp.int32), depth)
    out = oh * on_value + (1.0 - oh) * off_value
    return out.astype(resolve_dtype(dtype))


@register_op("gather_nd")
def gather_nd(data, indices, **kw):
    idx = tuple(indices.astype(jnp.int32))
    return data[idx]


@register_op("scatter_nd")
def scatter_nd(data, indices, shape=None, **kw):
    out = jnp.zeros(tuple(shape), data.dtype)
    idx = tuple(indices.astype(jnp.int32))
    return out.at[idx].set(data)


@register_op("Concat", aliases=["concat"])
def concat(*args, dim=1, num_args=None, **kw):
    return jnp.concatenate(args, axis=dim)


@register_op("stack")
def stack(*args, axis=0, num_args=None, **kw):
    return jnp.stack(args, axis=axis)


@register_op("SliceChannel", aliases=["split"], num_outputs=-1)
def split(data, num_outputs=2, axis=1, squeeze_axis=False, **kw):
    outs = jnp.split(data, num_outputs, axis=axis)
    if squeeze_axis:
        outs = [jnp.squeeze(o, axis=axis) for o in outs]
    return tuple(outs)


@register_op("tile")
def tile(data, reps=(), **kw):
    return jnp.tile(data, tuple(reps))


@register_op("repeat")
def repeat(data, repeats=1, axis=None, **kw):
    return jnp.repeat(data, repeats, axis=axis)


@register_op("reverse", aliases=["flip"])
def reverse(data, axis=(), **kw):
    if isinstance(axis, int):
        axis = (axis,)
    return jnp.flip(data, axis=tuple(axis))


@register_op("Pad", aliases=["pad"])
def pad(data, mode="constant", pad_width=(), constant_value=0.0, **kw):
    pw = tuple(pad_width)
    pairs = tuple((pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2))
    jmode = {"constant": "constant", "edge": "edge", "reflect": "reflect"}[mode]
    if jmode == "constant":
        return jnp.pad(data, pairs, mode="constant", constant_values=constant_value)
    return jnp.pad(data, pairs, mode=jmode)


@register_op("where")
def where(condition, x, y, **kw):
    return jnp.where(condition != 0 if condition.dtype != jnp.bool_ else condition, x, y)


@register_op("Cast", aliases=["cast"], no_grad=False)
def cast(data, dtype="float32", **kw):
    return data.astype(resolve_dtype(dtype))


@register_op("zeros_like", no_grad=True)
def zeros_like(data, **kw):
    return jnp.zeros_like(data)


@register_op("ones_like", no_grad=True)
def ones_like(data, **kw):
    return jnp.ones_like(data)


@register_op("shape_array", no_grad=True)
def shape_array(data, **kw):
    return jnp.asarray(data.shape, jnp.int64)


@register_op("size_array", no_grad=True)
def size_array(data, **kw):
    return jnp.asarray([data.size], jnp.int64)


@register_op("diag")
def diag(data, k=0, **kw):
    return jnp.diag(data, k=k) if data.ndim <= 2 else jnp.diagonal(data, offset=k)


@register_op("depth_to_space")
def depth_to_space(data, block_size=1, **kw):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, b, b, c // (b * b), h, w)
    x = x.transpose(0, 3, 4, 1, 5, 2)
    return x.reshape(n, c // (b * b), h * b, w * b)


@register_op("space_to_depth")
def space_to_depth(data, block_size=1, **kw):
    n, c, h, w = data.shape
    b = block_size
    x = data.reshape(n, c, h // b, b, w // b, b)
    x = x.transpose(0, 3, 5, 1, 2, 4)
    return x.reshape(n, c * b * b, h // b, w // b)


@register_op("dot")
def dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    """Reference: src/operator/tensor/dot.cc — contracts lhs's last axis with
    rhs's first (NOT numpy matmul semantics for ndim>2)."""
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    return jnp.tensordot(a, b, axes=1)


@register_op("batch_dot")
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, **kw):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)


@register_op("L2Normalization")
def l2_normalization(data, eps=1e-10, mode="instance", **kw):
    """Reference: src/operator/l2_normalization.cc."""
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    else:  # spatial
        ax = tuple(range(2, data.ndim))
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return data / norm


@register_op("sequence_mask", aliases=["SequenceMask"])
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0, **kw):
    """Reference: src/operator/sequence_mask.cc. data is (T,N,...) for axis=0."""
    if not use_sequence_length or sequence_length is None:
        return data
    T = data.shape[axis]
    pos = jnp.arange(T)
    # broadcast positions against (N,) lengths
    if axis == 0:
        mask = pos[:, None] < sequence_length[None, :].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    else:  # axis == 1: (N, T, ...)
        mask = pos[None, :] < sequence_length[:, None].astype(jnp.int32)
        mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register_op("sequence_last", aliases=["SequenceLast"])
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0, **kw):
    if not use_sequence_length or sequence_length is None:
        idx = data.shape[axis] - 1
        return jnp.take(data, idx, axis=axis)
    last = (sequence_length.astype(jnp.int32) - 1)
    moved = jnp.moveaxis(data, axis, 0)  # (T, N, ...)
    gathered = jnp.take_along_axis(
        moved, last.reshape((1, -1) + (1,) * (moved.ndim - 2)), axis=0)
    return jnp.squeeze(gathered, axis=0)


@register_op("sequence_reverse", aliases=["SequenceReverse"])
def sequence_reverse(data, sequence_length=None, use_sequence_length=False,
                     axis=0, **kw):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=axis)
    T = data.shape[0]
    lens = sequence_length.astype(jnp.int32)
    pos = jnp.arange(T)[:, None]
    rev_idx = jnp.where(pos < lens[None, :], lens[None, :] - 1 - pos, pos)
    moved = data  # (T, N, ...)
    idx = rev_idx.reshape(rev_idx.shape + (1,) * (moved.ndim - 2))
    return jnp.take_along_axis(moved, idx, axis=0)
