"""Contrib operators: CTC loss, SSD MultiBox family, box_nms.

Reference analogs: src/operator/contrib/ctc_loss.cc, multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, bounding_box.cc. All are
re-derived as vectorized jax/lax code (fixed shapes, scan/while-free where
possible) so XLA can fuse and tile them for TPU; none of the reference's
kernel code is used.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .registry import register_op

_NEG = -1e30  # large-negative stand-in for -inf: keeps grads finite


# ---------------------------------------------------------------------------
# CTC loss (reference: src/operator/contrib/ctc_loss.cc — warp-ctc kernels;
# here: log-space alpha recursion under lax.scan, grads via autodiff)
# ---------------------------------------------------------------------------
def _ctc_one(logp, label, t_len, l_len, blank):
    """Negative log likelihood for one sequence.

    logp: (T, C) log-probabilities. label: (L,) int32 token ids.
    t_len/l_len: actual lengths. blank: blank id.
    """
    T, C = logp.shape
    L = label.shape[0]
    S = 2 * L + 1
    # extended label sequence: blank, l1, blank, l2, ..., blank
    z = jnp.full((S,), blank, jnp.int32).at[1::2].set(label.astype(jnp.int32))
    pos = jnp.arange(S)
    valid = pos < 2 * l_len + 1
    # skip-transition allowed when z[s] != blank and z[s] != z[s-2]
    can_skip = jnp.concatenate(
        [jnp.zeros((2,), bool), (z[2:] != blank) & (z[2:] != z[:-2])])

    alpha0 = jnp.full((S,), _NEG).at[0].set(logp[0, z[0]])
    alpha0 = jnp.where((pos == 1) & (l_len > 0),
                       logp[0, z[jnp.minimum(1, S - 1)]], alpha0)
    alpha0 = jnp.where(valid, alpha0, _NEG)

    def step(alpha, tlp):
        t, lp = tlp
        a1 = alpha
        a2 = jnp.concatenate([jnp.full((1,), _NEG), alpha[:-1]])
        a3 = jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]])
        a3 = jnp.where(can_skip, a3, _NEG)
        m = jnp.maximum(jnp.maximum(a1, a2), a3)
        tot = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m)
                          + jnp.exp(a3 - m))
        new = jnp.where(valid, tot + lp[z], _NEG)
        # frozen once t >= t_len so the final alpha is the one at t_len-1
        new = jnp.where(t < t_len, new, alpha)
        return new, None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(step, alpha0, (ts, logp[1:]))
    s_last = 2 * l_len  # index of final blank
    a_end = alpha[jnp.minimum(s_last, S - 1)]
    a_pre = jnp.where(l_len > 0,
                      alpha[jnp.maximum(jnp.minimum(s_last - 1, S - 1), 0)],
                      _NEG)
    m = jnp.maximum(a_end, a_pre)
    ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_pre - m))
    return -ll


@register_op("CTCLoss", aliases=["ctc_loss", "_contrib_CTCLoss",
                                 "_contrib_ctc_loss"])
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first", **kw):
    """CTC negative log likelihood per sample.

    data: (T, N, C) unnormalized activations (softmax applied internally,
    matching the reference op). label: (N, L) padded token ids. Returns (N,)
    losses. blank is class 0 ('first', padding value 0) or C-1 ('last',
    padding value -1).
    """
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    label = label.astype(jnp.int32)
    pad_val = 0 if blank_label == "first" else -1
    if use_data_lengths and data_lengths is not None:
        t_lens = data_lengths.astype(jnp.int32)
    else:
        t_lens = jnp.full((N,), T, jnp.int32)
    if use_label_lengths and label_lengths is not None:
        l_lens = label_lengths.astype(jnp.int32)
    else:
        l_lens = (label != pad_val).sum(axis=1).astype(jnp.int32)
    logp_n = jnp.transpose(logp, (1, 0, 2))  # (N, T, C)
    return jax.vmap(_ctc_one, in_axes=(0, 0, 0, 0, None))(
        logp_n, label, t_lens, l_lens, blank)


# ---------------------------------------------------------------------------
# SSD MultiBox family + box_nms
# (reference: src/operator/contrib/multibox_prior.cc, multibox_target.cc,
# multibox_detection.cc, bounding_box.cc. Re-derived as fixed-shape
# vectorized lax: the reference's sequential CPU loops become masked argmax
# scans / pairwise-IoU matrices that XLA can fuse; no dynamic shapes.)
# ---------------------------------------------------------------------------
def _tuplef(v, default):
    """Attr coercion: tuples arrive as python sequences or MXNet-style
    '(a,b)' strings (symbol JSON)."""
    if v is None:
        return tuple(default)
    if isinstance(v, str):
        v = v.strip("()[] ")
        return tuple(float(x) for x in v.split(",") if x.strip())
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


def _box_iou(a, b):
    """Pairwise IoU of corner-format boxes: (A,4) x (B,4) -> (A,B)
    (reference: CalculateOverlap, multibox_target.cc)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("MultiBoxPrior", aliases=["_contrib_MultiBoxPrior"], no_grad=True)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Generate SSD anchor boxes from a feature map.

    data: (N, C, H, W); output (1, H*W*K, 4) corner boxes, K = num_sizes - 1
    + num_ratios, ordered [all sizes at ratio 1, then ratios[1:] at sizes[0]]
    per location (reference: multibox_prior.cc:40-72 MultiBoxPriorForward).
    """
    sizes = _tuplef(sizes, (1.0,))
    ratios = _tuplef(ratios, (1.0,))
    steps = _tuplef(steps, (-1.0, -1.0))
    offsets = _tuplef(offsets, (0.5, 0.5))
    H, W = int(data.shape[2]), int(data.shape[3])
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    # half-widths/heights per anchor kind; w carries the H/W aspect
    # correction the reference applies (multibox_prior.cc:50,62)
    ws = [s * H / W / 2 for s in sizes] + \
         [sizes[0] * H / W * (r ** 0.5) / 2 for r in ratios[1:]]
    hs = [s / 2 for s in sizes] + \
         [sizes[0] / (r ** 0.5) / 2 for r in ratios[1:]]
    w = jnp.asarray(ws, jnp.float32)
    h = jnp.asarray(hs, jnp.float32)
    cxg = jnp.broadcast_to(cx[None, :, None], (H, W, w.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (H, W, w.shape[0]))
    boxes = jnp.stack([cxg - w, cyg - h, cxg + w, cyg + h], axis=-1)
    boxes = boxes.reshape(1, H * W * w.shape[0], 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


def _encode_loc(anchors, gt):
    """Box regression targets (reference: AssignLocTargets,
    multibox_target.cc:32-55). Variances divided out by the caller."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    eps = 1e-12
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, eps),
        (gy - ay) / jnp.maximum(ah, eps),
        jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)),
        jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)),
    ], axis=1)


def _multibox_target_one(anchors, label, cls_pred, overlap_threshold,
                         ignore_label, negative_mining_ratio,
                         negative_mining_thresh, minimum_negative_samples,
                         variances):
    """Single-sample anchor matching (reference: MultiBoxTargetForward,
    multibox_target.cc:72-277). The sequential greedy bipartite match is a
    fixed-length lax.scan (one round per ground-truth slot)."""
    A = anchors.shape[0]
    L = label.shape[0]
    valid = label[:, 0] > -0.5
    iou = _box_iou(anchors, label[:, 1:5])
    iou = jnp.where(valid[None, :], iou, -1.0)

    # stage 1: greedy global bipartite matching, at most L rounds
    def bipartite_round(state, _):
        a_used, g_used, m_gt, m_iou = state
        masked = jnp.where(a_used[:, None] | g_used[None, :], -1.0, iou)
        flat = jnp.argmax(masked)
        ai, gi = flat // L, flat % L
        ok = masked[ai, gi] > 1e-6
        a_used = a_used.at[ai].set(a_used[ai] | ok)
        g_used = g_used.at[gi].set(g_used[gi] | ok)
        m_gt = m_gt.at[ai].set(jnp.where(ok, gi.astype(jnp.int32), m_gt[ai]))
        m_iou = m_iou.at[ai].set(jnp.where(ok, masked[ai, gi], m_iou[ai]))
        return (a_used, g_used, m_gt, m_iou), None

    init = (jnp.zeros(A, bool), jnp.zeros(L, bool),
            jnp.full(A, -1, jnp.int32), jnp.full(A, -1.0))
    (matched, _, match_gt, match_iou), _ = jax.lax.scan(
        bipartite_round, init, None, length=L)

    # stage 2: per-anchor threshold matching for still-unmatched anchors
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou, axis=1)
    match_gt = jnp.where(matched, match_gt, best_gt)
    match_iou = jnp.where(matched, match_iou, best_iou)
    thr_pos = (~matched) & (best_iou > overlap_threshold) \
        if overlap_threshold > 0 else jnp.zeros(A, bool)
    positive = matched | thr_pos
    num_pos = positive.sum()

    # negatives: hard-negative mining by background prob, or everything
    if negative_mining_ratio > 0:
        prob = jax.nn.softmax(cls_pred, axis=0)[0]  # background prob (A,)
        cand = (~positive) & (match_iou < negative_mining_thresh)
        num_neg = jnp.minimum(
            jnp.maximum((num_pos * negative_mining_ratio).astype(jnp.int32),
                        int(minimum_negative_samples)),
            A - num_pos)
        score = jnp.where(cand, -prob, -jnp.inf)  # hardest = lowest bg prob
        rank = jnp.argsort(jnp.argsort(-score))
        negative = cand & (rank < num_neg)
    else:
        negative = ~positive

    cls_of_gt = label[jnp.clip(match_gt, 0, L - 1), 0]
    cls_target = jnp.where(positive, cls_of_gt + 1.0,
                           jnp.where(negative, 0.0, float(ignore_label)))
    gt_boxes = label[jnp.clip(match_gt, 0, L - 1), 1:5]
    enc = _encode_loc(anchors, gt_boxes) / jnp.asarray(variances)
    loc_target = jnp.where(positive[:, None], enc, 0.0).reshape(A * 4)
    loc_mask = jnp.where(positive[:, None],
                         jnp.ones((A, 4)), 0.0).reshape(A * 4)
    return loc_target, loc_mask, cls_target


@register_op("MultiBoxTarget", aliases=["_contrib_MultiBoxTarget"],
             no_grad=True, num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """Compute SSD training targets.

    anchor: (1, A, 4); label: (B, L, 5+) rows [cls, xmin, ymin, xmax, ymax],
    -1-padded; cls_pred: (B, C, A). Returns (loc_target (B, A*4),
    loc_mask (B, A*4), cls_target (B, A))
    (reference: multibox_target.cc, multibox_target-inl.h:60-81).
    """
    variances = _tuplef(variances, (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    # no_grad ops bypass the registry's per-(op,attrs) jit cache, so cache
    # the jitted batch fn per attr-tuple here (re-tracing the bipartite scan
    # per call would dominate the step)
    fn = _mbt_jit(float(overlap_threshold), float(ignore_label),
                  float(negative_mining_ratio), float(negative_mining_thresh),
                  int(minimum_negative_samples), variances)
    loc_t, loc_m, cls_t = fn(anchors, label, cls_pred)
    return loc_t, loc_m, cls_t


@functools.lru_cache(maxsize=None)
def _mbt_jit(ot, il, nmr, nmt, mns, variances):
    def batch(anchors, label, cls_pred):
        one = lambda lb, cp: _multibox_target_one(
            anchors, lb, cp, ot, il, nmr, nmt, mns, variances)
        return jax.vmap(one)(label, cls_pred)
    return jax.jit(batch)


def _decode_boxes(anchors, loc_pred, variances, clip):
    """Decode regression output to corner boxes (reference:
    TransformLocations, multibox_detection.cc:46-71)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    p = loc_pred.reshape(-1, 4)
    ox = p[:, 0] * variances[0] * aw + ax
    oy = p[:, 1] * variances[1] * ah + ay
    ow = jnp.exp(p[:, 2] * variances[2]) * aw * 0.5
    oh = jnp.exp(p[:, 3] * variances[3]) * ah * 0.5
    boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _greedy_nms_keep(boxes, ids, valid, nms_threshold, force_suppress):
    """Greedy NMS over score-sorted boxes: returns keep mask.

    The reference's O(N^2) sequential suppression (multibox_detection.cc:
    152-167) as a fori_loop over a precomputed pairwise IoU matrix."""
    N = boxes.shape[0]
    iou = _box_iou(boxes, boxes)
    same = jnp.ones((N, N), bool) if force_suppress \
        else ids[:, None] == ids[None, :]
    later = jnp.arange(N)[None, :] > jnp.arange(N)[:, None]
    sup_mat = (iou >= nms_threshold) & same & later

    def body(i, keep):
        return keep & ~(keep[i] & sup_mat[i])

    return jax.lax.fori_loop(0, N, body, valid)


def _multibox_detection_one(cls_prob, loc_pred, anchors, threshold, clip,
                            variances, nms_threshold, force_suppress,
                            nms_topk):
    A = cls_prob.shape[1]
    fg = cls_prob[1:, :]                       # drop background row
    cid = jnp.argmax(fg, axis=0).astype(jnp.float32)   # 0-based class id
    score = jnp.max(fg, axis=0)
    valid = score >= threshold
    boxes = _decode_boxes(anchors, loc_pred, variances, clip)
    order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
    cid, score, boxes, valid = cid[order], score[order], boxes[order], valid[order]
    if nms_topk > 0:
        valid = valid & (jnp.arange(A) < nms_topk)
    if 0 < nms_threshold <= 1:
        keep = _greedy_nms_keep(boxes, cid, valid, nms_threshold,
                                force_suppress)
    else:
        keep = valid
    row = jnp.concatenate([cid[:, None], score[:, None], boxes], axis=1)
    return jnp.where(keep[:, None], row, -1.0)


@register_op("MultiBoxDetection", aliases=["_contrib_MultiBoxDetection"],
             no_grad=True)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1, **kw):
    """Decode predictions into detections with per-class NMS.

    cls_prob: (B, C, A) softmax class probabilities (class 0 = background);
    loc_pred: (B, A*4); anchor: (1, A, 4). Output (B, A, 6) rows
    [class_id, score, xmin, ymin, xmax, ymax], suppressed/invalid rows -1
    (reference: multibox_detection.cc:83-169, -inl.h:48-73).
    """
    variances = _tuplef(variances, (0.1, 0.1, 0.2, 0.2))
    if int(background_id) != 0:
        # the reference kernel also assumes class 0 is background (its
        # scan starts at j=1, multibox_detection.cc:108) — reject rather
        # than silently return wrong detections
        raise NotImplementedError("MultiBoxDetection: background_id must "
                                  "be 0 (class 0 is background)")
    anchors = anchor.reshape(-1, 4)
    fn = _mbd_jit(float(threshold), bool(clip), variances,
                  float(nms_threshold), bool(force_suppress), int(nms_topk))
    return fn(cls_prob, loc_pred, anchors)


@functools.lru_cache(maxsize=None)
def _mbd_jit(threshold, clip, variances, nms_threshold, force_suppress,
             nms_topk):
    def batch(cls_prob, loc_pred, anchors):
        one = lambda cp, lp: _multibox_detection_one(
            cp, lp, anchors, threshold, clip, variances, nms_threshold,
            force_suppress, nms_topk)
        return jax.vmap(one)(cls_prob, loc_pred)
    return jax.jit(batch)


@register_op("box_nms", aliases=["_contrib_box_nms", "box_non_maximum_suppression",
                                 "_contrib_box_non_maximum_suppression"],
             no_grad=True)
def box_nms(data, overlap_thresh=0.5, topk=-1, coord_start=2, score_index=1,
            id_index=-1, force_suppress=False, in_format="corner",
            out_format="corner", valid_thresh=0.0, **kw):
    """Generic non-maximum suppression over (..., N, K) box records
    (reference: bounding_box.cc box_nms, bounding_box-inl.h:50-86).

    Entries are sorted by descending score; suppressed/invalid entries are
    set to -1. Boxes with score <= valid_thresh are invalid.
    """
    shape = data.shape
    N, K = shape[-2], shape[-1]
    flat = data.reshape((-1, N, K))
    cs, si = int(coord_start), int(score_index)

    def one(d):
        score = d[:, si]
        valid = score > valid_thresh
        boxes = d[:, cs:cs + 4]
        if in_format == "center":
            cxy, wh = boxes[:, :2], boxes[:, 2:]
            boxes = jnp.concatenate([cxy - wh / 2, cxy + wh / 2], axis=1)
        ids = d[:, int(id_index)] if int(id_index) >= 0 \
            else jnp.zeros(N, d.dtype)
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
        d_s, boxes_s, ids_s = d[order], boxes[order], ids[order]
        valid_s, score_s = valid[order], score[order]
        if topk > 0:
            valid_s = valid_s & (jnp.arange(N) < int(topk))
        keep = _greedy_nms_keep(boxes_s, ids_s, valid_s,
                                float(overlap_thresh),
                                bool(force_suppress) or int(id_index) < 0)
        out = d_s
        if out_format == "center" and in_format == "corner":
            b = d_s[:, cs:cs + 4]
            out = out.at[:, cs:cs + 4].set(jnp.concatenate(
                [(b[:, :2] + b[:, 2:]) / 2, b[:, 2:] - b[:, :2]], axis=1))
        elif out_format == "corner" and in_format == "center":
            out = out.at[:, cs:cs + 4].set(boxes_s)
        return jnp.where(keep[:, None], out, -1.0)

    return jax.vmap(one)(flat).reshape(shape)
