"""Contrib operators: CTC loss, the SSD MultiBox family, box_nms, FFT,
Correlation, Crop, RPN Proposal/MultiProposal, count_sketch,
DeformableConvolution, and the PSROI pooling family.

Reference analogs: src/operator/contrib/{ctc_loss, multibox_prior,
multibox_target, multibox_detection, bounding_box, fft, ifft, proposal,
multi_proposal, count_sketch, deformable_convolution,
psroi_pooling, deformable_psroi_pooling}.cc and src/operator/
{correlation, crop}.cc. All are re-derived as vectorized jax/lax code
(fixed shapes, scan/while-free where possible) so XLA can fuse and tile
them for TPU; none of the reference's kernel code is used.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op

_NEG = -1e30  # large-negative stand-in for -inf: keeps grads finite


# ---------------------------------------------------------------------------
# CTC loss (reference: src/operator/contrib/ctc_loss.cc — warp-ctc kernels;
# here: log-space alpha recursion under lax.scan, grads via autodiff)
# ---------------------------------------------------------------------------
def _ctc_one(logp, label, t_len, l_len, blank):
    """Negative log likelihood for one sequence.

    logp: (T, C) log-probabilities. label: (L,) int32 token ids.
    t_len/l_len: actual lengths. blank: blank id.
    """
    T, C = logp.shape
    L = label.shape[0]
    S = 2 * L + 1
    # extended label sequence: blank, l1, blank, l2, ..., blank
    z = jnp.full((S,), blank, jnp.int32).at[1::2].set(label.astype(jnp.int32))
    pos = jnp.arange(S)
    valid = pos < 2 * l_len + 1
    # skip-transition allowed when z[s] != blank and z[s] != z[s-2]
    can_skip = jnp.concatenate(
        [jnp.zeros((2,), bool), (z[2:] != blank) & (z[2:] != z[:-2])])

    alpha0 = jnp.full((S,), _NEG).at[0].set(logp[0, z[0]])
    alpha0 = jnp.where((pos == 1) & (l_len > 0),
                       logp[0, z[jnp.minimum(1, S - 1)]], alpha0)
    alpha0 = jnp.where(valid, alpha0, _NEG)

    def step(alpha, tlp):
        t, lp = tlp
        a1 = alpha
        a2 = jnp.concatenate([jnp.full((1,), _NEG), alpha[:-1]])
        a3 = jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]])
        a3 = jnp.where(can_skip, a3, _NEG)
        m = jnp.maximum(jnp.maximum(a1, a2), a3)
        tot = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m)
                          + jnp.exp(a3 - m))
        new = jnp.where(valid, tot + lp[z], _NEG)
        # frozen once t >= t_len so the final alpha is the one at t_len-1
        new = jnp.where(t < t_len, new, alpha)
        return new, None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(step, alpha0, (ts, logp[1:]))
    s_last = 2 * l_len  # index of final blank
    a_end = alpha[jnp.minimum(s_last, S - 1)]
    a_pre = jnp.where(l_len > 0,
                      alpha[jnp.maximum(jnp.minimum(s_last - 1, S - 1), 0)],
                      _NEG)
    m = jnp.maximum(a_end, a_pre)
    ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_pre - m))
    return -ll


@register_op("CTCLoss", aliases=["ctc_loss", "_contrib_CTCLoss",
                                 "_contrib_ctc_loss"])
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first", **kw):
    """CTC negative log likelihood per sample.

    data: (T, N, C) unnormalized activations (softmax applied internally,
    matching the reference op). label: (N, L) padded token ids. Returns (N,)
    losses. blank is class 0 ('first', padding value 0) or C-1 ('last',
    padding value -1).
    """
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    label = label.astype(jnp.int32)
    pad_val = 0 if blank_label == "first" else -1
    if use_data_lengths and data_lengths is not None:
        t_lens = data_lengths.astype(jnp.int32)
    else:
        t_lens = jnp.full((N,), T, jnp.int32)
    if use_label_lengths and label_lengths is not None:
        l_lens = label_lengths.astype(jnp.int32)
    else:
        l_lens = (label != pad_val).sum(axis=1).astype(jnp.int32)
    logp_n = jnp.transpose(logp, (1, 0, 2))  # (N, T, C)
    return jax.vmap(_ctc_one, in_axes=(0, 0, 0, 0, None))(
        logp_n, label, t_lens, l_lens, blank)


# ---------------------------------------------------------------------------
# SSD MultiBox family + box_nms
# (reference: src/operator/contrib/multibox_prior.cc, multibox_target.cc,
# multibox_detection.cc, bounding_box.cc. Re-derived as fixed-shape
# vectorized lax: the reference's sequential CPU loops become masked argmax
# scans / pairwise-IoU matrices that XLA can fuse; no dynamic shapes.)
# ---------------------------------------------------------------------------
def _tuplef(v, default):
    """Attr coercion: tuples arrive as python sequences or MXNet-style
    '(a,b)' strings (symbol JSON)."""
    if v is None:
        return tuple(default)
    if isinstance(v, str):
        v = v.strip("()[] ")
        return tuple(float(x) for x in v.split(",") if x.strip())
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


def _box_iou(a, b):
    """Pairwise IoU of corner-format boxes: (A,4) x (B,4) -> (A,B)
    (reference: CalculateOverlap, multibox_target.cc)."""
    tl = jnp.maximum(a[:, None, :2], b[None, :, :2])
    br = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(br - tl, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[:, 2] - a[:, 0], 0) * jnp.maximum(a[:, 3] - a[:, 1], 0)
    area_b = jnp.maximum(b[:, 2] - b[:, 0], 0) * jnp.maximum(b[:, 3] - b[:, 1], 0)
    union = area_a[:, None] + area_b[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


@register_op("MultiBoxPrior", aliases=["_contrib_MultiBoxPrior"], no_grad=True)
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), **kw):
    """Generate SSD anchor boxes from a feature map.

    data: (N, C, H, W); output (1, H*W*K, 4) corner boxes, K = num_sizes - 1
    + num_ratios, ordered [all sizes at ratio 1, then ratios[1:] at sizes[0]]
    per location (reference: multibox_prior.cc:40-72 MultiBoxPriorForward).
    """
    sizes = _tuplef(sizes, (1.0,))
    ratios = _tuplef(ratios, (1.0,))
    steps = _tuplef(steps, (-1.0, -1.0))
    offsets = _tuplef(offsets, (0.5, 0.5))
    H, W = int(data.shape[2]), int(data.shape[3])
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(W, dtype=jnp.float32) + offsets[1]) * step_x
    # half-widths/heights per anchor kind; w carries the H/W aspect
    # correction the reference applies (multibox_prior.cc:50,62)
    ws = [s * H / W / 2 for s in sizes] + \
         [sizes[0] * H / W * (r ** 0.5) / 2 for r in ratios[1:]]
    hs = [s / 2 for s in sizes] + \
         [sizes[0] / (r ** 0.5) / 2 for r in ratios[1:]]
    w = jnp.asarray(ws, jnp.float32)
    h = jnp.asarray(hs, jnp.float32)
    cxg = jnp.broadcast_to(cx[None, :, None], (H, W, w.shape[0]))
    cyg = jnp.broadcast_to(cy[:, None, None], (H, W, w.shape[0]))
    boxes = jnp.stack([cxg - w, cyg - h, cxg + w, cyg + h], axis=-1)
    boxes = boxes.reshape(1, H * W * w.shape[0], 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes.astype(data.dtype)


def _encode_loc(anchors, gt):
    """Box regression targets (reference: AssignLocTargets,
    multibox_target.cc:32-55). Variances divided out by the caller."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    gw = gt[:, 2] - gt[:, 0]
    gh = gt[:, 3] - gt[:, 1]
    gx = (gt[:, 0] + gt[:, 2]) * 0.5
    gy = (gt[:, 1] + gt[:, 3]) * 0.5
    eps = 1e-12
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, eps),
        (gy - ay) / jnp.maximum(ah, eps),
        jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)),
        jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)),
    ], axis=1)


def _multibox_target_one(anchors, label, cls_pred, overlap_threshold,
                         ignore_label, negative_mining_ratio,
                         negative_mining_thresh, minimum_negative_samples,
                         variances):
    """Single-sample anchor matching (reference: MultiBoxTargetForward,
    multibox_target.cc:72-277). The sequential greedy bipartite match is a
    fixed-length lax.scan (one round per ground-truth slot)."""
    A = anchors.shape[0]
    L = label.shape[0]
    valid = label[:, 0] > -0.5
    iou = _box_iou(anchors, label[:, 1:5])
    iou = jnp.where(valid[None, :], iou, -1.0)

    # stage 1: greedy global bipartite matching, at most L rounds
    def bipartite_round(state, _):
        a_used, g_used, m_gt, m_iou = state
        masked = jnp.where(a_used[:, None] | g_used[None, :], -1.0, iou)
        flat = jnp.argmax(masked)
        ai, gi = flat // L, flat % L
        ok = masked[ai, gi] > 1e-6
        a_used = a_used.at[ai].set(a_used[ai] | ok)
        g_used = g_used.at[gi].set(g_used[gi] | ok)
        m_gt = m_gt.at[ai].set(jnp.where(ok, gi.astype(jnp.int32), m_gt[ai]))
        m_iou = m_iou.at[ai].set(jnp.where(ok, masked[ai, gi], m_iou[ai]))
        return (a_used, g_used, m_gt, m_iou), None

    init = (jnp.zeros(A, bool), jnp.zeros(L, bool),
            jnp.full(A, -1, jnp.int32), jnp.full(A, -1.0))
    (matched, _, match_gt, match_iou), _ = jax.lax.scan(
        bipartite_round, init, None, length=L)

    # stage 2: per-anchor threshold matching for still-unmatched anchors
    best_gt = jnp.argmax(iou, axis=1).astype(jnp.int32)
    best_iou = jnp.max(iou, axis=1)
    match_gt = jnp.where(matched, match_gt, best_gt)
    match_iou = jnp.where(matched, match_iou, best_iou)
    thr_pos = (~matched) & (best_iou > overlap_threshold) \
        if overlap_threshold > 0 else jnp.zeros(A, bool)
    positive = matched | thr_pos
    num_pos = positive.sum()

    # negatives: hard-negative mining by background prob, or everything
    if negative_mining_ratio > 0:
        prob = jax.nn.softmax(cls_pred, axis=0)[0]  # background prob (A,)
        cand = (~positive) & (match_iou < negative_mining_thresh)
        num_neg = jnp.minimum(
            jnp.maximum((num_pos * negative_mining_ratio).astype(jnp.int32),
                        int(minimum_negative_samples)),
            A - num_pos)
        score = jnp.where(cand, -prob, -jnp.inf)  # hardest = lowest bg prob
        rank = jnp.argsort(jnp.argsort(-score))
        negative = cand & (rank < num_neg)
    else:
        negative = ~positive

    cls_of_gt = label[jnp.clip(match_gt, 0, L - 1), 0]
    cls_target = jnp.where(positive, cls_of_gt + 1.0,
                           jnp.where(negative, 0.0, float(ignore_label)))
    gt_boxes = label[jnp.clip(match_gt, 0, L - 1), 1:5]
    enc = _encode_loc(anchors, gt_boxes) / jnp.asarray(variances)
    loc_target = jnp.where(positive[:, None], enc, 0.0).reshape(A * 4)
    loc_mask = jnp.where(positive[:, None],
                         jnp.ones((A, 4)), 0.0).reshape(A * 4)
    return loc_target, loc_mask, cls_target


@register_op("MultiBoxTarget", aliases=["_contrib_MultiBoxTarget"],
             no_grad=True, num_outputs=3)
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2), **kw):
    """Compute SSD training targets.

    anchor: (1, A, 4); label: (B, L, 5+) rows [cls, xmin, ymin, xmax, ymax],
    -1-padded; cls_pred: (B, C, A). Returns (loc_target (B, A*4),
    loc_mask (B, A*4), cls_target (B, A))
    (reference: multibox_target.cc, multibox_target-inl.h:60-81).
    """
    variances = _tuplef(variances, (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    # no_grad ops bypass the registry's per-(op,attrs) jit cache, so cache
    # the jitted batch fn per attr-tuple here (re-tracing the bipartite scan
    # per call would dominate the step)
    fn = _mbt_jit(float(overlap_threshold), float(ignore_label),
                  float(negative_mining_ratio), float(negative_mining_thresh),
                  int(minimum_negative_samples), variances)
    loc_t, loc_m, cls_t = fn(anchors, label, cls_pred)
    return loc_t, loc_m, cls_t


@functools.lru_cache(maxsize=None)
def _mbt_jit(ot, il, nmr, nmt, mns, variances):
    def batch(anchors, label, cls_pred):
        one = lambda lb, cp: _multibox_target_one(
            anchors, lb, cp, ot, il, nmr, nmt, mns, variances)
        return jax.vmap(one)(label, cls_pred)
    return jax.jit(batch)


def _decode_boxes(anchors, loc_pred, variances, clip):
    """Decode regression output to corner boxes (reference:
    TransformLocations, multibox_detection.cc:46-71)."""
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    ax = (anchors[:, 0] + anchors[:, 2]) * 0.5
    ay = (anchors[:, 1] + anchors[:, 3]) * 0.5
    p = loc_pred.reshape(-1, 4)
    ox = p[:, 0] * variances[0] * aw + ax
    oy = p[:, 1] * variances[1] * ah + ay
    ow = jnp.exp(p[:, 2] * variances[2]) * aw * 0.5
    oh = jnp.exp(p[:, 3] * variances[3]) * ah * 0.5
    boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=1)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _greedy_nms_keep(boxes, ids, valid, nms_threshold, force_suppress):
    """Greedy NMS over score-sorted boxes: returns keep mask.

    The reference's O(N^2) sequential suppression (multibox_detection.cc:
    152-167) as a fori_loop over a precomputed pairwise IoU matrix."""
    N = boxes.shape[0]
    iou = _box_iou(boxes, boxes)
    same = jnp.ones((N, N), bool) if force_suppress \
        else ids[:, None] == ids[None, :]
    later = jnp.arange(N)[None, :] > jnp.arange(N)[:, None]
    sup_mat = (iou >= nms_threshold) & same & later

    def body(i, keep):
        return keep & ~(keep[i] & sup_mat[i])

    return jax.lax.fori_loop(0, N, body, valid)


def _multibox_detection_one(cls_prob, loc_pred, anchors, threshold, clip,
                            variances, nms_threshold, force_suppress,
                            nms_topk):
    A = cls_prob.shape[1]
    fg = cls_prob[1:, :]                       # drop background row
    cid = jnp.argmax(fg, axis=0).astype(jnp.float32)   # 0-based class id
    score = jnp.max(fg, axis=0)
    valid = score >= threshold
    boxes = _decode_boxes(anchors, loc_pred, variances, clip)
    order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
    cid, score, boxes, valid = cid[order], score[order], boxes[order], valid[order]
    if nms_topk > 0:
        valid = valid & (jnp.arange(A) < nms_topk)
    if 0 < nms_threshold <= 1:
        keep = _greedy_nms_keep(boxes, cid, valid, nms_threshold,
                                force_suppress)
    else:
        keep = valid
    row = jnp.concatenate([cid[:, None], score[:, None], boxes], axis=1)
    return jnp.where(keep[:, None], row, -1.0)


@register_op("MultiBoxDetection", aliases=["_contrib_MultiBoxDetection"],
             no_grad=True)
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1, **kw):
    """Decode predictions into detections with per-class NMS.

    cls_prob: (B, C, A) softmax class probabilities (class 0 = background);
    loc_pred: (B, A*4); anchor: (1, A, 4). Output (B, A, 6) rows
    [class_id, score, xmin, ymin, xmax, ymax], suppressed/invalid rows -1
    (reference: multibox_detection.cc:83-169, -inl.h:48-73).
    """
    variances = _tuplef(variances, (0.1, 0.1, 0.2, 0.2))
    if int(background_id) != 0:
        # the reference kernel also assumes class 0 is background (its
        # scan starts at j=1, multibox_detection.cc:108) — reject rather
        # than silently return wrong detections
        raise NotImplementedError("MultiBoxDetection: background_id must "
                                  "be 0 (class 0 is background)")
    anchors = anchor.reshape(-1, 4)
    fn = _mbd_jit(float(threshold), bool(clip), variances,
                  float(nms_threshold), bool(force_suppress), int(nms_topk))
    return fn(cls_prob, loc_pred, anchors)


@functools.lru_cache(maxsize=None)
def _mbd_jit(threshold, clip, variances, nms_threshold, force_suppress,
             nms_topk):
    def batch(cls_prob, loc_pred, anchors):
        one = lambda cp, lp: _multibox_detection_one(
            cp, lp, anchors, threshold, clip, variances, nms_threshold,
            force_suppress, nms_topk)
        return jax.vmap(one)(cls_prob, loc_pred)
    return jax.jit(batch)


@register_op("box_nms", aliases=["_contrib_box_nms", "box_non_maximum_suppression",
                                 "_contrib_box_non_maximum_suppression"],
             no_grad=True)
def box_nms(data, overlap_thresh=0.5, topk=-1, coord_start=2, score_index=1,
            id_index=-1, force_suppress=False, in_format="corner",
            out_format="corner", valid_thresh=0.0, **kw):
    """Generic non-maximum suppression over (..., N, K) box records
    (reference: bounding_box.cc box_nms, bounding_box-inl.h:50-86).

    Entries are sorted by descending score; suppressed/invalid entries are
    set to -1. Boxes with score <= valid_thresh are invalid.
    """
    shape = data.shape
    N, K = shape[-2], shape[-1]
    flat = data.reshape((-1, N, K))
    cs, si = int(coord_start), int(score_index)

    def one(d):
        score = d[:, si]
        valid = score > valid_thresh
        boxes = d[:, cs:cs + 4]
        if in_format == "center":
            cxy, wh = boxes[:, :2], boxes[:, 2:]
            boxes = jnp.concatenate([cxy - wh / 2, cxy + wh / 2], axis=1)
        ids = d[:, int(id_index)] if int(id_index) >= 0 \
            else jnp.zeros(N, d.dtype)
        order = jnp.argsort(-jnp.where(valid, score, -jnp.inf))
        d_s, boxes_s, ids_s = d[order], boxes[order], ids[order]
        valid_s, score_s = valid[order], score[order]
        if topk > 0:
            valid_s = valid_s & (jnp.arange(N) < int(topk))
        keep = _greedy_nms_keep(boxes_s, ids_s, valid_s,
                                float(overlap_thresh),
                                bool(force_suppress) or int(id_index) < 0)
        out = d_s
        if out_format == "center" and in_format == "corner":
            b = d_s[:, cs:cs + 4]
            out = out.at[:, cs:cs + 4].set(jnp.concatenate(
                [(b[:, :2] + b[:, 2:]) / 2, b[:, 2:] - b[:, :2]], axis=1))
        elif out_format == "corner" and in_format == "center":
            out = out.at[:, cs:cs + 4].set(boxes_s)
        return jnp.where(keep[:, None], out, -1.0)

    return jax.vmap(one)(flat).reshape(shape)


# ---------------------------------------------------------------------------
# FFT / IFFT (reference: src/operator/contrib/fft-inl.h, ifft-inl.h —
# cuFFT C2C; here jnp.fft, output layout interleaved [re, im] per element)
# ---------------------------------------------------------------------------
@register_op("fft", aliases=["_contrib_fft"])
def fft(data, compute_size=128, **kw):
    """Real input (..., d) -> (..., 2d) interleaved real/imag of the
    unnormalized FFT along the last axis (reference: fft-inl.h; layout
    verified against tests/python/gpu/test_operator_gpu.py:189)."""
    spec = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([spec.real, spec.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(data.dtype)


@register_op("ifft", aliases=["_contrib_ifft"])
def ifft(data, compute_size=128, **kw):
    """Interleaved (..., 2d) -> real (..., d), unnormalized (x d) like
    cuFFT inverse (reference: ifft-inl.h; test_operator_gpu.py:108
    compares out/d with np.fft.ifft)."""
    d = data.shape[-1] // 2
    pairs = data.reshape(data.shape[:-1] + (d, 2)).astype(jnp.float32)
    spec = jax.lax.complex(pairs[..., 0], pairs[..., 1])
    out = jnp.fft.ifft(spec, axis=-1).real * d
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# Correlation (FlowNet cost volume; reference: src/operator/correlation.cc)
# ---------------------------------------------------------------------------
@register_op("Correlation")
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True, **kw):
    """Patch correlation between two NCHW feature maps
    (reference: correlation.cc:40-82 CorrelationForward). The reference's
    6-deep displacement loop becomes one fused jnp expression per
    displacement (G = (2*max_displacement/stride2+1)^2 static slices);
    gradients come from autodiff instead of the hand-written backward.
    """
    kernel_size = int(kernel_size)
    max_displacement = int(max_displacement)
    stride1, stride2, pad_size = int(stride1), int(stride2), int(pad_size)
    is_multiply = bool(is_multiply)
    n, c, h, w = data1.shape
    kernel_radius = (kernel_size - 1) // 2
    border = max_displacement + kernel_radius
    padded_h, padded_w = h + 2 * pad_size, w + 2 * pad_size
    top_h = int(np.ceil((padded_h - border * 2) / float(stride1)))
    top_w = int(np.ceil((padded_w - border * 2) / float(stride1)))
    grid_radius = max_displacement // stride2
    grid_width = 2 * grid_radius + 1
    sumelems = kernel_size * kernel_size * c

    p1 = jnp.pad(data1, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))
    p2 = jnp.pad(data2, ((0, 0), (0, 0), (pad_size, pad_size),
                         (pad_size, pad_size)))

    # top-left corners of the kernel window in the padded maps:
    # x1 = j*stride1 + max_displacement - kernel_radius ... but the
    # reference indexes tmp[y1+h][x1+w] with y1 = i*stride1 + max_disp
    # over a (kernel) window, i.e. window origin y1 (kernel_radius folded
    # into border for the output size only)
    ys = jnp.arange(top_h) * stride1 + max_displacement
    xs = jnp.arange(top_w) * stride1 + max_displacement

    outs = []
    for tc in range(grid_width * grid_width):
        s2o = (tc % grid_width - grid_radius) * stride2
        s2p = (tc // grid_width - grid_radius) * stride2
        acc = 0.0
        for kh in range(kernel_size):
            for kw_ in range(kernel_size):
                a = p1[:, :, ys[:, None] + kh, xs[None, :] + kw_]
                b = p2[:, :, ys[:, None] + s2p + kh,
                       xs[None, :] + s2o + kw_]
                acc = acc + (a * b if is_multiply else jnp.abs(a - b))
        outs.append(acc.sum(axis=1) / sumelems)      # (n, top_h, top_w)
    return jnp.stack(outs, axis=1)                   # (n, G^2, top_h, top_w)


# ---------------------------------------------------------------------------
# Crop (legacy; reference: src/operator/crop.cc MXNET_REGISTER_OP_PROPERTY)
# ---------------------------------------------------------------------------
@register_op("Crop", num_outputs=1)
def crop_op(*inputs, offset=(0, 0), h_w=(0, 0), center_crop=False,
            num_args=None, **kw):
    """Crop an NCHW tensor to h_w or to the size of a second input
    (reference: crop-inl.h)."""
    data = inputs[0]
    if len(inputs) > 1:
        out_h, out_w = inputs[1].shape[2], inputs[1].shape[3]
    else:
        out_h, out_w = (int(x) for x in h_w)
    if center_crop:
        o_h = (data.shape[2] - out_h) // 2
        o_w = (data.shape[3] - out_w) // 2
    else:
        o_h, o_w = (int(x) for x in offset)
    return data[:, :, o_h:o_h + out_h, o_w:o_w + out_w]


# ---------------------------------------------------------------------------
# RPN Proposal (reference: src/operator/contrib/proposal.cc,
# multi_proposal.cc)
# ---------------------------------------------------------------------------
def _generate_base_anchors(feature_stride, scales, ratios):
    """(reference: proposal-inl.h:184-223 GenerateAnchors — including the
    floor/round quirks, which the test-suite numerics depend on)."""
    base = [0.0, 0.0, feature_stride - 1.0, feature_stride - 1.0]
    w = base[2] - base[0] + 1.0
    h = base[3] - base[1] + 1.0
    x_ctr = base[0] + 0.5 * (w - 1.0)
    y_ctr = base[1] + 0.5 * (h - 1.0)
    size = w * h
    anchors = []
    for ratio in ratios:
        size_ratio = np.floor(size / ratio)
        new_w = np.floor(np.sqrt(size_ratio) + 0.5)
        new_h = np.floor(new_w * ratio + 0.5)
        for scale in scales:
            sw, sh = new_w * scale, new_h * scale
            anchors.append([x_ctr - 0.5 * (sw - 1), y_ctr - 0.5 * (sh - 1),
                            x_ctr + 0.5 * (sw - 1), y_ctr + 0.5 * (sh - 1)])
    return np.asarray(anchors, np.float32)


def _proposal_one(scores_fg, bbox_deltas, im_info, base_anchors,
                  feature_stride, rpn_pre_nms_top_n, rpn_post_nms_top_n,
                  threshold, rpn_min_size):
    """Single-image RPN proposal generation (reference: proposal.cc:300+
    Forward): shift anchors, decode deltas, clip, filter small, pre-NMS
    top-k, greedy NMS, post-NMS top-k."""
    A = base_anchors.shape[0]
    H, W = scores_fg.shape[1], scores_fg.shape[2]
    shift_x = jnp.arange(W, dtype=jnp.float32) * feature_stride
    shift_y = jnp.arange(H, dtype=jnp.float32) * feature_stride
    # anchor layout index = h*(W*A) + w*A + a
    sx = jnp.broadcast_to(shift_x[None, :, None], (H, W, A))
    sy = jnp.broadcast_to(shift_y[:, None, None], (H, W, A))
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1)
    anchors = (base_anchors[None, None, :, :] + shifts).reshape(-1, 4)
    # deltas (4A, H, W) -> (H, W, A, 4) -> (N, 4); scores (A,H,W)->(N,)
    d = bbox_deltas.reshape(A, 4, H, W).transpose(2, 3, 0, 1).reshape(-1, 4)
    s = scores_fg.transpose(1, 2, 0).reshape(-1)

    widths = anchors[:, 2] - anchors[:, 0] + 1.0
    heights = anchors[:, 3] - anchors[:, 1] + 1.0
    ctr_x = anchors[:, 0] + 0.5 * (widths - 1.0)
    ctr_y = anchors[:, 1] + 0.5 * (heights - 1.0)
    pred_ctr_x = d[:, 0] * widths + ctr_x
    pred_ctr_y = d[:, 1] * heights + ctr_y
    pred_w = jnp.exp(d[:, 2]) * widths
    pred_h = jnp.exp(d[:, 3]) * heights
    im_h, im_w = im_info[0], im_info[1]
    x1 = jnp.clip(pred_ctr_x - 0.5 * (pred_w - 1), 0, im_w - 1)
    y1 = jnp.clip(pred_ctr_y - 0.5 * (pred_h - 1), 0, im_h - 1)
    x2 = jnp.clip(pred_ctr_x + 0.5 * (pred_w - 1), 0, im_w - 1)
    y2 = jnp.clip(pred_ctr_y + 0.5 * (pred_h - 1), 0, im_h - 1)
    boxes = jnp.stack([x1, y1, x2, y2], axis=1)
    # filter too-small boxes (reference FilterBox: score -> -1)
    iw = x2 - x1 + 1.0
    ih = y2 - y1 + 1.0
    min_size = rpn_min_size * im_info[2]  # scaled by im_scale
    s = jnp.where((iw < min_size) | (ih < min_size), -1.0, s)

    order = jnp.argsort(-s)
    if rpn_pre_nms_top_n > 0:
        order = order[:rpn_pre_nms_top_n]
    boxes_s, s_s = boxes[order], s[order]
    valid = s_s > -1.0
    keep = _greedy_nms_keep(boxes_s, jnp.zeros(boxes_s.shape[0]), valid,
                            threshold, True)
    # compact kept boxes to the front, pad with the first kept one
    rank = jnp.argsort(~keep, stable=True)       # kept first, stable order
    boxes_k = boxes_s[rank]
    score_k = s_s[rank]
    n_keep = keep.sum()
    idx = jnp.minimum(jnp.arange(rpn_post_nms_top_n), n_keep - 1)
    rois = boxes_k[idx]
    roi_scores = score_k[idx]
    return rois, roi_scores


@register_op("Proposal", aliases=["_contrib_Proposal"], no_grad=True,
             num_outputs=1)
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False, **kw):
    """RPN region proposals (reference: src/operator/contrib/proposal.cc).

    cls_prob: (B, 2A, H, W) softmax fg/bg; bbox_pred: (B, 4A, H, W);
    im_info: (B, 3) [height, width, scale]. Output rois
    (B*rpn_post_nms_top_n, 5) rows [batch_idx, x1, y1, x2, y2].
    """
    if iou_loss:
        raise NotImplementedError("Proposal: iou_loss=True")
    scales = _tuplef(scales, (4, 8, 16, 32))
    ratios = _tuplef(ratios, (0.5, 1, 2))
    base = jnp.asarray(_generate_base_anchors(float(feature_stride),
                                              scales, ratios))
    B = cls_prob.shape[0]
    A = base.shape[0]
    rois_all, scores_all = [], []
    for b in range(B):
        fg = cls_prob[b, A:, :, :]  # foreground scores (A, H, W)
        rois, rs = _proposal_one(
            fg, bbox_pred[b], im_info[b], base, float(feature_stride),
            int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n),
            float(threshold), float(rpn_min_size))
        batch_col = jnp.full((rois.shape[0], 1), float(b))
        rois_all.append(jnp.concatenate([batch_col, rois], axis=1))
        scores_all.append(rs[:, None])
    out = jnp.concatenate(rois_all, axis=0)
    if output_score:
        return out, jnp.concatenate(scores_all, axis=0)
    return out


@register_op("MultiProposal", aliases=["_contrib_MultiProposal"],
             no_grad=True)
def multi_proposal(cls_prob, bbox_pred, im_info, **kw):
    """Batch variant (reference: src/operator/contrib/multi_proposal.cc —
    same math as Proposal over every image)."""
    kw.pop("output_score", None)
    return proposal(cls_prob, bbox_pred, im_info, output_score=False, **kw)


# ---------------------------------------------------------------------------
# count_sketch (reference: src/operator/contrib/count_sketch-inl.h:47 —
# compact bilinear pooling building block)
# ---------------------------------------------------------------------------
@register_op("count_sketch", aliases=["_contrib_count_sketch"])
def count_sketch(data, h, s, out_dim=None, processing_batch_size=32, **kw):
    """Count sketch projection: out[n, h[i]] += s[i] * data[n, i].

    data: (n, in_dim); h: (1, in_dim) int hash bucket per input dim;
    s: (1, in_dim) signs in {-1, +1}. Output (n, out_dim). The scatter-add
    maps to one segment_sum; gradients come from autodiff (the reference
    hand-writes the mirrored gather kernel)."""
    if out_dim is None:
        raise ValueError("count_sketch requires out_dim")
    out_dim = int(out_dim)
    n, in_dim = data.shape
    hh = h.reshape(-1).astype(jnp.int32)
    ss = s.reshape(-1).astype(data.dtype)
    signed = data * ss[None, :]
    out = jax.ops.segment_sum(signed.T, hh, num_segments=out_dim)  # (out, n)
    return out.T


# ---------------------------------------------------------------------------
# Deformable convolution (DCN v1; reference:
# src/operator/contrib/deformable_convolution-inl.h,
# nn/deformable_im2col.cuh:216-260 — offset layout [dg][2*(i*Kw+j)] with the
# h-offset first, sample = (h_in + i*dil + off_h, w_in + j*dil + off_w),
# zero outside the image)
# ---------------------------------------------------------------------------
def _bilinear_sample_chw(img, ys, xs):
    """Bilinear sample a (C, H, W) image at float positions ys/xs (...,).
    Out-of-image points and out-of-range corners contribute zero, matching
    the reference kernel's bounds checks."""
    C, H, W = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    dy = ys - y0
    dx = xs - x0
    out = 0.0
    for cy, wy in ((y0, 1 - dy), (y0 + 1, dy)):
        for cx, wx in ((x0, 1 - dx), (x0 + 1, dx)):
            valid = (cy >= 0) & (cy < H) & (cx >= 0) & (cx < W)
            yi = jnp.clip(cy, 0, H - 1).astype(jnp.int32)
            xi = jnp.clip(cx, 0, W - 1).astype(jnp.int32)
            v = img[:, yi, xi]                        # (C, ...)
            out = out + jnp.where(valid, wy * wx, 0.0) * v
    # no whole-point mask: the reference guard is h_im > -1 (partial
    # bilinear contributions at the border), which the per-corner checks
    # above reproduce exactly
    return out                                        # (C, ...)


def _deform_conv_one(data, offset, weight, kernel, stride, dilate, pad,
                     num_group, num_deformable_group):
    """Single-sample deformable conv: data (C,H,W), offset (2*dg*Kh*Kw,
    oh,ow), weight (F, C/g, Kh, Kw)."""
    C, H, W = data.shape
    kh, kw = kernel
    sh, sw = stride
    dh, dw = dilate
    ph, pw = pad
    oh = (H + 2 * ph - (dh * (kh - 1) + 1)) // sh + 1
    ow = (W + 2 * pw - (dw * (kw - 1) + 1)) // sw + 1
    dg = num_deformable_group
    off = offset.reshape(dg, kh * kw, 2, oh, ow)
    h_in = jnp.arange(oh) * sh - ph
    w_in = jnp.arange(ow) * sw - pw

    cpg = C // dg                                    # channels per dg
    cols = []
    for tap in range(kh * kw):
        i, j = tap // kw, tap % kw
        tap_cols = []
        for g in range(dg):
            ys = h_in[:, None] + i * dh + off[g, tap, 0]
            xs = w_in[None, :] + j * dw + off[g, tap, 1]
            sampled = _bilinear_sample_chw(
                data[g * cpg:(g + 1) * cpg], ys, xs)   # (cpg, oh, ow)
            tap_cols.append(sampled)
        cols.append(jnp.concatenate(tap_cols, axis=0))  # (C, oh, ow)
    col = jnp.stack(cols, axis=1)                       # (C, Kh*Kw, oh, ow)

    F = weight.shape[0]
    cg = C // num_group
    fg = F // num_group
    outs = []
    for g in range(num_group):
        w_g = weight[g * fg:(g + 1) * fg].reshape(fg, cg * kh * kw)
        c_g = col[g * cg:(g + 1) * cg].reshape(cg * kh * kw, oh * ow)
        outs.append((w_g @ c_g).reshape(fg, oh, ow))
    return jnp.concatenate(outs, axis=0)                # (F, oh, ow)


@register_op("DeformableConvolution",
             aliases=["_contrib_DeformableConvolution"])
def deformable_convolution(data, offset, weight, bias=None, kernel=None,
                           stride=(1, 1), dilate=(1, 1), pad=(0, 0),
                           num_filter=0, num_group=1,
                           num_deformable_group=1, no_bias=False, **kw):
    """Deformable convolution: sampling locations shifted by learned
    offsets. Gradients (data, offset, weight) all come from autodiff of
    the bilinear sampling — the reference hand-writes three kernels
    (deformable_col2im, _col2im_coord, im2col)."""
    kernel = tuple(int(k) for k in _tuplef(kernel, (3, 3)))
    stride = tuple(int(s) for s in _tuplef(stride, (1, 1)))
    dilate = tuple(int(d) for d in _tuplef(dilate, (1, 1)))
    pad = tuple(int(p) for p in _tuplef(pad, (0, 0)))
    fn = lambda d, o: _deform_conv_one(d, o, weight, kernel, stride,
                                       dilate, pad, int(num_group),
                                       int(num_deformable_group))
    out = jax.vmap(fn)(data, offset)
    if bias is not None and not no_bias:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ---------------------------------------------------------------------------
# Position-sensitive ROI pooling (R-FCN family; reference:
# src/operator/contrib/psroi_pooling.cu:51-120,
# deformable_psroi_pooling.cu:71-161)
# ---------------------------------------------------------------------------
def _psroi_one(data, roi, spatial_scale, output_dim, group_size, pooled):
    """One ROI over one batch of feature maps: data (B, C, H, W),
    roi [batch_ind, x1, y1, x2, y2]. Integer-grid average pooling of the
    position-sensitive channel (psroi_pooling.cu:51)."""
    B, C, H, W = data.shape
    G = group_size
    img = data[roi[0].astype(jnp.int32)]
    ps = img.reshape(output_dim, G, G, H, W)
    # floor(x + 0.5) = C round() for the non-negative ROI coords
    # (jnp.round is half-to-even and would shift half-integer ROIs)
    start_w = jnp.floor(roi[1] + 0.5) * spatial_scale
    start_h = jnp.floor(roi[2] + 0.5) * spatial_scale
    end_w = (jnp.floor(roi[3] + 0.5) + 1.0) * spatial_scale
    end_h = (jnp.floor(roi[4] + 0.5) + 1.0) * spatial_scale
    roi_w = jnp.maximum(end_w - start_w, 0.1)
    roi_h = jnp.maximum(end_h - start_h, 0.1)
    bin_h = roi_h / pooled
    bin_w = roi_w / pooled
    hs = jnp.arange(H, dtype=jnp.float32)
    ws = jnp.arange(W, dtype=jnp.float32)
    out = []
    for ph in range(pooled):
        row = []
        for pw in range(pooled):
            hstart = jnp.clip(jnp.floor(ph * bin_h + start_h), 0, H)
            hend = jnp.clip(jnp.ceil((ph + 1) * bin_h + start_h), 0, H)
            wstart = jnp.clip(jnp.floor(pw * bin_w + start_w), 0, W)
            wend = jnp.clip(jnp.ceil((pw + 1) * bin_w + start_w), 0, W)
            mask = ((hs[:, None] >= hstart) & (hs[:, None] < hend)
                    & (ws[None, :] >= wstart) & (ws[None, :] < wend))
            gh = min(max(int(ph * G // pooled), 0), G - 1)
            gw = min(max(int(pw * G // pooled), 0), G - 1)
            sel = ps[:, gh, gw]                       # (output_dim, H, W)
            total = jnp.sum(sel * mask, axis=(1, 2))
            area = jnp.maximum(mask.sum(), 1)
            empty = (hend <= hstart) | (wend <= wstart)
            row.append(jnp.where(empty, 0.0, total / area))
        out.append(jnp.stack(row, axis=-1))
    return jnp.stack(out, axis=-2)                    # (output_dim, p, p)


@register_op("PSROIPooling", aliases=["_contrib_PSROIPooling"])
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=None,
                  pooled_size=None, group_size=0, **kw):
    """Position-sensitive ROI pooling (reference: psroi_pooling.cu:51).
    data: (B, output_dim*G*G, H, W); rois: (R, 5). Output
    (R, output_dim, pooled, pooled)."""
    group_size = int(group_size) or int(pooled_size)
    fn = lambda r: _psroi_one(data, r, float(spatial_scale),
                              int(output_dim), group_size,
                              int(pooled_size))
    return jax.vmap(fn)(rois)


def _dpsroi_one(data, roi, trans, spatial_scale, output_dim, group_size,
                pooled, part_size, sample_per_part, trans_std, num_classes):
    """Deformable PSROI pooling for one ROI, fully vectorized over
    (output_dim, pooled, pooled, samples) — the reference unrolls this as
    a CUDA grid (deformable_psroi_pooling.cu:71-161)."""
    B, C, H, W = data.shape
    G = group_size
    P = pooled
    S = sample_per_part
    img = data[roi[0].astype(jnp.int32)]
    ps = img.reshape(output_dim, G, G, H, W)
    start_w = jnp.floor(roi[1] + 0.5) * spatial_scale - 0.5
    start_h = jnp.floor(roi[2] + 0.5) * spatial_scale - 0.5
    end_w = (jnp.floor(roi[3] + 0.5) + 1.0) * spatial_scale - 0.5
    end_h = (jnp.floor(roi[4] + 0.5) + 1.0) * spatial_scale - 0.5
    roi_w = jnp.maximum(end_w - start_w, 0.1)
    roi_h = jnp.maximum(end_h - start_h, 0.1)
    bin_h = roi_h / P
    bin_w = roi_w / P
    sub_h = bin_h / S
    sub_w = bin_w / S

    ph = jnp.arange(P)
    pw = jnp.arange(P)
    # per-bin trans offsets; class of channel ctop = ctop // cls_per
    cls_per = output_dim // num_classes
    if trans is None:
        tx = jnp.zeros((output_dim, P, P))
        ty = jnp.zeros((output_dim, P, P))
    else:
        part_h = (ph * part_size // P)                        # (P,)
        part_w = (pw * part_size // P)                        # (P,)
        cls = jnp.arange(output_dim) // cls_per               # (D,)
        tx = trans[cls[:, None, None] * 2,
                   part_h[None, :, None], part_w[None, None, :]] * trans_std
        ty = trans[cls[:, None, None] * 2 + 1,
                   part_h[None, :, None], part_w[None, None, :]] * trans_std

    # sample positions: (D, P, P, S, S)
    ih = jnp.arange(S)
    iw = jnp.arange(S)
    hpos = (ph[None, :, None, None, None] * bin_h + start_h
            + ty[:, :, :, None, None] * roi_h
            + ih[None, None, None, :, None] * sub_h)
    wpos = (pw[None, None, :, None, None] * bin_w + start_w
            + tx[:, :, :, None, None] * roi_w
            + iw[None, None, None, None, :] * sub_w)
    hpos = jnp.broadcast_to(hpos, (output_dim, P, P, S, S))
    wpos = jnp.broadcast_to(wpos, (output_dim, P, P, S, S))

    ok = ((wpos >= -0.5) & (wpos <= W - 0.5)
          & (hpos >= -0.5) & (hpos <= H - 0.5))
    hc = jnp.clip(hpos, 0.0, H - 1.0)
    wc = jnp.clip(wpos, 0.0, W - 1.0)
    h0 = jnp.floor(hc)
    w0 = jnp.floor(wc)
    dh = hc - h0
    dw = wc - w0
    h0i = h0.astype(jnp.int32)
    w0i = w0.astype(jnp.int32)
    h1i = jnp.minimum(h0i + 1, H - 1)
    w1i = jnp.minimum(w0i + 1, W - 1)

    # position-sensitive channel per bin: sel (D, P, P, H, W)
    gh = jnp.clip(ph * G // P, 0, G - 1)
    gw = jnp.clip(pw * G // P, 0, G - 1)
    sel = ps[:, gh[:, None], gw[None, :]]                     # (D,P,P,H,W)

    d_ix = jnp.arange(output_dim)[:, None, None, None, None]
    p_ix = jnp.arange(P)[None, :, None, None, None]
    q_ix = jnp.arange(P)[None, None, :, None, None]
    v = (sel[d_ix, p_ix, q_ix, h0i, w0i] * (1 - dh) * (1 - dw)
         + sel[d_ix, p_ix, q_ix, h0i, w1i] * (1 - dh) * dw
         + sel[d_ix, p_ix, q_ix, h1i, w0i] * dh * (1 - dw)
         + sel[d_ix, p_ix, q_ix, h1i, w1i] * dh * dw)
    acc = jnp.sum(jnp.where(ok, v, 0.0), axis=(3, 4))
    cnt = jnp.sum(ok, axis=(3, 4))
    return jnp.where(cnt > 0, acc / jnp.maximum(cnt, 1), 0.0)


@register_op("DeformablePSROIPooling",
             aliases=["_contrib_DeformablePSROIPooling"])
def deformable_psroi_pooling(data, rois, trans=None, spatial_scale=1.0,
                             output_dim=None, group_size=None,
                             pooled_size=None, part_size=0,
                             sample_per_part=4, trans_std=0.0,
                             no_trans=False, **kw):
    """Deformable position-sensitive ROI pooling (R-FCN / DCN v1;
    reference: deformable_psroi_pooling.cu:71). trans: (R,
    num_classes*2, part, part) normalized bin offsets."""
    part_size = int(part_size) or int(pooled_size)
    if no_trans:
        trans = None
    num_classes = 1
    if trans is not None:
        num_classes = trans.shape[1] // 2
    fn = lambda r, t: _dpsroi_one(
        data, r, t, float(spatial_scale), int(output_dim),
        int(group_size), int(pooled_size), part_size,
        int(sample_per_part), float(trans_std), num_classes)
    if trans is None:
        return jax.vmap(lambda r: fn(r, None))(rois)
    return jax.vmap(fn)(rois, trans)
