"""Contrib operators: CTC loss, SSD MultiBox family, box_nms.

Reference analogs: src/operator/contrib/ctc_loss.cc, multibox_prior.cc,
multibox_target.cc, multibox_detection.cc, bounding_box.cc. All are
re-derived as vectorized jax/lax code (fixed shapes, scan/while-free where
possible) so XLA can fuse and tile them for TPU; none of the reference's
kernel code is used.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op

_NEG = -1e30  # large-negative stand-in for -inf: keeps grads finite


# ---------------------------------------------------------------------------
# CTC loss (reference: src/operator/contrib/ctc_loss.cc — warp-ctc kernels;
# here: log-space alpha recursion under lax.scan, grads via autodiff)
# ---------------------------------------------------------------------------
def _ctc_one(logp, label, t_len, l_len, blank):
    """Negative log likelihood for one sequence.

    logp: (T, C) log-probabilities. label: (L,) int32 token ids.
    t_len/l_len: actual lengths. blank: blank id.
    """
    T, C = logp.shape
    L = label.shape[0]
    S = 2 * L + 1
    # extended label sequence: blank, l1, blank, l2, ..., blank
    z = jnp.full((S,), blank, jnp.int32).at[1::2].set(label.astype(jnp.int32))
    pos = jnp.arange(S)
    valid = pos < 2 * l_len + 1
    # skip-transition allowed when z[s] != blank and z[s] != z[s-2]
    can_skip = jnp.concatenate(
        [jnp.zeros((2,), bool), (z[2:] != blank) & (z[2:] != z[:-2])])

    alpha0 = jnp.full((S,), _NEG).at[0].set(logp[0, z[0]])
    alpha0 = jnp.where((pos == 1) & (l_len > 0),
                       logp[0, z[jnp.minimum(1, S - 1)]], alpha0)
    alpha0 = jnp.where(valid, alpha0, _NEG)

    def step(alpha, tlp):
        t, lp = tlp
        a1 = alpha
        a2 = jnp.concatenate([jnp.full((1,), _NEG), alpha[:-1]])
        a3 = jnp.concatenate([jnp.full((2,), _NEG), alpha[:-2]])
        a3 = jnp.where(can_skip, a3, _NEG)
        m = jnp.maximum(jnp.maximum(a1, a2), a3)
        tot = m + jnp.log(jnp.exp(a1 - m) + jnp.exp(a2 - m)
                          + jnp.exp(a3 - m))
        new = jnp.where(valid, tot + lp[z], _NEG)
        # frozen once t >= t_len so the final alpha is the one at t_len-1
        new = jnp.where(t < t_len, new, alpha)
        return new, None

    ts = jnp.arange(1, T)
    alpha, _ = jax.lax.scan(step, alpha0, (ts, logp[1:]))
    s_last = 2 * l_len  # index of final blank
    a_end = alpha[jnp.minimum(s_last, S - 1)]
    a_pre = jnp.where(l_len > 0,
                      alpha[jnp.maximum(jnp.minimum(s_last - 1, S - 1), 0)],
                      _NEG)
    m = jnp.maximum(a_end, a_pre)
    ll = m + jnp.log(jnp.exp(a_end - m) + jnp.exp(a_pre - m))
    return -ll


@register_op("CTCLoss", aliases=["ctc_loss", "_contrib_CTCLoss",
                                 "_contrib_ctc_loss"])
def ctc_loss(data, label, data_lengths=None, label_lengths=None,
             use_data_lengths=False, use_label_lengths=False,
             blank_label="first", **kw):
    """CTC negative log likelihood per sample.

    data: (T, N, C) unnormalized activations (softmax applied internally,
    matching the reference op). label: (N, L) padded token ids. Returns (N,)
    losses. blank is class 0 ('first', padding value 0) or C-1 ('last',
    padding value -1).
    """
    T, N, C = data.shape
    logp = jax.nn.log_softmax(data.astype(jnp.float32), axis=-1)
    blank = 0 if blank_label == "first" else C - 1
    label = label.astype(jnp.int32)
    pad_val = 0 if blank_label == "first" else -1
    if use_data_lengths and data_lengths is not None:
        t_lens = data_lengths.astype(jnp.int32)
    else:
        t_lens = jnp.full((N,), T, jnp.int32)
    if use_label_lengths and label_lengths is not None:
        l_lens = label_lengths.astype(jnp.int32)
    else:
        l_lens = (label != pad_val).sum(axis=1).astype(jnp.int32)
    logp_n = jnp.transpose(logp, (1, 0, 2))  # (N, T, C)
    return jax.vmap(_ctc_one, in_axes=(0, 0, 0, 0, None))(
        logp_n, label, t_lens, l_lens, blank)
