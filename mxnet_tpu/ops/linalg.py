"""Linear-algebra operators.

Reference surface: src/operator/tensor/la_op.cc (linalg_gemm/gemm2/potrf/potri/
trmm/trsm/sumlogdiag/syrk/gelqf/syevd) — cuBLAS/LAPACK there, XLA here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .registry import register_op


def _t(x, transpose):
    return jnp.swapaxes(x, -1, -2) if transpose else x


@register_op("linalg_gemm", aliases=["_linalg_gemm"])
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2, **kw):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b)) + beta * C


@register_op("linalg_gemm2", aliases=["_linalg_gemm2"])
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0, axis=-2, **kw):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))


@register_op("linalg_potrf", aliases=["_linalg_potrf"])
def linalg_potrf(A, **kw):
    return jnp.linalg.cholesky(A)


@register_op("linalg_potri", aliases=["_linalg_potri"])
def linalg_potri(A, **kw):
    """Inverse from Cholesky factor L: (L L^T)^-1."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jsl.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register_op("linalg_trsm", aliases=["_linalg_trsm"])
def linalg_trsm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **kw):
    if rightside:
        # X A = alpha B  ⇔  A^T X^T = alpha B^T; passing A^T flips triangularity
        xt = jsl.solve_triangular(_t(A, not transpose), _t(alpha * B, True),
                                  lower=lower if transpose else not lower)
        return _t(xt, True)
    return jsl.solve_triangular(_t(A, transpose), alpha * B,
                                lower=(not lower) if transpose else lower)


@register_op("linalg_trmm", aliases=["_linalg_trmm"])
def linalg_trmm(A, B, transpose=False, rightside=False, lower=True, alpha=1.0, **kw):
    tri = jnp.tril(A) if lower else jnp.triu(A)
    tri = _t(tri, transpose)
    return alpha * (jnp.matmul(B, tri) if rightside else jnp.matmul(tri, B))


@register_op("linalg_sumlogdiag", aliases=["_linalg_sumlogdiag"])
def linalg_sumlogdiag(A, **kw):
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register_op("linalg_syrk", aliases=["_linalg_syrk"])
def linalg_syrk(A, transpose=False, alpha=1.0, **kw):
    a = _t(A, transpose)
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))


@register_op("linalg_gelqf", aliases=["_linalg_gelqf"], num_outputs=2)
def linalg_gelqf(A, **kw):
    """LQ factorization: A = L Q with Q orthonormal rows (reference la_op.cc)."""
    q, r = jnp.linalg.qr(jnp.swapaxes(A, -1, -2))
    return jnp.swapaxes(r, -1, -2), jnp.swapaxes(q, -1, -2)


@register_op("linalg_syevd", aliases=["_linalg_syevd"], num_outputs=2)
def linalg_syevd(A, **kw):
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


@register_op("khatri_rao")
def khatri_rao(*args, **kw):
    """Column-wise Khatri-Rao product (reference: src/operator/contrib/krprod.cc)."""
    out = args[0]
    for b in args[1:]:
        out = jnp.einsum("ik,jk->ijk", out, b).reshape(-1, out.shape[1])
    return out
