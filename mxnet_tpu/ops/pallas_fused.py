"""Pallas fused BN-apply(+ReLU)+matmul kernel and its graph-level op.

docs/perf_analysis.md §3 shows single-chip ResNet-50 training is
HBM-bandwidth bound: every BN'd activation is touched ~8x per step and
XLA cannot fuse the normalize/activation pass across the BN statistics
barrier into the MXU convolution that consumes it. The cuDNN-style fix —
the one the reference gets from NVIDIA's libraries — is a kernel whose
PROLOGUE applies BN+ReLU while tiles stream into the matmul,
eliminating the materialized normalized tensor (one write + one read of
the full activation) per 1x1 convolution.

``bn_relu_matmul`` is that kernel for the generic (M, K) @ (K, N) case
(promoted here from tools/pallas_fused_bn_bench.py once the graph-level
integration landed; the tool now imports it from here). The graph op
uses the NCHW-native orientation (``_make_nchw_kernel``): per sample
the (C, H·W) slab of an NCHW activation is contiguous, so contracting
``w (O, C) @ xhat (C, H·W)`` streams the activation directly — no
relayout on either side.

``_FusedBNReLUConv`` is the internal graph op the fusion rewrite pass
(symbol/fusion.py) substitutes for matched ``BatchNorm -> Activation
(relu) -> Convolution(1x1)`` subgraphs. It preserves exact BatchNorm
semantics — per-batch statistics in training, moving stats otherwise —
and mirrors BatchNorm's (out, mean, var) output layout and (…,
moving_mean, moving_var) input positions so the executors' running-stat
fold applies unchanged.

Differentiation: ONE custom VJP covers the whole op, statistics
included — the analytic fused BatchNorm backward (the same coverage as
cuDNN's BatchNormBackward), which assembles d(data) in a single
full-tensor pass instead of naive autodiff's separate mean/var chains.
On TPU the backward recomputes the normalized activation from the raw
residuals (one elementwise pass — precisely the memory-traffic win);
off-TPU the interpreter has to materialize it anyway, so it doubles as
the residual. Off-TPU the whole path runs in interpret mode / stock XLA
ops, so tier-1 CPU tests exercise the same op, rewrite, and VJP.
"""
from __future__ import annotations

import contextlib
import functools
import os
import threading

import jax
import jax.numpy as jnp

from .registry import register_op

try:  # jax>=0.4.35 moved shard_map out of experimental
    from jax import shard_map as _shard_map  # type: ignore
except ImportError:  # pragma: no cover - version shim
    from jax.experimental.shard_map import shard_map as _shard_map

__all__ = ["bn_relu_matmul", "bn_relu_conv_nchw", "select_tiles",
           "select_conv_tiles", "conv_tile_failure",
           "fused_bn_relu_conv", "mesh_scope", "active_mesh"]

# ---------------------------------------------------------------------------
# trace-time mesh scope (ROADMAP item 1: shard_map-compatible kernels)
# ---------------------------------------------------------------------------
# GSPMD cannot partition an opaque Pallas custom call, so under a mesh
# bind the kernel invocations below wrap themselves in shard_map over
# the batch axis — each device runs the kernel on its batch shard, and
# the surrounding statistics/folding/backward stay plain jnp for GSPMD
# to partition (global BN batch stats, psum'd parameter gradients).
# The mesh reaches the op at TRACE time through this scope: the fused
# step / pass-manager measurement enters mesh_scope(mesh, axis) around
# lowering, and the op reads it when the pallas_call is built. AD never
# differentiates through the shard_map (it sits inside the ops' custom
# VJPs, whose backward is plain jnp): jax cannot transpose a
# check_rep=False shard_map, and check_rep=False is mandatory because
# pallas_call has no replication rule.
_MESH_SCOPE = threading.local()


@contextlib.contextmanager
def mesh_scope(mesh, axis="data"):
    """Declare the mesh/batch-axis for fused kernels traced inside the
    scope (thread-local; trace-time only — the compiled program carries
    the shard_map, not the scope)."""
    prev = getattr(_MESH_SCOPE, "value", None)
    _MESH_SCOPE.value = None if mesh is None else (mesh, axis)
    try:
        yield
    finally:
        _MESH_SCOPE.value = prev


def active_mesh():
    """The (mesh, batch_axis) declared by the innermost
    :func:`mesh_scope`, or None (single-device trace)."""
    return getattr(_MESH_SCOPE, "value", None)


def _batch_shards(batch):
    """(mesh, axis, per-device batch) when a mesh scope is active and
    the batch divides its axis; else None (the kernel stays unwrapped —
    off-mesh traces, and mesh traces whose batch cannot split, which
    the rewrite passes' bytes gate then judges as-is)."""
    scope = active_mesh()
    if scope is None:
        return None
    mesh, axis = scope
    if axis not in getattr(mesh, "shape", {}):
        return None
    ndev = int(mesh.shape[axis])
    if ndev <= 1 or batch % ndev:
        return None
    return mesh, axis, batch // ndev

# output-tile candidates, largest first; TPU-friendly multiples of 8.
# small trailing candidates keep interpret-mode (CPU test) shapes fusable.
_BM_CANDIDATES = (1024, 512, 256, 128, 64, 32, 16, 8)
_BN_CANDIDATES = (512, 256, 128, 64, 32, 16, 8)

# MXTPU_PALLAS_TILES parse cache: (raw env string, parsed (bm, bn))
_TILE_OVERRIDE_CACHE = ("", None)


def _tile_override():
    """The ``MXTPU_PALLAS_TILES`` override — ``"<bm>,<bn>"``, the
    candidate pair tried FIRST by :func:`select_tiles` (and, mapped to
    (bs, bo), by :func:`select_conv_tiles`) before the built-in
    largest-first scan. This is the tuner's per-trial tile knob.

    Validation is loud and strict: two positive integers, each a
    multiple of 8 (MXU sublane alignment — see the TPU tile-shape
    table), bounded by the built-in candidate maxima (bm ≤ 1024,
    bn ≤ 512). Anything else raises MXNetError at selection time, so a
    bad tile fails the BIND/TRIAL that consulted it, never the process
    and never silently. A valid tile that merely doesn't divide the
    shape at hand is not an error — selection falls back to the
    built-in candidates (the knob steers, the shape decides)."""
    global _TILE_OVERRIDE_CACHE
    raw = os.environ.get("MXTPU_PALLAS_TILES", "").strip()
    if not raw:
        return None
    if _TILE_OVERRIDE_CACHE[0] == raw:
        return _TILE_OVERRIDE_CACHE[1]
    from ..base import MXNetError

    def bad(why):
        return MXNetError(
            f"MXTPU_PALLAS_TILES={raw!r} is invalid ({why}): expected "
            f"'<bm>,<bn>' with positive multiples of 8, bm <= "
            f"{_BM_CANDIDATES[0]}, bn <= {_BN_CANDIDATES[0]}")

    parts = [p.strip() for p in raw.split(",")]
    if len(parts) != 2:
        raise bad("need exactly two comma-separated values")
    try:
        bm, bn = int(parts[0]), int(parts[1])
    except ValueError:
        raise bad("non-integer value")
    if bm <= 0 or bn <= 0:
        raise bad("non-positive tile")
    if bm % 8 or bn % 8:
        raise bad("not a multiple of 8")
    if bm > _BM_CANDIDATES[0] or bn > _BN_CANDIDATES[0]:
        raise bad("out of bounds")
    _TILE_OVERRIDE_CACHE = (raw, (bm, bn))
    return (bm, bn)


def select_tiles(m, n):
    """(bm, bn) output-tile split for an (M, K) @ (K, N) fused matmul,
    or None when no candidate divides (a truncated grid would leave
    output tiles uninitialized). An ``MXTPU_PALLAS_TILES`` override is
    preferred per dimension when it divides."""
    ov = _tile_override()
    bm = ov[0] if ov is not None and m % ov[0] == 0 else \
        next((c for c in _BM_CANDIDATES if m % c == 0), None)
    bn = ov[1] if ov is not None and n % ov[1] == 0 else \
        next((c for c in _BN_CANDIDATES if n % c == 0), None)
    if bm is None or bn is None:
        return None
    return bm, bn


def select_conv_tiles(n_out, spatial):
    """(bo, bs) output tiles for the NCHW-native fused 1×1 conv — bo over
    output channels, bs over the flattened spatial dim — or None (the
    rewrite pass's bail-out rule). Output channels must divide by an
    8-multiple candidate (MXU sublane alignment); the spatial dim may
    instead be taken whole when small, because odd per-sample extents
    (7·7=49, 14·14=196) are the NORM mid-network and still block fine.
    An ``MXTPU_PALLAS_TILES`` override ``"<bm>,<bn>"`` maps to
    (bs, bo) — bm is the spatial-like dim, bn the channel-like one —
    and is preferred per dimension when it divides."""
    ov = _tile_override()
    bo = ov[1] if ov is not None and n_out % ov[1] == 0 else \
        next((c for c in _BN_CANDIDATES if n_out % c == 0), None)
    bs = ov[0] if ov is not None and spatial % ov[0] == 0 else \
        next((c for c in _BM_CANDIDATES if spatial % c == 0), None)
    if bs is None and spatial <= 1024:
        bs = int(spatial)
    if bo is None or bs is None:
        return None
    return bo, bs


def conv_tile_failure(n_out, spatial):
    """Which dimension made ``select_conv_tiles`` return None — the
    fusion report's bail-out reason must point at the right one."""
    why = []
    if next((c for c in _BN_CANDIDATES if n_out % c == 0), None) is None:
        why.append(f"num_filter={n_out} not divisible by 8")
    if next((c for c in _BM_CANDIDATES if spatial % c == 0), None) \
            is None and spatial > 1024:
        why.append(f"spatial={spatial} not divisible by 8 and too "
                   "large (> 1024) for a whole-row block")
    return "; ".join(why) or "no tile split fits"


# ---------------------------------------------------------------------------
# kernels
# ---------------------------------------------------------------------------
def _make_kernel(relu):
    def _kernel(x_ref, w_ref, scale_ref, shift_ref, o_ref):
        """One (bm, bn) output tile of the (M, K) @ (K, N) form:
        normalize (+ReLU) the x tile on the fly (VMEM, fused into the
        MXU feed) and contract over the whole K."""
        x = x_ref[...]
        z = x * scale_ref[...] + shift_ref[...]
        if relu:
            z = jnp.maximum(z, 0.0)
        o_ref[...] = jnp.dot(
            z.astype(x.dtype), w_ref[...],
            preferred_element_type=jnp.float32).astype(o_ref.dtype)
    return _kernel


def _make_nchw_kernel(relu):
    def _kernel(w_ref, x_ref, scale_ref, shift_ref, o_ref):
        """One (1, bo, bs) output block of the NCHW-native fused conv:
        normalize (+ReLU) the (1, C, bs) activation block on the fly
        and contract the (bo, C) weight block over the whole C."""
        x = x_ref[...]                       # (1, C, bs)
        z = x * scale_ref[...] + shift_ref[...]  # (C, 1) broadcasts
        if relu:
            z = jnp.maximum(z, 0.0)
        o_ref[...] = jnp.dot(
            w_ref[...], z[0].astype(x.dtype),
            preferred_element_type=jnp.float32
        ).astype(o_ref.dtype)[None]
    return _kernel


def _make_prologue_kernel(relu):
    def _kernel(x_ref, scale_ref, shift_ref, o_ref):
        """Whole-array BN-apply(+ReLU) prologue (interpret path): the
        normalized activation the fused-matmul kernel would stream."""
        z = x_ref[...] * scale_ref[...] + shift_ref[...]
        if relu:
            z = jnp.maximum(z, 0.0)
        o_ref[...] = z.astype(o_ref.dtype)
    return _kernel


def _conv1x1(xhat, w4):
    dn = jax.lax.conv_dimension_numbers(xhat.shape, w4.shape,
                                        ("NCHW", "OIHW", "NCHW"))
    return jax.lax.conv_general_dilated(
        xhat, w4, (1, 1), [(0, 0), (0, 0)], dimension_numbers=dn)


# ---------------------------------------------------------------------------
# the generic (M, K) @ (K, N) fused matmul (bench tool / kernel tests)
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _fused_matmul(relu, bm, bn, interpret):
    from jax.experimental import pallas as pl
    kernel = _make_kernel(relu)

    @jax.custom_vjp
    def f(x, w, scale, shift):
        m, k = x.shape
        n = w.shape[1]
        return pl.pallas_call(
            kernel,
            grid=(m // bm, n // bn),
            in_specs=[
                pl.BlockSpec((bm, k), lambda i, j: (i, 0)),
                pl.BlockSpec((k, bn), lambda i, j: (0, j)),
                pl.BlockSpec((1, k), lambda i, j: (0, 0)),
                pl.BlockSpec((1, k), lambda i, j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            out_shape=jax.ShapeDtypeStruct((m, n), x.dtype),
            interpret=interpret,
        )(x, w, scale.reshape(1, k), shift.reshape(1, k))

    def f_fwd(x, w, scale, shift):
        # raw-input residuals: the normalized activation is recomputed
        # in f_bwd (one elementwise pass) rather than written out
        return f(x, w, scale, shift), (x, w, scale, shift)

    def f_bwd(res, g):
        x, w, scale, shift = res
        z = x * scale + shift
        xhat = (jnp.maximum(z, 0.0) if relu else z).astype(x.dtype)
        dxhat = jnp.dot(g, w.T, preferred_element_type=jnp.float32)
        dz = jnp.where(xhat > 0, dxhat, 0.0) if relu else dxhat
        dx = (dz * scale).astype(x.dtype)
        dscale = jnp.sum(dz * x, axis=0).astype(scale.dtype)
        dshift = jnp.sum(dz, axis=0).astype(scale.dtype)
        dw = jnp.dot(xhat.T, g,
                     preferred_element_type=jnp.float32).astype(w.dtype)
        return dx, dw, dscale, dshift

    f.defvjp(f_fwd, f_bwd)
    return f


def bn_relu_matmul(x, w, scale, shift, bm=None, bn=None, relu=True,
                   interpret=None):
    """``act(x * scale + shift) @ w`` without materializing the
    normalized activation. x: (M, K); w: (K, N); scale/shift: (K,) — the
    folded BN parameters gamma/sqrt(var+eps) and beta - mu*scale.

    Tiles default to ``select_tiles``; explicit bm/bn must divide M/N.
    ``interpret`` defaults to True off-TPU so the same code path runs in
    CPU tests. Differentiable via a custom VJP (exact gradients of the
    composed expression, normalized activation recomputed in backward).
    """
    m, k = x.shape
    n = w.shape[1]
    # each tile is selected independently, so an explicit bm (or bn)
    # only needs the OTHER dimension to have a dividing candidate
    if bm is None:
        bm = next((c for c in _BM_CANDIDATES if m % c == 0), None)
        if bm is None:
            raise ValueError(
                f"bn_relu_matmul: no tile candidate divides M={m} "
                "(must be divisible by 8); pad the problem or pass an "
                "explicit bm")
    if bn is None:
        bn = next((c for c in _BN_CANDIDATES if n % c == 0), None)
        if bn is None:
            raise ValueError(
                f"bn_relu_matmul: no tile candidate divides N={n} "
                "(must be divisible by 8); pad the problem or pass an "
                "explicit bn")
    if m % bm or n % bn:
        raise ValueError(
            f"bn_relu_matmul needs M % bm == 0 and N % bn == 0 "
            f"(got M={m}, N={n}, bm={bm}, bn={bn}); pad the problem or "
            "pass smaller blocks — a truncated grid would leave output "
            "tiles uninitialized")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    return _fused_matmul(bool(relu), int(bm), int(bn),
                         bool(interpret))(x, w, scale, shift)


# ---------------------------------------------------------------------------
# the NCHW-native fused conv forward (used by the graph op)
# ---------------------------------------------------------------------------
def bn_relu_conv_nchw(x, w, scale, shift, relu=True, interpret=None):
    """NCHW-native fused BN-apply(+ReLU)+1×1-conv FORWARD: ``act(x *
    scale + shift) ⊛ w`` contracted over channels, x (B, C, H, W),
    w (O, C) → (B, O, H, W). On TPU this is the tiled fused-matmul
    kernel — the normalized activation never reaches HBM. In interpret
    mode (CPU tests) the interpreter must materialize it regardless, so
    the prologue runs as a whole-array Pallas kernel and the stock 1×1
    convolution does the contraction; pass ``interpret=False`` to force
    the tiled kernel (still interpretable off-TPU only via
    ``interpret=True`` in its pallas_call — i.e. don't).

    Forward only; the graph op's custom VJP (analytic fused BN backward)
    lives in ``_fused_bn_conv_vjp``.

    Under an active :func:`mesh_scope` whose batch axis divides B, the
    pallas_call wraps itself in ``shard_map(..., check_rep=False)``
    over the batch dimension — per-device kernel on the batch shard,
    weights/folded-stats replicated — so the op composes with GSPMD
    partitioning instead of being an opaque custom call the mesh bind
    must reject (ROADMAP item 1)."""
    from jax.experimental import pallas as pl
    b, c, h, w_sp = x.shape
    s = h * w_sp
    o = w.shape[0]
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if interpret:
        kern = _make_prologue_kernel(relu)

        def _prologue(xl, sc, sh):
            return pl.pallas_call(
                kern,
                out_shape=jax.ShapeDtypeStruct(xl.shape, xl.dtype),
                interpret=True,
            )(xl, sc, sh)

        sc = scale.reshape(1, c, 1, 1)
        sh = shift.reshape(1, c, 1, 1)
        ms = _batch_shards(b)
        if ms is not None:
            from jax.sharding import PartitionSpec as P
            mesh, axis, _ = ms
            xhat = _shard_map(_prologue, mesh=mesh,
                              in_specs=(P(axis), P(), P()),
                              out_specs=P(axis),
                              check_rep=False)(x, sc, sh)
        else:
            xhat = _prologue(x, sc, sh)
        return _conv1x1(xhat, w.reshape(o, c, 1, 1)).astype(x.dtype), \
            xhat
    tiles = select_conv_tiles(o, s)
    if tiles is None:
        raise ValueError(
            f"bn_relu_conv_nchw: {conv_tile_failure(o, s)}; pad the "
            "problem")
    bo, bs = tiles
    kern = _make_nchw_kernel(relu)

    def _tiled(wl, xl, sc, sh):
        bl = xl.shape[0]          # per-device batch inside shard_map
        return pl.pallas_call(
            kern,
            grid=(bl, o // bo, s // bs),
            in_specs=[
                pl.BlockSpec((bo, c), lambda g, i, j: (i, 0)),
                pl.BlockSpec((1, c, bs), lambda g, i, j: (g, 0, j)),
                pl.BlockSpec((c, 1), lambda g, i, j: (0, 0)),
                pl.BlockSpec((c, 1), lambda g, i, j: (0, 0)),
            ],
            out_specs=pl.BlockSpec((1, bo, bs),
                                   lambda g, i, j: (g, i, j)),
            out_shape=jax.ShapeDtypeStruct((bl, o, s), xl.dtype),
            interpret=False,
        )(wl, xl, sc, sh)

    xr = x.reshape(b, c, s)
    sc = scale.reshape(c, 1)
    sh = shift.reshape(c, 1)
    ms = _batch_shards(b)
    if ms is not None:
        from jax.sharding import PartitionSpec as P
        mesh, axis, _ = ms
        out = _shard_map(_tiled, mesh=mesh,
                         in_specs=(P(), P(axis), P(), P()),
                         out_specs=P(axis),
                         check_rep=False)(w, xr, sc, sh)
    else:
        out = _tiled(w, xr, sc, sh)
    return out.reshape(b, o, h, w_sp), None


# ---------------------------------------------------------------------------
# the graph op: BN(+ReLU)+1×1 conv with the analytic fused backward
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _fused_bn_conv_vjp(relu, batch_stats, fix_gamma, eps, interpret):
    """Whole-op custom VJP: (data, gamma, beta, moving_mean, moving_var,
    w2 (O, C)) -> (out, mean, var). The backward is the ANALYTIC fused
    BatchNorm backward (cuDNN BatchNormBackward coverage): d(data) is
    assembled in one full-tensor pass,

        dx = scale·dz + cx·x + c0,   scale/cx/c0 all (C,)-sized,

    instead of naive autodiff's separate mean-/var-chain passes.
    Running-stat inputs receive no gradient (reference semantics: aux
    states are not differentiated, batch_norm.cc)."""

    def stats(x):
        if batch_stats:
            return jnp.mean(x, axis=(0, 2, 3)), jnp.var(x, axis=(0, 2, 3))
        return None, None

    def fold(x, gamma, beta, mean, var):
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        scale = g * jax.lax.rsqrt(var + eps)
        return g, scale, beta - mean * scale

    def fwd(x, gamma, beta, mm, mv, w2):
        mean, var = stats(x)
        if mean is None:
            mean, var = mm, mv
        _, scale, shift = fold(x, gamma, beta, mean, var)
        out, xhat = bn_relu_conv_nchw(x, w2, scale, shift, relu=relu,
                                      interpret=interpret)
        return out, mean, var, xhat

    @jax.custom_vjp
    def f(x, gamma, beta, mm, mv, w2):
        out, mean, var, _ = fwd(x, gamma, beta, mm, mv, w2)
        return out, mean, var

    def f_fwd(x, gamma, beta, mm, mv, w2):
        out, mean, var, xhat = fwd(x, gamma, beta, mm, mv, w2)
        # on TPU xhat is None: the backward recomputes it from the raw
        # residuals (that recompute IS the traffic win); the interpreter
        # materializes it anyway, so there it doubles as the residual
        return (out, mean, var), (x, gamma, beta, mean, var, w2, xhat)

    def f_bwd(res, cts):
        g_out, g_mean, g_var = cts
        x, gamma, beta, mean, var, w2, xhat = res
        b, c, h, w_sp = x.shape
        n = b * h * w_sp
        o = w2.shape[0]
        g_eff, scale, shift = fold(x, gamma, beta, mean, var)
        inv = jax.lax.rsqrt(var + eps)
        if xhat is None:
            z = x * scale[:, None, None] + shift[:, None, None]
            xhat = (jnp.maximum(z, 0.0) if relu else z).astype(x.dtype)
        # dxhat/dw through XLA's own conv-grad lowering
        _, conv_vjp = jax.vjp(_conv1x1, xhat, w2.reshape(o, c, 1, 1))
        dxhat, dw4 = conv_vjp(g_out.astype(xhat.dtype))
        # relu mask from xhat (xhat > 0 ⟺ z > 0)
        dz = jnp.where(xhat > 0, dxhat, 0.0) if relu else dxhat
        # (C,)-sized moments of dz in ONE variadic reduction (a second
        # pass re-reading dz would double the traffic);
        # sum(dz·(x-mean)) = s1 - mean·s0
        dzx = dz * x
        s0, s1 = jax.lax.reduce(
            (dz, dzx), (jnp.zeros((), dz.dtype), jnp.zeros((), dzx.dtype)),
            lambda a, b: (a[0] + b[0], a[1] + b[1]), (0, 2, 3))
        t = s1 - mean * s0
        dbeta = s0.astype(beta.dtype)
        dgamma = jnp.zeros_like(gamma) if fix_gamma \
            else (t * inv).astype(gamma.dtype)
        if batch_stats:
            # analytic training-mode dx — one assembly pass — plus the
            # (usually zero) cotangents of the mean/var outputs folded
            # into the same coefficients
            coef = g_eff * (inv ** 3) * t / n
            cx = -coef + 2.0 * g_var / n
            c0 = (-scale * s0 + coef * mean * n) / n + g_mean / n \
                - 2.0 * mean * g_var / n
            dx = (dz * scale[:, None, None] + x * cx[:, None, None]
                  + c0[:, None, None]).astype(x.dtype)
        else:
            # moving stats are constants wrt x; their output cotangents
            # belong to the running-stat inputs, which take no gradient
            dx = (dz * scale[:, None, None]).astype(x.dtype)
        return (dx, dgamma, dbeta, jnp.zeros_like(mean),
                jnp.zeros_like(var),
                dw4.reshape(o, c).astype(w2.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


# ---------------------------------------------------------------------------
# the residual-chain graph op: BN(+ReLU)+conv of ANY geometry with the
# same analytic fused backward (round 12's residual_fusion pass)
# ---------------------------------------------------------------------------
@functools.lru_cache(maxsize=None)
def _fused_bn_convk_vjp(relu, batch_stats, fix_gamma, eps, stride, pad,
                        dilate, groups):
    """Whole-op custom VJP for the GENERAL conv case: (data, gamma,
    beta, moving_mean, moving_var, w4 (O, C/g, kh, kw)) -> (out, mean,
    var). The forward is the stock lax convolution over the normalized
    activation (no Pallas kernel — arbitrary k×k/stride/pad geometries
    don't tile like the 1×1 contraction), but the BACKWARD is the same
    analytic fused BatchNorm backward as the 1×1 op: the normalized
    activation is RECOMPUTED from raw residuals instead of stored
    (dropping an activation-sized saved tensor per site — the bytes win
    the pass manager's gate verifies), d(data) assembles in one
    full-tensor pass, and the (C,)-sized dz moments come from one
    variadic reduction. The conv half of the gradient goes through
    XLA's own conv-grad lowering via ``jax.vjp``."""

    def _conv(xhat, w4):
        dn = jax.lax.conv_dimension_numbers(xhat.shape, w4.shape,
                                            ("NCHW", "OIHW", "NCHW"))
        return jax.lax.conv_general_dilated(
            xhat, w4, stride, [(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=dn, feature_group_count=groups)

    def stats(x):
        if batch_stats:
            return jnp.mean(x, axis=(0, 2, 3)), jnp.var(x, axis=(0, 2, 3))
        return None, None

    def fold(gamma, beta, mean, var):
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        scale = g * jax.lax.rsqrt(var + eps)
        return g, scale, beta - mean * scale

    def fwd(x, gamma, beta, mm, mv, w4):
        mean, var = stats(x)
        if mean is None:
            mean, var = mm, mv
        _, scale, shift = fold(gamma, beta, mean, var)
        z = x * scale[:, None, None] + shift[:, None, None]
        xhat = (jnp.maximum(z, 0.0) if relu else z).astype(x.dtype)
        out = _conv(xhat, w4.astype(x.dtype)).astype(x.dtype)
        return out, mean, var

    @jax.custom_vjp
    def f(x, gamma, beta, mm, mv, w4):
        return fwd(x, gamma, beta, mm, mv, w4)

    def f_fwd(x, gamma, beta, mm, mv, w4):
        out, mean, var = fwd(x, gamma, beta, mm, mv, w4)
        # raw-input residuals only: xhat recomputes in f_bwd (one
        # elementwise pass instead of an activation-sized store)
        return (out, mean, var), (x, gamma, beta, mean, var, w4)

    def f_bwd(res, cts):
        g_out, g_mean, g_var = cts
        x, gamma, beta, mean, var, w4 = res
        b, c, h, w_sp = x.shape
        n = b * h * w_sp
        g_eff, scale, shift = fold(gamma, beta, mean, var)
        inv = jax.lax.rsqrt(var + eps)
        z = x * scale[:, None, None] + shift[:, None, None]
        xhat = (jnp.maximum(z, 0.0) if relu else z).astype(x.dtype)
        _, conv_vjp = jax.vjp(_conv, xhat, w4.astype(x.dtype))
        dxhat, dw4 = conv_vjp(g_out.astype(xhat.dtype))
        dz = jnp.where(xhat > 0, dxhat, 0.0) if relu else dxhat
        dzx = dz * x
        s0, s1 = jax.lax.reduce(
            (dz, dzx), (jnp.zeros((), dz.dtype), jnp.zeros((), dzx.dtype)),
            lambda a, b: (a[0] + b[0], a[1] + b[1]), (0, 2, 3))
        t = s1 - mean * s0
        dbeta = s0.astype(beta.dtype)
        dgamma = jnp.zeros_like(gamma) if fix_gamma \
            else (t * inv).astype(gamma.dtype)
        if batch_stats:
            coef = g_eff * (inv ** 3) * t / n
            cx = -coef + 2.0 * g_var / n
            c0 = (-scale * s0 + coef * mean * n) / n + g_mean / n \
                - 2.0 * mean * g_var / n
            dx = (dz * scale[:, None, None] + x * cx[:, None, None]
                  + c0[:, None, None]).astype(x.dtype)
        else:
            dx = (dz * scale[:, None, None]).astype(x.dtype)
        return (dx, dgamma, dbeta, jnp.zeros_like(mean),
                jnp.zeros_like(var), dw4.astype(w4.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


def _tup2(v, default):
    if v is None:
        return default
    if isinstance(v, (int, float)):
        return (int(v), int(v))
    return tuple(int(x) for x in v)


@register_op("_FusedBNReLUConvK", num_outputs=3)
def fused_bn_relu_conv_general(data, gamma, beta, moving_mean, moving_var,
                               weight, bias=None, eps=1e-3, momentum=0.9,
                               fix_gamma=True, use_global_stats=False,
                               act_type="relu", axis=1, kernel=None,
                               stride=None, pad=None, dilate=None,
                               num_filter=None, num_group=1, no_bias=True,
                               training=False, **kw):
    """BatchNorm -> [Activation(relu) ->] Convolution of ANY geometry as
    ONE op with the analytic fused BN backward (internal; substituted by
    symbol/passes/residual_fusion.py, never user-built). Mirrors
    BatchNorm's (out, mean, var) output layout and (…, moving_mean,
    moving_var) input positions 3/4 so the executors' running-aux fold
    (Symbol._bn_aux_updates) applies unchanged; ``momentum`` is consumed
    there, not here."""
    batch_stats = bool(training) and not use_global_stats
    out, mean, var = _fused_bn_convk_vjp(
        act_type == "relu", batch_stats, bool(fix_gamma), float(eps),
        _tup2(stride, (1, 1)), _tup2(pad, (0, 0)), _tup2(dilate, (1, 1)),
        int(num_group or 1),
    )(data, gamma, beta, moving_mean, moving_var, weight)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(data.dtype), mean, var


@register_op("_FusedBNReLUConv", num_outputs=3)
def fused_bn_relu_conv(data, gamma, beta, moving_mean, moving_var, weight,
                       bias=None, eps=1e-3, momentum=0.9, fix_gamma=True,
                       use_global_stats=False, act_type="relu", axis=1,
                       num_filter=None, no_bias=True, training=False, **kw):
    """BatchNorm -> Activation(relu) -> Convolution(1x1/s1/p0) as ONE op
    (internal; substituted by symbol/fusion.py, never user-built).

    Returns (conv_out, batch_mean, batch_var) — BatchNorm's output
    layout, with moving_mean/moving_var at input positions 3/4 like
    BatchNorm, so the executors' running-aux fold (Symbol._bn_aux_updates)
    applies to this op unchanged. ``momentum`` is consumed there, not
    here."""
    B, C, H, W = data.shape
    O = weight.shape[0]
    batch_stats = bool(training) and not use_global_stats
    if select_conv_tiles(O, H * W) is None:
        # shapes the rewrite pass should have bailed on — compute the
        # reference composition instead of failing mid-trace
        if batch_stats:
            mean = jnp.mean(data, axis=(0, 2, 3))
            var = jnp.var(data, axis=(0, 2, 3))
        else:
            mean, var = moving_mean, moving_var
        g = jnp.ones_like(gamma) if fix_gamma else gamma
        scale = g * jax.lax.rsqrt(var + eps)
        shift = beta - mean * scale
        z = data * scale.reshape(1, C, 1, 1) + shift.reshape(1, C, 1, 1)
        if act_type == "relu":
            z = jnp.maximum(z, 0.0)
        out = _conv1x1(z.astype(data.dtype),
                       weight.astype(data.dtype).reshape(O, C, 1, 1))
    else:
        out, mean, var = _fused_bn_conv_vjp(
            act_type == "relu", batch_stats, bool(fix_gamma), float(eps),
            jax.default_backend() != "tpu",
        )(data, gamma, beta, moving_mean, moving_var,
          weight.reshape(O, C).astype(data.dtype))
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out.astype(data.dtype), mean, var
