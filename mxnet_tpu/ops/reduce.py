"""Reduction operators.

Reference surface: src/operator/tensor/broadcast_reduce_op_value.cc,
broadcast_reduce_op_index.cc (sum/mean/prod/max/min/norm/argmax/argmin with
``axis``/``keepdims``/``exclude`` semantics).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op, alias


def _norm_axis(axis, ndim, exclude=False):
    if axis is None:
        return None
    if isinstance(axis, int):
        axis = (axis,)
    axis = tuple(a % ndim for a in axis)
    if exclude:
        axis = tuple(a for a in range(ndim) if a not in axis)
    return axis


def _reduce(fn):
    def impl(data, axis=None, keepdims=False, exclude=False, **kw):
        ax = _norm_axis(axis, data.ndim, exclude)
        return fn(data, axis=ax, keepdims=bool(keepdims))
    return impl


register_op("sum", aliases=["sum_axis"])(_reduce(jnp.sum))
register_op("mean")(_reduce(jnp.mean))
register_op("prod")(_reduce(jnp.prod))
register_op("nansum")(_reduce(jnp.nansum))
register_op("nanprod")(_reduce(jnp.nanprod))
register_op("max", aliases=["max_axis"])(_reduce(jnp.max))
register_op("min", aliases=["min_axis"])(_reduce(jnp.min))


@register_op("norm")
def norm(data, ord=2, axis=None, keepdims=False, **kw):
    ax = _norm_axis(axis, data.ndim)
    if ord == 1:
        return jnp.sum(jnp.abs(data), axis=ax, keepdims=bool(keepdims))
    return jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=bool(keepdims)))


def _index_reduce(fn):
    def impl(data, axis=None, keepdims=False, **kw):
        out = fn(data, axis=axis)
        if keepdims and axis is not None:
            out = jnp.expand_dims(out, axis)
        # reference returns float indices (mshadow legacy)
        return out.astype(jnp.float32)
    return impl


register_op("argmax", no_grad=True)(_index_reduce(jnp.argmax))
register_op("argmin", no_grad=True)(_index_reduce(jnp.argmin))


@register_op("argmax_channel", no_grad=True)
def argmax_channel(data, **kw):
    """argmax over axis 1 (reference: broadcast_reduce_op_index.cc)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register_op("pick")
def pick(data, index, axis=-1, keepdims=False, mode="clip", **kw):
    """Pick elements along an axis by index (reference:
    src/operator/tensor/broadcast_reduce_op_index.cc pick)."""
    axis = axis % data.ndim
    idx = index.astype(jnp.int32)
    if mode == "clip":
        idx = jnp.clip(idx, 0, data.shape[axis] - 1)
    else:
        idx = idx % data.shape[axis]
    picked = jnp.take_along_axis(data, jnp.expand_dims(idx, axis), axis=axis)
    if not keepdims:
        picked = jnp.squeeze(picked, axis=axis)
    return picked


# broadcasting "expand" ops live with reductions in the reference
@register_op("broadcast_to")
def broadcast_to(data, shape=None, **kw):
    shape = tuple(shape)
    tgt = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register_op("broadcast_axis", aliases=["broadcast_axes"])
def broadcast_axis(data, axis=(), size=(), **kw):
    if isinstance(axis, int):
        axis, size = (axis,), (size,)
    tgt = list(data.shape)
    for a, s in zip(axis, size):
        tgt[a % data.ndim] = s
    return jnp.broadcast_to(data, tuple(tgt))


@register_op("broadcast_like")
def broadcast_like(lhs, rhs, **kw):
    return jnp.broadcast_to(lhs, rhs.shape)
