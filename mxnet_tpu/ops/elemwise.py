"""Elementwise unary/binary/scalar/comparison operators.

Reference surface: src/operator/tensor/elemwise_unary_op_basic.cc,
elemwise_binary_op_basic.cc, elemwise_binary_broadcast_op_*.cc,
elemwise_binary_scalar_op_*.cc, src/operator/mshadow_op.h. All lower to jnp —
XLA fuses chains of these into single TPU kernels, replacing the reference's
hand-written mshadow expression templates.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register_op, alias


# ---------------------------------------------------------------------------
# unary
# ---------------------------------------------------------------------------
_UNARY = {
    "abs": jnp.abs,
    "sign": jnp.sign,
    "rint": jnp.rint,
    "ceil": jnp.ceil,
    "floor": jnp.floor,
    "trunc": jnp.trunc,
    "fix": jnp.trunc,  # round toward zero (jnp.fix deprecated alias)
    "square": jnp.square,
    "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt,
    "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp,
    "log": jnp.log,
    "log10": jnp.log10,
    "log2": jnp.log2,
    "log1p": jnp.log1p,
    "expm1": jnp.expm1,
    "sin": jnp.sin,
    "cos": jnp.cos,
    "tan": jnp.tan,
    "arcsin": jnp.arcsin,
    "arccos": jnp.arccos,
    "arctan": jnp.arctan,
    "sinh": jnp.sinh,
    "cosh": jnp.cosh,
    "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh,
    "arccosh": jnp.arccosh,
    "arctanh": jnp.arctanh,
    "degrees": jnp.degrees,
    "radians": jnp.radians,
    "erf": jax.scipy.special.erf,
    "erfinv": jax.scipy.special.erfinv,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "reciprocal": jnp.reciprocal,
    "negative": jnp.negative,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
    "relu": jax.nn.relu,
    "sigmoid": jax.nn.sigmoid,
    "softsign": jax.nn.soft_sign,
}

for _name, _fn in _UNARY.items():
    register_op(_name)(
        (lambda f: lambda data, **kw: f(data))(_fn))

alias("relu", "Relu")
register_op("identity", aliases=["_copy"])(lambda data, **kw: data)
register_op("BlockGrad", aliases=["stop_gradient"])(
    lambda data, **kw: jax.lax.stop_gradient(data))
register_op("make_loss", aliases=["MakeLoss"])(lambda data, **kw: data)


@register_op("add_n", aliases=["ElementWiseSum", "_sum"])
def add_n(*args, num_args=None, **kw):
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


@register_op("smooth_l1")
def smooth_l1(data, scalar=1.0, **kw):
    """Reference: src/operator/mshadow_op.h smooth_l1 (used by SSD/RCNN)."""
    s2 = scalar * scalar
    absd = jnp.abs(data)
    return jnp.where(absd < 1.0 / s2, 0.5 * s2 * data * data, absd - 0.5 / s2)


# ---------------------------------------------------------------------------
# binary (broadcasting; MXNet's elemwise_* and broadcast_* collapse to one
# implementation since jnp broadcasts by default)
# ---------------------------------------------------------------------------
def _fmod(a, b):
    return jnp.fmod(a, b)


_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": _fmod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
    "arctan2": jnp.arctan2,
}

_BIN_ALIASES = {
    "broadcast_add": ["elemwise_add", "_add", "_plus", "_Plus"],
    "broadcast_sub": ["elemwise_sub", "_sub", "_minus", "_Minus"],
    "broadcast_mul": ["elemwise_mul", "_mul", "_Mul"],
    "broadcast_div": ["elemwise_div", "_div", "_Div"],
    "broadcast_mod": ["_mod"],
    "broadcast_power": ["_power", "_Power", "pow"],
    "broadcast_maximum": ["_maximum", "maximum"],
    "broadcast_minimum": ["_minimum", "minimum"],
}

for _name, _fn in _BINARY.items():
    register_op(_name, aliases=_BIN_ALIASES.get(_name, ()))(
        (lambda f: lambda lhs, rhs, **kw: f(lhs, rhs))(_fn))


# comparisons return float (0/1) like the reference (mshadow_op.h eq/ne/...)
def _cmp(f):
    def impl(lhs, rhs, **kw):
        out = f(lhs, rhs)
        return out.astype(jnp.result_type(lhs))
    return impl


for _name, _fn, _al in [
    ("broadcast_equal", jnp.equal, ["_equal"]),
    ("broadcast_not_equal", jnp.not_equal, ["_not_equal"]),
    ("broadcast_greater", jnp.greater, ["_greater"]),
    ("broadcast_greater_equal", jnp.greater_equal, ["_greater_equal"]),
    ("broadcast_lesser", jnp.less, ["_lesser"]),
    ("broadcast_lesser_equal", jnp.less_equal, ["_lesser_equal"]),
    ("broadcast_logical_and", jnp.logical_and, ["_logical_and"]),
    ("broadcast_logical_or", jnp.logical_or, ["_logical_or"]),
    ("broadcast_logical_xor", jnp.logical_xor, ["_logical_xor"]),
]:
    register_op(_name, aliases=_al, no_grad=True)(_cmp(_fn))


# ---------------------------------------------------------------------------
# scalar ops (reference: elemwise_binary_scalar_op_*.cc)
# ---------------------------------------------------------------------------
_SCALAR = {
    "_plus_scalar": lambda x, s: x + s,
    "_minus_scalar": lambda x, s: x - s,
    "_rminus_scalar": lambda x, s: s - x,
    "_mul_scalar": lambda x, s: x * s,
    "_div_scalar": lambda x, s: x / s,
    "_rdiv_scalar": lambda x, s: s / x,
    "_mod_scalar": lambda x, s: jnp.fmod(x, s),
    "_rmod_scalar": lambda x, s: jnp.fmod(s, x),
    "_power_scalar": lambda x, s: x ** s,
    "_rpower_scalar": lambda x, s: jnp.asarray(s, x.dtype) ** x,
    "_maximum_scalar": lambda x, s: jnp.maximum(x, s),
    "_minimum_scalar": lambda x, s: jnp.minimum(x, s),
    "_hypot_scalar": lambda x, s: jnp.hypot(x, jnp.asarray(s, x.dtype)),
    "_equal_scalar": lambda x, s: (x == s).astype(x.dtype),
    "_not_equal_scalar": lambda x, s: (x != s).astype(x.dtype),
    "_greater_scalar": lambda x, s: (x > s).astype(x.dtype),
    "_greater_equal_scalar": lambda x, s: (x >= s).astype(x.dtype),
    "_lesser_scalar": lambda x, s: (x < s).astype(x.dtype),
    "_lesser_equal_scalar": lambda x, s: (x <= s).astype(x.dtype),
    "_logical_and_scalar": lambda x, s: jnp.logical_and(x, s).astype(x.dtype),
    "_logical_or_scalar": lambda x, s: jnp.logical_or(x, s).astype(x.dtype),
    "_logical_xor_scalar": lambda x, s: jnp.logical_xor(x, s).astype(x.dtype),
}

for _name, _fn in _SCALAR.items():
    register_op(_name, aliases=[_name.lstrip("_")])(
        (lambda f: lambda data, scalar=0.0, **kw: f(data, scalar))(_fn))
