"""Optimizer update operators.

Reference surface: src/operator/optimizer_op.cc (sgd_update, sgd_mom_update,
mp_sgd_update, adam_update, rmsprop_update, rmspropalex_update, ftrl_update,
signsgd_update, signum_update, ftml_update). Functional: return new tensors;
the Optimizer/Trainer layer rebinds state. XLA fuses each update into a single
elementwise kernel, replacing the reference's hand-written CUDA kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op


def _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return g + wd * weight


@register_op("sgd_update", no_grad=True)
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0, lazy_update=False, **kw):
    g = _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd)
    return weight - lr * g


@register_op("sgd_mom_update", no_grad=True, num_outputs=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, lazy_update=False, **kw):
    g = _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd)
    mom_new = momentum * mom - lr * g
    return weight + mom_new, mom_new


@register_op("nag_mom_update", no_grad=True, num_outputs=2)
def nag_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd)
    mom_new = momentum * mom + g
    return weight - lr * (g + momentum * mom_new), mom_new


@register_op("adam_update", no_grad=True, num_outputs=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                lazy_update=False, **kw):
    g = _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd)
    mean_new = beta1 * mean + (1 - beta1) * g
    var_new = beta2 * var + (1 - beta2) * jnp.square(g)
    w_new = weight - lr * mean_new / (jnp.sqrt(var_new) + epsilon)
    return w_new, mean_new, var_new


@register_op("rmsprop_update", no_grad=True, num_outputs=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.9, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0, **kw):
    g = _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd)
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w_new = weight - lr * g / jnp.sqrt(n_new + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w_new = jnp.clip(w_new, -clip_weights, clip_weights)
    return w_new, n_new


@register_op("rmspropalex_update", no_grad=True, num_outputs=4)
def rmspropalex_update(weight, grad, n, g_state, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0, **kw):
    g = _apply_wd_rescale_clip(grad, weight, rescale_grad, clip_gradient, wd)
    n_new = gamma1 * n + (1 - gamma1) * jnp.square(g)
    g_new = gamma1 * g_state + (1 - gamma1) * g
    delta_new = gamma2 * delta - lr * g / jnp.sqrt(n_new - jnp.square(g_new) + epsilon)
    w_new = weight + delta_new
    if clip_weights is not None and clip_weights > 0:
        w_new = jnp.clip(w_new, -clip_weights, clip_weights)
    return w_new, n_new, g_new, delta_new


@register_op("ftrl_update", no_grad=True, num_outputs=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    n_new = n + jnp.square(g)
    sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
    z_new = z + g - sigma * weight
    w_new = jnp.where(
        jnp.abs(z_new) <= lamda1, jnp.zeros_like(weight),
        (jnp.sign(z_new) * lamda1 - z_new) /
        ((beta + jnp.sqrt(n_new)) / lr + wd))
    return w_new, z_new, n_new


@register_op("signsgd_update", no_grad=True)
def signsgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
                   clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    return weight - lr * (jnp.sign(g) + wd * weight)


@register_op("signum_update", no_grad=True, num_outputs=2)
def signum_update(weight, grad, mom, lr=0.01, momentum=0.9, wd=0.0,
                  rescale_grad=1.0, clip_gradient=-1.0, wd_lh=0.0, **kw):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mom_new = momentum * mom - (1 - momentum) * (g + wd * weight)
    w_new = (1 - lr * wd_lh) * weight + lr * jnp.sign(mom_new)
    return w_new, mom_new


@register_op("ftml_update", no_grad=True, num_outputs=4)
def ftml_update(weight, grad, d, v, z, lr=0.0025, beta1=0.6, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_grad=-1.0, t=1, **kw):
    g = grad * rescale_grad + wd * weight
    if clip_grad is not None and clip_grad > 0:
        g = jnp.clip(g, -clip_grad, clip_grad)
    v_new = beta2 * v + (1 - beta2) * jnp.square(g)
    d_new = (1 - beta1 ** t) / lr * (
        jnp.sqrt(v_new / (1 - beta2 ** t)) + epsilon)
    sigma = d_new - beta1 * d
    z_new = beta1 * z + (1 - beta1) * g - sigma * weight
    return -z_new / d_new, d_new, v_new, z_new
