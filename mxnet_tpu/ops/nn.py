"""Neural-network operators.

Reference surface: src/operator/nn/ (convolution, fully_connected, pooling,
batch_norm, layer_norm, softmax, dropout, activation, deconvolution, lrn) and
src/operator/{rnn,leaky_relu,instance_norm,softmax_output}.

TPU notes: data layout follows the reference's NCHW at the API, but conv and
pooling are expressed through ``lax.conv_general_dilated`` / ``lax.reduce_window``
with explicit dimension_numbers so XLA picks MXU-friendly internal layouts.
bf16 inputs hit the MXU directly. These replace the reference's cuDNN kernels
(src/operator/nn/cudnn/) — XLA *is* the kernel library.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .registry import register_op
from ..dtype import resolve_dtype


def _tup(v, n=None):
    if v is None:
        return None
    if isinstance(v, (int, float)):
        v = (int(v),) * (n or 1)
    return tuple(int(x) for x in v)


# ---------------------------------------------------------------------------
# FullyConnected (reference: src/operator/nn/fully_connected.cc:228-309)
# ---------------------------------------------------------------------------
@register_op("FullyConnected")
def fully_connected(data, weight, bias=None, num_hidden=None, no_bias=False,
                    flatten=True, **kw):
    x = data.reshape(data.shape[0], -1) if flatten and data.ndim > 2 else data
    out = jnp.matmul(x, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution (reference: src/operator/nn/convolution.cc; cuDNN path
# src/operator/nn/cudnn/cudnn_convolution-inl.h — here: XLA HLO convolution)
# ---------------------------------------------------------------------------
def _conv_dnums(ndim):
    if ndim == 3:
        return ("NCH", "OIH", "NCH")
    if ndim == 4:
        return ("NCHW", "OIHW", "NCHW")
    return ("NCDHW", "OIDHW", "NCDHW")


@register_op("Convolution")
def convolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                pad=None, num_filter=None, num_group=1, no_bias=False,
                cudnn_tune=None, cudnn_off=False, workspace=None, layout=None, **kw):
    nd = data.ndim
    sdims = nd - 2
    stride = _tup(stride, sdims) or (1,) * sdims
    dilate = _tup(dilate, sdims) or (1,) * sdims
    pad = _tup(pad, sdims) or (0,) * sdims
    dn = jax.lax.conv_dimension_numbers(data.shape, weight.shape, _conv_dnums(nd))
    # bf16 inputs: the TPU MXU accumulates in f32 natively; an explicit
    # preferred_element_type breaks this JAX version's conv transpose rule
    out = jax.lax.conv_general_dilated(
        data, weight.astype(data.dtype), window_strides=stride,
        padding=[(p, p) for p in pad], lhs_dilation=None, rhs_dilation=dilate,
        dimension_numbers=dn, feature_group_count=int(num_group))
    out = out.astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * sdims)
    return out


@register_op("Deconvolution")
def deconvolution(data, weight, bias=None, kernel=None, stride=None, dilate=None,
                  pad=None, adj=None, target_shape=None, num_filter=None,
                  num_group=1, no_bias=True, workspace=None, cudnn_tune=None,
                  cudnn_off=False, layout=None, **kw):
    """Transposed convolution (reference: src/operator/nn/deconvolution.cc)."""
    nd = data.ndim
    sdims = nd - 2
    stride = _tup(stride, sdims) or (1,) * sdims
    dilate = _tup(dilate, sdims) or (1,) * sdims
    pad = _tup(pad, sdims) or (0,) * sdims
    adj = _tup(adj, sdims) or (0,) * sdims
    kernel = _tup(kernel, sdims) or weight.shape[2:]
    # gradient-of-conv formulation: lhs_dilation=stride, flipped spatial pad
    pads = []
    for k, p, a, d in zip(kernel, pad, adj, dilate):
        eff_k = (k - 1) * d + 1
        pads.append((eff_k - 1 - p, eff_k - 1 - p + a))
    # weight layout is (Cin, Cout/g, *k) in MXNet deconv; conv wants (O, I, *k)
    w = jnp.swapaxes(weight, 0, 1)
    w = jnp.flip(w, axis=tuple(range(2, nd)))
    if num_group > 1:
        # regroup: (g, Cout/g, Cin/g, *k) → (Cout, Cin/g, *k)
        cin = data.shape[1]
        wg = weight.reshape((num_group, cin // num_group) + weight.shape[1:])
        wg = jnp.swapaxes(wg, 1, 2)
        w = wg.reshape((-1, cin // num_group) + weight.shape[2:])
        w = jnp.flip(w, axis=tuple(range(2, nd)))
    dn = jax.lax.conv_dimension_numbers(data.shape, w.shape, _conv_dnums(nd))
    out = jax.lax.conv_general_dilated(
        data, w, window_strides=(1,) * sdims, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilate, dimension_numbers=dn,
        feature_group_count=int(num_group))
    out = out.astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * sdims)
    return out


# ---------------------------------------------------------------------------
# Pooling (reference: src/operator/nn/pooling.cc)
# ---------------------------------------------------------------------------
@register_op("conv_s2d_stem", aliases=["_contrib_conv_s2d_stem"])
def conv_s2d_stem(data, weight, **kw):
    """Mathematically exact space-to-depth rewrite of the 7x7/s2/pad3
    ImageNet stem conv: block-2 space-to-depth on the input, the SAME
    (O,C,7,7) weights front-padded to 8x8 and folded to (O,C*4,4,4), then
    a stride-1 conv with block-space pads (2,1). Identical output to
    Convolution(kernel=7, stride=2, pad=3) for even H,W — checkpoint
    compatible both directions (derivation: output pixel i reads
    x[2i-3..2i+3]; splitting x into even/odd phases gives 4 block taps per
    phase with the tap table w8[2a'+p] for the front-padded kernel).

    Why: the MXU contracts over C*kh*kw; with C=3 the standard stem
    wastes most of the 128-deep contraction lanes, and the folded form
    quadruples the input-channel depth (the MLPerf ResNet TPU technique).
    """
    # the rewrite below is derived specifically for kernel 7x7, stride 2,
    # pad 3, no dilation/groups, and needs even H,W — reject anything
    # else loudly instead of silently computing the wrong convolution
    def _is(name, want):
        v = kw.get(name)
        return v is None or tuple(v) == want
    if not (_is("kernel", (7, 7)) and _is("stride", (2, 2))
            and _is("pad", (3, 3)) and _is("dilate", (1, 1))
            and int(kw.get("num_group", 1)) == 1):
        raise ValueError(
            "conv_s2d_stem implements exactly Convolution(kernel=(7,7), "
            f"stride=(2,2), pad=(3,3), no dilation/groups); got attrs "
            f"{ {k: v for k, v in kw.items() if k in ('kernel', 'stride', 'pad', 'dilate', 'num_group')} }. "
            "Use the plain Convolution op for other geometries.")
    B, C, H, W = data.shape
    if H % 2 or W % 2:
        raise ValueError(
            f"conv_s2d_stem needs even spatial dims (space-to-depth "
            f"block 2); got input {H}x{W}")
    O = weight.shape[0]
    xs = data.reshape(B, C, H // 2, 2, W // 2, 2).transpose(
        0, 1, 3, 5, 2, 4).reshape(B, C * 4, H // 2, W // 2)
    w8 = jnp.pad(weight.astype(data.dtype),
                 ((0, 0), (0, 0), (1, 0), (1, 0)))
    wf = w8.reshape(O, C, 4, 2, 4, 2).transpose(
        0, 1, 3, 5, 2, 4).reshape(O, C * 4, 4, 4)
    return jax.lax.conv_general_dilated(
        xs, wf, (1, 1), ((2, 1), (2, 1)),
        dimension_numbers=("NCHW", "OIHW", "NCHW")).astype(data.dtype)


@register_op("Pooling")
def pooling(data, kernel=None, pool_type="max", global_pool=False, stride=None,
            pad=None, pooling_convention="valid", cudnn_off=False,
            count_include_pad=True, **kw):
    nd = data.ndim
    sdims = nd - 2
    if global_pool:
        ax = tuple(range(2, nd))
        if pool_type == "max":
            return jnp.max(data, axis=ax, keepdims=True)
        if pool_type == "sum":
            return jnp.sum(data, axis=ax, keepdims=True)
        return jnp.mean(data, axis=ax, keepdims=True)
    kernel = _tup(kernel, sdims)
    stride = _tup(stride, sdims) or (1,) * sdims
    pad = _tup(pad, sdims) or (0,) * sdims
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    if pooling_convention == "full":
        # ceil-mode: pad on the high side so ceil((x+2p-k)/s)+1 windows fit
        pads = [(0, 0), (0, 0)]
        for i in range(sdims):
            x = data.shape[2 + i]
            out_sz = int(np.ceil((x + 2 * pad[i] - kernel[i]) / stride[i])) + 1
            need = (out_sz - 1) * stride[i] + kernel[i] - x - pad[i]
            pads.append((pad[i], max(need, pad[i])))
    else:
        pads = [(0, 0), (0, 0)] + [(p, p) for p in pad]

    # NOTE: init values must be python scalars — a traced/array init prevents
    # JAX from selecting the differentiable reduce_window_{max,sum} primitives
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) \
            else int(jnp.iinfo(data.dtype).min)
        return jax.lax.reduce_window(data, init,
                                     jax.lax.max, window, strides, pads)
    zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
    summed = jax.lax.reduce_window(data, zero,
                                   jax.lax.add, window, strides, pads)
    if pool_type == "sum":
        return summed
    if count_include_pad:
        denom = np.prod(kernel)
        return summed / jnp.asarray(denom, data.dtype)
    ones = jnp.ones(data.shape, data.dtype)
    counts = jax.lax.reduce_window(ones, jnp.asarray(0, data.dtype),
                                   jax.lax.add, window, strides, pads)
    return summed / counts


# ---------------------------------------------------------------------------
# Activations (reference: src/operator/nn/activation.cc, leaky_relu.cc)
# ---------------------------------------------------------------------------
@register_op("Activation")
def activation(data, act_type="relu", **kw):
    fns = {"relu": jax.nn.relu, "sigmoid": jax.nn.sigmoid, "tanh": jnp.tanh,
           "softrelu": jax.nn.softplus, "softsign": jax.nn.soft_sign}
    return fns[act_type](data)


@register_op("LeakyReLU")
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, **kw):
    if act_type == "leaky":
        return jnp.where(data > 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data > 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data > 0, data, g * data)
    if act_type == "selu":
        alpha, scale = 1.6732632423543772, 1.0507009873554805
        return scale * jnp.where(data > 0, data, alpha * jnp.expm1(data))
    if act_type == "rrelu":
        # inference behavior: use mean slope (reference: leaky_relu-inl.h)
        s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data > 0, data, s * data)
    raise ValueError(f"unknown act_type {act_type}")


# ---------------------------------------------------------------------------
# softmax family (reference: src/operator/nn/softmax.cc, softmax_output.cc,
# loss_binary_op.cc)
# ---------------------------------------------------------------------------
@register_op("softmax")
def softmax(data, axis=-1, temperature=None, length=None, **kw):
    x = data / temperature if temperature else data
    return jax.nn.softmax(x, axis=axis)


@register_op("log_softmax")
def log_softmax(data, axis=-1, temperature=None, **kw):
    x = data / temperature if temperature else data
    return jax.nn.log_softmax(x, axis=axis)


@register_op("SoftmaxActivation")
def softmax_activation(data, mode="instance", **kw):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register_op("softmax_cross_entropy")
def softmax_cross_entropy(data, label, **kw):
    logp = jax.nn.log_softmax(data, axis=-1)
    nll = -jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return jnp.sum(nll)


@register_op("SoftmaxOutput", aliases=["Softmax"])
def softmax_output(data, label=None, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0, **kw):
    """Forward = softmax; the custom backward (∂=p-y) is realized by pairing
    with the cross-entropy loss at the framework level (reference:
    src/operator/softmax_output.cc). Module's fit wires this through
    ``_softmax_output_loss`` below."""
    if multi_output:
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data, axis=-1)


def softmax_output_loss(data, label, grad_scale=1.0, ignore_label=-1.0,
                        use_ignore=False, multi_output=False,
                        normalization="null", smooth_alpha=0.0, **kw):
    """Cross-entropy whose gradient wrt data equals SoftmaxOutput's backward."""
    axis = 1 if multi_output else -1
    logp = jax.nn.log_softmax(data, axis=axis)
    lab = label.astype(jnp.int32)
    nll = -jnp.take_along_axis(logp, jnp.expand_dims(lab, axis), axis=axis)
    nll = jnp.squeeze(nll, axis)
    if use_ignore:
        mask = (lab != int(ignore_label)).astype(data.dtype)
        nll = nll * mask
        if normalization == "valid":
            return grad_scale * jnp.sum(nll) / jnp.maximum(jnp.sum(mask), 1.0)
    # reference backward semantics (softmax_output.cc): "null" leaves each
    # sample's (p - y) unscaled → implicit loss is the SUM of per-sample CE
    # (the optimizer's rescale_grad=1/batch does the averaging); "batch"
    # divides by batch size.
    if normalization == "batch":
        return grad_scale * jnp.mean(nll)
    return grad_scale * jnp.sum(nll)


# ---------------------------------------------------------------------------
# Normalization (reference: src/operator/nn/batch_norm.cc, layer_norm.cc,
# src/operator/instance_norm.cc, lrn.cc)
# ---------------------------------------------------------------------------
@register_op("BatchNorm", num_outputs=3)
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, training=False, **kw):
    """Returns (out, batch_mean, batch_var). Running-stat update is done by the
    caller (gluon layer / executor) — functional style; the reference mutates
    aux states in-place (batch_norm.cc)."""
    ax = axis % data.ndim
    red = tuple(i for i in range(data.ndim) if i != ax)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    if training and not use_global_stats:
        mean = jnp.mean(data, axis=red)
        var = jnp.var(data, axis=red)
    else:
        mean, var = moving_mean, moving_var
    g = jnp.ones_like(gamma) if fix_gamma else gamma
    inv = jax.lax.rsqrt(var + eps)
    out = (data - mean.reshape(bshape)) * (g * inv).reshape(bshape) + beta.reshape(bshape)
    return out.astype(data.dtype), mean, var


@register_op("LayerNorm")
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False, **kw):
    ax = axis % data.ndim
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.var(data, axis=ax, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    bshape = tuple(data.shape[ax] if i == ax else 1 for i in range(data.ndim))
    out = (data - mean) * inv * gamma.reshape(bshape) + beta.reshape(bshape)
    if output_mean_var:
        return out, jnp.squeeze(mean, ax), jnp.squeeze(var, ax)
    return out


# ---------------------------------------------------------------------------
# CausalSelfAttention — no reference analog (MXNet ~1.1 predates attention);
# the single-device graduation of parallel/ring.py's blockwise math: one
# resident block, no ring hop, same stable max/denominator recurrence and
# the same -1e30 additive-mask convention (masked logits underflow to an
# exact 0.0 contribution, so padding/stale rows can never perturb outputs).
# ---------------------------------------------------------------------------
@register_op("CausalSelfAttention")
def causal_self_attention(data, num_heads=1, scale=None, **kw):
    """Causal multi-head self-attention over packed QKV.

    data: (B, S, 3*num_heads*head_dim) — the fused QKV projection
    (FullyConnected with flatten=False). Returns (B, S, num_heads*head_dim);
    position i attends to positions <= i.
    """
    from ..parallel.ring import local_attention_block, _NEG
    b, s, three_hd = data.shape
    h = int(num_heads)
    d = three_hd // (3 * h)
    qkv = data.reshape(b, s, 3, h, d)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    pos = jnp.arange(s)
    bias = jnp.where(pos[:, None] >= pos[None, :], 0.0, _NEG)[None, None]
    o, _, l = local_attention_block(q, k, v, bias=bias, scale=scale)
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.reshape(b, s, h * d).astype(data.dtype)


@register_op("InstanceNorm")
def instance_norm(data, gamma, beta, eps=1e-3, **kw):
    red = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=red, keepdims=True)
    var = jnp.var(data, axis=red, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(bshape) + \
        beta.reshape(bshape)


@register_op("LRN")
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5, **kw):
    """Local response norm across channels (reference: src/operator/nn/lrn.cc)."""
    sq = jnp.square(data)
    half = nsize // 2
    padded = jnp.pad(sq, ((0, 0), (half, half)) + ((0, 0),) * (data.ndim - 2))
    # NOTE: init must be a python scalar — an array init stops JAX from
    # selecting the differentiable reduce_window_sum primitive, and the
    # generic reduce_window has no reverse-mode rule (found by the
    # registry gradient sweep, tests/test_op_gradients.py)
    window = jax.lax.reduce_window(
        padded, 0.0, jax.lax.add,
        (1, nsize) + (1,) * (data.ndim - 2), (1,) * data.ndim,
        [(0, 0)] * data.ndim)
    return data / jnp.power(knorm + alpha / nsize * window, beta)


# ---------------------------------------------------------------------------
# Dropout (reference: src/operator/nn/dropout.cc) — needs an RNG key; eager
# mode uses the global random state, traced mode must pass `key`.
# ---------------------------------------------------------------------------
@register_op("Dropout")
def dropout(data, p=0.5, mode="training", axes=None, key=None, training=None, **kw):
    from ..random import next_key
    is_training = training if training is not None else True
    if not is_training and mode != "always":
        return data
    if p <= 0.0:
        return data
    if key is None:
        key = next_key()
    shape = data.shape
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, shape).astype(data.dtype) / keep
    return data * mask


# ---------------------------------------------------------------------------
# RNN — fused multi-layer RNN/LSTM/GRU via lax.scan
# (reference: src/operator/rnn-inl.h + cudnn_rnn-inl.h; the cuDNN fused kernel
# maps to one scan whose body is MXU matmuls over the whole batch)
# ---------------------------------------------------------------------------
def _rnn_gate_count(mode):
    return {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}[mode]


def rnn_unpack_params(params, mode, num_layers, input_size, state_size,
                      bidirectional=False):
    """Split the reference's flat cuDNN-layout parameter vector into per-layer
    (Wx, Wh, bx, bh) (reference layout: rnn-inl.h GetRnnParamSize)."""
    ngates = _rnn_gate_count(mode)
    dirs = 2 if bidirectional else 1
    layers = []
    off = 0
    for layer in range(num_layers):
        for d in range(dirs):
            isz = input_size if layer == 0 else state_size * dirs
            wx_n = ngates * state_size * isz
            wh_n = ngates * state_size * state_size
            wx = params[off:off + wx_n].reshape(ngates * state_size, isz); off += wx_n
            wh = params[off:off + wh_n].reshape(ngates * state_size, state_size); off += wh_n
            layers.append([wx, wh, None, None])
    for layer in range(num_layers):
        for d in range(dirs):
            b_n = ngates * state_size
            layers[layer * dirs + d][2] = params[off:off + b_n]; off += b_n
            layers[layer * dirs + d][3] = params[off:off + b_n]; off += b_n
    return layers


@register_op("_rnn_zero_state")
def rnn_zero_state(data, state_size=0, num=0, batch_axis=0, **kw):
    """Zero initial RNN state derived from a data symbol's batch dim —
    lets cell.unroll(begin_state=None) work at graph-build time without
    a concrete batch size (the reference creates shape-(0,...) zeros and
    lets InferShape fill them in; here shapes flow through eval_shape).
    data (T,N,C) + num>0 -> zeros (num, N, state_size); otherwise
    zeros (data.shape[batch_axis], state_size) — the caller passes the
    layout's batch axis (NTC->0, TNC->1)."""
    n = data.shape[1] if num else data.shape[int(batch_axis)]
    shape = (num, n, state_size) if num else (n, state_size)
    return jnp.zeros(shape, data.dtype)


def rnn_param_size(mode, num_layers, input_size, state_size, bidirectional=False):
    ngates = _rnn_gate_count(mode)
    dirs = 2 if bidirectional else 1
    total = 0
    for layer in range(num_layers):
        isz = input_size if layer == 0 else state_size * dirs
        total += dirs * ngates * state_size * (isz + state_size + 2)
    return total


def _lstm_cell_step(carry, x_t, wx, wh, bx, bh, h):
    c, hprev = carry
    gates = x_t @ wx.T + hprev @ wh.T + bx + bh
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
    g = jnp.tanh(g)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return (c_new, h_new), h_new


def _gru_cell_step(carry, x_t, wx, wh, bx, bh, h):
    (hprev,) = carry
    gx = x_t @ wx.T + bx
    gh = hprev @ wh.T + bh
    rx, zx, nx = jnp.split(gx, 3, axis=-1)
    rh, zh, nh = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(rx + rh)
    z = jax.nn.sigmoid(zx + zh)
    n = jnp.tanh(nx + r * nh)
    h_new = (1 - z) * n + z * hprev
    return (h_new,), h_new


def _vanilla_cell_step(act):
    def step(carry, x_t, wx, wh, bx, bh, h):
        (hprev,) = carry
        h_new = act(x_t @ wx.T + hprev @ wh.T + bx + bh)
        return (h_new,), h_new
    return step


def _run_layer(xs, mode, wx, wh, bx, bh, h0, c0=None, reverse=False):
    step = {"lstm": _lstm_cell_step, "gru": _gru_cell_step,
            "rnn_tanh": _vanilla_cell_step(jnp.tanh),
            "rnn_relu": _vanilla_cell_step(jax.nn.relu)}[mode]
    init = (c0, h0) if mode == "lstm" else (h0,)

    def body(carry, x_t):
        return step(carry, x_t, wx, wh, bx, bh, None)

    carry, ys = jax.lax.scan(body, init, xs, reverse=reverse)
    return carry, ys


@register_op("RNN", num_outputs=-1)
def rnn(data, parameters, state, state_cell=None, state_size=None,
        num_layers=1, mode="lstm", bidirectional=False, p=0.0,
        state_outputs=False, lstm_state_clip_min=None, lstm_state_clip_max=None,
        lstm_state_clip_nan=False, training=None, key=None, **kw):
    """Fused RNN (reference: src/operator/rnn-inl.h, data layout (T, N, C);
    state (L*dirs, N, H)). Implemented as stacked ``lax.scan`` — the TPU-native
    replacement of the cuDNN fused RNN kernel. ``p`` applies dropout between
    stacked layers in training mode (rnn-inl.h inter-layer dropout)."""
    T, N, C = data.shape
    dirs = 2 if bidirectional else 1
    layers = rnn_unpack_params(parameters, mode, num_layers, C, state_size,
                               bidirectional)
    apply_dropout = p and p > 0.0 and (training is None or training)
    if apply_dropout and key is None:
        from ..random import next_key
        key = next_key()
    xs = data
    h_out, c_out = [], []
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            li = layer * dirs + d
            wx, wh, bx, bh = layers[li]
            h0 = state[li]
            c0 = state_cell[li] if mode == "lstm" else None
            carry, ys = _run_layer(xs, mode, wx, wh, bx, bh, h0, c0,
                                   reverse=(d == 1))
            outs.append(ys)
            if mode == "lstm":
                c_out.append(carry[0]); h_out.append(carry[1])
            else:
                h_out.append(carry[0])
        xs = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if apply_dropout and layer < num_layers - 1:
            sub = jax.random.fold_in(key, layer)
            keep = 1.0 - p
            mask = jax.random.bernoulli(sub, keep, xs.shape)
            xs = xs * mask.astype(xs.dtype) / keep
    out = xs
    if state_outputs:
        hs = jnp.stack(h_out)
        if mode == "lstm":
            return out, hs, jnp.stack(c_out)
        return out, hs
    return out


# ---------------------------------------------------------------------------
# misc vision ops
# ---------------------------------------------------------------------------
@register_op("UpSampling")
def upsampling(*args, scale=1, sample_type="nearest", num_args=1, num_filter=0,
               multi_input_mode="concat", workspace=None, **kw):
    data = args[0]
    if sample_type == "nearest":
        if num_args > 1 and multi_input_mode == "concat":
            outs = [jnp.repeat(jnp.repeat(a, scale, axis=2), scale, axis=3)
                    for a in args]
            return jnp.concatenate(outs, axis=1)
        return jnp.repeat(jnp.repeat(data, scale, axis=2), scale, axis=3)
    # bilinear = deconvolution with bilinear kernel (args[1])
    weight = args[1]
    pad = scale // 2
    return deconvolution(data, weight, None, kernel=(scale * 2 - scale % 2,) * 2,
                         stride=(scale,) * 2, pad=(pad,) * 2,
                         num_filter=data.shape[1], num_group=data.shape[1],
                         no_bias=True)


@register_op("ROIPooling")
def roi_pooling(data, rois, pooled_size=(1, 1), spatial_scale=1.0, **kw):
    """Reference: src/operator/roi_pooling.cc. Vectorized over rois."""
    ph, pw = _tup(pooled_size, 2)
    N, C, H, W = data.shape

    def one_roi(roi):
        batch_idx = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * spatial_scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = data[batch_idx]  # (C,H,W)
        ys = jnp.arange(H)
        xs = jnp.arange(W)

        def pool_cell(iy, ix):
            hstart = y1 + (iy * rh) // ph
            hend = y1 + ((iy + 1) * rh + ph - 1) // ph
            wstart = x1 + (ix * rw) // pw
            wend = x1 + ((ix + 1) * rw + pw - 1) // pw
            mask = ((ys[:, None] >= hstart) & (ys[:, None] < hend) &
                    (xs[None, :] >= wstart) & (xs[None, :] < wend))
            masked = jnp.where(mask[None], img, -jnp.inf)
            val = jnp.max(masked, axis=(1, 2))
            return jnp.where(jnp.isfinite(val), val, 0.0)

        grid = jax.vmap(lambda iy: jax.vmap(lambda ix: pool_cell(iy, ix))(
            jnp.arange(pw)))(jnp.arange(ph))  # (ph, pw, C)
        return jnp.transpose(grid, (2, 0, 1))

    return jax.vmap(one_roi)(rois)


@register_op("GridGenerator", no_grad=True)
def grid_generator(data, transform_type="affine", target_shape=(0, 0), **kw):
    h, w = target_shape
    ys = jnp.linspace(-1, 1, h)
    xs = jnp.linspace(-1, 1, w)
    gx, gy = jnp.meshgrid(xs, ys)
    ones = jnp.ones_like(gx)
    base = jnp.stack([gx.ravel(), gy.ravel(), ones.ravel()])  # (3, h*w)
    if transform_type == "affine":
        theta = data.reshape(-1, 2, 3)
        out = jnp.einsum("nij,jk->nik", theta, base)
        return out.reshape(-1, 2, h, w)
    return data + jnp.stack([gx, gy])[None]


@register_op("BilinearSampler")
def bilinear_sampler(data, grid, **kw):
    """Reference: src/operator/bilinear_sampler.cc. grid in [-1,1], (N,2,H,W)."""
    N, C, H, W = data.shape
    _, _, outH, outW = grid.shape
    gx = (grid[:, 0] + 1) * (W - 1) / 2
    gy = (grid[:, 1] + 1) * (H - 1) / 2

    x0 = jnp.floor(gx); y0 = jnp.floor(gy)
    x1, y1 = x0 + 1, y0 + 1
    wx1 = gx - x0; wy1 = gy - y0
    wx0 = 1 - wx1; wy0 = 1 - wy1

    def sample(img, xi, yi):
        xi_c = jnp.clip(xi.astype(jnp.int32), 0, W - 1)
        yi_c = jnp.clip(yi.astype(jnp.int32), 0, H - 1)
        valid = ((xi >= 0) & (xi <= W - 1) & (yi >= 0) & (yi <= H - 1))
        vals = img[:, yi_c, xi_c]  # (C, outH, outW)
        return vals * valid[None]

    def per_image(img, x0i, y0i, x1i, y1i, w00, w01, w10, w11):
        return (sample(img, x0i, y0i) * w00[None] + sample(img, x1i, y0i) * w01[None]
                + sample(img, x0i, y1i) * w10[None] + sample(img, x1i, y1i) * w11[None])

    return jax.vmap(per_image)(data, x0, y0, x1, y1,
                               wy0 * wx0, wy0 * wx1, wy1 * wx0, wy1 * wx1)


@register_op("SpatialTransformer")
def spatial_transformer(data, loc, target_shape=(0, 0), transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False, **kw):
    grid = grid_generator(loc, transform_type, target_shape)
    return bilinear_sampler(data, grid)


# legacy v0.x interface names (reference: MXNET_REGISTER_OP_PROPERTY
# batch_norm_v1 src/operator/batch_norm_v1.cc, convolution_v1, pooling_v1 —
# same math behind the older Operator interface; here plain aliases)
from .registry import alias as _alias
_alias("BatchNorm", "BatchNorm_v1")
_alias("Convolution", "Convolution_v1")
_alias("Pooling", "Pooling_v1")
