"""Array creation + sorting/searching ops.

Reference surface: src/operator/tensor/init_op.cc (zeros/ones/full/arange/eye),
ordering_op.cc (sort/argsort/topk).
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register_op
from ..dtype import resolve_dtype


@register_op("_zeros", aliases=["zeros"], no_grad=True)
def zeros(shape=(), ctx=None, dtype="float32", **kw):
    return jnp.zeros(tuple(shape) if not isinstance(shape, int) else (shape,),
                     resolve_dtype(dtype))


@register_op("_ones", aliases=["ones"], no_grad=True)
def ones(shape=(), ctx=None, dtype="float32", **kw):
    return jnp.ones(tuple(shape) if not isinstance(shape, int) else (shape,),
                    resolve_dtype(dtype))


@register_op("_full", aliases=["full"], no_grad=True)
def full(shape=(), value=0.0, ctx=None, dtype="float32", **kw):
    return jnp.full(tuple(shape) if not isinstance(shape, int) else (shape,),
                    value, resolve_dtype(dtype))


@register_op("_arange", aliases=["arange"], no_grad=True)
def arange(start=0, stop=None, step=1.0, repeat=1, ctx=None, dtype="float32",
           infer_range=False, **kw):
    out = jnp.arange(start, stop, step, resolve_dtype(dtype))
    if repeat != 1:
        out = jnp.repeat(out, repeat)
    return out


@register_op("_eye", aliases=["eye"], no_grad=True)
def eye(N=0, M=0, k=0, ctx=None, dtype="float32", **kw):
    return jnp.eye(int(N), int(M) if M else None, k=int(k),
                   dtype=resolve_dtype(dtype))


@register_op("_linspace", aliases=["linspace"], no_grad=True)
def linspace(start=0.0, stop=1.0, num=50, endpoint=True, ctx=None,
             dtype="float32", **kw):
    return jnp.linspace(start, stop, int(num), endpoint=endpoint,
                        dtype=resolve_dtype(dtype))


# ---------------------------------------------------------------------------
# ordering (reference: src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------
@register_op("sort")
def sort(data, axis=-1, is_ascend=True, **kw):
    if axis is None:
        data = data.reshape(-1)
        axis = -1
    out = jnp.sort(data, axis=axis)
    return out if is_ascend else jnp.flip(out, axis=axis)


@register_op("argsort", no_grad=True)
def argsort(data, axis=-1, is_ascend=True, dtype="float32", **kw):
    if axis is None:
        data = data.reshape(-1)
        axis = -1
    idx = jnp.argsort(data, axis=axis)
    if not is_ascend:
        idx = jnp.flip(idx, axis=axis)
    return idx.astype(resolve_dtype(dtype))


@register_op("topk", no_grad=True)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32", **kw):
    """Reference: ordering_op.cc TopK. ret_typ in {value, indices, mask, both}."""
    axis = axis % data.ndim if axis is not None else None
    if axis is None:
        data = data.reshape(-1)
        axis = 0
    x = -data if is_ascend else data  # lax.top_k selects the largest
    moved = jnp.moveaxis(x, axis, -1)
    _, idx = __top_k(moved, k)
    true_vals = jnp.take_along_axis(
        jnp.moveaxis(data, axis, -1), idx, axis=-1)
    true_vals = jnp.moveaxis(true_vals, -1, axis)
    indices = jnp.moveaxis(idx, -1, axis).astype(resolve_dtype(dtype))
    if ret_typ == "value":
        return true_vals
    if ret_typ == "indices":
        return indices
    if ret_typ == "mask":
        oh = jnp.sum(jnp.eye(data.shape[axis], dtype=resolve_dtype(dtype))[idx],
                     axis=-2)
        return jnp.moveaxis(oh, -1, axis)
    return true_vals, indices


def __top_k(x, k):
    import jax.lax as lax
    return lax.top_k(x, k)
