"""Op-surface completion: the remaining reference registration sites.

Every op here closes a specific gap found by ``tools/opdiff.py`` against
the reference's NNVM_REGISTER_OP / MXNET_REGISTER_OP_PROPERTY sites:

- output heads: SVMOutput (src/operator/svm_output.cc), the regression
  outputs (src/operator/regression_output.cc) — forward ops; their
  implicit-loss backward lives in executor._IMPLICIT_LOSS,
- tensor utilities: reshape_like, round, _hypot, cast_storage,
  _slice_assign[_scalar], _scatter_* (src/operator/tensor/),
- sparse-aware kernels in their dense form: _sparse_retain, _square_sum,
  _sparse_adagrad_update (src/operator/tensor/sparse_retain.cc,
  square_sum-inl.h) — the row_sparse NDArray layer reuses these,
- multi-precision SGD: mp_sgd_update / mp_sgd_mom_update
  (src/operator/optimizer_op.cc),
- per-element distribution sampling: _sample_uniform/normal/gamma/
  exponential/poisson/negative_binomial/generalized_negative_binomial
  (src/operator/random/sample_op.cc),
- image ops: _image_to_tensor/_image_normalize (src/operator/image/
  image_random.cc) and the host-side _cvimdecode/_cvimread/_cvimresize/
  _cvcopyMakeBorder (plugin/opencv — eager-only, like the reference),
- contrib: quadratic, box_iou, bipartite_matching, SparseEmbedding
  (src/operator/contrib/), and the INT8 quantization family
  (src/operator/quantization/) backed by contrib.quantization's math,
- KL sparsity regularizer IdentityAttachKLSparseReg
  (src/operator/regression_output.cc sibling, identity_attach_KL_sparse_reg.cc).
"""
from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp

from .registry import register_op, alias, get_op

# ---------------------------------------------------------------------------
# output heads
# ---------------------------------------------------------------------------


@register_op("SVMOutput")
def svm_output(data, label=None, margin=1.0, regularization_coefficient=1.0,
               use_linear=False, **kw):
    """Forward = identity scores (src/operator/svm_output.cc:45); the hinge
    backward is an implicit loss (executor._IMPLICIT_LOSS)."""
    return data


@register_op("LinearRegressionOutput")
def linear_regression_output(data, label=None, grad_scale=1.0, **kw):
    return data


@register_op("MAERegressionOutput")
def mae_regression_output(data, label=None, grad_scale=1.0, **kw):
    return data


@register_op("LogisticRegressionOutput")
def logistic_regression_output(data, label=None, grad_scale=1.0, **kw):
    return jax.nn.sigmoid(data)


@register_op("IdentityAttachKLSparseReg")
def identity_attach_kl_sparse_reg(data, sparseness_target=0.1, penalty=0.001,
                                  momentum=0.9, **kw):
    """Identity with a KL sparsity penalty on the gradient (reference:
    src/operator/identity_attach_KL_sparse_reg.cc). The reference smooths
    the per-unit mean activation in an aux state with ``momentum``; here
    the penalty uses the current batch's mean (documented deviation — the
    functional graph has no op-local mutable aux)."""

    @jax.custom_vjp
    def _f(x):
        return x

    def _fwd(x):
        rho_hat = jnp.clip(jnp.mean(x, axis=0), 1e-6, 1 - 1e-6)
        return x, (rho_hat, x.shape[0])

    def _bwd(res, g):
        rho_hat, n = res
        kl_grad = penalty * (-sparseness_target / rho_hat +
                             (1 - sparseness_target) / (1 - rho_hat))
        return (g + jnp.broadcast_to(kl_grad, g.shape) / n,)

    _f.defvjp(_fwd, _bwd)
    return _f(data)


# ---------------------------------------------------------------------------
# tensor utilities
# ---------------------------------------------------------------------------

@register_op("reshape_like")
def reshape_like(lhs, rhs, **kw):
    return lhs.reshape(rhs.shape)


@register_op("round")
def round_(data, **kw):
    # half away from zero (mshadow_op::round), not numpy's half-to-even
    return jnp.sign(data) * jnp.floor(jnp.abs(data) + 0.5)


@register_op("_hypot", aliases=["hypot"])
def hypot(lhs, rhs, **kw):
    return jnp.hypot(lhs, rhs)


@register_op("_hypot_scalar", aliases=["hypot_scalar"])
def hypot_scalar(data, scalar=0.0, **kw):
    return jnp.hypot(data, scalar)


@register_op("cast_storage")
def cast_storage(data, stype="default", **kw):
    """Storage conversion is an NDArray-level concern here (ndarray.sparse
    tostype); as a graph op on dense values it is the identity, matching
    the dense->dense case of src/operator/tensor/cast_storage.cc. A
    non-default target stype inside a compiled graph cannot produce a
    sparse value (XLA programs are dense) — raise instead of silently
    returning dense (the eager nd.cast_storage routes to tostype)."""
    if stype not in (None, "default"):
        raise ValueError(
            f"cast_storage(stype={stype!r}) inside a compiled graph "
            "would silently produce a dense result; sparse storage "
            "conversion is NDArray-level — use .tostype() / "
            "nd.cast_storage eagerly (ndarray/sparse.py)")
    return data


@register_op("_identity_with_attr_like_rhs")
def identity_with_attr_like_rhs(lhs, rhs, **kw):
    return lhs


def _slice_tuple(shape, begin, end, step=None):
    step = step or [None] * len(begin)
    sl = []
    for i in range(len(shape)):
        if i < len(begin):
            b = begin[i] if begin[i] is not None else None
            e = end[i] if i < len(end) and end[i] is not None else None
            s = step[i] if i < len(step) and step[i] is not None else None
            sl.append(slice(b, e, s))
        else:
            sl.append(slice(None))
    return tuple(sl)


@register_op("_slice_assign", aliases=["_crop_assign"])
def slice_assign(lhs, rhs, begin=(), end=(), step=(), **kw):
    return lhs.at[_slice_tuple(lhs.shape, begin, end, step)].set(rhs)


@register_op("_slice_assign_scalar", aliases=["_crop_assign_scalar"])
def slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=(), **kw):
    return data.at[_slice_tuple(data.shape, begin, end, step)].set(scalar)


@register_op("_scatter_plus_scalar")
def scatter_plus_scalar(data, scalar=0.0, **kw):
    # on dense storage the scatter_ scalar family equals the plain op
    # (the row_sparse variant touches only stored rows — ndarray.sparse)
    return data + scalar


@register_op("_scatter_minus_scalar")
def scatter_minus_scalar(data, scalar=0.0, **kw):
    return data - scalar


@register_op("_scatter_elemwise_div")
def scatter_elemwise_div(lhs, rhs, **kw):
    return lhs / rhs


@register_op("_scatter_set_nd")
def scatter_set_nd(lhs, rhs, indices, shape=None, **kw):
    idx = tuple(indices.astype(jnp.int32))
    return lhs.at[idx].set(rhs)


# ---------------------------------------------------------------------------
# sparse kernels (dense form)
# ---------------------------------------------------------------------------

@register_op("_sparse_retain", aliases=["sparse_retain"])
def sparse_retain(data, indices, **kw):
    """Keep only the given rows, zero the rest (dense semantics of
    src/operator/tensor/sparse_retain.cc)."""
    rows = indices.astype(jnp.int32)
    out = jnp.zeros_like(data)
    return out.at[rows].set(data[rows])


@register_op("_square_sum", aliases=["square_sum"])
def square_sum(data, axis=None, keepdims=False, **kw):
    return jnp.sum(jnp.square(data), axis=axis, keepdims=keepdims)


@register_op("_sparse_adagrad_update", no_grad=True, num_outputs=2)
def sparse_adagrad_update(weight, grad, history, lr=0.01, epsilon=1e-7,
                          wd=0.0, rescale_grad=1.0, clip_gradient=-1.0, **kw):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    h_new = history + jnp.square(g)
    w_new = weight - lr * (g / jnp.sqrt(h_new + epsilon) + wd * weight)
    return w_new, h_new


@register_op("mp_sgd_update", no_grad=True, num_outputs=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0, lazy_update=False, **kw):
    """fp16/bf16 weight + fp32 master (src/operator/optimizer_op.cc
    MP_SGD_Update)."""
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    w32 = weight32 - lr * (g + wd * weight32)
    return w32.astype(weight.dtype), w32


@register_op("mp_sgd_mom_update", no_grad=True, num_outputs=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                      lazy_update=False, **kw):
    g = grad.astype(jnp.float32) * rescale_grad
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    mom_new = momentum * mom - lr * (g + wd * weight32)
    w32 = weight32 + mom_new
    return w32.astype(weight.dtype), mom_new, w32


# ---------------------------------------------------------------------------
# per-element distribution sampling (src/operator/random/sample_op.cc)
# ---------------------------------------------------------------------------


def _key_or_next(key):
    if key is None:
        from ..random import next_key
        return next_key()
    return key

def _sample_shape(param, shape):
    if shape is None:
        shape = ()
    elif isinstance(shape, int):
        shape = (shape,)
    return tuple(param.shape) + tuple(shape), tuple(shape)


def _expand(param, sample_shape):
    return param.reshape(param.shape + (1,) * len(sample_shape)) \
        if sample_shape else param


@register_op("_sample_uniform", aliases=["sample_uniform"], no_grad=True)
def sample_uniform(low, high, shape=None, dtype="float32", key=None, **kw):
    key = _key_or_next(key)
    out_shape, ss = _sample_shape(low, shape)
    u = jax.random.uniform(key, out_shape, jnp.float32)
    return (_expand(low, ss) + u * (_expand(high, ss) - _expand(low, ss))) \
        .astype(dtype)


@register_op("_sample_normal", aliases=["sample_normal"], no_grad=True)
def sample_normal(mu, sigma, shape=None, dtype="float32", key=None, **kw):
    key = _key_or_next(key)
    out_shape, ss = _sample_shape(mu, shape)
    z = jax.random.normal(key, out_shape, jnp.float32)
    return (_expand(mu, ss) + z * _expand(sigma, ss)).astype(dtype)


@register_op("_sample_gamma", aliases=["sample_gamma"], no_grad=True)
def sample_gamma(alpha, beta, shape=None, dtype="float32", key=None, **kw):
    key = _key_or_next(key)
    out_shape, ss = _sample_shape(alpha, shape)
    g = jax.random.gamma(key, _expand(alpha, ss), out_shape, jnp.float32)
    return (g * _expand(beta, ss)).astype(dtype)


@register_op("_sample_exponential", aliases=["sample_exponential"],
             no_grad=True)
def sample_exponential(lam, shape=None, dtype="float32", key=None, **kw):
    key = _key_or_next(key)
    out_shape, ss = _sample_shape(lam, shape)
    e = jax.random.exponential(key, out_shape, jnp.float32)
    return (e / _expand(lam, ss)).astype(dtype)


@register_op("_sample_poisson", aliases=["sample_poisson"], no_grad=True)
def sample_poisson(lam, shape=None, dtype="float32", key=None, **kw):
    key = _key_or_next(key)
    out_shape, ss = _sample_shape(lam, shape)
    p = jax.random.poisson(key, _expand(lam, ss), out_shape)
    return p.astype(dtype)


@register_op("_sample_negative_binomial", aliases=["sample_negative_binomial"],
             no_grad=True)
def sample_negative_binomial(k, p, shape=None, dtype="float32", key=None,
                             **kw):
    key = _key_or_next(key)
    # gamma-poisson mixture: NB(k, p) = Poisson(Gamma(k, (1-p)/p))
    out_shape, ss = _sample_shape(k, shape)
    k1, k2 = jax.random.split(key)
    kk = _expand(k, ss).astype(jnp.float32)
    pp = _expand(p, ss).astype(jnp.float32)
    lam = jax.random.gamma(k1, kk, out_shape, jnp.float32) * (1 - pp) / pp
    return jax.random.poisson(k2, lam, out_shape).astype(dtype)


@register_op("_sample_generalized_negative_binomial",
             aliases=["sample_generalized_negative_binomial"], no_grad=True)
def sample_gen_negative_binomial(mu, alpha, shape=None, dtype="float32",
                                 key=None, **kw):
    key = _key_or_next(key)
    out_shape, ss = _sample_shape(mu, shape)
    k1, k2 = jax.random.split(key)
    mm = _expand(mu, ss).astype(jnp.float32)
    aa = jnp.maximum(_expand(alpha, ss).astype(jnp.float32), 1e-8)
    lam = jax.random.gamma(k1, 1.0 / aa, out_shape, jnp.float32) * aa * mm
    return jax.random.poisson(k2, lam, out_shape).astype(dtype)


# ---------------------------------------------------------------------------
# image ops
# ---------------------------------------------------------------------------

@register_op("_image_to_tensor", aliases=["image_to_tensor"])
def image_to_tensor(data, **kw):
    """HWC uint8 [0,255] -> CHW float32 [0,1] (image_random.cc ToTensor);
    batched NHWC -> NCHW."""
    x = data.astype(jnp.float32) / 255.0
    if x.ndim == 3:
        return jnp.transpose(x, (2, 0, 1))
    return jnp.transpose(x, (0, 3, 1, 2))


@register_op("_image_normalize", aliases=["image_normalize"])
def image_normalize(data, mean=0.0, std=1.0, **kw):
    mean = jnp.asarray(mean, jnp.float32)
    std = jnp.asarray(std, jnp.float32)
    if mean.ndim == 1:
        mean = mean.reshape((-1, 1, 1))
        std = std.reshape((-1, 1, 1))
    return (data - mean) / std


def _require_cv2():
    try:
        import cv2
        return cv2
    except ImportError as e:  # pragma: no cover
        raise RuntimeError("OpenCV is required for the _cv* ops") from e


@register_op("_cvimdecode", aliases=["imdecode"], no_grad=True)
def cvimdecode(buf, flag=1, to_rgb=True, **kw):
    """Host-side JPEG/PNG decode (plugin/opencv cv_api.cc). Eager only —
    the reference's is a CPU-only op too."""
    cv2 = _require_cv2()
    img = cv2.imdecode(np.frombuffer(np.asarray(buf).tobytes(), np.uint8),
                       int(flag))
    if img is None:
        raise ValueError("imdecode: could not decode buffer")
    if to_rgb and img.ndim == 3:
        img = img[..., ::-1]
    return jnp.asarray(img)


@register_op("_cvimread", aliases=["imread"], no_grad=True)
def cvimread(filename, flag=1, to_rgb=True, **kw):
    cv2 = _require_cv2()
    img = cv2.imread(filename, int(flag))
    if img is None:
        raise ValueError(f"imread: could not read {filename}")
    if to_rgb and img.ndim == 3:
        img = img[..., ::-1]
    return jnp.asarray(img)


@register_op("_cvimresize", aliases=["imresize"], no_grad=True)
def cvimresize(src, w=0, h=0, interp=1, **kw):
    cv2 = _require_cv2()
    return jnp.asarray(cv2.resize(np.asarray(src), (int(w), int(h)),
                                  interpolation=int(interp)))


@register_op("_cvcopyMakeBorder", aliases=["copyMakeBorder"], no_grad=True)
def cvcopy_make_border(src, top=0, bot=0, left=0, right=0, type=0,
                       value=0.0, **kw):
    cv2 = _require_cv2()
    return jnp.asarray(cv2.copyMakeBorder(
        np.asarray(src), int(top), int(bot), int(left), int(right),
        int(type), value=value))


# ---------------------------------------------------------------------------
# contrib
# ---------------------------------------------------------------------------

@register_op("_contrib_quadratic", aliases=["quadratic"])
def quadratic(data, a=0.0, b=0.0, c=0.0, **kw):
    """(src/operator/contrib/quadratic_op.cc — the tutorial op)"""
    return a * jnp.square(data) + b * data + c


@register_op("_contrib_box_iou", aliases=["box_iou"])
def box_iou(lhs, rhs, format="corner", **kw):
    """Pairwise IoU (src/operator/contrib/bounding_box.cc BoxIoU):
    lhs (..., N, 4), rhs (..., M, 4) -> (..., N, M)."""
    def corners(b):
        if format == "center":
            x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
            return x - w / 2, y - h / 2, x + w / 2, y + h / 2
        return b[..., 0], b[..., 1], b[..., 2], b[..., 3]

    lx1, ly1, lx2, ly2 = corners(lhs)
    rx1, ry1, rx2, ry2 = corners(rhs)
    lx1, ly1, lx2, ly2 = (t[..., :, None] for t in (lx1, ly1, lx2, ly2))
    rx1, ry1, rx2, ry2 = (t[..., None, :] for t in (rx1, ry1, rx2, ry2))
    iw = jnp.maximum(jnp.minimum(lx2, rx2) - jnp.maximum(lx1, rx1), 0.0)
    ih = jnp.maximum(jnp.minimum(ly2, ry2) - jnp.maximum(ly1, ry1), 0.0)
    inter = iw * ih
    area_l = jnp.maximum((lx2 - lx1) * (ly2 - ly1), 0.0)
    area_r = jnp.maximum((rx2 - rx1) * (ry2 - ry1), 0.0)
    return inter / jnp.maximum(area_l + area_r - inter, 1e-12)


@register_op("_contrib_bipartite_matching", aliases=["bipartite_matching"],
             no_grad=True, num_outputs=2)
def bipartite_matching(data, is_ascend=False, threshold=0.5, topk=-1, **kw):
    """Greedy bipartite matching on a score matrix
    (src/operator/contrib/bounding_box.cc BipartiteMatching). data
    (..., N, M); returns (row_match (..., N), col_match (..., M))."""
    scores = data
    batched = scores.ndim > 2
    if not batched:
        scores = scores[None]
    flat = scores.reshape(scores.shape[0], -1)
    N, M = scores.shape[-2], scores.shape[-1]
    order = jnp.argsort(flat, axis=-1)
    if not is_ascend:
        order = order[:, ::-1]
    k = order.shape[1] if topk is None or topk <= 0 \
        else min(int(topk) * max(N, M), order.shape[1])

    def match_one(score_f, order_row):
        def body(i, carry):
            row_m, col_m = carry
            idx = order_row[i]
            r, c = idx // M, idx % M
            s = score_f[idx]
            ok = (row_m[r] < 0) & (col_m[c] < 0) & \
                ((s < threshold) if is_ascend else (s > threshold))
            row_m = row_m.at[r].set(jnp.where(ok, c, row_m[r]))
            col_m = col_m.at[c].set(jnp.where(ok, r, col_m[c]))
            return row_m, col_m

        init = (-jnp.ones((N,), jnp.float32), -jnp.ones((M,), jnp.float32))
        row_m, col_m = jax.lax.fori_loop(0, k, body, init)
        return row_m, col_m

    row_m, col_m = jax.vmap(match_one)(flat, order)
    if not batched:
        row_m, col_m = row_m[0], col_m[0]
    else:
        row_m = row_m.reshape(data.shape[:-2] + (N,))
        col_m = col_m.reshape(data.shape[:-2] + (M,))
    return row_m, col_m


def _sparse_embedding_fwd(data, weight, input_dim=None, output_dim=None,
                          dtype="float32", sparse_grad=True, **kw):
    """Reference: src/operator/tensor/indexing_op.cc SparseEmbedding.
    Forward is the same gather as dense Embedding; the custom VJP
    (sparse/embedding.py) dedups the backward to unique rows via
    segment-sum — one (n, dim) scatter instead of one per occurrence.
    The fused Module step detects these nodes and never materializes
    the dense (vocab, dim) cotangent at all (row-sparse routing)."""
    from ..sparse.embedding import sparse_embedding
    return sparse_embedding(data, weight)


register_op("_contrib_SparseEmbedding",
            aliases=["SparseEmbedding"])(_sparse_embedding_fwd)


def _sparse_segment_sum(data, segment_ids, num_segments=None, **kw):
    """Row dedup building block (sparse/rowsparse.py): sums data rows
    into num_segments buckets. Registered so the numerical-gradient
    sweep (tools/op_grad_cases.py) covers the segment-sum the
    SparseEmbedding backward is built from."""
    from ..sparse.rowsparse import segment_rows
    n = int(num_segments) if num_segments is not None \
        else int(data.shape[0])
    return segment_rows(data, segment_ids, n)


register_op("_contrib_sparse_segment_sum")(_sparse_segment_sum)


# ---------------------------------------------------------------------------
# INT8 quantization family (src/operator/quantization/*.cc), backed by the
# same arithmetic as contrib.quantization
# ---------------------------------------------------------------------------

def _qscale(min_range, max_range):
    return 127.0 / jnp.maximum(jnp.maximum(jnp.abs(min_range),
                                           jnp.abs(max_range)), 1e-12)


@register_op("_contrib_quantize", aliases=["quantize"], no_grad=True,
             num_outputs=3)
def contrib_quantize(data, min_range, max_range, out_type="int8", **kw):
    scale = _qscale(min_range, max_range)
    q = jnp.clip(jnp.round(data * scale), -127, 127).astype(jnp.int8)
    amax = jnp.maximum(jnp.abs(min_range), jnp.abs(max_range))
    return q, -amax, amax


@register_op("_contrib_dequantize", aliases=["dequantize"], no_grad=True)
def contrib_dequantize(data, min_range, max_range, out_type="float32", **kw):
    scale = _qscale(min_range, max_range)
    return data.astype(jnp.float32) / scale


@register_op("_contrib_requantize", aliases=["requantize"], no_grad=True,
             num_outputs=3)
def contrib_requantize(data, min_range, max_range, min_calib_range=None,
                       max_calib_range=None, **kw):
    """int32 accumulator -> int8 with calibrated range
    (src/operator/quantization/requantize.cc)."""
    real = data.astype(jnp.float32) * \
        jnp.maximum(jnp.abs(min_range), jnp.abs(max_range)) / (127.0 * 127.0)
    if min_calib_range is None:
        max_calib_range = jnp.max(jnp.abs(real))
        min_calib_range = -max_calib_range
    scale = _qscale(jnp.asarray(min_calib_range), jnp.asarray(max_calib_range))
    q = jnp.clip(jnp.round(real * scale), -127, 127).astype(jnp.int8)
    return q, jnp.asarray(min_calib_range, jnp.float32), \
        jnp.asarray(max_calib_range, jnp.float32)


@register_op("_contrib_quantized_flatten", aliases=["quantized_flatten"],
             no_grad=True, num_outputs=3)
def quantized_flatten(data, min_data, max_data, **kw):
    return data.reshape(data.shape[0], -1), min_data, max_data


@register_op("_contrib_quantized_pooling", aliases=["quantized_pooling"],
             no_grad=True, num_outputs=3)
def quantized_pooling(data, min_data, max_data, **kw):
    pooled = get_op("Pooling").fn(data.astype(jnp.float32), **kw)
    if kw.get("pool_type", "max") == "max":
        pooled = pooled.astype(data.dtype)
    else:
        pooled = jnp.clip(jnp.round(pooled), -127, 127).astype(data.dtype)
    return pooled, min_data, max_data


@register_op("_contrib_quantized_fully_connected",
             aliases=["quantized_fully_connected"], no_grad=True,
             num_outputs=3)
def quantized_fully_connected(data, weight, bias=None, min_data=None, max_data=None,
                              min_weight=None, max_weight=None,
                              min_bias=None, max_bias=None, num_hidden=None, no_bias=False,
                              flatten=True, **kw):
    """int8 x int8 -> int32 MXU matmul (quantized_fully_connected.cc)."""
    x = data.reshape(data.shape[0], -1) if flatten else data
    acc = jax.lax.dot_general(
        x.astype(jnp.int8), weight.astype(jnp.int8).T,
        (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    out_absmax = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) * \
        jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight))
    if not no_bias and bias is not None:
        # bias arrives int8 with its own range; rescale into the
        # accumulator's scale (127*127 / (|d| * |w|))
        b_absmax = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias))
        b_real = bias.astype(jnp.float32) * b_absmax / 127.0
        acc = acc + jnp.round(b_real * (127.0 * 127.0) /
                              jnp.maximum(out_absmax, 1e-12)
                              ).astype(jnp.int32)
    return acc, -out_absmax, out_absmax


@register_op("_contrib_quantized_conv", aliases=["quantized_conv"],
             no_grad=True, num_outputs=3)
def quantized_conv(data, weight, bias=None, min_data=None, max_data=None,
                   min_weight=None, max_weight=None,
                   min_bias=None, max_bias=None, kernel=None,
                   stride=None, pad=None, num_filter=None, no_bias=False,
                   **kw):
    """int8 conv with int32 accumulation on the MXU
    (quantized_conv.cc; cf. contrib.quantization._int8_conv)."""
    ks = tuple(kernel)
    strides = tuple(stride) if stride else (1,) * len(ks)
    pads = tuple(pad) if pad else (0,) * len(ks)
    acc = jax.lax.conv_general_dilated(
        data.astype(jnp.int8), weight.astype(jnp.int8),
        window_strides=strides,
        padding=[(p, p) for p in pads],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=jnp.int32)
    out_absmax = jnp.maximum(jnp.abs(min_data), jnp.abs(max_data)) * \
        jnp.maximum(jnp.abs(min_weight), jnp.abs(max_weight))
    if not no_bias and bias is not None:
        b_absmax = jnp.maximum(jnp.abs(min_bias), jnp.abs(max_bias))
        b_real = bias.astype(jnp.float32) * b_absmax / 127.0
        b_acc = jnp.round(b_real * (127.0 * 127.0) /
                          jnp.maximum(out_absmax, 1e-12)).astype(jnp.int32)
        acc = acc + b_acc.reshape((1, -1) + (1,) * len(ks))
    return acc, -out_absmax, out_absmax


# cuDNN-era alias
alias("BatchNorm", "CuDNNBatchNorm")
