"""Post-training quantization subsystem (round 19).

On a bandwidth-bound machine halving bytes IS the speedup (the step has
sat at ~114% of the HBM roofline since BENCH_r05), and quantization is
the largest untouched byte lever: int8 weights move a quarter of the
f32 bytes, and an int8 KV-cache halves-and-then-some the decode state
that every decode step re-reads. Two measured deliverables:

- **int8 weight PTQ as a graph pass** (symbol/passes/int8_ptq.py):
  :func:`calibrate` observes a module's conv/FC weights (per-channel
  absmax / percentile, :mod:`.observers`) into a :class:`QuantConfig`;
  under :func:`quant_scope` the ``int8_ptq`` pass rewrites enabled
  sites to ``dequantize(int8_weight) · scale`` with the scale derived
  IN-GRAPH from the current weights. Predictor hoisting then
  precomputes the int8 weight as a program argument while a
  ``__no_hoist__`` barrier on the dequantize keeps the f32 expansion
  inside the program — the serving program's weight traffic is int8,
  verified by the pass manager's measured bytes gate (Relay's
  quantization-as-graph-rewrite, arXiv:1810.00952, under our
  arXiv:2301.13062 cost-model verifier).
- **int8 KV-cache** for decode serving (serving/decode/):
  ``MXTPU_DECODE_KV_DTYPE=int8`` stores each cache row quantized with
  a per-(slot, position, head) f32 scale, dequantized at f32 compute.
  Per-row scales keep slot lanes independent, so continuous batching
  stays bit-identical to solo decode — the r16 pin, now under int8.

Observability: ``quant::`` telemetry (``mx.quant_report()``) and the
``tools/quant.py`` CLI (calibrate / show / verify).
"""
from __future__ import annotations

from .observers import (AbsMaxObserver, PercentileObserver, make_observer,
                        compute_scales, quantize_np, dequantize_np,
                        QMAX, SCALE_FLOOR)
from .calibrate import (QuantConfig, calibrate, find_sites, set_config,
                        current_config, quant_scope)

__all__ = ["AbsMaxObserver", "PercentileObserver", "make_observer",
           "compute_scales", "quantize_np", "dequantize_np", "QMAX",
           "SCALE_FLOOR", "QuantConfig", "calibrate", "find_sites",
           "set_config", "current_config", "quant_scope", "quant_report"]


def _collect(reset):
    from ..telemetry import registry as _treg
    snap = _treg.snapshot(reset=reset, prefix="quant::")
    out = {}
    for name, vals in snap.items():
        out[name.split("::", 1)[1]] = vals.get("value")
    return out


from ..telemetry import registry as _treg_mod  # noqa: E402

quant_report = _treg_mod.collector_view("quant", _collect)
