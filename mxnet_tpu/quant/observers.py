"""Calibration observers and the numpy quantization oracle.

The observers are pure numpy — calibration is a host-side, deterministic
analysis (no RNG, no device state), so the same module + iterator always
produce the same ``QuantConfig`` and the scale math here doubles as the
test oracle for the in-graph rewrite (tests/test_quant.py compares the
``int8_ptq`` pass's hoisted int8 weights against ``quantize_np``).

Two observers, per the classic PTQ split:

- ``AbsMaxObserver`` — symmetric absmax: the scale covers the full
  range of the tensor, nothing saturates, coarse under outliers.
- ``PercentileObserver`` — clips at the ``percentile``-th percentile of
  |w|; the handful of outlier weights saturate to ±127 and everything
  else gets a finer grid. The clip is carried as a scalar per-layer
  ``clip_fraction`` (clip point / global absmax) so the graph rewrite
  can re-derive the exact scale from the CURRENT weights (absmax ·
  clip_fraction / 127) — a reloaded checkpoint re-quantizes itself
  without a stale scale constant baked into the graph.

Granularity: ``per_channel`` reduces over every axis except the output
channel (axis 0 for both conv ``(O,I,kh,kw)`` and FullyConnected
``(O,I)`` weights), keepdims so the scale broadcasts back; ``per_tensor``
reduces everything to one scalar scale.
"""
from __future__ import annotations

import numpy as np

__all__ = ["AbsMaxObserver", "PercentileObserver", "make_observer",
           "compute_scales", "quantize_np", "dequantize_np",
           "QMAX", "SCALE_FLOOR"]

QMAX = 127.0
# floor keeps an all-zero channel from producing scale 0 → div-by-zero;
# the graph rewrite applies the same floor via _maximum_scalar
SCALE_FLOOR = 1e-12


def _reduce_axes(ndim: int, per_channel: bool, channel_axis: int = 0):
    if not per_channel:
        return tuple(range(ndim))
    return tuple(i for i in range(ndim) if i != channel_axis)


class AbsMaxObserver:
    """Symmetric absmax observer; ``clip_fraction`` is always 1.0."""

    kind = "absmax"

    def __init__(self, per_channel: bool = True, channel_axis: int = 0):
        self.per_channel = bool(per_channel)
        self.channel_axis = int(channel_axis)
        self._absmax = None

    def observe(self, arr):
        arr = np.asarray(arr, dtype=np.float32)
        axes = _reduce_axes(arr.ndim, self.per_channel, self.channel_axis)
        m = np.max(np.abs(arr), axis=axes, keepdims=True)
        self._absmax = m if self._absmax is None \
            else np.maximum(self._absmax, m)
        return self

    def absmax(self):
        if self._absmax is None:
            raise ValueError("observer has seen no data")
        return self._absmax

    def clip_fraction(self) -> float:
        return 1.0

    def scales(self):
        return np.maximum(
            self.absmax() * (self.clip_fraction() / QMAX),
            SCALE_FLOOR).astype(np.float32)


class PercentileObserver(AbsMaxObserver):
    """Clips at the ``percentile``-th percentile of |w| (whole tensor).

    The fraction is scalar per layer — the graph rewrite applies it to
    the per-channel absmax, so per-channel granularity still gets
    per-channel scales with one shared saturation posture.
    """

    kind = "percentile"

    def __init__(self, percentile: float = 99.9, per_channel: bool = True,
                 channel_axis: int = 0):
        super().__init__(per_channel=per_channel, channel_axis=channel_axis)
        self.percentile = float(percentile)
        self._clip = None

    def observe(self, arr):
        super().observe(arr)
        a = np.abs(np.asarray(arr, dtype=np.float32)).reshape(-1)
        c = float(np.percentile(a, self.percentile))
        self._clip = c if self._clip is None else max(self._clip, c)
        return self

    def clip_fraction(self) -> float:
        gmax = float(np.max(self.absmax()))
        if self._clip is None or gmax <= 0.0:
            return 1.0
        return min(1.0, max(self._clip / gmax, SCALE_FLOOR))


def make_observer(kind: str, per_channel: bool = True,
                  percentile: float = 99.9) -> AbsMaxObserver:
    k = str(kind).strip().lower()
    if k == "absmax":
        return AbsMaxObserver(per_channel=per_channel)
    if k == "percentile":
        return PercentileObserver(percentile=percentile,
                                  per_channel=per_channel)
    raise ValueError(f"unknown observer kind: {kind!r} "
                     "(expected 'absmax' or 'percentile')")


def compute_scales(w, per_channel: bool = True, clip_fraction: float = 1.0,
                   channel_axis: int = 0):
    """Scale tensor exactly as the in-graph rewrite derives it:
    ``max(absmax · clip_fraction / 127, floor)`` with keepdims so it
    broadcasts against the weight."""
    w = np.asarray(w, dtype=np.float32)
    axes = _reduce_axes(w.ndim, per_channel, channel_axis)
    amax = np.max(np.abs(w), axis=axes, keepdims=True)
    return np.maximum(amax * (float(clip_fraction) / QMAX),
                      SCALE_FLOOR).astype(np.float32)


def quantize_np(w, scale):
    """int8 weights under half-away-from-zero rounding — the symbol
    ``round`` op's convention (``sign·floor(|x|+0.5)``), NOT numpy's
    banker's rounding, so the oracle matches the graph bit-for-bit."""
    q = np.asarray(w, dtype=np.float32) / np.asarray(scale, np.float32)
    q = np.sign(q) * np.floor(np.abs(q) + 0.5)
    return np.clip(q, -QMAX, QMAX).astype(np.int8)


def dequantize_np(q, scale):
    return q.astype(np.float32) * np.asarray(scale, np.float32)
