"""Post-training calibration: weights + data -> ``QuantConfig``.

``calibrate(module, data_iter)`` is the user entry point: it walks the
module's symbol for quantizable sites (Convolution / FullyConnected
nodes whose weight is a bound parameter), runs the configured observer
over each weight, and — when a calibration iterator is supplied —
replays the batches through the graph twice (f32 vs simulated-quant
weights) to measure the end-to-end output error the quantization would
introduce. Everything is host-side numpy and deterministic: the same
module + iterator always yield byte-identical JSON.

The accuracy guard is per-layer: a layer whose weight-space relative L2
error exceeds ``tolerance`` (``MXTPU_QUANT_ACC_TOL``) is DISABLED in
the config — shipped exact rather than shipped wrong — and the reason
is recorded. The ``int8_ptq`` pass only rewrites enabled layers.

The config is AMBIENT for the pass pipeline: ``set_config`` /
``quant_scope`` install it process-wide, the pass reads
``current_config()`` at apply time and counts a ``no_quant_config``
skip when none is installed (which is why every pre-r19 test and
program is untouched by the new pass).
"""
from __future__ import annotations

import json
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

import numpy as np

from .observers import (make_observer, compute_scales, quantize_np,
                        dequantize_np)

__all__ = ["QuantConfig", "calibrate", "find_sites", "set_config",
           "current_config", "quant_scope"]

_QUANT_OPS = {"Convolution": "conv", "Convolution_v1": "conv",
              "FullyConnected": "fc"}


class QuantConfig:
    """Per-layer quantization decisions, keyed by the op node's BASE
    name (pass-era renames like ``{conv}__bnfold`` are stripped at
    lookup, so the config survives the bn_fold rewrite)."""

    def __init__(self, layers: Optional[Dict[str, dict]] = None,
                 granularity: str = "per_channel",
                 observer: str = "percentile",
                 tolerance: float = 0.02):
        self.layers = dict(layers or {})
        self.granularity = granularity
        self.observer = observer
        self.tolerance = float(tolerance)
        self.model_error = None

    def lookup(self, name: str) -> Optional[dict]:
        if name.endswith("__bnfold"):
            name = name[: -len("__bnfold")]
        return self.layers.get(name)

    def enabled_layers(self) -> List[str]:
        return [n for n, e in self.layers.items() if e.get("enabled")]

    def to_dict(self) -> dict:
        return {"granularity": self.granularity,
                "observer": self.observer,
                "tolerance": self.tolerance,
                "model_error": self.model_error,
                "layers": self.layers}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), indent=2, sort_keys=True)

    @classmethod
    def from_dict(cls, d: dict) -> "QuantConfig":
        cfg = cls(layers=d.get("layers", {}),
                  granularity=d.get("granularity", "per_channel"),
                  observer=d.get("observer", "percentile"),
                  tolerance=d.get("tolerance", 0.02))
        cfg.model_error = d.get("model_error")
        return cfg

    @classmethod
    def from_json(cls, text: str) -> "QuantConfig":
        return cls.from_dict(json.loads(text))

    def save(self, path: str):
        with open(path, "w") as f:
            f.write(self.to_json() + "\n")

    @classmethod
    def load(cls, path: str) -> "QuantConfig":
        with open(path) as f:
            return cls.from_json(f.read())


# ---------------------------------------------------------------------
# ambient config (what the int8_ptq pass reads at apply time)

_ACTIVE: List[Optional[QuantConfig]] = [None]


def set_config(cfg: Optional[QuantConfig]) -> Optional[QuantConfig]:
    """Install ``cfg`` as the process-wide quantization config;
    returns the previous one (pass ``None`` to clear)."""
    prev = _ACTIVE[0]
    _ACTIVE[0] = cfg
    return prev


def current_config() -> Optional[QuantConfig]:
    return _ACTIVE[0]


@contextmanager
def quant_scope(cfg: Optional[QuantConfig]):
    """Scoped ``set_config`` — the idiomatic way to stage a quantized
    Predictor: ``with mx.quant.quant_scope(cfg): pred = mod.as_predictor(...)``."""
    prev = set_config(cfg)
    try:
        yield cfg
    finally:
        set_config(prev)


# ---------------------------------------------------------------------
# site discovery + calibration

def find_sites(sym) -> List[Tuple[object, str, str]]:
    """Quantizable sites of a PRE-pipeline symbol: ``(node, kind,
    weight_var_name)`` for every conv/FC whose weight input is a plain
    variable (composite or derived weights calibrate after their own
    rewrites, at pass time, not here)."""
    out = []
    for n in sym._topo_nodes():
        kind = _QUANT_OPS.get(n.op)
        if kind is None or len(n.inputs) < 2:
            continue
        w, wi = n.inputs[1]
        if w.op is None and wi == 0:
            out.append((n, kind, w.name))
    return out


def _resolve_symbol_params(module):
    def _np(d):
        # Module.get_params() hands back NDArrays; the observers and
        # the eval_arrays_ex error probe both want host numpy
        # (np.asarray alone would produce a dtype=object scalar wrapper)
        return {k: np.asarray(v.asnumpy() if hasattr(v, "asnumpy")
                              else v)
                for k, v in (d or {}).items()}

    if isinstance(module, tuple) and len(module) == 2:
        sym, params = module
        return sym, _np(params), {}
    sym = getattr(module, "symbol", None)
    if sym is None or not hasattr(module, "get_params"):
        raise TypeError(
            "calibrate() wants a bound Module (or a (symbol, params) "
            f"tuple); got {type(module).__name__}")
    arg_params, aux_params = module.get_params()
    return sym, _np(arg_params), _np(aux_params)


def _batch_feed(batch, data_names) -> Dict[str, np.ndarray]:
    if isinstance(batch, dict):
        return {k: np.asarray(v) for k, v in batch.items()}
    data = getattr(batch, "data", None)
    if data is not None:
        feed = {}
        label = getattr(batch, "label", None) or []
        vals = list(data) + list(label)
        for name, v in zip(data_names, vals):
            feed[name] = np.asarray(v)
        return feed
    if isinstance(batch, (list, tuple)):
        return {n: np.asarray(v) for n, v in zip(data_names, batch)}
    return {data_names[0]: np.asarray(batch)}


def calibrate(module, data_iter=None, observer: Optional[str] = None,
              granularity: Optional[str] = None, percentile: float = 99.9,
              tolerance: Optional[float] = None,
              max_batches: int = 8) -> QuantConfig:
    """Calibrate ``module`` for int8 weight PTQ; returns a
    ``QuantConfig`` ready for ``quant_scope``.

    ``module``: a bound, initialized Module — or a ``(symbol,
    {name: array})`` tuple. ``data_iter``: optional iterable of
    calibration batches (dicts, DataBatches, arrays); used to measure
    the f32-vs-simulated-quant output error recorded as
    ``model_error``. ``observer``: ``"percentile"`` (default) or
    ``"absmax"``; ``granularity``: ``"per_channel"`` /
    ``"per_tensor"`` (default ``MXTPU_QUANT_GRANULARITY``);
    ``tolerance``: per-layer weight-error guard (default
    ``MXTPU_QUANT_ACC_TOL``)."""
    from .. import config as _config
    from ..telemetry import registry as _treg

    sym, arg_params, aux_params = _resolve_symbol_params(module)
    if granularity is None:
        granularity = str(_config.get("MXTPU_QUANT_GRANULARITY",
                                      "per_channel")).strip().lower()
    if granularity not in ("per_channel", "per_tensor"):
        raise ValueError(f"unknown granularity: {granularity!r}")
    if tolerance is None:
        tolerance = float(_config.get("MXTPU_QUANT_ACC_TOL", 0.02))
    obs_kind = (observer or "percentile").strip().lower()
    per_channel = granularity == "per_channel"

    cfg = QuantConfig(granularity=granularity, observer=obs_kind,
                      tolerance=tolerance)
    qweights: Dict[str, np.ndarray] = {}
    for node, kind, wname in find_sites(sym):
        w = arg_params.get(wname)
        if w is None:
            continue
        w = np.asarray(w, dtype=np.float32)
        ob = make_observer(obs_kind, per_channel=per_channel,
                           percentile=percentile).observe(w)
        frac = float(ob.clip_fraction())
        scale = compute_scales(w, per_channel=per_channel,
                               clip_fraction=frac)
        deq = dequantize_np(quantize_np(w, scale), scale)
        denom = float(np.linalg.norm(w.reshape(-1)))
        err = float(np.linalg.norm((deq - w).reshape(-1)) /
                    max(denom, 1e-12))
        enabled = err <= tolerance
        cfg.layers[node.name] = {
            "name": node.name, "kind": kind, "weight": wname,
            "granularity": granularity, "observer": obs_kind,
            "clip_fraction": frac,
            "absmax": float(np.max(ob.absmax())),
            "scales": [float(s) for s in scale.reshape(-1)],
            "error": err, "enabled": bool(enabled),
            "reason": "" if enabled else
            f"weight error {err:.6f} > tolerance {tolerance:g}",
        }
        if enabled:
            qweights[wname] = deq

    # end-to-end error over the calibration batches: the same program,
    # f32 weights vs simulated-quant weights, relative L2 on outputs
    if data_iter is not None and qweights:
        data_names = [a for a in sym.list_arguments()
                      if a not in arg_params]
        base = dict(arg_params)
        base.update(aux_params)
        errs = []
        for bi, batch in enumerate(data_iter):
            if bi >= max_batches:
                break
            feed = _batch_feed(batch, data_names)
            amap = dict(base)
            amap.update(feed)
            outs_f, _ = sym.eval_arrays_ex(amap, training=False)
            amap_q = dict(amap)
            amap_q.update(qweights)
            outs_q, _ = sym.eval_arrays_ex(amap_q, training=False)
            for of, oq in zip(outs_f, outs_q):
                of = np.asarray(of, dtype=np.float32).reshape(-1)
                oq = np.asarray(oq, dtype=np.float32).reshape(-1)
                errs.append(float(np.linalg.norm(oq - of) /
                                  max(float(np.linalg.norm(of)), 1e-12)))
        if errs:
            cfg.model_error = float(np.mean(errs))

    _treg.counter("quant::calibrations").inc()
    _treg.counter("quant::layers_total").inc(len(cfg.layers))
    _treg.counter("quant::layers_enabled").inc(len(cfg.enabled_layers()))
    if cfg.model_error is not None:
        _treg.gauge("quant::model_error").set(cfg.model_error)
    return cfg
