"""mxnet_tpu: a TPU-native deep learning framework with MXNet's capabilities.

A ground-up rebuild of Apache MXNet (~v1.1) for TPU: JAX/XLA is the execution
engine (replacing the dependency engine + graph executor + kernel library,
reference: src/engine, src/executor, src/operator), ``jax.sharding`` over
device meshes replaces KVStore/ps-lite/NCCL (reference: src/kvstore), and the
imperative/symbolic/Gluon API surfaces are re-implemented natively on top.

Usage mirrors the reference:

    import mxnet_tpu as mx
    x = mx.nd.zeros((2, 3), ctx=mx.tpu(0))
    with mx.autograd.record():
        y = (x + 1).sum()
    y.backward()
"""
__version__ = "0.1.0"

from . import base
from .base import MXNetError
from .context import Context, cpu, gpu, tpu, current_context, num_gpus, num_tpus
from . import operator  # registers the Custom op before nd codegen runs
from . import ndarray
from . import ndarray as nd
from . import autograd
from . import random
from .random import seed
from . import name
from . import attribute
from .attribute import AttrScope
from . import symbol
from . import symbol as sym
from .symbol import Symbol
from .symbol.fusion import fusion_report
from .symbol.passes import pass_report
from . import executor
from .executor import Executor
from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import io
from . import recordio
from . import image
from .io_native import CSVIter, LibSVMIter
from . import kvstore
from . import kvstore as kv
from . import callback
from . import model
from . import module
from . import module as mod
from . import monitor
from . import monitor as mon
from . import telemetry
from .telemetry import memory_report
from . import profiler
from . import rtc
from . import config
from . import engine
from . import runtime
from . import kvstore_server
from . import test_utils
from . import visualization
from . import visualization as viz
from . import serving
from .serving import serving_report
from . import fault
from .fault import fault_report
from . import data
from .data import data_report
from . import faultinject
from . import compile  # noqa: A004 — package named for mxnet_tpu.compile
from .compile import compile_report
from . import checkpoint
from .checkpoint import CheckpointManager
from . import sparse
from .sparse import sparse_report
from . import tune
from .tune import tune_report
from . import quant
from .quant import quant_report
from . import contrib
from . import gluon
from . import rnn
from . import parallel
from .io import DataBatch, DataIter
