"""The trial runner: deterministic measured search over a SearchSpace.

One search = one pass over the space's deterministic trial sequence
(``SearchSpace.configs(seed)``), in three stages:

1. **Static pruning** — the workload's ``static(cfg)`` hook judges a
   configuration from compile-time analysis alone (XLA cost-analysis
   bytes, ``memory_analysis()`` peak HBM vs. the headroom budget) and
   returns a prune reason or None. Pruned configs are recorded (status
   ``pruned``) and never measured — the cheap gate in front of the
   expensive one.
2. **Measured trials** — ``measure(cfg, budget)`` returns the objective
   (lower is better; a dict return carries extra metrics under the
   ``"objective"`` key). Env-kind knobs are applied around the call via
   ``config.override`` and the pass manager's measurement memo is
   scoped per trial (``measure_memo_scope``) so no trial ever reuses a
   measurement taken under another flag regime. A failing trial
   (``MXTPU_PALLAS_TILES`` rejecting a bad tile, an OOM'd compile) is
   recorded ``failed`` and the search continues — a bad configuration
   fails the TRIAL, never the process.
3. **Successive halving** — above ``halving_threshold`` surviving
   configs, trials run in rungs: everyone is measured at a small
   budget, the best ``1/eta`` graduate to an ``eta``-times larger
   budget, until the survivors fit one exhaustive final rung. Small
   spaces skip straight to exhaustive full-budget measurement.

Crash safety: each completed trial is committed to the
:class:`~.record.TrialJournal` as it finishes; the ``tune_trial``
faultinject site is consulted at that commit boundary (``trial=N``,
``action=kill`` is the SIGKILL-mid-search drill). A resumed search
replays journaled results (status ``reused``) instead of re-measuring.
"""
from __future__ import annotations

import math
import time
from typing import Callable, Dict, List, Optional

from .. import config as _config
from .. import faultinject
from ..symbol.passes.manager import measure_memo_scope
from .space import SearchSpace

__all__ = ["Trial", "TrialRunner"]


class Trial:
    """One configuration's outcome within a search."""

    __slots__ = ("config", "config_id", "status", "objective", "budget",
                 "reason", "metrics", "wall_s")

    def __init__(self, config, config_id, status="pending",
                 objective=None, budget=0, reason=None, metrics=None,
                 wall_s=0.0):
        self.config = dict(config)
        self.config_id = config_id
        self.status = status          # pruned | measured | reused | failed
        self.objective = objective
        self.budget = budget
        self.reason = reason
        self.metrics = dict(metrics or {})
        self.wall_s = wall_s

    def to_entry(self) -> dict:
        """The journal/report serialization."""
        return {"config": self.config, "config_id": self.config_id,
                "status": self.status, "objective": self.objective,
                "budget": self.budget, "reason": self.reason,
                "metrics": self.metrics, "wall_s": self.wall_s}

    @classmethod
    def from_entry(cls, e: dict) -> "Trial":
        return cls(e["config"], e["config_id"], e.get("status", "?"),
                   e.get("objective"), e.get("budget", 0),
                   e.get("reason"), e.get("metrics"),
                   e.get("wall_s", 0.0))

    def __repr__(self):
        return (f"Trial({self.config_id}, {self.status}, "
                f"objective={self.objective})")


class TrialRunner:
    """See module docstring.

    ``measure(cfg, budget)`` -> objective float (or dict with an
    ``"objective"`` key); ``static(cfg)`` -> prune-reason string or
    None. ``budget`` starts at ``base_budget`` repeats/steps and grows
    by ``eta`` per halving rung up to ``full_budget``.
    """

    def __init__(self, space: SearchSpace, measure: Callable, *,
                 static: Optional[Callable] = None, seed: int = 0,
                 max_trials: Optional[int] = None, eta: int = 2,
                 halving_threshold: int = 8, base_budget: int = 1,
                 full_budget: int = 4,
                 journal=None, on_trial: Optional[Callable] = None,
                 name: str = "search"):
        self.space = space
        self.measure = measure
        self.static = static
        self.seed = int(seed)
        if max_trials is None:
            max_trials = int(_config.get("MXTPU_TUNE_MAX_TRIALS", 0))
        self.max_trials = int(max_trials)
        self.eta = max(2, int(eta))
        self.halving_threshold = max(1, int(halving_threshold))
        self.base_budget = max(1, int(base_budget))
        self.full_budget = max(self.base_budget, int(full_budget))
        self.journal = journal
        self.on_trial = on_trial
        self.name = name
        self.trials: List[Trial] = []
        self._ordinal = 0           # tune_trial site coordinate

    # -- one measured trial ---------------------------------------------------
    def _applied(self, cfg):
        """Env-kind knobs as a stack of config.override scopes."""
        import contextlib
        stack = contextlib.ExitStack()
        for name, value in self.space.env_items(cfg):
            stack.enter_context(_config.override(
                name, None if value in (None, "") else value))
        return stack

    def _run_one(self, trial: Trial, budget: int):
        from . import _note
        t0 = time.time()
        try:
            with self._applied(trial.config), measure_memo_scope():
                out = self.measure(trial.config, budget)
            if isinstance(out, dict):
                trial.metrics = {k: v for k, v in out.items()
                                 if k != "objective"}
                out = out["objective"]
            trial.objective = float(out)
            trial.status = "measured"
            trial.budget = budget
            _note("trials_run")
        except Exception as e:
            trial.status = "failed"
            trial.reason = repr(e)
            trial.objective = None
            _note("trials_failed")
        trial.wall_s = time.time() - t0
        self._commit(trial)

    def _commit(self, trial: Trial):
        """The per-trial durability boundary: consult the tune_trial
        fault site (the kill-mid-search drill lands here, between a
        finished measurement and its journal line), then journal."""
        self._ordinal += 1
        params = faultinject.active("tune_trial")
        if params is not None and "trial" in params and \
                faultinject.fire("tune_trial", trial=self._ordinal):
            # byte=/bytes= arm the record WRITE (record.py), not this
            # boundary — only a trial= coordinate belongs to the commit
            raise faultinject.FaultInjected("tune_trial",
                                            trial=self._ordinal)
        if self.journal is not None:
            self.journal.append(trial.to_entry())
        if self.on_trial is not None:
            self.on_trial(trial)

    # -- the search loop ------------------------------------------------------
    def search(self):
        """Run the search; returns (best measured Trial or None, all
        trials). Deterministic for a fixed (space, seed, journal
        state)."""
        from . import _note
        t0 = time.time()
        configs = self.space.configs(self.seed, self.max_trials)
        done: Dict[str, dict] = {}
        if self.journal is not None:
            for e in self.journal.load():
                done[e["config_id"]] = e

        candidates: List[Trial] = []
        for cfg in configs:
            cid = self.space.config_id(cfg)
            trial = Trial(cfg, cid)
            self.trials.append(trial)
            prev = done.get(cid)
            if prev is not None and prev.get("status") in ("measured",
                                                           "pruned",
                                                           "failed"):
                # resume: replay the journaled outcome, never re-measure
                trial.status = "reused"
                trial.objective = prev.get("objective")
                trial.budget = prev.get("budget", 0)
                trial.reason = prev.get("reason")
                trial.metrics = dict(prev.get("metrics") or {})
                _note("trials_reused")
                if self.on_trial is not None:
                    self.on_trial(trial)
                if prev.get("status") == "measured":
                    candidates.append(trial)
                continue
            if self.static is not None:
                try:
                    with self._applied(cfg), measure_memo_scope():
                        reason = self.static(cfg)
                except Exception as e:
                    reason = f"static analysis failed: {e!r}"
                if reason:
                    trial.status = "pruned"
                    trial.reason = str(reason)
                    _note("trials_pruned")
                    self._commit(trial)
                    continue
            candidates.append(trial)

        pending = [t for t in candidates if t.status == "pending"]
        if len(pending) > self.halving_threshold:
            self._halving(pending)
        else:
            for t in pending:
                self._run_one(t, self.full_budget)

        measured = [t for t in self.trials
                    if t.status in ("measured", "reused")
                    and t.objective is not None]
        best = min(measured, key=lambda t: t.objective, default=None)
        from ..telemetry import registry as _treg
        _treg.gauge(f"tune::{self.name}::search_wall_s").set(
            time.time() - t0)
        return best, self.trials

    def _halving(self, pending: List[Trial]):
        """Successive halving: measure every survivor at the rung's
        budget, keep the best ceil(n/eta) for the next, eta-times
        larger, budget; reused trials keep their journaled objective
        and compete without re-measuring."""
        budget = self.base_budget
        rung = pending
        while len(rung) > self.halving_threshold and \
                budget < self.full_budget:
            for t in rung:
                if t.status == "pending":
                    self._run_one(t, budget)
            alive = sorted(
                (t for t in rung if t.objective is not None),
                key=lambda t: t.objective)
            rung = alive[:max(1, math.ceil(len(alive) / self.eta))]
            budget = min(self.full_budget, budget * self.eta)
        for t in rung:
            if t.status == "pending" or (t.status == "measured"
                                         and t.budget < self.full_budget):
                self._run_one(t, self.full_budget)
