"""Tunable workloads: what a search measures, keyed like a program.

A :class:`Workload` binds a :class:`~.space.SearchSpace` to a concrete
measurement — it owns the canonical cache key (built through
``compile.program_key`` with kind ``"tune"``, so a tuning record is
keyed by the same material as the compiled programs it selects: symbol
digest, input shapes, optimizer, mesh, backend identity, plus the
space and objective), the static-pruning hook, and the ``measure``
function the trial runner drives.

Three measurement families, all reusing machinery that already exists:

- :class:`TrainStepWorkload` — objective ``step_bytes_per_row``: XLA
  cost-analysis bytes-accessed of the train-step proxy
  (``passes.measure_symbol_bytes`` — the same gate currency as r12)
  after running the pass pipeline under the trial's flag regime,
  normalized per batch row. Compile-time, deterministic, CPU-proxy
  friendly. Static pruning bounds the batch knob by peak-HBM headroom
  (``memory_analysis()`` of the compiled proxy vs.
  ``MXTPU_TUNE_HBM_BUDGET``).
- :class:`ServingWorkload` — objective ``p99_ms`` at a fixed
  closed-loop load (``serving/loadgen.py`` through a DynamicBatcher —
  the ONE closed-loop measurement implementation, shared with
  ``tools/serving_bench.py``) over bucket-set × ``max_wait_us`` knobs.
- :class:`DataPipelineWorkload` — objective ``wall_s_per_batch`` to
  drain N batches through a ``DataPipeline`` under the trial's
  ``MXTPU_DATA_WORKERS`` / ``MXTPU_DATA_STAGE_AHEAD``.

``conv_proxy()`` / ``sparse_proxy()`` are the built-in CPU-proxy
workloads (the conv family's BN→ReLU→1×1-conv tower and the sparse
family's two-tower embedding+conv recommender) shared by ``bench.py
tuned_vs_default``, ``tools/tune.py``, and the tier-1 tests.
"""
from __future__ import annotations

from typing import Dict, Optional, Sequence

from .space import SearchSpace, Knob, pass_knobs, batch_knob, \
    serving_knobs, data_knobs, decode_knobs, quant_knobs, spec_knobs

__all__ = ["Workload", "TrainStepWorkload", "ServingWorkload",
           "DecodeServingWorkload", "DataPipelineWorkload",
           "QuantWorkload", "SpecDecodeWorkload", "conv_proxy",
           "sparse_proxy", "decode_proxy", "quant_proxy",
           "spec_decode_proxy", "builtin_workload", "measure_serving",
           "measure_decode_serving", "BUILTIN_WORKLOADS"]


class Workload:
    """Base: a named, keyed, measurable search target."""

    name = "workload"
    objective = "objective"
    builtin: Optional[str] = None    # tools/tune.py rebuild tag

    def __init__(self, space: SearchSpace):
        self.space = space

    def key(self):
        """Canonical ProgramKey (kind "tune") — see module docstring."""
        from ..compile import program_key
        return program_key("tune", f"tune:{self.name}",
                           **self.key_material())

    def key_material(self) -> dict:
        return {"extra": {"space": self.space.describe(),
                          "objective": self.objective,
                          "builtin": self.builtin}}

    def static(self, cfg: Dict) -> Optional[str]:
        """Prune reason from compile-time analysis, or None."""
        return None

    def measure(self, cfg: Dict, budget: int) -> float:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# train step: bytes-accessed objective over pass flags / tiles / batch
# ---------------------------------------------------------------------------
class TrainStepWorkload(Workload):
    """See module docstring. ``feed_shapes`` are the data/label feed
    shapes WITHOUT the batch dimension resolved per trial when a
    ``batch`` knob is present — they are given at the default batch and
    rescaled along axis 0."""

    objective = "step_bytes_per_row"

    def __init__(self, name, symbol, feed_shapes: Dict[str, tuple],
                 space: SearchSpace, optimizer=None, mesh=None,
                 batch_axis: int = 0, hbm_budget: Optional[int] = None):
        super().__init__(space)
        self.name = name
        self.symbol = symbol
        self.feed_shapes = {n: tuple(s) for n, s in feed_shapes.items()}
        self.optimizer = optimizer
        self.mesh = mesh
        self.batch_axis = int(batch_axis)
        self.hbm_budget = hbm_budget
        self.default_batch = next(iter(self.feed_shapes.values())
                                  )[self.batch_axis]

    def key_material(self):
        from ..compile.key import symbol_digest
        m = super().key_material()
        m.update(symbol_sha=symbol_digest(self.symbol),
                 input_sigs=sorted(self.feed_shapes.items()),
                 optimizer=self.optimizer, mesh=self.mesh)
        return m

    # -- shape plumbing -------------------------------------------------------
    def _shapes(self, cfg) -> Dict[str, tuple]:
        """Full arg+aux shape map at the trial's batch size."""
        batch = int(cfg.get("batch", self.default_batch))
        kw = {}
        for n, s in self.feed_shapes.items():
            s = list(s)
            s[self.batch_axis] = batch
            kw[n] = tuple(s)
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(**kw)
        shapes = dict(zip(self.symbol.list_arguments(), arg_shapes))
        shapes.update(zip(self.symbol.list_auxiliary_states(),
                          aux_shapes))
        return shapes

    def _pipeline(self, cfg):
        """The trial's rewritten graph (or the original when no pass
        fired) under the already-applied env regime."""
        from ..symbol import passes as P
        shapes = self._shapes(cfg)
        final, _rep = P.apply_pipeline(self.symbol, shapes, tag="tune",
                                       mode="train")
        return (final if final is not None else self.symbol), shapes

    # -- static pruning: peak-HBM headroom ------------------------------------
    def _budget_bytes(self):
        from .. import config as _config
        if self.hbm_budget is not None:
            return int(self.hbm_budget)
        return int(_config.get("MXTPU_TUNE_HBM_BUDGET", 0))

    def static(self, cfg):
        budget = self._budget_bytes()
        if not budget or "batch" not in cfg:
            return None
        if int(cfg["batch"]) == self.default_batch:
            return None           # the baseline is never pruned away
        peak = self.static_peak_bytes(cfg)
        if peak is not None and peak > budget:
            return (f"peak HBM {peak} > budget {budget} at "
                    f"batch={cfg['batch']}")
        return None

    def static_peak_bytes(self, cfg):
        """``memory_analysis()`` peak of the compiled train-step proxy
        at the trial's batch (None when the backend exposes none)."""
        try:
            import jax
            import numpy as np
            from ..executor import build_graph_fns
            from ..telemetry import memory as _tmem
            sym, shapes = self._pipeline(cfg)
            arg_names = sym.list_arguments()
            aux_names = sym.list_auxiliary_states()
            if any(n not in shapes for n in arg_names + aux_names):
                return None

            def sds(n):
                return jax.ShapeDtypeStruct(tuple(shapes[n]),
                                            np.float32)

            fwd, fwd_loss, _ = build_graph_fns(sym)

            def fn(arg_vals, aux_vals, key):
                return jax.grad(fwd_loss, argnums=0, has_aux=True)(
                    arg_vals, aux_vals, None, key)

            exe = jax.jit(fn).lower(
                tuple(sds(n) for n in arg_names),
                tuple(sds(n) for n in aux_names),
                jax.random.PRNGKey(0)).compile()
            mem = _tmem.analyze(exe)
            return mem.get("peak_bytes") or None
        except Exception:
            return None

    # -- the measured objective -----------------------------------------------
    def measure(self, cfg, budget):
        from ..base import MXNetError
        from ..symbol.passes import measure_symbol_bytes
        sym, shapes = self._pipeline(cfg)
        by = measure_symbol_bytes(sym, shapes, mode="train")
        if by is None:
            raise MXNetError(
                f"{self.name}: backend exposes no cost analysis — the "
                "bytes objective cannot be measured")
        batch = int(cfg.get("batch", self.default_batch))
        return {"objective": by / batch, "step_bytes": by,
                "batch": batch}


# ---------------------------------------------------------------------------
# serving: closed-loop p99 over bucket sets × coalescing windows
# ---------------------------------------------------------------------------
def measure_serving(predictor, feat, max_wait_us, clients, per_client=8,
                    timeout=600):
    """THE closed-loop serving measurement: single-row clients through
    a DynamicBatcher over ``predictor``, plus the RAW compiled predict
    rate at the top bucket for the efficiency column. Shared verbatim
    by :class:`ServingWorkload` and ``tools/serving_bench.py``."""
    import numpy as np
    from .. import serving
    from ..serving import loadgen
    rng = np.random.RandomState(0)
    top = predictor.max_batch
    x_top = rng.rand(top, *feat).astype(np.float32)
    predictor.warmup()
    raw_rows_s = loadgen.raw_predict_rate(predictor, x_top, steps=8)
    with serving.DynamicBatcher(predictor, max_wait_us=max_wait_us,
                                max_queue=100_000,
                                name=f"tune{max_wait_us}") as bat:
        x1 = rng.rand(1, *feat).astype(np.float32)
        bat.predict(x1)
        r = loadgen.closed_loop(bat, x1, clients, per_client,
                                timeout=timeout)
        rep = bat.report()
    hot = max(rep["per_bucket"].items(),
              key=lambda kv: kv[1]["batches"] or 0)
    return {
        "objective": r["p99_ms"],
        "rows_s": r["rows_s"],
        "p50_ms": r["p50_ms"],
        "p99_ms": r["p99_ms"],
        "raw_rows_s": raw_rows_s,
        "efficiency": r["rows_s"] / raw_rows_s if raw_rows_s else None,
        "hot_bucket": hot[0],
        "occupancy": hot[1]["occupancy"],
        "retraces": predictor.retraces,
    }


class ServingWorkload(Workload):
    """Bucket-set × ``max_wait_us`` search for a Predictor behind a
    DynamicBatcher. ``make_predictor(buckets)`` builds the Predictor
    for one bucket set (the expensive, per-bucket-set half);
    measurement is :func:`measure_serving` at a fixed closed-loop load.
    ``budget`` scales the per-client request count."""

    objective = "p99_ms"

    def __init__(self, name, make_predictor, feat,
                 bucket_sets: Sequence[str], waits: Sequence[int],
                 space: Optional[SearchSpace] = None,
                 clients: int = 8, per_client: int = 4,
                 symbol=None):
        space = space or SearchSpace(serving_knobs(bucket_sets, waits),
                                     name=f"{name}-serving")
        super().__init__(space)
        self.name = name
        self.make_predictor = make_predictor
        self.feat = tuple(feat)
        self.clients = int(clients)
        self.per_client = int(per_client)
        self.symbol = symbol
        self._cache = {}

    def key_material(self):
        m = super().key_material()
        if self.symbol is not None:
            from ..compile.key import symbol_digest
            m["symbol_sha"] = symbol_digest(self.symbol)
        m["input_sigs"] = [("feat", self.feat),
                           ("clients", self.clients),
                           ("per_client", self.per_client)]
        return m

    def _predictor(self, buckets_spec):
        if buckets_spec not in self._cache:
            buckets = tuple(int(b) for b in
                            str(buckets_spec).split(","))
            self._cache[buckets_spec] = self.make_predictor(buckets)
        return self._cache[buckets_spec]

    def measure(self, cfg, budget):
        pred = self._predictor(cfg["buckets"])
        return measure_serving(pred, self.feat,
                               int(cfg["max_wait_us"]), self.clients,
                               per_client=self.per_client * max(1, budget))


# ---------------------------------------------------------------------------
# decode serving: token-SLO objective over slots × seq buckets × window
# ---------------------------------------------------------------------------
def measure_decode_serving(predictor, prompts, max_wait_us, clients,
                           per_client=2, max_new_tokens=6, timeout=600):
    """THE closed-loop decode measurement: streaming clients through a
    DecodeBatcher over ``predictor`` (``loadgen.token_closed_loop``,
    the one token-granularity driver — shared with
    ``tools/serving_bench.py --decode`` and the bench section). The
    objective folds both token SLOs into one end-to-end generation p99
    proxy: ``ttft_p99 + max_new_tokens * inter_token_p99``."""
    from ..serving import loadgen
    from ..serving.decode import DecodeBatcher
    predictor.warmup()
    with DecodeBatcher(predictor, max_wait_us=max_wait_us,
                       max_queue=100_000,
                       name=f"tune-decode{max_wait_us}") as bat:
        r = loadgen.token_closed_loop(
            bat, prompts, clients, per_client,
            max_new_tokens=max_new_tokens, timeout=timeout)
        rep = bat.report()
    ttft99 = r["ttft_p99_ms"] or 0.0
    itl99 = r["inter_token_p99_ms"] or 0.0
    return {
        "objective": ttft99 + max_new_tokens * itl99,
        "tok_s": r["tok_s"],
        "ttft_p50_ms": r["ttft_p50_ms"],
        "ttft_p99_ms": r["ttft_p99_ms"],
        "inter_token_p50_ms": r["inter_token_p50_ms"],
        "inter_token_p99_ms": r["inter_token_p99_ms"],
        "tokens": r["tokens"],
        "served_generations": rep["served_generations"],
        "retraces": predictor.retraces,
    }


class DecodeServingWorkload(Workload):
    """Slots × seq-bucket-set × first-fill-window search for a
    DecodePredictor behind a DecodeBatcher. ``make_engine(slots,
    seq_buckets)`` builds the engine for one (lanes, bucket-set) point —
    the expensive compile half, cached per point; measurement is
    :func:`measure_decode_serving` at a fixed streaming load (``budget``
    scales the per-client generation count)."""

    objective = "gen_p99_proxy_ms"

    def __init__(self, name, make_engine, prompts,
                 slot_counts: Sequence[int],
                 bucket_sets: Sequence[str], waits: Sequence[int],
                 space: Optional[SearchSpace] = None,
                 clients: int = 4, per_client: int = 2,
                 max_new_tokens: int = 6, spec=None):
        space = space or SearchSpace(
            decode_knobs(slot_counts, bucket_sets, waits),
            name=f"{name}-decode")
        super().__init__(space)
        self.name = name
        self.make_engine = make_engine
        self.prompts = list(prompts)
        self.clients = int(clients)
        self.per_client = int(per_client)
        self.max_new_tokens = int(max_new_tokens)
        self.spec = spec
        self._cache = {}

    def key_material(self):
        m = super().key_material()
        if self.spec is not None:
            m["extra"] = dict(m["extra"], **self.spec.key_material())
        m["input_sigs"] = [
            ("prompt_lens", tuple(int(p.shape[0]) for p in self.prompts)),
            ("clients", self.clients),
            ("per_client", self.per_client),
            ("max_new_tokens", self.max_new_tokens)]
        return m

    def _engine(self, slots, buckets_spec):
        key = (int(slots), str(buckets_spec))
        if key not in self._cache:
            buckets = tuple(int(b) for b in
                            str(buckets_spec).split(","))
            self._cache[key] = self.make_engine(int(slots), buckets)
        return self._cache[key]

    def measure(self, cfg, budget):
        eng = self._engine(cfg["slots"], cfg["seq_buckets"])
        return measure_decode_serving(
            eng, self.prompts, int(cfg["max_wait_us"]), self.clients,
            per_client=self.per_client * max(1, budget),
            max_new_tokens=self.max_new_tokens)


# ---------------------------------------------------------------------------
# speculative decode: bytes-per-ACCEPTED-token over k × draft size
# ---------------------------------------------------------------------------
class SpecDecodeWorkload(Workload):
    """Round-21 speculative-posture search: speculation depth ``k`` ×
    draft shrink factor × draft layer count. The expensive half per
    draft-size point is DISTILLATION (``spec.distill_draft`` — the
    draft is trained to imitate the target's greedy rollouts), cached
    per (shrink, layers) so every ``k`` trial at that size reuses it;
    the measurement streams a fixed prompt set through a speculative
    ``DecodeBatcher`` and reads the predictor's own accounting.

    The objective is ``spec_bytes_per_accepted_token`` — XLA
    cost-analysis bytes of one verify launch plus ``k`` draft steps,
    divided by the tokens the verify rounds actually emitted. It is the
    r12 gate currency normalized by the quantity speculation exists to
    maximize: a deep ``k`` with a bad draft measures WORSE than plain
    decode (wasted draft bytes), and so does a draft so large its own
    steps eat the amortization — only the measured trial sees where
    acceptance and draft cost balance."""

    objective = "spec_bytes_per_accepted_token"

    def __init__(self, name, spec, params, prompts,
                 space: Optional[SearchSpace] = None,
                 ks: Sequence[int] = (4, 2, 6),
                 shrinks: Sequence[int] = (2, 4),
                 draft_layers: Sequence[int] = (1,),
                 slots: int = 2, seq_buckets: Sequence[int] = (16,),
                 max_new_tokens: int = 12, distill_rollout: int = 40,
                 distill_epochs: int = 6):
        space = space or SearchSpace(
            spec_knobs(ks, shrinks, draft_layers), name=f"{name}-spec")
        super().__init__(space)
        self.name = name
        self.spec = spec
        self.params = dict(params)
        self.prompts = list(prompts)
        self.slots = int(slots)
        self.seq_buckets = tuple(int(b) for b in seq_buckets)
        self.max_new_tokens = int(max_new_tokens)
        self.distill_rollout = int(distill_rollout)
        self.distill_epochs = int(distill_epochs)
        self._target = None          # distillation rollout source
        self._drafts = {}            # (shrink, layers) -> (spec, params)

    def key_material(self):
        m = super().key_material()
        m["extra"] = dict(m["extra"], **self.spec.key_material())
        m["input_sigs"] = [
            ("prompt_lens", tuple(int(p.shape[0]) for p in self.prompts)),
            ("slots", self.slots), ("seq_buckets", self.seq_buckets),
            ("max_new_tokens", self.max_new_tokens),
            ("distill", (self.distill_rollout, self.distill_epochs))]
        return m

    def _draft(self, shrink, layers):
        key = (int(shrink), int(layers))
        if key not in self._drafts:
            from ..serving.decode import DecodePredictor
            from ..serving.decode.spec import make_draft_spec, \
                distill_draft
            if self._target is None:
                self._target = DecodePredictor(
                    self.spec, self.params, slots=1,
                    seq_buckets=self.seq_buckets,
                    name=f"{self.name}-distill-src")
            dspec = make_draft_spec(self.spec, num_layers=int(layers),
                                    shrink=int(shrink),
                                    name=f"{self.name}-d{shrink}x{layers}")
            dparams = distill_draft(self._target, dspec,
                                    rollout=self.distill_rollout,
                                    num_epoch=self.distill_epochs,
                                    seed=0)
            self._drafts[key] = (dspec, dparams)
        return self._drafts[key]

    def measure(self, cfg, budget):
        from ..base import MXNetError
        from ..serving.decode import DecodeBatcher
        from ..serving.decode.spec import SpecDecodePredictor
        dspec, dparams = self._draft(cfg["draft_shrink"],
                                     cfg["draft_layers"])
        pred = SpecDecodePredictor(
            self.spec, self.params, dspec, dparams,
            k=int(cfg["spec_k"]), slots=self.slots,
            seq_buckets=self.seq_buckets,
            name=f"{self.name}-k{cfg['spec_k']}")
        pred.warmup()
        with DecodeBatcher(pred, max_wait_us=0, max_queue=100_000,
                           name=f"tune-spec{cfg['spec_k']}") as bat:
            for _ in range(max(1, budget)):
                streams = [bat.submit(
                    p, max_new_tokens=self.max_new_tokens)
                    for p in self.prompts]
                for s in streams:
                    for _tok in s:
                        pass
        rep = pred.report()["spec"]
        bpt = pred.spec_bytes_per_accepted_token()
        if bpt is None:
            raise MXNetError(
                f"{self.name}: no verify rounds ran (or the backend "
                "exposes no cost analysis) — the bytes-per-accepted-"
                "token objective cannot be measured")
        plain = pred.decode_bytes_per_token()
        return {"objective": float(bpt),
                "plain_bytes_per_token": plain,
                "bytes_ratio_vs_plain":
                    float(bpt) / plain if plain else None,
                "accepted_per_step": rep["accepted_per_step"],
                "acceptance_rate": rep["acceptance_rate"],
                "rounds": rep["rounds"],
                "degrade_events": rep["degrade_events"],
                "retraces": pred.retraces}


# ---------------------------------------------------------------------------
# quantization posture: total-bytes objective over granularity × KV dtype
# ---------------------------------------------------------------------------
class QuantWorkload(Workload):
    """Round-19 quantization-posture search: weight-scale granularity ×
    decode KV-cache dtype (both env knobs — the runner applies them via
    ``config.override``, this workload only reads the ambient values).
    The objective is one bytes total in the r12 gate currency: the
    int8-PTQ-rewritten serving program's cost-analysis bytes
    (calibrated at the trial's granularity — a layer the accuracy guard
    disables stays fp32, so a granularity that trips the guard measures
    WORSE, never silently wrong) + the decode-step bytes + the KV-cache
    footprint of an engine built at the trial's KV dtype. A "win" here
    is the same measured claim the pass manager's gate enforces."""

    objective = "quant_bytes_total"

    def __init__(self, name, symbol, params, feed_shapes: Dict[str, tuple],
                 make_engine, space: Optional[SearchSpace] = None,
                 data_names: Optional[Sequence[str]] = None):
        space = space or SearchSpace(quant_knobs(), name=f"{name}-quant")
        super().__init__(space)
        self.name = name
        self.symbol = symbol
        self.params = dict(params)
        self.feed_shapes = {n: tuple(s) for n, s in feed_shapes.items()}
        self.make_engine = make_engine
        self.data_names = set(data_names or self.feed_shapes)
        self._engines = {}     # kv_dtype -> warmed engine (compile half)

    def key_material(self):
        from ..compile.key import symbol_digest
        m = super().key_material()
        m["symbol_sha"] = symbol_digest(self.symbol)
        m["input_sigs"] = sorted(self.feed_shapes.items())
        return m

    def _shapes(self) -> Dict[str, tuple]:
        arg_shapes, _, aux_shapes = self.symbol.infer_shape(
            **self.feed_shapes)
        shapes = dict(zip(self.symbol.list_arguments(), arg_shapes))
        shapes.update(zip(self.symbol.list_auxiliary_states(),
                          aux_shapes))
        return shapes

    def _engine(self, kv_dtype):
        if kv_dtype not in self._engines:
            eng = self.make_engine(kv_dtype)
            eng.warmup()
            self._engines[kv_dtype] = eng
        return self._engines[kv_dtype]

    def measure(self, cfg, budget):
        from .. import config as _config
        from .. import quant as _q
        from ..base import MXNetError
        from ..symbol import passes as P
        gran = str(_config.get("MXTPU_QUANT_GRANULARITY", "per_channel"))
        kvd = str(_config.get("MXTPU_DECODE_KV_DTYPE", "float32"))
        qcfg = _q.calibrate((self.symbol, self.params), granularity=gran)
        shapes = self._shapes()
        # force the pass on: the trial IS the measurement, so the gate's
        # auto-posture double-measure is redundant work here (forced
        # flags are trusted under MXTPU_PASS_GATE_BYTES=auto)
        with _q.quant_scope(qcfg), \
                _config.override("MXTPU_PASS_INT8_PTQ", "1"):
            final, _rep = P.apply_pipeline(
                self.symbol, shapes, tag="tune", mode="serving",
                data_names=self.data_names)
            sym2 = final if final is not None else self.symbol
            serving = P.measure_symbol_bytes(
                sym2, shapes, mode="serving", data_names=self.data_names)
        if serving is None:
            raise MXNetError(
                f"{self.name}: backend exposes no cost analysis — the "
                "bytes objective cannot be measured")
        eng = self._engine(kvd)
        decode = float(eng.program_cost("decode").get(
            "bytes accessed", 0.0))
        kv = float(eng.kv_cache_bytes())
        return {"objective": float(serving) + decode + kv,
                "serving_bytes": float(serving),
                "decode_step_bytes": decode,
                "kv_cache_bytes": kv,
                "granularity": gran, "kv_dtype": kvd,
                "quant_layers_enabled": len(qcfg.enabled_layers())}


# ---------------------------------------------------------------------------
# data pipeline: drain-wall objective over worker/staging knobs
# ---------------------------------------------------------------------------
class DataPipelineWorkload(Workload):
    """``MXTPU_DATA_WORKERS`` × ``MXTPU_DATA_STAGE_AHEAD`` search:
    objective is the wall per batch to drain ``make_iter()`` through a
    DataPipeline (budget multiplies the drained-batch count). The env
    knobs are applied by the runner; the pipeline reads them at
    construction."""

    objective = "wall_s_per_batch"

    def __init__(self, name, make_iter, batches: int = 16,
                 space: Optional[SearchSpace] = None,
                 consume_s: float = 0.0):
        space = space or SearchSpace(data_knobs(), name=f"{name}-data")
        super().__init__(space)
        self.name = name
        self.make_iter = make_iter
        self.batches = int(batches)
        self.consume_s = float(consume_s)

    def key_material(self):
        m = super().key_material()
        m["input_sigs"] = [("batches", self.batches),
                           ("consume_s", self.consume_s)]
        return m

    def measure(self, cfg, budget):
        import time as _time
        from ..data import DataPipeline
        n = self.batches * max(1, budget)
        pipe = DataPipeline(self.make_iter())
        t0 = _time.time()
        got = 0
        try:
            for _ in pipe:
                got += 1
                if self.consume_s:
                    _time.sleep(self.consume_s)
                if got >= n:
                    break
        finally:
            pipe.close()
        wall = _time.time() - t0
        if not got:
            raise RuntimeError(f"{self.name}: iterator yielded nothing")
        return {"objective": wall / got, "batches": got,
                "stats": pipe.stats()}


# ---------------------------------------------------------------------------
# built-in CPU proxies (bench.py tuned_vs_default / tools/tune.py / tests)
# ---------------------------------------------------------------------------
def _conv_symbol():
    """The conv family proxy: a BN→ReLU→1×1-conv tower (the exact
    subgraph the Pallas fusion pass targets) + classifier — ResNet-50's
    hot pattern at interactive CPU size."""
    from .. import symbol as sym
    data = sym.Variable("data")
    cur = data
    for i in range(2):
        bn = sym.BatchNorm(cur, name=f"bn{i}", fix_gamma=False)
        act = sym.Activation(bn, act_type="relu", name=f"relu{i}")
        cur = sym.Convolution(act, kernel=(1, 1), num_filter=16,
                              no_bias=True, name=f"conv{i}")
    fc = sym.FullyConnected(sym.Flatten(cur), num_hidden=8, name="fc")
    return sym.SoftmaxOutput(fc, name="softmax")


def _sparse_symbol(vocab=1000, dim=16):
    """The sparse family proxy: the two-tower recommender shape — an
    embedding lookup tower concatenated with a conv/BN dense tower (the
    r13 workload family; lookup-only graphs take the pass manager's
    ``embedding_graph`` skip, so the dense tower is what the pass knobs
    act on)."""
    from .. import symbol as sym
    img = sym.Variable("img")
    bn = sym.BatchNorm(img, name="bn1", fix_gamma=False)
    a = sym.Activation(bn, act_type="relu", name="relu1")
    conv = sym.Convolution(a, kernel=(1, 1), num_filter=16,
                           no_bias=True, name="conv1")
    ids = sym.Variable("ids")
    emb = sym.Embedding(data=ids, input_dim=vocab, output_dim=dim,
                        name="emb")
    cat = sym.Concat(sym.Flatten(conv), sym.Flatten(emb), dim=1)
    fc = sym.FullyConnected(cat, num_hidden=8, name="fc")
    return sym.SoftmaxOutput(fc, name="softmax")


def conv_proxy(batch: int = 8, batches=(8, 16, 32),
               hbm_budget: Optional[int] = None) -> TrainStepWorkload:
    """The conv-family built-in: pass-flag + tile + batch knobs over
    the BN→ReLU→1×1-conv proxy, bytes-per-row objective."""
    from .space import tile_knobs
    knobs = pass_knobs(("MXTPU_PALLAS_FUSION",
                        "MXTPU_PASS_RESIDUAL_FUSION",
                        "MXTPU_PASS_BF16")) + tile_knobs() + \
        [batch_knob(tuple(dict.fromkeys((batch,) + tuple(batches))),
                    default=batch)]
    wl = TrainStepWorkload(
        "conv_small", _conv_symbol(),
        {"data": (batch, 8, 8, 8), "softmax_label": (batch,)},
        SearchSpace(knobs, name="conv_small"), hbm_budget=hbm_budget)
    wl.builtin = "conv"
    return wl


def sparse_proxy(batch: int = 8, batches=(8, 16, 32),
                 hbm_budget: Optional[int] = None) -> TrainStepWorkload:
    """The sparse-family built-in: pass-flag + batch knobs over the
    two-tower embedding+conv proxy, bytes-per-row objective."""
    knobs = pass_knobs(("MXTPU_PALLAS_FUSION", "MXTPU_PASS_BF16")) + \
        [batch_knob(tuple(dict.fromkeys((batch,) + tuple(batches))),
                    default=batch)]
    wl = TrainStepWorkload(
        "sparse_two_tower", _sparse_symbol(),
        {"img": (batch, 8, 4, 4), "ids": (batch, 2),
         "softmax_label": (batch,)},
        SearchSpace(knobs, name="sparse_two_tower"),
        hbm_budget=hbm_budget)
    wl.builtin = "sparse"
    return wl


def decode_proxy(slot_counts=(2, 4), bucket_sets=("16", "16,32"),
                 waits=(2000, 0), clients: int = 4,
                 per_client: int = 2,
                 max_new_tokens: int = 6) -> DecodeServingWorkload:
    """The decode-family built-in: a pocket transformer LM
    (serving/decode/model.py at interactive CPU size) searched over
    KV-cache lanes × prefill buckets × first-fill window against the
    token-SLO objective."""
    import numpy as np
    from ..serving.decode import TransformerLMSpec, DecodePredictor, \
        init_params
    spec = TransformerLMSpec(vocab_size=64, num_embed=32, num_heads=2,
                             num_layers=2, max_seq=32, name="tunelm")
    params = init_params(spec, seed=0)

    def make_engine(slots, seq_buckets):
        return DecodePredictor(spec, params, slots=slots,
                               seq_buckets=seq_buckets)

    rng = np.random.RandomState(3)
    prompts = [rng.randint(1, spec.vocab_size, size=n).astype(np.int32)
               for n in (5, 9, 3, 12, 7, 14)]
    wl = DecodeServingWorkload(
        "decode_lm", make_engine, prompts, slot_counts, bucket_sets,
        waits, clients=clients, per_client=per_client,
        max_new_tokens=max_new_tokens, spec=spec)
    wl.builtin = "decode"
    return wl


def quant_proxy(batch: int = 4, slots: int = 2,
                seq_buckets=(8,)) -> QuantWorkload:
    """The quant-family built-in: granularity × KV-dtype knobs over the
    conv proxy (deterministic seed-0 weights — the FC "fc" layer
    exercises the dense-off bailout on CPU backends) plus a pocket
    decode engine, total-bytes objective."""
    import numpy as np
    from ..serving.decode import TransformerLMSpec, DecodePredictor, \
        init_params
    sym = _conv_symbol()
    feed = {"data": (batch, 8, 8, 8), "softmax_label": (batch,)}
    arg_shapes, _, aux_shapes = sym.infer_shape(**feed)
    rng = np.random.RandomState(0)
    params = {}
    for n, s in list(zip(sym.list_arguments(), arg_shapes)) + \
            list(zip(sym.list_auxiliary_states(), aux_shapes)):
        if n not in feed:
            params[n] = rng.uniform(-0.5, 0.5, size=s).astype(np.float32)
    spec = TransformerLMSpec(vocab_size=64, num_embed=32, num_heads=2,
                             num_layers=2, max_seq=16, name="quantlm")
    lm_params = init_params(spec, seed=0)

    def make_engine(kv_dtype):
        return DecodePredictor(spec, lm_params, slots=slots,
                               seq_buckets=tuple(seq_buckets),
                               kv_dtype=kv_dtype)

    wl = QuantWorkload("quant_posture", sym, params, feed, make_engine)
    wl.builtin = "quant"
    return wl


def spec_decode_proxy(ks=(4, 2), shrinks=(2,), draft_layers=(1,),
                      slots: int = 2, seq_buckets=(16,),
                      max_new_tokens: int = 10) -> SpecDecodeWorkload:
    """The speculative-decode built-in: a pocket transformer target
    (deterministic seed-0 weights) with per-trial distilled drafts,
    searched over depth × draft size against the
    bytes-per-accepted-token objective. Distillation epochs are kept
    small — the proxy exists to exercise the search loop at
    interactive CPU cost, not to reach bench-grade acceptance."""
    import numpy as np
    from ..serving.decode import TransformerLMSpec, init_params
    spec = TransformerLMSpec(vocab_size=64, num_embed=32, num_heads=2,
                             num_layers=2, max_seq=48, name="speclm")
    params = init_params(spec, seed=0)
    rng = np.random.RandomState(5)
    prompts = [rng.randint(1, spec.vocab_size, size=n).astype(np.int32)
               for n in (5, 9, 3, 12)]
    wl = SpecDecodeWorkload(
        "spec_decode_lm", spec, params, prompts, ks=ks, shrinks=shrinks,
        draft_layers=draft_layers, slots=slots, seq_buckets=seq_buckets,
        max_new_tokens=max_new_tokens, distill_rollout=24,
        distill_epochs=4)
    wl.builtin = "spec_decode"
    return wl


BUILTIN_WORKLOADS = {"conv": conv_proxy, "sparse": sparse_proxy,
                     "decode": decode_proxy, "quant": quant_proxy,
                     "spec_decode": spec_decode_proxy}


def builtin_workload(name: str, **kwargs) -> Workload:
    """Rebuild a built-in proxy workload by tag — how ``tools/tune.py
    verify`` re-measures a stored record's objective."""
    try:
        return BUILTIN_WORKLOADS[name](**kwargs)
    except KeyError:
        raise KeyError(f"unknown builtin workload {name!r}; known: "
                       f"{sorted(BUILTIN_WORKLOADS)}")
