"""Declarative search spaces: the tunable-knob half of the autotuner.

A :class:`SearchSpace` is an ordered set of :class:`Knob` definitions,
each with a typed, finite domain and an explicit default — the
configuration a process runs when nobody tuned it, and the baseline
every tuned result is measured against. Knobs come in two kinds:

- ``env`` knobs name an ``MXTPU_*`` configuration variable; the trial
  runner applies them via :func:`mxnet_tpu.config.override` around each
  trial (the pass-pipeline flags, ``MXTPU_PALLAS_TILES``,
  ``MXTPU_DATA_WORKERS`` / ``MXTPU_DATA_STAGE_AHEAD``...).
- ``param`` knobs are plain values the workload's measure function
  consumes directly (batch size, serving bucket set, ``max_wait_us``).

Spaces are deliberately small and declarative — TVM's lesson (PAPERS.md)
is that measured search over a *well-chosen* finite space beats
hand-tuning; the framework's job here is to make enumeration
deterministic, configurations canonically identifiable (so a killed
search can resume from its trial journal), and the space itself part of
the tuning record's cache key (a changed space is a different search,
never a warm hit).
"""
from __future__ import annotations

import hashlib
import itertools
import json
import random
from typing import Dict, List, Optional, Sequence

__all__ = ["Knob", "SearchSpace", "pass_knobs", "tile_knobs",
           "data_knobs", "serving_knobs", "decode_knobs", "batch_knob",
           "quant_knobs", "spec_knobs"]


class Knob:
    """One tunable: a name, a finite ordered domain, a default (must be
    in the domain), and the kind (``env`` applies through the
    environment, ``param`` feeds the workload's measure fn)."""

    __slots__ = ("name", "values", "default", "kind", "doc")

    def __init__(self, name: str, values: Sequence, default=None,
                 kind: str = "param", doc: str = ""):
        values = tuple(values)
        if not values:
            raise ValueError(f"knob '{name}' has an empty domain")
        if kind not in ("env", "param"):
            raise ValueError(f"knob '{name}': kind must be 'env' or "
                             f"'param', got {kind!r}")
        self.name = name
        self.values = values
        self.default = values[0] if default is None else default
        if self.default not in values:
            raise ValueError(
                f"knob '{name}': default {self.default!r} not in domain")
        self.kind = kind
        self.doc = doc

    def describe(self):
        return {"name": self.name, "kind": self.kind,
                "values": list(self.values), "default": self.default}

    def __repr__(self):
        return (f"Knob({self.name!r}, {self.values!r}, "
                f"default={self.default!r}, kind={self.kind!r})")


class SearchSpace:
    """An ordered set of knobs; the cartesian product is the trial
    space. Enumeration order is deterministic (knobs in declared order,
    values in domain order) so a fixed seed always yields the same
    trial sequence — the resumability and reproducibility contract."""

    def __init__(self, knobs: Sequence[Knob], name: str = "space"):
        self.name = name
        self.knobs = list(knobs)
        seen = set()
        for k in self.knobs:
            if k.name in seen:
                raise ValueError(f"duplicate knob '{k.name}'")
            seen.add(k.name)

    @property
    def size(self) -> int:
        n = 1
        for k in self.knobs:
            n *= len(k.values)
        return n

    def default_config(self) -> Dict:
        return {k.name: k.default for k in self.knobs}

    def enumerate(self) -> List[Dict]:
        """Every configuration, in deterministic declared order."""
        names = [k.name for k in self.knobs]
        return [dict(zip(names, combo)) for combo in
                itertools.product(*(k.values for k in self.knobs))]

    def configs(self, seed: int = 0, max_trials: int = 0) -> List[Dict]:
        """The trial sequence: full enumeration when the space fits
        ``max_trials`` (or it is 0 = unbounded), else a seeded sample
        without replacement. Either way the order is a deterministic
        function of (space, seed) — and always includes the default
        configuration, so best-vs-default is measured, not assumed."""
        all_cfgs = self.enumerate()
        if max_trials and len(all_cfgs) > max_trials:
            rng = random.Random(int(seed))
            all_cfgs = rng.sample(all_cfgs, max_trials)
        else:
            rng = random.Random(int(seed))
            rng.shuffle(all_cfgs)
        default = self.default_config()
        if default in all_cfgs:
            all_cfgs.remove(default)
        return [default] + all_cfgs

    def config_id(self, cfg: Dict) -> str:
        """Canonical short id of one configuration — the trial journal's
        resume key (stable across processes and dict orderings)."""
        blob = json.dumps(sorted(cfg.items()), sort_keys=True,
                          default=str).encode("utf-8")
        return hashlib.sha256(blob).hexdigest()[:16]

    def describe(self) -> dict:
        """Key material: two spaces differing in any knob name, domain,
        or default are different searches (no warm-hit sharing)."""
        return {"name": self.name,
                "knobs": [k.describe() for k in self.knobs]}

    def env_items(self, cfg: Dict):
        """[(env var, value)] for the env-kind knobs of ``cfg``."""
        return [(k.name, cfg[k.name]) for k in self.knobs
                if k.kind == "env" and k.name in cfg]

    def param_items(self, cfg: Dict) -> Dict:
        return {k.name: cfg[k.name] for k in self.knobs
                if k.kind == "param" and k.name in cfg}

    def __repr__(self):
        return (f"SearchSpace({self.name!r}, {len(self.knobs)} knobs, "
                f"size={self.size})")


# ---------------------------------------------------------------------------
# prebuilt knob families over the knobs the framework already exposes
# ---------------------------------------------------------------------------
_PASS_FLAGS = ("MXTPU_PALLAS_FUSION", "MXTPU_PASS_RESIDUAL_FUSION",
               "MXTPU_PASS_BN_FOLD", "MXTPU_PASS_BF16")


def pass_knobs(flags: Optional[Sequence[str]] = None) -> List[Knob]:
    """On/off knobs over the r12 pass-pipeline flags. Default "auto" is
    the untuned posture (on for TPU backends, off elsewhere); the tuner
    explores forcing each pass on and off — the measured trial, not the
    backend heuristic, decides."""
    return [Knob(f, ("auto", "1", "0"), default="auto", kind="env",
                 doc="pass-pipeline flag") for f in (flags or _PASS_FLAGS)]


def tile_knobs(candidates: Sequence[str] = ("", "256,128", "128,128",
                                            "512,256")) -> List[Knob]:
    """``MXTPU_PALLAS_TILES`` output-tile override candidates ("" =
    built-in largest-dividing selection). Candidates must satisfy the
    knob's own validation (multiples of 8, within the built-in candidate
    bounds) — an invalid tile fails the TRIAL loudly, never the
    process."""
    return [Knob("MXTPU_PALLAS_TILES", tuple(candidates), default="",
                 kind="env", doc="Pallas output-tile override")]


def data_knobs(workers=(2, 1, 4), stage_ahead=(2, 1, 4)) -> List[Knob]:
    """Data-pipeline shape: decode worker count × device stage-ahead
    depth (defaults first — they are the registered env defaults)."""
    return [
        Knob("MXTPU_DATA_WORKERS", tuple(workers), kind="env",
             doc="pipeline decode workers"),
        Knob("MXTPU_DATA_STAGE_AHEAD", tuple(stage_ahead), kind="env",
             doc="device staging depth"),
    ]


def serving_knobs(bucket_sets: Sequence[str],
                  waits: Sequence[int]) -> List[Knob]:
    """Serving frontier knobs: bucket set (comma-separated string, the
    ``MXTPU_SERVING_BUCKETS`` format) × DynamicBatcher coalescing
    window."""
    return [
        Knob("buckets", tuple(bucket_sets), kind="param",
             doc="Predictor bucket set"),
        Knob("max_wait_us", tuple(int(w) for w in waits), kind="param",
             doc="DynamicBatcher coalescing window"),
    ]


def decode_knobs(slot_counts: Sequence[int],
                 bucket_sets: Sequence[str],
                 waits: Sequence[int]) -> List[Knob]:
    """Decode-serving frontier knobs: KV-cache lane count × prefill
    seq-bucket set (comma-separated, the ``MXTPU_DECODE_SEQ_BUCKETS``
    format) × first-fill window. Slots trade decode-step cost (every
    lane rides every step) against continuous-batching concurrency;
    buckets trade prefill program count against padding waste — only a
    measured trial sees where TTFT and inter-token latency actually
    balance."""
    return [
        Knob("slots", tuple(int(s) for s in slot_counts), kind="param",
             doc="KV-cache lanes (concurrent generations)"),
        Knob("seq_buckets", tuple(bucket_sets), kind="param",
             doc="prefill seq-bucket set"),
        Knob("max_wait_us", tuple(int(w) for w in waits), kind="param",
             doc="DecodeBatcher first-fill window"),
    ]


def quant_knobs(granularities: Sequence[str] = ("per_channel",
                                                "per_tensor"),
                kv_dtypes: Sequence[str] = ("float32", "int8")
                ) -> List[Knob]:
    """Quantization posture knobs (round 19): weight-scale granularity
    (per-channel scales track outlier channels; per-tensor ships fewer
    scale bytes but one bad channel can blow the layer past the
    accuracy guard and DISABLE it — measurably worse bytes, which is
    the point of searching) × decode KV-cache storage dtype. Defaults
    first — they are the registered env defaults, so the tuner measures
    int8-KV as an IMPROVEMENT over the default posture rather than
    assuming it."""
    return [
        Knob("MXTPU_QUANT_GRANULARITY", tuple(granularities),
             kind="env", doc="int8 PTQ weight-scale granularity"),
        Knob("MXTPU_DECODE_KV_DTYPE", tuple(kv_dtypes), kind="env",
             doc="decode KV-cache storage dtype"),
    ]


def spec_knobs(ks: Sequence[int] = (4, 2, 6),
               shrinks: Sequence[int] = (2, 4),
               draft_layers: Sequence[int] = (1,)) -> List[Knob]:
    """Speculative-decode posture knobs (round 21): speculation depth
    ``k`` (draft tokens offered per verify round — deeper amortizes the
    verify launch over more candidate tokens but wastes draft work past
    the first rejection) × draft size (embed/head shrink factor and
    layer count vs. the target — a smaller draft is cheaper per
    proposal but accepts less). Neither tail is knowable analytically:
    the product ``bytes-moved-per-ACCEPTED-token`` is what the trial
    measures, and the defaults (first values — ``MXTPU_SPEC_K``'s
    registered default and the ``make_draft_spec`` defaults) are the
    untuned posture every win is measured against."""
    return [
        Knob("spec_k", tuple(int(k) for k in ks), kind="param",
             doc="speculation depth (draft tokens per verify round)"),
        Knob("draft_shrink", tuple(int(s) for s in shrinks),
             kind="param", doc="draft embed/head shrink vs target"),
        Knob("draft_layers", tuple(int(n) for n in draft_layers),
             kind="param", doc="draft transformer layer count"),
    ]


def batch_knob(candidates: Sequence[int], default: Optional[int] = None
               ) -> Knob:
    """Train-step batch size, bounded at search time by the workload's
    static peak-HBM pruning (memory_analysis headroom), not here."""
    return Knob("batch", tuple(int(c) for c in candidates),
                default=default, kind="param", doc="train batch size")
