"""mx.tune: search-driven autotuning over the measured config space.

r11–r14 built the measurement stack — per-pass XLA bytes deltas,
per-program ``memory_analysis()`` peak HBM, per-bucket serving
p50/p99, step-phase wall attribution — and left every knob those
measurements could drive hand-set. This subsystem closes the loop, in
the spirit of TVM's measured search (PAPERS.md): a declarative
:class:`~.space.SearchSpace` over the knobs the framework already
exposes, a deterministic :class:`~.runner.TrialRunner` (static pruning
→ measured trials → successive halving), and CRC-guarded
:class:`~.record.TuningRecord` persistence keyed like the compile
registry — so a tuned process boots tuned, with zero re-search.

Entry point::

    import mxnet_tpu as mx
    wl = mx.tune.workloads.conv_proxy(batch=8)
    rec = mx.tune.autotune(wl)        # warm hit or search-and-record
    params = rec.apply()              # env knobs exported; params dict
                                      # (batch, buckets...) returned

Observability: the ``tune`` telemetry collector (``mx.tune_report()``)
carries trials run/pruned/reused/failed, warm hits, records
written/rejected, and per-search summaries with the best-vs-default
delta; flat ``tune::*`` counters/gauges mirror into
``mx.telemetry.report()``.
"""
from __future__ import annotations

import threading
import time
from typing import List, Optional

from ..telemetry import registry as _treg

__all__ = ["SearchSpace", "Knob", "Trial", "TrialRunner",
           "TuningRecord", "TuneStore", "TrialJournal", "TuneRecordError",
           "default_store", "autotune", "tune_report",
           "space", "record", "runner", "workloads"]

_LOCK = threading.Lock()
_COUNTER_KEYS = ("trials_run", "trials_pruned", "trials_reused",
                 "trials_failed", "warm_hits", "records_written",
                 "records_rejected", "journal_lines_rejected",
                 "searches")
_STATS = {k: 0 for k in _COUNTER_KEYS}
_SEARCHES: List[dict] = []
_MAX_SEARCHES = 32


def _note(key: str, n: int = 1):
    """Count once into both layers: the collector's local store and the
    flat ``tune::`` registry counter."""
    with _LOCK:
        _STATS[key] = _STATS.get(key, 0) + n
    _treg.counter(f"tune::{key}").inc(n)


def _note_search(summary: dict):
    with _LOCK:
        _SEARCHES.append(summary)
        del _SEARCHES[:-_MAX_SEARCHES]
    _treg.gauge(f"tune::{summary['name']}::best_vs_default").set(
        summary.get("improvement") or 0.0)


def _collect(reset: bool = False) -> dict:
    with _LOCK:
        out = {k: _STATS.get(k, 0) for k in _COUNTER_KEYS}
        out["recent_searches"] = list(_SEARCHES)
        if reset:
            for k in _STATS:
                _STATS[k] = 0
            _SEARCHES.clear()
    return out


tune_report = _treg.collector_view("tune", _collect)

from . import space          # noqa: E402
from . import record         # noqa: E402
from . import runner         # noqa: E402
from . import workloads      # noqa: E402
from .space import SearchSpace, Knob                    # noqa: E402
from .record import (TuningRecord, TuneStore, TrialJournal,  # noqa: E402
                     TuneRecordError, default_store)
from .runner import Trial, TrialRunner                  # noqa: E402


def autotune(workload, *, store=None, seed: int = 0,
             max_trials: Optional[int] = None, force: bool = False,
             apply: bool = False, on_trial=None, **runner_kwargs):
    """Tune one workload: boot from a valid stored record when one
    exists (zero trials, zero measurement compiles — the warm path),
    else run the search, persist the winner, and return its
    :class:`TuningRecord`.

    ``store=None`` uses :func:`default_store` (``MXTPU_TUNE_DIR`` /
    ``<MXTPU_COMPILE_CACHE_DIR>/tune``; may itself be None = no
    persistence). ``force=True`` re-searches even over a valid record.
    ``apply=True`` exports the winner's env knobs into ``os.environ``
    before returning (the boot-tuned path; param knobs come back via
    ``record.param_items()``).

    The search ALWAYS measures the space's default configuration, so
    ``default_value`` is measured, never assumed; when no explored
    configuration strictly beats it, the record stores the default as
    best (tuning never regresses the workload).
    """
    if store is None:
        store = default_store()
    key = workload.key()
    if store is not None and store.enabled and not force:
        rec = store.load(key.digest)
        if rec is not None:
            _note("warm_hits")
            if apply:
                rec.apply()
            return rec

    journal = None
    if store is not None and store.enabled:
        import os
        os.makedirs(store.directory, exist_ok=True)
        journal = TrialJournal(store.journal_path(key.digest))
    t0 = time.time()
    r = TrialRunner(workload.space, workload.measure,
                    static=workload.static, seed=seed,
                    max_trials=max_trials, journal=journal,
                    on_trial=on_trial, name=workload.name,
                    **runner_kwargs)
    best, trials = r.search()
    wall = time.time() - t0

    default_cfg = workload.space.default_config()
    default_id = workload.space.config_id(default_cfg)
    default_t = next((t for t in trials if t.config_id == default_id),
                     None)
    default_value = default_t.objective if default_t is not None \
        else None
    if best is None or (default_value is not None
                        and best.objective is not None
                        and best.objective >= default_value):
        best_cfg, best_value = default_cfg, default_value
    else:
        best_cfg, best_value = best.config, best.objective

    counts = {"run": sum(t.status == "measured" for t in trials),
              "pruned": sum(t.status == "pruned" for t in trials),
              "reused": sum(t.status == "reused" for t in trials),
              "failed": sum(t.status == "failed" for t in trials)}
    rec = TuningRecord({
        "digest": key.digest,
        "name": workload.name,
        "workload": getattr(workload, "builtin", None),
        "objective": workload.objective,
        "space": workload.space.describe(),
        "default_config": default_cfg,
        "default_value": default_value,
        "best_config": best_cfg,
        "best_value": best_value,
        "trials": counts,
        "search_wall_s": wall,
        "created": time.time(),
        "seed": int(seed),
    })
    _note("searches")
    _note_search({"name": workload.name, "digest": key.digest,
                  "objective": workload.objective,
                  "default": default_value, "best": best_value,
                  "improvement": rec.improvement(),
                  "trials": counts, "wall_s": wall})
    if store is not None and store.enabled:
        store.put(rec)
        _note("records_written")
        if journal is not None:
            journal.remove()   # the record supersedes the crash log
    if apply:
        rec.apply()
    return rec
