"""Tuning-record persistence: a tuned process boots tuned.

One file per tuned workload, named by the key digest (the same
canonical material discipline as the r10 compile registry — symbol
digest, input shapes, optimizer, mesh, backend identity — plus the
search space itself)::

    <dir>/<sha256-digest>.mxtune

Entry layout mirrors the compile cache's (compile/cache.py), and for
the same reasons::

    b"MXTUNE1\\n"                     magic
    uint32 big-endian header length
    header JSON   {version, digest, name, kind, fingerprint, crc32,
                   payload_len, created}
    payload       record JSON

Every write is atomic (``base.atomic_write``: temp + fsync + rename) —
a search SIGKILLed at any byte never tears an existing record. On read
a record is rejected loudly (warning + ``tune::records_rejected``
counter), never applied, when the magic/header/CRC don't check out
(``corrupt``) or the stored version fingerprint differs from the
running stack (``stale``); the caller falls back to a fresh search
that overwrites the entry. The ``tune_trial`` fault-injection site
covers both failure shapes (``byte=N`` dies mid-write, ``bytes=N``
truncates after the rename commits).

The directory defaults to ``<MXTPU_COMPILE_CACHE_DIR>/tune`` — tuning
records live alongside the compiled programs they select — and is
overridable via ``MXTPU_TUNE_DIR``.

:class:`TrialJournal` is the search's crash log: one CRC-guarded JSON
line per completed trial, appended as trials finish. A resumed search
replays completed trials from the journal instead of re-measuring; a
torn final line (the kill landed mid-append) fails its CRC and is
skipped, losing at most the one in-flight trial.
"""
from __future__ import annotations

import json
import logging
import os
import struct
import time
import zlib

from ..base import MXNetError, atomic_write

__all__ = ["TuningRecord", "TuneRecordError", "TuneStore",
           "TrialJournal", "default_store"]

_MAGIC = b"MXTUNE1\n"
_SUFFIX = ".mxtune"
_log = logging.getLogger("mxnet_tpu.tune")


class TuneRecordError(MXNetError):
    """A tuning record exists but must not be applied. ``reason`` is
    ``"corrupt"`` (magic/CRC/length mismatch) or ``"stale"`` (version
    fingerprint mismatch)."""

    def __init__(self, path, reason, detail=""):
        super().__init__(
            f"tuning record '{os.path.basename(path)}' is {reason}"
            f"{': ' + detail if detail else ''}; falling back to a "
            "fresh search (the record will be overwritten)")
        self.path = path
        self.reason = reason


class TuningRecord:
    """The winning configuration of one search, plus everything needed
    to judge it later: the measured objective of the default and best
    configurations, the knob kinds (so :meth:`env_items` can re-apply
    the env half), trial counts, and the search wall time."""

    __slots__ = ("data",)

    _FIELDS = ("digest", "name", "workload", "objective", "space",
               "default_config", "default_value", "best_config",
               "best_value", "trials", "search_wall_s", "created",
               "seed")

    def __init__(self, data: dict):
        missing = [f for f in self._FIELDS if f not in data]
        if missing:
            raise ValueError(f"TuningRecord missing fields: {missing}")
        self.data = dict(data)

    def __getattr__(self, name):
        try:
            return self.data[name]
        except KeyError:
            raise AttributeError(name)

    def env_items(self):
        """[(env var, value)] of the best config's env-kind knobs."""
        kinds = {k["name"]: k["kind"] for k in self.space["knobs"]}
        return [(n, v) for n, v in sorted(self.best_config.items())
                if kinds.get(n) == "env"]

    def param_items(self):
        kinds = {k["name"]: k["kind"] for k in self.space["knobs"]}
        return {n: v for n, v in self.best_config.items()
                if kinds.get(n) == "param"}

    def improvement(self):
        """Fractional objective reduction of best over default (0.0
        when the search couldn't beat the default)."""
        d, b = self.default_value, self.best_value
        if not d or b is None:
            return 0.0
        return max(0.0, 1.0 - float(b) / float(d))

    def apply(self, environ=None):
        """Export the env half of the best config into ``environ``
        (default ``os.environ``) — the boot-time application path; the
        param half is returned for the caller to feed its constructors
        (batch size, bucket set...)."""
        env = os.environ if environ is None else environ
        for name, value in self.env_items():
            if value is None or value == "":
                env.pop(name, None)
            else:
                env[name] = str(value)
        return self.param_items()

    def __repr__(self):
        return (f"TuningRecord({self.name!r}@{self.digest[:10]}, "
                f"{self.objective}: {self.default_value} -> "
                f"{self.best_value})")


class TuneStore:
    """CRC-guarded atomic record store (see module docstring)."""

    def __init__(self, directory):
        self.directory = os.fspath(directory)

    @property
    def enabled(self):
        return bool(self.directory)

    def path_for(self, digest):
        return os.path.join(self.directory, digest + _SUFFIX)

    def journal_path(self, digest):
        return os.path.join(self.directory, digest + ".trials.jsonl")

    # -- write ----------------------------------------------------------------
    def put(self, record: TuningRecord, fingerprint=None):
        """Atomically write one record; returns the entry path.
        ``fingerprint`` is overridable for tests only."""
        from ..compile import key as key_mod
        from .. import faultinject
        os.makedirs(self.directory, exist_ok=True)
        payload = json.dumps(record.data, sort_keys=True).encode("utf-8")
        header = {
            "version": key_mod.FORMAT_VERSION,
            "digest": record.digest,
            "name": record.name,
            "kind": "tune",
            "fingerprint": fingerprint or key_mod.fingerprint(),
            "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
            "payload_len": len(payload),
            "created": time.time(),
        }
        hdr = json.dumps(header, sort_keys=True).encode("utf-8")
        path = self.path_for(record.digest)
        with atomic_write(path) as f:
            f = faultinject.guarded_write(f, path=path, site="tune_trial")
            f.write(_MAGIC)
            f.write(struct.pack(">I", len(hdr)))
            f.write(hdr)
            f.write(payload)
        # post-commit tearing (storage lying below the rename): the CRC
        # in the header is what must catch it on load. Only a bytes=
        # spec arms this shape — a trial=-armed commit drill must not
        # truncate the record a completed search then writes.
        armed = faultinject.active("tune_trial")
        if armed is not None and "bytes" in armed:
            faultinject.maybe_truncate(path, site="tune_trial")
        return path

    # -- read -----------------------------------------------------------------
    def read_header(self, path):
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    raise TuneRecordError(path, "corrupt", "bad magic")
                (hlen,) = struct.unpack(">I", f.read(4))
                if hlen <= 0 or hlen > (1 << 20):
                    raise TuneRecordError(path, "corrupt",
                                          "implausible header length")
                return json.loads(f.read(hlen).decode("utf-8"))
        except TuneRecordError:
            raise
        except (OSError, ValueError, struct.error,
                UnicodeDecodeError) as e:
            raise TuneRecordError(path, "corrupt", str(e))

    def get(self, digest):
        """The validated :class:`TuningRecord` for ``digest``, None when
        absent; raises :class:`TuneRecordError` on corrupt/stale."""
        from ..compile import key as key_mod
        path = self.path_for(digest)
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    raise TuneRecordError(path, "corrupt", "bad magic")
                (hlen,) = struct.unpack(">I", f.read(4))
                if hlen <= 0 or hlen > (1 << 20):
                    raise TuneRecordError(path, "corrupt",
                                          "implausible header length")
                header = json.loads(f.read(hlen).decode("utf-8"))
                payload = f.read()
        except FileNotFoundError:
            return None
        except TuneRecordError:
            raise
        except (OSError, ValueError, struct.error,
                UnicodeDecodeError) as e:
            raise TuneRecordError(path, "corrupt", str(e))
        if header.get("fingerprint") != key_mod.fingerprint():
            raise TuneRecordError(
                path, "stale",
                f"built by {header.get('fingerprint')!r}, running "
                f"{key_mod.fingerprint()!r}")
        if len(payload) != header.get("payload_len") or \
                (zlib.crc32(payload) & 0xFFFFFFFF) != header.get("crc32"):
            raise TuneRecordError(
                path, "corrupt",
                f"payload CRC/length mismatch ({len(payload)} bytes)")
        try:
            return TuningRecord(json.loads(payload.decode("utf-8")))
        except (ValueError, UnicodeDecodeError) as e:
            raise TuneRecordError(path, "corrupt", str(e))

    def load(self, digest):
        """:meth:`get` with the fallback contract applied: a corrupt or
        stale record is rejected with a warning + counter and reported
        as absent — the caller re-searches and overwrites. A torn write
        can therefore never be APPLIED, only replaced."""
        try:
            return self.get(digest)
        except TuneRecordError as e:
            from . import _note
            _note("records_rejected")
            _log.warning("%s", e)
            return None

    # -- maintenance ----------------------------------------------------------
    def entries(self):
        """[(path, header-or-TuneRecordError)], newest first."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                out.append((path, self.read_header(path)))
            except TuneRecordError as e:
                out.append((path, e))
        out.sort(key=lambda pe: -os.path.getmtime(pe[0]))
        return out

    def verify(self):
        """Fully validate every record (header + fingerprint + CRC).
        Returns (ok_count, [(path, reason), ...])."""
        ok, bad = 0, []
        for path, header in self.entries():
            if isinstance(header, TuneRecordError):
                bad.append((path, header.reason))
                continue
            try:
                self.get(header["digest"])
                ok += 1
            except TuneRecordError as e:
                bad.append((path, e.reason))
        return ok, bad


class TrialJournal:
    """Append-only completed-trial log for one search (see module
    docstring). Each line is ``{"crc": crc32(entry-json), "e": entry}``
    — self-validating, so a torn tail line is detected and skipped,
    never half-replayed."""

    def __init__(self, path):
        self.path = os.fspath(path)

    def append(self, entry: dict):
        blob = json.dumps(entry, sort_keys=True)
        line = json.dumps(
            {"crc": zlib.crc32(blob.encode("utf-8")) & 0xFFFFFFFF,
             "e": entry}, sort_keys=True)
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()
            os.fsync(f.fileno())

    def load(self):
        """Every valid completed-trial entry, in append order; invalid
        or torn lines are counted and skipped."""
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                    blob = json.dumps(rec["e"], sort_keys=True)
                    if (zlib.crc32(blob.encode("utf-8")) & 0xFFFFFFFF) \
                            != rec["crc"]:
                        raise ValueError("trial line CRC mismatch")
                    out.append(rec["e"])
                except (ValueError, KeyError, TypeError):
                    from . import _note
                    _note("journal_lines_rejected")
                    _log.warning(
                        "tune trial journal %s: skipping torn/invalid "
                        "line", os.path.basename(self.path))
        return out

    def remove(self):
        try:
            os.unlink(self.path)
        except OSError:
            pass


def default_store():
    """The env-configured store, or None when disabled: MXTPU_TUNE_DIR
    when set, else ``<MXTPU_COMPILE_CACHE_DIR>/tune`` (tuning records
    live beside the compiled programs they select); MXTPU_TUNE_CACHE=0
    switches persistence off entirely."""
    from .. import config
    if str(config.get("MXTPU_TUNE_CACHE")).lower() in ("0", "false",
                                                       "off"):
        return None
    directory = str(config.get("MXTPU_TUNE_DIR") or "")
    if not directory:
        cache_dir = str(config.get("MXTPU_COMPILE_CACHE_DIR") or "")
        if not cache_dir:
            return None
        directory = os.path.join(cache_dir, "tune")
    return TuneStore(directory)
