"""KVStore facade.

TPU-native rebuild of ``mxnet.kvstore`` (reference: python/mxnet/kvstore.py;
native src/kvstore/ — KVStoreLocal kvstore_local.h:52, CommDevice comm.h:428,
KVStoreNCCL kvstore_nccl.h:62, KVStoreDist kvstore_dist.h:44).

Architectural mapping: the reference's four backends (local CPU-reduce,
device P2P-reduce, NCCL collectives, ps-lite parameter server) all collapse
on TPU into XLA collectives over the ICI mesh. This module keeps the
KVStore *API* (init/push/pull/row_sparse_pull/set_optimizer) because Module,
Trainer, and user scripts program against it:

- 'local' / 'device' / 'nccl'  → single-process store; "reduction" over the
  per-device gradient copies is a sum (with one logical array per parameter
  the copies are sharded views, and the actual cross-chip reduction is a
  ``psum`` XLA inserts inside the pjit'd step — see mxnet_tpu.parallel).
- 'dist_sync' / 'dist_device_sync' / 'dist_async' → multi-process data
  parallelism over jax.distributed; push+pull becomes an all-reduce across
  processes (see mxnet_tpu.parallel.dist). The parameter-server *role*
  disappears; "update_on_kvstore" maps to running the optimizer on the
  reduced gradient once per key, which is semantically the server-side
  optimizer of kvstore_dist_server.h:187.
"""
from __future__ import annotations

import pickle
from typing import Dict, List, Optional

from .ndarray.ndarray import NDArray

__all__ = ["KVStore", "create"]


def _copy_store_value(src, t):
    """Copy a stored value into a pull target, converting storage type when
    they differ (reference analog: cast_storage on pull)."""
    src_stype = getattr(src, "stype", "default")
    t_stype = getattr(t, "stype", "default")
    if src_stype != t_stype:
        src = src.todense() if src_stype != "default" else src
        if t_stype == "default":
            t._data = src._data
            return
        from .ndarray.sparse import dense_to_sparse
        src = dense_to_sparse(src, t_stype)
    t._data = src._data
    if t_stype != "default":
        t._indices = src._indices
        t._sshape = src._sshape
        if t_stype == "csr":
            t._indptr = src._indptr


def _as_key_list(key, value):
    """Normalize (key, value) to parallel lists (reference:
    python/mxnet/kvstore.py _ctype_key_value)."""
    if isinstance(key, (list, tuple)):
        keys, values = [], []
        for k, v in zip(key, value):
            keys.append(k)
            values.append(v)
        return keys, values
    return [key], [value]


class KVStore:
    """Key-value store for parameter synchronization (reference:
    python/mxnet/kvstore.py:55)."""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._data: Dict = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None

    # -- basic ----------------------------------------------------------------
    def init(self, key, value):
        """Initialize key(s) once (reference: kvstore.py:93)."""
        keys, values = _as_key_list(key, value)
        for k, v in zip(keys, values):
            if k in self._data:
                continue
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._data[k] = v0.copy()

    def set(self, key, value):
        """Overwrite stored value(s) — unlike :meth:`init`, existing keys
        are replaced. Needed when a bound module's params change after
        ``init_optimizer`` (checkpoint restore / ``set_params``): with
        update-on-kvstore the store holds the master weights, so later
        pulls must return the new values, not the ones captured at init.
        Callers must provide rank-consistent values in distributed mode
        (checkpoint restores are: params are synced before every save)."""
        keys, values = _as_key_list(key, value)
        for k, v in zip(keys, values):
            v0 = v[0] if isinstance(v, (list, tuple)) else v
            self._data[k] = v0.copy()

    def push(self, key, value, priority=0):
        """Push (accumulate) values (reference: kvstore.py:130).

        Per-key semantics match KVStoreLocal::Push: multiple device copies
        are summed, then either stored (for later pull) or fed to the
        updater if one is set (update_on_kvstore)."""
        keys, values = _as_key_list(key, value)
        for k, v in zip(keys, values):
            vals = v if isinstance(v, (list, tuple)) else [v]
            agg = vals[0]
            for extra in vals[1:]:
                agg = agg + extra
            agg = self._apply_compression(k, agg)
            if self._updater is not None:
                if k not in self._data:
                    raise ValueError(f"key {k} not initialized")
                self._updater(_key_int(k), agg, self._data[k])
            else:
                self._merged = getattr(self, "_merged", {})
                self._merged[k] = agg

    def pull(self, key, out=None, priority=0, ignore_sparse=True):
        """Pull current value into out (reference: kvstore.py:164)."""
        keys, outs = _as_key_list(key, out)
        for k, o in zip(keys, outs):
            targets = o if isinstance(o, (list, tuple)) else [o]
            merged = getattr(self, "_merged", {})
            if self._updater is None and k in merged:
                src = merged[k]
            else:
                src = self._data[k]
            for t in targets:
                _copy_store_value(src, t)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference: kvstore.py:195-209,
        native PullRowSparse_ kvstore_dist.h:259-288 — the sharded-embedding
        path: only the rows a batch touches travel to the worker).

        A ``RowSparseNDArray`` out receives (values, unique-sorted row ids);
        a dense out receives the rows stacked in row_ids order."""
        import jax.numpy as jnp
        import numpy as np
        from .ndarray.sparse import RowSparseNDArray

        assert row_ids is not None, "row_ids is required for row_sparse_pull"
        keys, outs = _as_key_list(key, out)
        rids = row_ids if isinstance(row_ids, (list, tuple)) else [row_ids]
        merged = getattr(self, "_merged", {})
        for k, o, r in zip(keys, outs, rids * (len(keys) // max(len(rids), 1) or 1)):
            targets = o if isinstance(o, (list, tuple)) else [o]
            if self._updater is None and k in merged:
                src = merged[k]
            else:
                src = self._data[k]
            r_np = r.asnumpy().astype(np.int64) if isinstance(r, NDArray) \
                else np.asarray(r, np.int64)
            uniq = np.unique(r_np)

            def gather(rows):
                # gather rows without densifying the whole table: a
                # row_sparse store maps requested ids onto its stored rows
                # (missing ids read as zero)
                if isinstance(src, RowSparseNDArray):
                    have = np.asarray(src._indices)
                    pos = np.searchsorted(have, rows)
                    pos_c = np.clip(pos, 0, max(len(have) - 1, 0))
                    hit = (pos < len(have)) & (have[pos_c] == rows) \
                        if len(have) else np.zeros(len(rows), bool)
                    vals = src._data[pos_c] if len(have) else \
                        jnp.zeros((len(rows),) + src._data.shape[1:],
                                  src._data.dtype)
                    return jnp.where(
                        jnp.asarray(hit).reshape((-1,) + (1,) * (vals.ndim - 1)),
                        vals, 0)
                return src._data[rows]

            for t in targets:
                if isinstance(t, RowSparseNDArray):
                    t._data = gather(uniq)
                    t._indices = jnp.asarray(uniq, t._indices.dtype)
                    t._sshape = tuple(src.shape)
                else:
                    t._data = gather(r_np.reshape(-1))

    # -- optimizer ------------------------------------------------------------
    def set_updater(self, updater):
        """(reference: kvstore.py:360)"""
        self._updater = updater

    def set_optimizer(self, optimizer):
        """Run this optimizer inside the store — "update_on_kvstore"
        (reference: kvstore.py:323; dist server analog
        kvstore_dist_server.h:187)."""
        from . import optimizer as opt
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit gradient compression with error feedback on pushed
        gradients (reference: kvstore.py set_gradient_compression; native
        gradient_compression.h:37-134, applied at kvstore_dist.h:232 and
        comm.h:489 ReduceCompressed)."""
        from .gradient_compression import GradientCompression
        if self.type not in ("device", "dist", "dist_sync", "dist_async",
                             "dist_sync_device", "dist_device_sync"):
            # the reference only supports compression for device/dist
            # stores (kvstore.py set_gradient_compression check) — a
            # 'local' store has no wire to save
            raise ValueError("Gradient compression is not supported for "
                             f"this type of kvstore: {self.type!r}")
        self._compression_params = dict(compression_params)
        self._compression = GradientCompression(**self._compression_params)

    def _apply_compression(self, k, agg):
        comp = getattr(self, "_compression", None)
        if comp is None or not comp.active or \
                getattr(agg, "stype", "default") != "default":
            return agg
        return NDArray(comp.compress(k, agg._data))

    # -- cluster topology -----------------------------------------------------
    @property
    def rank(self):
        import jax
        return jax.process_index() if self.is_distributed else 0

    @property
    def num_workers(self):
        import jax
        return jax.process_count() if self.is_distributed else 1

    @property
    def is_distributed(self):
        return "dist" in self.type

    def barrier(self):
        if self.is_distributed:
            from .parallel import dist as _dist
            _dist.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        from .base import atomic_write
        with atomic_write(fname) as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def create(name="local"):
    """Create a KVStore (reference: python/mxnet/kvstore.py:628; native
    factory src/kvstore/kvstore.cc:40-75)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "device", "nccl", "local_allreduce_cpu",
             "local_allreduce_device", "dist_sync", "dist_device_sync",
             "dist_async", "dist")
    if name not in valid:
        raise ValueError(f"unknown kvstore type {name!r}; valid: {valid}")
    if "dist" in name:
        from .parallel.dist import DistKVStore
        return DistKVStore(name)
    return KVStore(name)
