"""Data iterators.

TPU-native rebuild of ``mxnet.io`` (reference: python/mxnet/io.py —
DataIter protocol :182, DataDesc/DataBatch :60-130, NDArrayIter :546,
ResizeIter :247, PrefetchingIter :349; native iterators src/io/).

Design: iterators produce host-side batches; the device transfer is an async
``jax.device_put`` so input pipeline overlaps compute (the reference gets
overlap from dmlc::ThreadedIter prefetch threads; JAX's async dispatch plus
``PrefetchingIter`` gives the same property).
"""
from __future__ import annotations

import threading
from collections import OrderedDict, namedtuple

import numpy as np

from . import ndarray as nd
from .ndarray.ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "MXDataIter", "CSVIter"]


class DataDesc(namedtuple("DataDesc", ["name", "shape"])):
    """Data layout description (reference: io.py:60)."""

    def __new__(cls, name, shape, dtype=np.float32, layout="NCHW"):
        ret = super().__new__(cls, name, shape)
        ret.dtype = dtype
        ret.layout = layout
        return ret

    def __repr__(self):
        return (f"DataDesc[{self.name},{self.shape},{self.dtype},"
                f"{self.layout}]")

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """A mini-batch (reference: io.py:130)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None:
            assert isinstance(data, (list, tuple)), "Data must be list of " \
                "NDArrays"
        if label is not None:
            assert isinstance(label, (list, tuple)), "Label must be list of " \
                "NDArrays"
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label

    def __str__(self):
        data_shapes = [d.shape for d in self.data]
        if self.label:
            label_shapes = [l.shape for l in self.label]
        else:
            label_shapes = None
        return (f"{self.__class__.__name__}: data shapes: {data_shapes} "
                f"label shapes: {label_shapes}")


class DataIter:
    """Base data iterator (reference: io.py:182)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        # deterministic fault site: 'data_iter:batch=B' raises at this
        # iterator's B-th batch (1-based) — the chaos suites' stand-in
        # for a dying input-pipeline worker
        from . import faultinject
        if faultinject.active("data_iter") is not None:
            self._fi_ordinal = getattr(self, "_fi_ordinal", 0) + 1
            if faultinject.fire("data_iter", batch=self._fi_ordinal):
                raise faultinject.FaultInjected(
                    "data_iter", batch=self._fi_ordinal)
        return self.next()

    def iter_next(self):
        pass

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize an iterator to ``size`` batches per epoch
    (reference: io.py:247)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad

    # -- checkpointable cursor (data/pipeline.py protocol) ---------------------
    def get_state(self):
        """A ``cur`` counter alone cannot place the wrapped iterator, so
        this refuses (loudly) to claim resume support the inner iterator
        can't honor — callers that probe (fit's epoch-end save, the
        pipeline's epoch snapshot) degrade gracefully."""
        inner = getattr(self.data_iter, "get_state", None)
        if not callable(inner):
            raise NotImplementedError(
                "ResizeIter cursor needs the wrapped iterator to support "
                f"get_state(); {type(self.data_iter).__name__} does not")
        return {"cur": int(self.cur), "inner": inner()}

    def set_state(self, state):
        if not isinstance(state, dict) or "cur" not in state or \
                "inner" not in state:
            raise ValueError(
                "not a ResizeIter cursor (missing 'cur'/'inner'; got "
                f"keys {sorted(state) if isinstance(state, dict) else state})")
        setter = getattr(self.data_iter, "set_state", None)
        if not callable(setter):
            raise ValueError(
                "ResizeIter cursor carries an inner-iterator state but "
                f"{type(self.data_iter).__name__} has no set_state(); "
                "refusing a resume that would silently replay from the "
                "wrong position")
        setter(state["inner"])
        self.cur = int(state.get("cur", 0))


class PrefetchingIter(DataIter):
    """Thread-based prefetcher over one or more iterators
    (reference: io.py:349; native analog iter_prefetcher.h:142).

    Hardened shutdown path (shared with ``data.DataPipeline`` via
    ``data/workers.py``): worker exceptions are captured and re-raised
    at ``next()``/``reset()`` instead of silently truncating the epoch,
    ``close()`` joins the prefetch threads (idempotent, also run from
    ``__del__`` and the atexit registry), and a dead worker can never
    hang the consumer on an event that would never fire."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]
        from .data import workers as _wk
        self._group = _wk.WorkerGroup("prefetch")

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                except BaseException as e:
                    # surface at next(), don't fake an end-of-data; wake
                    # the consumer before dying so it can't block forever
                    self.next_batch[i] = None
                    self._group.fail(e)
                    self.data_taken[i].clear()
                    self.data_ready[i].set()
                    raise
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            self._group.spawn(prefetch_func, self, i,
                              name=f"prefetch-{i}")
            for i in range(self.n_iter)]
        _wk.register_closeable(self)

    def close(self):
        """Stop and JOIN the prefetch threads (they used to leak across
        reset()/GC as parked daemons). Idempotent; registered atexit."""
        if not self.started:
            return
        self.started = False
        self._group.stop()
        for e in self.data_taken:
            e.set()
        self._group.join()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[
            DataDesc(r[x.name], x.shape, x.dtype)
            if isinstance(x, DataDesc) else DataDesc(*x)
            for x in i.provide_data
        ] for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[
            DataDesc(r[x.name], x.shape, x.dtype)
            if isinstance(x, DataDesc) else DataDesc(*x)
            for x in i.provide_label
        ] for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        self._group.raise_error()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        # a worker exception ends the epoch HERE, loudly (it used to be
        # swallowed into a silent StopIteration)
        self._group.raise_error()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, \
                "Different pad values in the data batches"
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, array) (reference: io.py:499)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = OrderedDict([(default_name, data[0])])
        else:
            data = OrderedDict(
                [(f"_{i}_{default_name}", d) for i, d in enumerate(data)])
    if not isinstance(data, dict):
        raise TypeError(
            "Input must be NDArray, numpy.ndarray, a list of them or dict "
            "with them as values")
    for k, v in data.items():
        if not isinstance(v, NDArray):
            try:
                data[k] = nd.array(v)
            except Exception:
                raise TypeError(
                    f"Invalid type '{type(v)}' for {k}, should be NDArray or "
                    "numpy.ndarray")
    return list(data.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference: io.py:546-765)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v.asnumpy()[self.idx]) for k, v in self.data]
            self.label = [(k, v.asnumpy()[self.idx]) for k, v in self.label]
            self.data = [(k, nd.array(v)) for k, v in self.data]
            self.label = [(k, nd.array(v)) for k, v in self.label]
        # the FULL physical-row permutation (idx gets truncated below for
        # 'discard'; batches slice physical rows, so this is what the
        # resume cursor must capture)
        self._row_order = self.idx.copy()
        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - \
                self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]
        self.data_list = [x[1] for x in self.data] + \
            [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.data]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])), v.dtype)
            for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and \
                self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def skip_batches(self, n):
        """Fast-forward ``n`` batches without materializing them (same
        cursor arithmetic as ``iter_next``) — lets the data pipeline's
        checkpoint resume seek instead of replay-and-discard."""
        self.cursor += int(n) * self.batch_size

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [x[1][self.cursor:self.cursor + self.batch_size]
                    for x in data_source]
        pad = self.batch_size - self.num_data + self.cursor
        return [
            nd.array(np.concatenate(
                (x[1][self.cursor:].asnumpy(), x[1][:pad].asnumpy()), axis=0))
            for x in data_source]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and \
                self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0

    # -- checkpointable cursor (data/pipeline.py protocol) ---------------------
    def get_state(self):
        """Resume cursor: position + the construction-time shuffle
        permutation, so a fresh process (whose ambient RNG drew a
        DIFFERENT permutation) restores the exact saved batch stream
        through ``CheckpointManager``/``fit(auto_resume=True)``.
        Unshuffled iterators store ``order=None`` (identity), keeping
        the per-checkpoint cursor a few bytes instead of one int per
        dataset row."""
        n = len(self._row_order)
        identity = np.array_equal(self._row_order, np.arange(n))
        return {"cursor": int(self.cursor),
                "order": None if identity
                else np.asarray(self._row_order, np.int64),
                "rows": int(n)}

    def set_state(self, state):
        if not isinstance(state, dict) or "cursor" not in state or \
                "rows" not in state:
            raise ValueError(
                "not an NDArrayIter cursor (missing 'cursor'/'rows'; got "
                f"keys {sorted(state) if isinstance(state, dict) else state}"
                ") — was this checkpoint saved under a different "
                "MXTPU_DATA_PIPELINE setting?")
        n = len(self._row_order)
        rows = int(state.get("rows", n))
        if rows != n:
            raise ValueError(
                "NDArrayIter cursor was saved for a different dataset: "
                f"saved order covers {rows} rows, this iterator holds {n}")
        order = state.get("order")
        order = np.arange(n) if order is None \
            else np.asarray(order, np.int64)
        if not np.array_equal(order, self._row_order):
            # stored rows are base rows permuted by _row_order; map to
            # the SAVED permutation: new[j] = base[order[j]]
            inv = np.empty(n, np.int64)
            inv[self._row_order] = np.arange(n)
            take = inv[order]
            self.data = [(k, nd.array(v.asnumpy()[take]))
                         for k, v in self.data]
            self.label = [(k, nd.array(v.asnumpy()[take]))
                          for k, v in self.label]
            self.data_list = [x[1] for x in self.data] + \
                [x[1] for x in self.label]
            self._row_order = order
            self.idx = order[:len(self.idx)]
        self.cursor = int(state.get("cursor", -self.batch_size))


class MXDataIter(DataIter):
    """Placeholder for the C++-backed registered iterators; in the TPU
    rebuild those iterators are implemented in ``mxnet_tpu.io_native``
    (RecordIO/Image/CSV/LibSVM) and constructed directly (reference:
    io.py:766 wraps handles from MXListDataIters)."""

    def __init__(self, *args, **kwargs):
        raise NotImplementedError(
            "MXDataIter wraps C-API handles; use ImageRecordIter/CSVIter/"
            "LibSVMIter from mxnet_tpu.io directly")


def CSVIter(*args, **kwargs):
    """CSV iterator (reference: src/io/iter_csv.cc:151); implemented in
    io_native once available."""
    from .io_native import CSVIter as _CSVIter
    return _CSVIter(*args, **kwargs)


def LibSVMIter(*args, **kwargs):
    """LibSVM iterator yielding CSR batches (reference:
    src/io/iter_libsvm.cc:200)."""
    from .io_native import LibSVMIter as _LibSVMIter
    return _LibSVMIter(*args, **kwargs)


def ImageRecordIter(path_imgrec, data_shape, batch_size, label_width=1,
                    mean_r=0.0, mean_g=0.0, mean_b=0.0,
                    std_r=1.0, std_g=1.0, std_b=1.0,
                    rand_crop=False, rand_mirror=False, shuffle=False,
                    num_parts=1, part_index=0, preprocess_threads=0,
                    prefetch_buffer=2, resize=0, data_name="data",
                    label_name="softmax_label", **kwargs):
    """RecordIO image iterator with the reference's parameter surface
    (reference: src/io/iter_image_recordio_2.cc:727 ImageRecordIter).

    ``preprocess_threads>0`` selects the multiprocess decode+augment
    pipeline (``image.mp_loader.MPImageRecordIter`` — worker processes
    filling shared-memory batch slots, the TPU rebuild of the reference's
    OpenCV decode thread pool). ``preprocess_threads=0`` keeps the
    single-process ``ImageIter`` path, wrapped in a prefetch thread unless
    ``prefetch_buffer=0``.
    """
    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b])
    std = None
    if (std_r, std_g, std_b) != (1.0, 1.0, 1.0):
        std = np.array([std_r, std_g, std_b])
    import os as _os
    _idx = kwargs.get("path_imgidx") or \
        _os.path.splitext(path_imgrec)[0] + ".idx"
    _mp_keys = ("dtype", "seed", "path_imgidx", "inter_method",
                "as_numpy", "fast_decode")
    _mp_unsupported = set(kwargs) - set(_mp_keys)
    if preprocess_threads and _os.path.isfile(_idx) and not _mp_unsupported:
        from .image.mp_loader import MPImageRecordIter
        return MPImageRecordIter(
            path_imgrec=path_imgrec, data_shape=data_shape,
            batch_size=batch_size, label_width=label_width,
            preprocess_threads=preprocess_threads,
            prefetch_buffer=prefetch_buffer, shuffle=shuffle,
            rand_crop=rand_crop, rand_mirror=rand_mirror, resize=resize,
            mean=mean, std=std, num_parts=num_parts,
            part_index=part_index, data_name=data_name,
            label_name=label_name,
            **{k: v for k, v in kwargs.items() if k in _mp_keys})
    if preprocess_threads:
        import warnings
        # mp-only knobs have no ImageIter equivalent: strip them so they
        # aren't silently swallowed, and say so
        dropped = sorted(set(kwargs) & {"as_numpy", "seed",
                                        "fast_decode"})
        for k in dropped:
            kwargs.pop(k)
        extra = f"; dropping mp-only kwargs {dropped}" if dropped else ""
        if _mp_unsupported:
            warnings.warn(
                "ImageRecordIter: kwargs "
                f"{sorted(_mp_unsupported)} are not supported by the "
                "multiprocess pipeline; falling back to the "
                f"single-process path{extra}")
        else:
            warnings.warn(
                f"ImageRecordIter: no index file at {_idx}; falling back "
                "to the single-process pipeline (preprocess_threads needs "
                f"a .idx — build one with tools/im2rec.py){extra}")
    from .image.image import ImageIter
    it = ImageIter(batch_size=batch_size, data_shape=data_shape,
                   label_width=label_width, path_imgrec=path_imgrec,
                   shuffle=shuffle, num_parts=num_parts,
                   part_index=part_index, rand_crop=rand_crop,
                   rand_mirror=rand_mirror, mean=mean, std=std,
                   resize=resize, data_name=data_name,
                   label_name=label_name, **kwargs)
    if prefetch_buffer:
        return PrefetchingIter(it)
    return it


def ImageDetRecordIter(path_imgrec, data_shape, batch_size,
                       mean_r=0.0, mean_g=0.0, mean_b=0.0, shuffle=False,
                       num_parts=1, part_index=0, **kwargs):
    """Detection RecordIO iterator (reference:
    src/io/iter_image_det_recordio.cc:582)."""
    mean = None
    if mean_r or mean_g or mean_b:
        mean = np.array([mean_r, mean_g, mean_b])
    from .image.detection import ImageDetIter
    return ImageDetIter(batch_size=batch_size, data_shape=data_shape,
                        path_imgrec=path_imgrec, shuffle=shuffle,
                        num_parts=num_parts, part_index=part_index,
                        mean=mean, **kwargs)
