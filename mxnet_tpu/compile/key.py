"""Canonical program cache keys.

A compiled XLA program is reusable exactly when everything that fed the
trace is identical: the graph (symbol JSON), the bound shapes/dtypes,
the optimizer configuration (hyperparameters are baked into the fused
step as trace-time constants — only ``lr`` and the step counter ride as
runtime arguments), the mesh/sharding layout, the fusion-pass flag, and
the backend the executable was built for. ``program_key`` folds all of
that into one sha256 digest; the registry and the persistent cache key
on it.

Version strings (jax / jaxlib / mxnet_tpu / entry format) are kept OUT
of the digest and carried alongside as the ``fingerprint``: a version
upgrade must not silently *miss* (that would quietly recompile forever
against a stale file) — it must *detect* the stale entry, warn, and
overwrite it in place. Hardware identity (backend platform, device
kind, device count) IS part of the digest: a CPU-proxy run and a TPU
run sharing one cache directory are different programs, not stale
versions of each other.
"""
from __future__ import annotations

import hashlib
import json

__all__ = ["ProgramKey", "program_key", "fingerprint", "arg_signature",
           "optimizer_fingerprint", "mesh_fingerprint", "symbol_digest"]

# bump when the on-disk entry layout or the key material schema changes
FORMAT_VERSION = 1

# optimizer attributes that do NOT feed the trace and so must stay OUT
# of the key: the step counter and the base learning rate are runtime
# ARGUMENTS of the fused program (module/fused.py step_fn takes t and
# lr). Hashing them would make a resumed process — restarting mid
# lr-schedule, or simply further along — silently miss every warm
# entry, the exact failure the cache exists to prevent.
_OPT_MUTABLE = {"num_update", "begin_num_update", "_index_update_count",
                "lr"}

_fingerprint_cache = [None]


def fingerprint():
    """Version fingerprint stored WITH each cache entry (not hashed into
    the key): jax/jaxlib/mxnet_tpu versions + entry format. A mismatch
    on load is the version-stale signal."""
    if _fingerprint_cache[0] is None:
        import jax
        try:
            import jaxlib
            jaxlib_v = getattr(jaxlib, "__version__", "?")
        except Exception:
            jaxlib_v = "?"
        from .. import __version__ as mxtpu_v
        _fingerprint_cache[0] = (
            f"jax={jax.__version__};jaxlib={jaxlib_v};"
            f"mxtpu={mxtpu_v};fmt={FORMAT_VERSION}")
    return _fingerprint_cache[0]


def _backend_identity():
    """Hardware identity hashed INTO the key (a different chip is a
    different program, not a stale one)."""
    import jax
    try:
        devs = jax.devices()
        kind = getattr(devs[0], "device_kind", "?")
        return {"platform": jax.default_backend(), "device_kind": kind,
                "ndev": len(devs)}
    except Exception:
        return {"platform": "?", "device_kind": "?", "ndev": 0}


def symbol_digest(symbol):
    """sha256 of the symbol's canonical JSON serialization — the graph
    identity half of every key (MXNet symbols rebuild deterministically
    from JSON, so equal JSON means equal traced graph)."""
    js = symbol.tojson()
    return hashlib.sha256(js.encode("utf-8")).hexdigest()


def arg_signature(args):
    """Structural signature of a concrete argument pytree: a tuple of
    (shape, dtype) per array leaf. The retrace guard stores this per
    entry point and reports the diverging signature when a program
    retraces."""
    import jax
    sig = []
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        if shape is not None:
            sig.append((tuple(int(d) for d in shape),
                        str(getattr(leaf, "dtype", "?"))))
    return tuple(sig)


def optimizer_fingerprint(optimizer):
    """Key material for an optimizer: type name plus every scalar
    hyperparameter and the per-name multiplier dicts. Hyperparameters
    (momentum, wd, betas, lr_mult/wd_mult...) are baked into the fused
    program as constants, so any change is a different program; the
    mutable step counters are runtime args and are excluded."""
    if optimizer is None:
        return None
    out = {"type": type(optimizer).__name__.lower()}
    for k, v in sorted(vars(optimizer).items()):
        if k in _OPT_MUTABLE:
            continue
        if isinstance(v, (int, float, bool, str)):
            out[k] = v
        elif isinstance(v, dict) and k in ("lr_mult", "wd_mult",
                                           "idx2name"):
            out[k] = sorted((str(a), b) for a, b in v.items()
                            if isinstance(b, (int, float, bool, str)))
    return out


def mesh_fingerprint(mesh):
    """Key material for a device mesh: axis names, axis sizes, and the
    device ids in mesh order (GSPMD partitions differently for any of
    these changing)."""
    if mesh is None:
        return None
    try:
        return {
            "axes": list(getattr(mesh, "axis_names", ())),
            "shape": [int(s) for s in
                      getattr(mesh.devices, "shape", ())],
            "devices": [int(getattr(d, "id", -1))
                        for d in mesh.devices.flat],
        }
    except Exception:
        return {"repr": repr(mesh)}


class ProgramKey:
    """One canonical program identity: ``digest`` (sha256 hex over the
    key materials), ``name`` (human label for reports), ``kind`` (entry
    point family), and the ``materials`` dict itself (kept for the
    retrace guard's what-changed diffs)."""

    __slots__ = ("kind", "name", "digest", "materials")

    def __init__(self, kind, name, digest, materials):
        self.kind = kind
        self.name = name
        self.digest = digest
        self.materials = materials

    @property
    def short(self):
        return self.digest[:10]

    def diff(self, other):
        """Names of top-level key materials that differ from ``other``
        (the retrace guard's 'why did this recompile' answer)."""
        if other is None:
            return []
        a, b = self.materials, other.materials
        keys = set(a) | set(b)
        return sorted(k for k in keys if a.get(k) != b.get(k))

    def __repr__(self):
        return f"ProgramKey({self.kind}:{self.name}@{self.short})"


def _canon(obj):
    """Canonicalize key material for json hashing (tuples -> lists,
    dtypes -> str)."""
    if isinstance(obj, dict):
        return {str(k): _canon(v) for k, v in sorted(obj.items())}
    if isinstance(obj, (list, tuple)):
        return [_canon(v) for v in obj]
    if isinstance(obj, (int, float, bool, str)) or obj is None:
        return obj
    return str(obj)


def program_key(kind, name, *, symbol=None, symbol_sha=None,
                input_sigs=(), optimizer=None, mesh=None, fusion=None,
                passes=None, partition=None, extra=None):
    """Build the canonical :class:`ProgramKey` for one entry point.

    ``input_sigs`` is any structural signature of the runtime inputs
    (shapes/dtypes); ``fusion`` the resolved fusion-flag material;
    ``passes`` the rewrite-pipeline fingerprint (per-pass flag/status/
    site count from symbol/passes/manager.py — cached executables must
    never mix pass regimes); ``partition`` the parameter-partition-rule
    fingerprint (parallel/partition.py ``rules_fingerprint`` — two
    processes resolving different layouts trace different programs;
    None when the feature is off keeps keys byte-identical with
    pre-partition builds); ``extra`` entry-point-specific trace inputs
    (guard flag, compute dtype, metric slot signatures, compiler
    options...). Either ``symbol`` or a precomputed ``symbol_sha``
    identifies the graph.
    """
    if symbol_sha is None and symbol is not None:
        symbol_sha = symbol_digest(symbol)
    materials = {
        "kind": kind,
        "symbol": symbol_sha,
        "inputs": _canon(input_sigs),
        "optimizer": _canon(optimizer_fingerprint(optimizer)
                            if optimizer is not None and
                            not isinstance(optimizer, dict) else optimizer),
        "mesh": _canon(mesh_fingerprint(mesh)
                       if mesh is not None and
                       not isinstance(mesh, dict) else mesh),
        "fusion": _canon(fusion),
        "passes": _canon(passes),
        "backend": _backend_identity(),
        "extra": _canon(extra or {}),
    }
    if partition is not None:
        materials["partition"] = _canon(partition)
    blob = json.dumps(materials, sort_keys=True).encode("utf-8")
    digest = hashlib.sha256(blob).hexdigest()
    return ProgramKey(kind, name, digest, materials)
