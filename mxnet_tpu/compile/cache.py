"""Persistent compiled-program cache (``MXTPU_COMPILE_CACHE_DIR``).

One file per program, named by the key digest::

    <dir>/<sha256-digest>.mxprog

Entry layout (self-describing, CRC-guarded)::

    b"MXPROG1\\n"                     magic
    uint32 big-endian header length
    header JSON   {version, digest, name, kind, fingerprint, crc32,
                   payload_len, created, backend}
    payload bytes (pickled (serialized_executable, in_tree, out_tree))

Every write is atomic (``base.atomic_write``: temp + fsync + rename), so
a process killed at any byte never tears an existing entry. On read the
entry is rejected — loudly, with a warning and a counter, never with a
wrong program — when the magic/header don't parse (``corrupt``), the
payload CRC32 or length disagree with the header (``corrupt``: bit rot,
truncation, torn storage below the rename), or the stored version
fingerprint differs from the running stack (``stale``: a jax / jaxlib /
mxnet_tpu upgrade). A rejected entry is overwritten in place by the
fresh compile that replaces it.

Fault injection: the ``compile_cache`` site covers both failure shapes —
``compile_cache:byte=N[:action=kill]`` arms a byte-budgeted write fault
(via the :func:`base.atomic_write` ``guarded_write`` hook), and
``compile_cache:bytes=N`` truncates the entry AFTER the rename commits
(storage lying below the rename), which the CRC must catch on load.
"""
from __future__ import annotations

import json
import os
import struct
import time
import zlib

from ..base import MXNetError, atomic_write

__all__ = ["PersistentCache", "CacheEntryError", "default_cache",
           "cache_enabled"]

_MAGIC = b"MXPROG1\n"
_SUFFIX = ".mxprog"


class CacheEntryError(MXNetError):
    """A cache entry exists but must not be used. ``reason`` is
    ``"corrupt"`` (magic/CRC/length mismatch) or ``"stale"`` (version
    fingerprint mismatch)."""

    def __init__(self, path, reason, detail=""):
        super().__init__(
            f"compile-cache entry '{os.path.basename(path)}' is {reason}"
            f"{': ' + detail if detail else ''}; falling back to a fresh "
            "compile (the entry will be overwritten)")
        self.path = path
        self.reason = reason


class PersistentCache:
    """See module docstring. Construct with an explicit directory, or
    use :func:`default_cache` for the ``MXTPU_COMPILE_CACHE_DIR`` one."""

    def __init__(self, directory):
        self.directory = os.fspath(directory)

    @property
    def enabled(self):
        return bool(self.directory)

    def path_for(self, digest):
        return os.path.join(self.directory, digest + _SUFFIX)

    # -- write ----------------------------------------------------------------
    def put(self, key, payload, fingerprint=None):
        """Atomically write one entry. ``payload`` is the pickled
        serialized-executable blob; ``key`` a ProgramKey. Returns the
        entry path. ``fingerprint`` is overridable for tests only."""
        from . import key as key_mod
        from .. import faultinject
        os.makedirs(self.directory, exist_ok=True)
        header = {
            "version": key_mod.FORMAT_VERSION,
            "digest": key.digest,
            "name": key.name,
            "kind": key.kind,
            "fingerprint": fingerprint or key_mod.fingerprint(),
            "crc32": zlib.crc32(payload) & 0xFFFFFFFF,
            "payload_len": len(payload),
            "created": time.time(),
            "backend": key.materials.get("backend"),
        }
        hdr = json.dumps(header, sort_keys=True).encode("utf-8")
        path = self.path_for(key.digest)
        # the byte-budget fault site rides atomic_write's guarded_write
        # hook, which arms on the 'ckpt_write' site by default — consult
        # the compile_cache site here and re-arm the generic hook
        with atomic_write(path) as f:
            f = faultinject.guarded_write(f, path=path,
                                          site="compile_cache")
            f.write(_MAGIC)
            f.write(struct.pack(">I", len(hdr)))
            f.write(hdr)
            f.write(payload)
        # post-commit tearing (lying storage below the rename): the CRC
        # recorded in the header is what must catch it on load
        faultinject.maybe_truncate(path, site="compile_cache")
        return path

    # -- read -----------------------------------------------------------------
    def read_header(self, path):
        """Parse one entry's header; raises CacheEntryError("corrupt")
        when the magic/header don't parse."""
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    raise CacheEntryError(path, "corrupt", "bad magic")
                (hlen,) = struct.unpack(">I", f.read(4))
                if hlen <= 0 or hlen > (1 << 20):
                    raise CacheEntryError(path, "corrupt",
                                          "implausible header length")
                return json.loads(f.read(hlen).decode("utf-8"))
        except CacheEntryError:
            raise
        except (OSError, ValueError, struct.error,
                UnicodeDecodeError) as e:
            raise CacheEntryError(path, "corrupt", str(e))

    def get(self, digest):
        """Return the payload bytes for ``digest`` after full
        validation, or None when there is no entry. Raises
        :class:`CacheEntryError` on a corrupt or version-stale entry —
        the caller falls back to a fresh compile and overwrites.

        One open, one sequential read: a concurrent overwrite of the
        entry (shared cache volume; atomic_write renames a fresh file
        into place) can never mix the old header with the new payload.
        """
        from . import key as key_mod
        path = self.path_for(digest)
        try:
            with open(path, "rb") as f:
                if f.read(len(_MAGIC)) != _MAGIC:
                    raise CacheEntryError(path, "corrupt", "bad magic")
                (hlen,) = struct.unpack(">I", f.read(4))
                if hlen <= 0 or hlen > (1 << 20):
                    raise CacheEntryError(path, "corrupt",
                                          "implausible header length")
                header = json.loads(f.read(hlen).decode("utf-8"))
                payload = f.read()
        except FileNotFoundError:
            return None
        except CacheEntryError:
            raise
        except (OSError, ValueError, struct.error,
                UnicodeDecodeError) as e:
            raise CacheEntryError(path, "corrupt", str(e))
        if header.get("fingerprint") != key_mod.fingerprint():
            raise CacheEntryError(
                path, "stale",
                f"built by {header.get('fingerprint')!r}, running "
                f"{key_mod.fingerprint()!r}")
        if len(payload) != header.get("payload_len") or \
                (zlib.crc32(payload) & 0xFFFFFFFF) != header.get("crc32"):
            raise CacheEntryError(
                path, "corrupt",
                f"payload CRC/length mismatch ({len(payload)} bytes)")
        return payload

    # -- maintenance (tools/compile_cache.py) ---------------------------------
    def entries(self):
        """[(path, header-or-CacheEntryError)] for every entry file,
        newest first."""
        if not os.path.isdir(self.directory):
            return []
        out = []
        for name in os.listdir(self.directory):
            if not name.endswith(_SUFFIX):
                continue
            path = os.path.join(self.directory, name)
            try:
                out.append((path, self.read_header(path)))
            except CacheEntryError as e:
                out.append((path, e))
        out.sort(key=lambda pe: -os.path.getmtime(pe[0]))
        return out

    def verify(self):
        """Fully validate every entry (header + fingerprint + CRC).
        Returns (ok_count, [(path, reason), ...] for the bad ones)."""
        ok, bad = 0, []
        for path, header in self.entries():
            if isinstance(header, CacheEntryError):
                bad.append((path, header.reason))
                continue
            try:
                self.get(header["digest"])
                ok += 1
            except CacheEntryError as e:
                bad.append((path, e.reason))
        return ok, bad

    def prune(self, max_age_s=None, max_bytes=None, remove_invalid=True):
        """Retention: drop entries older than ``max_age_s``, then drop
        oldest-first until total size fits ``max_bytes``; invalid
        entries always go first. Returns [(path, why)] removed."""
        removed = []
        entries = self.entries()
        now = time.time()
        live = []
        for path, header in entries:
            if isinstance(header, CacheEntryError):
                if remove_invalid:
                    removed.append((path, header.reason))
                    continue
                header = {}
            age = now - float(header.get("created") or
                              os.path.getmtime(path))
            if max_age_s is not None and max_age_s > 0 and age > max_age_s:
                removed.append((path, f"age {age / 86400.0:.1f}d"))
                continue
            live.append((path, os.path.getsize(path)))
        if max_bytes is not None and max_bytes > 0:
            total = sum(s for _, s in live)
            # live is newest-first: evict from the tail (oldest)
            while total > max_bytes and live:
                path, size = live.pop()
                total -= size
                removed.append((path, "size budget"))
        for path, _why in removed:
            try:
                os.unlink(path)
            except OSError:
                pass
        return removed


_jax_cache_wired = [False]


def _maybe_wire_jax_cache(directory):
    """Point JAX's own persistent compilation cache at ``<dir>/xla`` —
    a second, backend-level layer that caches the XLA optimization
    output on TPU/GPU (jax skips it on CPU). Our ``.mxprog`` entries
    remain the primary layer: they skip tracing AND compilation."""
    if _jax_cache_wired[0]:
        return
    _jax_cache_wired[0] = True
    from .. import config
    if not config.get("MXTPU_COMPILE_JAX_CACHE"):
        return
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(directory, "xla"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass


def cache_enabled():
    """Resolve MXTPU_COMPILE_CACHE / MXTPU_COMPILE_CACHE_DIR: on when a
    directory is configured and the switch isn't 0/off."""
    from .. import config
    if not str(config.get("MXTPU_COMPILE_CACHE_DIR") or ""):
        return False
    return str(config.get("MXTPU_COMPILE_CACHE")).lower() not in \
        ("0", "false", "off")


def default_cache():
    """The env-configured cache, or None when disabled."""
    from .. import config
    if not cache_enabled():
        return None
    directory = str(config.get("MXTPU_COMPILE_CACHE_DIR"))
    _maybe_wire_jax_cache(directory)
    return PersistentCache(directory)
