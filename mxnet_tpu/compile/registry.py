"""Program registry: compile observability + AOT load-or-compile.

Every jitted entry point in the framework registers its programs here
under a canonical :class:`~.key.ProgramKey`:

- ``FusedSymbolStep`` (module/fused.py) and ``serving.Predictor``
  route their compiles through :func:`load_or_compile` — full AOT: a
  populated persistent cache turns a cold start's XLA compile storm
  into file loads (``deserialize_and_load``), skipping tracing AND
  compilation.
- ``Executor`` (executor.py) routes its forward / forward+grad jits
  through :func:`shared_programs` + :class:`JitProgram` — identical
  program keys (e.g. two BucketingModule buckets with identical
  shapes) share ONE jitted callable, traces are counted at trace time,
  and first-call wall time is attributed as compile time.

``compile_report()`` (exported as ``mx.compile_report``) is the one
observability surface: per-program compile wall time, cache
hit/miss/error counters, and the retrace guard — per entry point, how
many times it recompiled and the diverging argument signature (or key
material) that caused it. Compile/load/serialize work runs inside
``compile::`` profiler spans so cold-start cost shows up in
``mx.profiler`` dumps next to the ``serving::``/``ft::`` domains.
"""
from __future__ import annotations

import logging
import pickle
import threading
import time
import weakref

from .cache import CacheEntryError, default_cache
from .key import arg_signature  # noqa: F401  (re-export for callers)

__all__ = ["ProgramRecord", "load_or_compile", "shared_programs",
           "JitProgram", "guarded_loaded_program", "note_entry_point",
           "get_record", "compile_report", "donation_supported", "reset"]

logger = logging.getLogger("mxnet_tpu.compile")

_lock = threading.Lock()
_records = {}            # digest -> ProgramRecord
_entry_points = {}       # name -> (ProgramKey, arg_sig)
_retraces = {}           # name -> {"count": int, "events": [...]}
_shared = weakref.WeakValueDictionary()   # digest -> live shared holder
_MAX_RETRACE_EVENTS = 8


class ProgramRecord:
    """Counters for one canonical program (one key digest)."""

    __slots__ = ("name", "kind", "digest", "compiles", "cache_hits",
                 "cache_misses", "cache_errors", "compile_s", "load_s",
                 "serialize_s", "serialized", "arg_sig", "source",
                 "peak_bytes")

    def __init__(self, key):
        self.name = key.name
        self.kind = key.kind
        self.digest = key.digest
        self.compiles = 0        # fresh XLA compiles (traces taken)
        self.cache_hits = 0      # AOT executables loaded from disk
        self.cache_misses = 0    # cache enabled but no entry yet
        self.cache_errors = 0    # corrupt/stale entries rejected
        self.compile_s = 0.0
        self.load_s = 0.0
        self.serialize_s = 0.0
        self.serialized = False  # an entry for this digest was written
        self.arg_sig = None
        self.source = None       # "compile" | "cache" (last acquisition)
        self.peak_bytes = None   # memory_analysis peak (telemetry.memory)

    def as_dict(self):
        out = {
            "name": self.name, "kind": self.kind,
            "digest": self.digest[:10],
            "compiles": self.compiles, "cache_hits": self.cache_hits,
            "cache_misses": self.cache_misses,
            "cache_errors": self.cache_errors,
            "compile_s": round(self.compile_s, 4),
            "load_s": round(self.load_s, 4),
            "serialized": self.serialized,
            "source": self.source,
        }
        if self.peak_bytes is not None:
            out["peak_bytes"] = self.peak_bytes
        return out


def get_record(key_or_digest):
    digest = getattr(key_or_digest, "digest", key_or_digest)
    with _lock:
        return _records.get(digest)


def _ensure(key):
    with _lock:
        rec = _records.get(key.digest)
        if rec is None:
            rec = _records[key.digest] = ProgramRecord(key)
        return rec


def _restore_record(rec):
    """Re-attach a live record after a ``reset()`` evicted it (long-
    lived JitPrograms keep counting across report windows): the current
    registry entry wins; an evicted record re-registers itself."""
    with _lock:
        cur = _records.get(rec.digest)
        if cur is not None:
            return cur
        _records[rec.digest] = rec
        return rec


def _span(name):
    from .. import profiler
    return profiler.Domain("compile").new_task(name)


def _count(name, delta=1):
    try:
        from .. import fault
        fault.count(name, delta)
    except Exception:
        pass


def _emit_event(key, source, secs):
    """Durable ``compile`` event (telemetry exporter; no-op unless
    MXTPU_TELEMETRY_DIR is set — cold-start storms become visible in
    the fleet event stream, not just the in-process report)."""
    try:
        from ..telemetry import export as _texp
        if _texp.enabled():
            _texp.emit_event("compile", name=key.name, kind=key.kind,
                             digest=key.digest[:10], source=source,
                             secs=round(secs, 4))
    except Exception:
        pass


def _note_memory(key, rec, exe):
    """Record the executable's ``memory_analysis()`` next to its cost
    record (telemetry.memory) — read off the program already in hand,
    never a second compile. Runs on BOTH acquisition paths (fresh
    compile and AOT cache load), so a warm start still reports HBM."""
    try:
        from ..telemetry import memory as _tmem
        stats = _tmem.record(key.name, key.kind, key.digest, exe)
        if stats:
            rec.peak_bytes = stats.get("peak_bytes")
    except Exception:
        pass


def note_entry_point(name, key, sig=None):
    """Retrace guard: one entry point (a fused step, a predictor, an
    executor) acquiring a program under a NEW key or argument signature
    after it already held one is a retrace — record how many and what
    diverged (the ISSUE-facing 'why did this recompile' answer)."""
    with _lock:
        prev = _entry_points.get(name)
        _entry_points[name] = (key, sig)
        if prev is None:
            return
        prev_key, prev_sig = prev
        if prev_key.digest == key.digest and prev_sig == sig:
            return
        ent = _retraces.setdefault(name, {"count": 0, "events": []})
        ent["count"] += 1
        if len(ent["events"]) < _MAX_RETRACE_EVENTS:
            ent["events"].append({
                "changed": key.diff(prev_key),
                "from_sig": _sig_summary(prev_sig),
                "to_sig": _sig_summary(sig),
            })


def _sig_summary(sig, limit=6):
    if sig is None:
        return None
    sig = list(sig)
    body = [f"{tuple(s)}:{d}" for s, d in sig[:limit]]
    if len(sig) > limit:
        body.append(f"...+{len(sig) - limit}")
    return body


def donation_supported(backend=None):
    """Whether the backend implements buffer donation. The CPU backend
    does not and warns per compile — the one place that policy lives
    (serving used to carry a local workaround; bench proxies inherit
    this too)."""
    if backend is None:
        import jax
        backend = jax.default_backend()
    return backend != "cpu"


# ---------------------------------------------------------------------------
# AOT path: FusedSymbolStep / Predictor
# ---------------------------------------------------------------------------
def load_or_compile(key, lower, cache=None):
    """Acquire the compiled executable for ``key``.

    ``lower`` is a thunk returning the ``jax.stages.Lowered`` for the
    program (called only on a cache miss). Returns ``(executable,
    source)`` with source ``"cache"`` (AOT-deserialized, zero fresh
    compiles) or ``"compile"`` (fresh trace+compile; the executable is
    then serialized back into the cache best-effort).

    A corrupt or version-stale entry is rejected LOUDLY — warning log,
    ``cache_errors`` counter, ``compile.cache_corrupt``/``_stale``
    fault counters — and falls back to the fresh compile, which
    overwrites the bad entry. It can never produce a wrong program: the
    digest pins every trace input and the CRC pins the bytes.
    """
    rec = _ensure(key)
    if cache is None:
        cache = default_cache()
    payload = None
    if cache is not None:
        try:
            payload = cache.get(key.digest)
            if payload is None:
                rec.cache_misses += 1
        except CacheEntryError as e:
            rec.cache_errors += 1
            _count(f"compile.cache_{e.reason}")
            logger.warning("%s", e)
            payload = None
    if payload is not None:
        try:
            from jax.experimental import serialize_executable
            t0 = time.perf_counter()
            with _span("load"):
                blob, in_tree, out_tree = pickle.loads(payload)
                exe = serialize_executable.deserialize_and_load(
                    blob, in_tree, out_tree)
            load_s = time.perf_counter() - t0
            rec.load_s += load_s
            rec.cache_hits += 1
            rec.source = "cache"
            _count("compile.cache_hits")
            _note_memory(key, rec, exe)
            _refresh_prof_counters()
            _emit_event(key, "cache", load_s)
            return exe, "cache"
        except Exception as e:
            # an entry that validated but won't deserialize (e.g. a
            # pickle from an incompatible stack that slipped the
            # fingerprint) — same loud fallback as corruption
            rec.cache_errors += 1
            _count("compile.cache_deserialize_errors")
            logger.warning(
                "compile-cache entry %s failed to deserialize (%s); "
                "falling back to a fresh compile", key.short, e)
    t0 = time.perf_counter()
    with _span("compile"):
        lowered = lower()
        exe = lowered.compile()
    compile_s = time.perf_counter() - t0
    rec.compile_s += compile_s
    rec.compiles += 1
    rec.source = "compile"
    _count("compile.fresh_compiles")
    if cache is not None:
        t0 = time.perf_counter()
        try:
            from jax.experimental import serialize_executable
            with _span("serialize"):
                blob, in_tree, out_tree = \
                    serialize_executable.serialize(exe)
                cache.put(key, pickle.dumps((blob, in_tree, out_tree)))
            rec.serialized = True
        except Exception as e:
            # backends without executable serialization (or unpicklable
            # shardings): the program still runs, it just isn't AOT
            # reusable — record why, don't fail the step
            _count("compile.serialize_unsupported")
            logger.debug("compile-cache serialize skipped for %s: %s",
                         key.short, e)
        rec.serialize_s += time.perf_counter() - t0
    _note_memory(key, rec, exe)
    _refresh_prof_counters()
    _emit_event(key, "compile", compile_s)
    return exe, "compile"


def guarded_loaded_program(exe, fallback, what, on_reject=None):
    """Wrap a cache-loaded executable so its FIRST call is guarded: an
    aval/layout mismatch the key failed to anticipate degrades to the
    ``fallback`` jit (a fresh in-process compile) with a warning and a
    counter — never a broken step. Argument checking happens before
    execution, so no donated buffer is consumed by the failed attempt.
    Once one call succeeds the guard is dropped. ``on_reject`` lets the
    caller repoint its program table at the fallback."""
    state = {"proven": False}

    def call(*args):
        if state["proven"]:
            return exe(*args)
        try:
            out = exe(*args)
            state["proven"] = True
            return out
        except Exception as err:
            logger.warning(
                "cache-loaded %s executable rejected at call time (%s); "
                "recompiling fresh", what, err)
            _count("compile.load_call_fallback")
            if on_reject is not None:
                on_reject()
            return fallback(*args)

    return call


# ---------------------------------------------------------------------------
# shared-jit path: Executor
# ---------------------------------------------------------------------------
class SharedPrograms:
    """Weakly-shared holder of an executor's jitted callables. Live
    executors with the same program key hold the same instance, so
    identical binds (two buckets with identical shapes) share one XLA
    program; when the last executor dies the programs are collectable."""

    def __init__(self, programs):
        self.programs = programs

    def __getitem__(self, name):
        return self.programs[name]


def shared_programs(key, builder):
    """Memoize ``builder()`` (a dict of jitted callables) on the key
    digest, weakly. Returns (SharedPrograms, was_shared)."""
    with _lock:
        holder = _shared.get(key.digest)
        if holder is not None:
            return holder, True
    built = builder()
    holder = SharedPrograms(built)
    with _lock:
        # a racing builder may have landed first — prefer the shared one
        existing = _shared.get(key.digest)
        if existing is not None:
            return existing, True
        _shared[key.digest] = holder
    return holder, False


class JitProgram:
    """Registry-aware wrapper around one ``jax.jit`` callable.

    Counts traces at trace time (a probe in the wrapped body runs only
    while tracing — the steady-state call adds two perf_counter reads
    and nothing else), attributes the wall time of any call that traced
    as compile time, and feeds the retrace guard with the argument
    signature that diverged. Used by Executor, where programs stay
    shape-polymorphic jits (eval/train static args, optional head
    grads) rather than AOT executables.
    """

    def __init__(self, fn, key, **jit_kwargs):
        import jax
        self.key = key
        self.rec = _ensure(key)

        def probed(*args, **kwargs):
            # runs at trace time only; re-attach the record in case a
            # compile_report(reset=True) window evicted it — a trace
            # after the reset must still be visible in the report
            rec = self.rec = _restore_record(self.rec)
            rec.compiles += 1
            _count("compile.fresh_compiles")
            return fn(*args, **kwargs)

        self._jfn = jax.jit(probed, **jit_kwargs)

    def __call__(self, *args):
        before_rec = self.rec
        before = before_rec.compiles
        t0 = time.perf_counter()
        out = self._jfn(*args)
        rec = self.rec       # the probe may have swapped the record
        if rec is not before_rec or rec.compiles != before:
            rec.compile_s += time.perf_counter() - t0
            rec.source = "compile"
            sig = arg_signature(args)
            note_entry_point(rec.name, self.key, sig)
            rec.arg_sig = sig
            _refresh_prof_counters()
        return out

    def lower(self, *args, **kwargs):
        return self._jfn.lower(*args, **kwargs)


# ---------------------------------------------------------------------------
# report
# ---------------------------------------------------------------------------
_prof_counters = [None]


def _refresh_prof_counters():
    """Mirror the registry totals into ``compile::`` profiler counters
    (profiler.counters()) so live jobs expose them without a report."""
    try:
        from .. import profiler
        if _prof_counters[0] is None:
            dom = profiler.Domain("compile")
            _prof_counters[0] = {
                "fresh_compiles": profiler.Counter(dom, "fresh_compiles"),
                "cache_hits": profiler.Counter(dom, "cache_hits"),
            }
        with _lock:
            fresh = sum(r.compiles for r in _records.values())
            hits = sum(r.cache_hits for r in _records.values())
        _prof_counters[0]["fresh_compiles"].set_value(fresh)
        _prof_counters[0]["cache_hits"].set_value(hits)
    except Exception:
        pass


def _collect(reset=False):
    """Aggregate compile observability (``mx.compile_report()``):

    - ``programs``: one row per canonical program — fresh compiles,
      cache hits/misses/rejections, compile + AOT-load wall seconds;
    - ``retraces``: per entry point, recompile count with the diverging
      argument signature / key material that caused each;
    - ``totals``: summed counters (the subprocess warm-start tests pin
      ``fresh_compiles == 0`` on these);
    - ``cache``: the persistent-cache configuration in effect.

    ``reset=True`` reads and clears inside ONE lock acquisition — a
    compile landing between the read and the clear counts in exactly
    one report window.
    """
    from .cache import cache_enabled
    from .. import config
    with _lock:
        programs = [r.as_dict() for r in _records.values()]
        retraces = {n: {"count": e["count"],
                        "events": list(e["events"])}
                    for n, e in _retraces.items()}
        if reset:
            _records.clear()
            _entry_points.clear()
            _retraces.clear()
    if reset:
        _refresh_prof_counters()
    totals = {
        "programs": len(programs),
        "fresh_compiles": sum(p["compiles"] for p in programs),
        "cache_hits": sum(p["cache_hits"] for p in programs),
        "cache_misses": sum(p["cache_misses"] for p in programs),
        "cache_errors": sum(p["cache_errors"] for p in programs),
        "compile_s": round(sum(p["compile_s"] for p in programs), 4),
        "load_s": round(sum(p["load_s"] for p in programs), 4),
        "retraces": sum(e["count"] for e in retraces.values()),
    }
    return {
        "programs": sorted(programs,
                           key=lambda p: (-p["compile_s"], p["name"])),
        "retraces": retraces,
        "totals": totals,
        "cache": {
            "enabled": cache_enabled(),
            "dir": str(config.get("MXTPU_COMPILE_CACHE_DIR") or "") or
            None,
        },
    }


from ..telemetry import registry as _treg  # noqa: E402

compile_report = _treg.collector_view("compile", _collect)


def reset():
    """Clear every record/retrace counter (between measurement windows
    or test cases). Live programs keep running; their records recreate
    on the next acquisition."""
    with _lock:
        _records.clear()
        _entry_points.clear()
        _retraces.clear()
    _refresh_prof_counters()
