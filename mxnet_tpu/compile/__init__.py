"""AOT compile & persistent program-cache subsystem.

MXNet's symbolic path made compiled-graph reuse first-class: bucketing
executors share plans, symbols serialize to JSON and rebuild
deterministically. This package is the TPU-native descendant — compiled
XLA programs become durable, keyed, reusable artifacts (the TVM lesson),
and compilation itself a measured, managed stage:

- :mod:`.key` — canonical program identity: sha256 over (symbol JSON,
  input shapes/dtypes, optimizer config, mesh/sharding, fusion flag,
  backend identity), with jax/jaxlib/mxnet_tpu versions carried as a
  separate staleness fingerprint.
- :mod:`.cache` — the persistent cache under ``MXTPU_COMPILE_CACHE_DIR``:
  one CRC-guarded ``.mxprog`` file per program holding the serialized
  executable; corrupt or version-stale entries are detected and rejected
  loudly (never a wrong program), then overwritten by the fresh compile.
- :mod:`.registry` — per-program compile wall time, cache hit/miss
  counters, the retrace guard (what recompiled and which argument
  signature diverged), ``compile::`` profiler spans, and the
  ``load_or_compile`` / ``shared_programs`` entry points the fused
  Module step, ``serving.Predictor``, and ``Executor`` route through.

With a populated cache, a second process running the same fused train
step and Predictor bucket set performs ZERO fresh XLA compiles — crash
auto-resume and serving restarts go from compile storm to file loads
(``mx.compile_report()["totals"]["fresh_compiles"] == 0``, pinned in
tests/test_compile_cache.py).

Inspect with ``mx.compile_report()``; manage the cache directory with
``tools/compile_cache.py`` (``ls`` / ``verify`` / ``prune``).
"""
from __future__ import annotations

from .key import (ProgramKey, program_key, fingerprint, arg_signature,
                  optimizer_fingerprint, mesh_fingerprint, symbol_digest)
from .cache import (PersistentCache, CacheEntryError, default_cache,
                    cache_enabled)
from .registry import (ProgramRecord, JitProgram, load_or_compile,
                       shared_programs, guarded_loaded_program,
                       note_entry_point, get_record, compile_report,
                       donation_supported, reset)

__all__ = [
    "ProgramKey", "program_key", "fingerprint", "arg_signature",
    "optimizer_fingerprint", "mesh_fingerprint", "symbol_digest",
    "PersistentCache", "CacheEntryError", "default_cache",
    "cache_enabled",
    "ProgramRecord", "JitProgram", "load_or_compile", "shared_programs",
    "guarded_loaded_program", "note_entry_point", "get_record",
    "compile_report", "donation_supported", "reset",
]
