"""Deterministic fault-injection harness for the fault-tolerance layer.

Faults are armed **by site and ordinal**, never randomly: a spec names a
site (``ckpt_write``, ``nan_grad``, ``data_iter``, ``data_worker``,
``dist_drop``, ``dist_init``, ``ckpt_truncate``, ``compile_cache``,
``telemetry_write``, ``sparse_update``, ``slow_step``,
``tune_trial``, ``decode_step``, ``replica_drop``,
``heartbeat_miss``, ``scale_up``, ``tenant_admit``,
``spec_verify``, ``kv_handoff``) plus
the exact coordinate at which it fires (byte offset, step index, batch
index, call ordinal). ``telemetry_write`` is consulted by the durable
telemetry exporter (telemetry/export.py) on every event append
(``event=N``) and every log rotation (``rotation=K``); with
``action=kill`` it is the kill-mid-write/mid-rotation drill that pins
"the next run tails the event log cleanly, no torn JSONL line". ``compile_cache`` covers both failure shapes of a
persistent compile-cache entry (compile/cache.py): ``byte=N`` dies at
byte N of the entry write, ``bytes=N`` truncates the entry after its
rename commits. ``data_iter`` fires on the consumer thread at an iterator's
B-th ``next()``; ``data_worker`` fires INSIDE a data-pipeline decode
worker at the B-th produced batch (``data/pipeline.py``) — with
``action=kill`` it is the dying-input-worker drill the chaos suite
resumes from checkpoint. ``sparse_update`` fires in the fused step at
the boundary where a row-sparse embedding update would commit
(``step=N``); with ``action=kill`` it is the kill-mid-row-scatter drill
proving checkpoint/resume restores sharded tables and lazy optimizer
state bit-for-bit. ``slow_step`` is consulted at the top of every fused
train step; with ``action=sleep:ms=N`` it stretches each step by N
milliseconds — the deterministic straggler-rank drill behind the fleet
telemetry aggregator's skew flagging (arm it in ONE rank's environment
and ``tools/telemetry.py fleet`` must name that rank). ``tune_trial``
covers the autotuner (tune/): ``trial=N`` fires at the N-th trial's
commit boundary in the search loop (``action=kill`` is the
SIGKILL-mid-search drill — the trial journal must hold only complete,
CRC-valid lines and the resumed search must reuse them), while
``byte=N`` / ``bytes=N`` arm the TuningRecord write itself
(mid-write death / post-rename truncation, which the record CRC must
catch on load). ``decode_step`` is consulted in the decode engine
(serving/decode/engine.py) before each continuous-batching decode
program launch (``token=N``, the engine-wide step ordinal): a raise
fails the in-flight generations with the KV-cache un-advanced, and
``action=kill`` is the SIGKILL-mid-decode drill — a restarted server
must re-serve the interrupted prompts to bit-identical token streams
from a clean compile cache. ``replica_drop`` is consulted by every
``Predictor._run_bucket`` micro-batch (serving/predictor.py):
``call=N`` kills the N-th micro-batch fleet-wide,
``replica=<telemetry id>`` targets one replica, ``action=kill``
SIGKILLs the serving process, ``action=sleep:ms=N`` stretches batches
(the straggler-replica drill), and a plain raise leaves the replica
PERMANENTLY dead — the in-process replica-loss drill the FleetRouter
(serving/fleet.py) must drain and replace with zero dropped requests.
``heartbeat_miss`` is consulted at every elastic heartbeat-lease
renewal (parallel/elastic.py): armed with ``times=K`` it suppresses K
consecutive renewals, so the OTHER ranks see this rank's lease go
stale and trigger the mesh re-form — the lost-worker detection drill
without an actual kill. ``scale_up`` is consulted by every
``FleetRouter.scale_up`` spin-up (serving/fleet.py) before the replica
factory runs (``tenant=<name>``, ``call=N``): a raise fails that
spin-up attempt — the autoscaler (serving/autoscale.py) must count it,
retry with exponential backoff, and keep its policy thread alive —
while ``action=sleep:ms=N`` stretches the spin-up (the hung-provision
drill). ``tenant_admit`` is consulted at every tenant-routed
``FleetRouter.submit`` admission (``tenant=<name>``): a fire sheds
that request cleanly with the tenant-tagged shed counter — the
admission-failure drill proving a broken tenant never poisons its
neighbors. ``spec_verify`` is consulted once per SPECULATIVE round by
``SpecDecodePredictor.spec_step`` (serving/decode/spec.py,
``round=N``): a fire simulates a draft/target divergence storm — the
round's proposals are replaced with deliberately wrong tokens, the
verify program still runs for real, acceptance records zero, and the
windowed degrade policy must drop to plain decode — the stream stays
bit-exact throughout (accept-prefix is unconditionally correct);
``action=kill`` is the SIGKILL-mid-speculation drill. ``kv_handoff``
is consulted at every disaggregated KV-lane transfer
(serving/decode/batcher.py, ``call=N``): a raise loses the handoff
after prefill — the decode side must RE-PREFILL the lane locally and
resume the stream with zero dropped tokens — and ``action=kill``
SIGKILLs mid-transfer. The same spec
always produces the same failure, so CI chaos suites are reproducible
bit-for-bit (contrast: the classic chaos-monkey coin flip, useless as a
regression gate).

Two arming surfaces, merged innermost-wins:

- env ``MXTPU_FAULT_INJECT`` — ``"site:key=val[:key=val];site2:..."``,
  inherited by subprocesses (how the kill-during-checkpoint resume test
  arms the child), and
- the ``inject(...)`` context manager for in-process tests.

Sites are *consulted* by production code via :func:`fire` (or
:func:`guarded_write` for byte-budgeted storage writes); an unarmed site
costs one dict lookup and no lock. Firing either raises
:class:`FaultInjected` (an ``OSError``, so storage sites propagate
through generic I/O handling) or, with ``action=kill``, SIGKILLs the
process — the honest simulation of a machine loss mid-write.
"""
from __future__ import annotations

import os
import threading

__all__ = ["FaultInjected", "inject", "active", "fire", "guarded_write",
           "maybe_truncate", "reset", "fired"]


class FaultInjected(OSError):
    """Raised at an armed fault site (subclasses OSError so storage-site
    failures take the same handling path as real I/O errors)."""

    def __init__(self, site, **ctx):
        detail = ", ".join(f"{k}={v}" for k, v in sorted(ctx.items()))
        super().__init__(f"injected fault at site '{site}' ({detail})")
        self.site = site
        self.ctx = ctx


_lock = threading.Lock()
_stack = []        # programmatic layers: list of {site: params}
_consults = {}     # site -> times fire() was consulted (the implicit 'call')
_fired = {}        # site -> times the site actually fired
_env_cache = (None, {})   # (raw MXTPU_FAULT_INJECT string, parsed spec)


def parse_spec(spec):
    """``"site:k=v:k2=v2;site2:..."`` -> {site: {k: v}} (ints parsed)."""
    out = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        params = {}
        for kv in fields[1:]:
            k, _, v = kv.partition("=")
            try:
                params[k.strip()] = int(v)
            except ValueError:
                params[k.strip()] = v.strip()
        out[fields[0].strip()] = params
    return out


def active(site):
    """The armed params for ``site`` (innermost ``inject`` layer wins,
    then the env spec), or None when unarmed. The unarmed fast path —
    every fused train step and every batch consult it — is lock-free:
    one list truthiness check plus one env lookup, with the parsed env
    spec cached against the raw string."""
    global _env_cache
    if _stack:                      # racy read is fine: arming is scoped
        with _lock:
            for layer in reversed(_stack):
                if site in layer:
                    return dict(layer[site])
    env = os.environ.get("MXTPU_FAULT_INJECT")
    if not env:
        return None
    if _env_cache[0] != env:
        _env_cache = (env, parse_spec(env))
    return _env_cache[1].get(site)


class inject:
    """Arm fault sites for a ``with`` scope::

        with faultinject.inject("nan_grad:step=3"):
            ...
        with faultinject.inject(dist_drop={"call": 1}):
            ...

    Layers nest; site counters reset on entry so ordinals are scoped to
    the injection, not process lifetime.
    """

    def __init__(self, spec=None, **sites):
        layer = parse_spec(spec) if isinstance(spec, str) else dict(spec or {})
        for site, params in sites.items():
            layer[site] = dict(params)
        self._layer = layer

    def __enter__(self):
        with _lock:
            _stack.append(self._layer)
            for site in self._layer:
                _consults.pop(site, None)
                _fired.pop(site, None)
        return self

    def __exit__(self, *exc):
        with _lock:
            _stack.remove(self._layer)


def _matches(params, ctx):
    """Every armed coordinate present in ``ctx`` must equal it; ``times``
    and ``action`` are modifiers, not coordinates."""
    for k, v in params.items():
        if k in ("times", "action", "byte", "bytes", "match", "ms"):
            continue
        if k in ctx and ctx[k] != v:
            return False
    return True


def _record_fire(site):
    _fired[site] = _fired.get(site, 0) + 1
    try:                                    # observability, never load-bearing
        from . import fault
        fault.count(f"injected.{site}")
    except Exception:
        pass


def fire(site, **ctx):
    """Consult a site. Returns True exactly when the armed coordinates
    match ``ctx`` (an implicit 1-based ``call`` ordinal is supplied for
    sites armed on ``call=N``). Honors ``times=N`` (fire at most N times).
    """
    params = active(site)
    if params is None:
        return False
    with _lock:
        _consults[site] = _consults.get(site, 0) + 1
        ctx.setdefault("call", _consults[site])
        if not _matches(params, ctx):
            return False
        if "times" in params and _fired.get(site, 0) >= params["times"]:
            return False
        _record_fire(site)
    action = params.get("action")
    if action == "kill":
        _sigkill(site)
    elif action == "sleep":
        import time
        time.sleep(max(0, params.get("ms", 10)) / 1000.0)
    return True


def fired(site):
    """How many times ``site`` has fired (test assertion helper)."""
    with _lock:
        return _fired.get(site, 0)


def reset():
    """Clear all ordinal/fired counters (between test cases)."""
    with _lock:
        _consults.clear()
        _fired.clear()


def _sigkill(site):
    import signal
    import sys
    print(f"faultinject: SIGKILL at site '{site}'", flush=True)
    sys.stdout.flush()
    os.kill(os.getpid(), signal.SIGKILL)


# -- storage sites -----------------------------------------------------------

class _ByteBudgetFile:
    """File proxy that dies after ``byte`` bytes have been written: the
    prefix that fits is written for real (a torn write, not a clean
    no-op), then the armed action runs — raise :class:`FaultInjected`
    or SIGKILL (``action=kill``)."""

    def __init__(self, fobj, site, params, path):
        self._f = fobj
        self._site = site
        self._params = params
        self._path = path
        self._written = 0
        self._budget = params.get("byte")

    def write(self, data):
        if self._budget is not None and \
                self._written + len(data) > self._budget:
            keep = max(0, self._budget - self._written)
            if keep:
                self._f.write(data[:keep])
            self._f.flush()
            self._written += keep
            with _lock:
                _record_fire(self._site)
            if self._params.get("action") == "kill":
                os.fsync(self._f.fileno())
                _sigkill(self._site)
            raise FaultInjected(self._site, path=self._path,
                                byte=self._budget)
        self._written += len(data)
        return self._f.write(data)

    def __getattr__(self, name):
        return getattr(self._f, name)


def guarded_write(fobj, path=None, site="ckpt_write"):
    """Wrap an open file with the ``ckpt_write`` byte-budget site (no-op
    when unarmed or when ``match=`` doesn't hit ``path``). ``call=N``
    arms only the N-th matching file — how the resume test kills the
    epoch-3 checkpoint write specifically, leaving epoch 2 good."""
    params = active(site)
    if params is None:
        return fobj
    match = params.get("match")
    if match and (path is None or match not in os.path.basename(path)):
        return fobj
    if "call" in params:
        with _lock:
            _consults[site] = _consults.get(site, 0) + 1
            if _consults[site] != params["call"]:
                return fobj
    return _ByteBudgetFile(fobj, site, params, path)


def maybe_truncate(path, site="ckpt_truncate"):
    """``ckpt_truncate:bytes=N[:match=substr]`` — after a file lands,
    truncate it to N bytes (simulates torn storage below the rename,
    e.g. a lying disk cache): the checkpoint loader must detect this
    via the CRC manifest and fall back."""
    params = active(site)
    if params is None:
        return
    match = params.get("match")
    if match and match not in os.path.basename(path):
        return
    n = params.get("bytes", 0)
    if os.path.getsize(path) <= n:
        return
    with _lock:
        _record_fire(site)
    with open(path, "rb+") as f:
        f.truncate(n)
