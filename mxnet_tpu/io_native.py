"""Text-format data iterators: CSV and LibSVM.

Rebuild of the reference's registered C++ iterators (reference:
src/io/iter_csv.cc:151 CSVIter, src/io/iter_libsvm.cc:200 LibSVMIter).
Parsing is vectorized numpy (the C++ used dmlc parsers); chunked reads keep
memory bounded for large files.
"""
from __future__ import annotations

import numpy as np

from . import ndarray as nd
from .io import DataBatch, DataDesc, DataIter

__all__ = ["CSVIter", "LibSVMIter"]


def _parse_shape(s):
    if isinstance(s, (tuple, list)):
        return tuple(int(x) for x in s)
    return tuple(int(x) for x in str(s).strip("()").split(",") if x.strip())


class CSVIter(DataIter):
    """Iterate over CSV files (reference: src/io/iter_csv.cc:151).

    data_csv/label_csv files; data_shape/label_shape are per-sample shapes.
    """

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, dtype="float32",
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self.data_shape = _parse_shape(data_shape)
        self.label_shape = _parse_shape(label_shape)
        self._data = np.loadtxt(data_csv, delimiter=",",
                                dtype=np.dtype(dtype), ndmin=2)
        n = self._data.shape[0]
        self._data = self._data.reshape((n,) + self.data_shape)
        if label_csv is not None:
            self._label = np.loadtxt(label_csv, delimiter=",",
                                     dtype=np.float32, ndmin=2)
            self._label = self._label.reshape((n,) + self.label_shape)
        else:
            self._label = np.zeros((n,) + self.label_shape, np.float32)
        self.round_batch = round_batch
        self.data_name = data_name
        self.label_name = label_name
        self.cursor = -batch_size
        self.num_data = n

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name,
                         (self.batch_size,) + self.label_shape)]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        lo = self.cursor
        hi = min(lo + self.batch_size, self.num_data)
        data = self._data[lo:hi]
        label = self._label[lo:hi]
        pad = self.batch_size - (hi - lo)
        if pad:
            if self.round_batch:
                data = np.concatenate([data, self._data[:pad]])
                label = np.concatenate([label, self._label[:pad]])
            else:
                data = np.concatenate(
                    [data, np.zeros((pad,) + data.shape[1:], data.dtype)])
                label = np.concatenate(
                    [label, np.zeros((pad,) + label.shape[1:],
                                     label.dtype)])
        return DataBatch([nd.array(data)], [nd.array(label)], pad=pad)


class LibSVMIter(DataIter):
    """Iterate over LibSVM-format sparse data (reference:
    src/io/iter_libsvm.cc:200).

    Yields CSR batches when the sparse package is present, dense otherwise.
    ``data_libsvm`` lines: ``label idx:val idx:val ...``.
    """

    @staticmethod
    def _parse_libsvm(path):
        labels, indptr, indices, values = [], [0], [], []
        with open(path) as fin:
            for line in fin:
                parts = line.strip().split()
                if not parts:
                    continue
                if ":" in parts[0]:
                    labels.append(0.0)
                    kvs = parts
                else:
                    labels.append(float(parts[0]))
                    kvs = parts[1:]
                for kv in kvs:
                    k, v = kv.split(":")
                    indices.append(int(k))
                    values.append(float(v))
                indptr.append(len(indices))
        return (np.asarray(labels, np.float32), np.asarray(indptr, np.int64),
                np.asarray(indices, np.int64), np.asarray(values, np.float32))

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__(batch_size)
        self.data_shape = _parse_shape(data_shape)
        num_features = int(np.prod(self.data_shape))
        labels, self._indptr, self._indices, self._values = \
            self._parse_libsvm(data_libsvm)
        if label_libsvm is not None:
            # separate (possibly multi-dim) label file (reference:
            # iter_libsvm.cc label_libsvm param)
            self.label_shape = _parse_shape(label_shape) if label_shape \
                else (1,)
            _, lptr, lind, lval = self._parse_libsvm(label_libsvm)
            width = int(np.prod(self.label_shape))
            dense = np.zeros((len(lptr) - 1, width), np.float32)
            for i in range(len(lptr) - 1):
                lo, hi = lptr[i], lptr[i + 1]
                dense[i, lind[lo:hi]] = lval[lo:hi]
            self._labels = dense.squeeze(-1) if width == 1 else dense
        else:
            self._labels = labels
        self.num_data = len(self._indptr) - 1
        self.num_features = num_features
        self.round_batch = round_batch
        self.data_name = data_name
        self.label_name = label_name
        self.cursor = -batch_size

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size, self.num_features))]

    @property
    def provide_label(self):
        return [DataDesc(self.label_name, (self.batch_size,))]

    def reset(self):
        self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if not self.iter_next():
            raise StopIteration
        lo = self.cursor
        hi = min(lo + self.batch_size, self.num_data)
        rows = list(range(lo, hi))
        pad = self.batch_size - len(rows)
        if pad and self.round_batch:
            rows += list(range(pad))
        # build the CSR batch directly from the stored CSR slices —
        # no densification (reference: iter_sparse_batchloader.h)
        from .ndarray.sparse import csr_matrix
        vals, inds, iptr = [], [], [0]
        for i in rows:
            rlo, rhi = int(self._indptr[i]), int(self._indptr[i + 1])
            vals.append(self._values[rlo:rhi])
            inds.append(self._indices[rlo:rhi])
            iptr.append(iptr[-1] + (rhi - rlo))
        label = self._labels[rows]
        if pad and not self.round_batch:
            # zero-pad to the promised batch shape (matches CSVIter):
            # padded rows are empty in CSR
            iptr.extend([iptr[-1]] * pad)
            label = np.concatenate(
                [label, np.zeros((pad,) + label.shape[1:], label.dtype)])
        batch = csr_matrix(
            (np.concatenate(vals) if vals else np.zeros(0, np.float32),
             np.concatenate(inds) if inds else np.zeros(0, np.int64),
             np.asarray(iptr, np.int64)),
            shape=(self.batch_size, self.num_features))
        return DataBatch([batch], [nd.array(label)], pad=pad)
