"""Gluon utilities (reference: python/mxnet/gluon/utils.py)."""
from __future__ import annotations

import hashlib
import os

import numpy as np

from .. import ndarray as nd
from ..context import Context, cpu

__all__ = ["split_data", "split_and_load", "clip_global_norm", "check_sha1",
           "download"]


def split_data(data, num_slice, batch_axis=0, even_split=True):
    """Split an NDArray along ``batch_axis`` into ``num_slice`` pieces
    (reference: utils.py:38-77)."""
    size = data.shape[batch_axis]
    if size < num_slice:
        raise ValueError(
            f"Too many slices for data with shape {data.shape}. Arguments are "
            f"num_slice={num_slice} and batch_axis={batch_axis}.")
    if even_split and size % num_slice != 0:
        raise ValueError(
            f"data with shape {data.shape} cannot be evenly split into "
            f"{num_slice} slices along axis {batch_axis}. Use a batch size "
            f"that's multiple of {num_slice} or set even_split=False to allow "
            "uneven partitioning of data.")
    step = size // num_slice
    if not even_split:
        slices = [
            data.slice_axis(batch_axis, i * step,
                            (i + 1) * step if i < num_slice - 1 else size)
            for i in range(num_slice)]
    else:
        slices = [data.slice_axis(batch_axis, i * step, (i + 1) * step)
                  for i in range(num_slice)]
    return slices


def split_and_load(data, ctx_list, batch_axis=0, even_split=True):
    """Split data and load each slice to one context
    (reference: utils.py:80-110).

    On TPU the idiomatic form is a single sharded array over the mesh; this
    per-context form is kept for reference-API compatibility and for the
    Module/executor-group emulation."""
    if not isinstance(data, nd.NDArray):
        data = nd.array(data, ctx=ctx_list[0])
    if len(ctx_list) == 1:
        return [data.as_in_context(ctx_list[0])]
    slices = split_data(data, len(ctx_list), batch_axis, even_split)
    return [s.as_in_context(ctx) for s, ctx in zip(slices, ctx_list)]


def clip_global_norm(arrays, max_norm):
    """Rescale so the sum of their 2-norms is at most ``max_norm``
    (reference: utils.py:113-133)."""
    import jax.numpy as jnp
    assert len(arrays) > 0
    total = jnp.sqrt(sum(jnp.sum(jnp.square(a._data)) for a in arrays))
    total_norm = float(total)
    scale = max_norm / (total_norm + 1e-8)
    if scale < 1.0:
        for a in arrays:
            a._data = a._data * scale
    return total_norm


def check_sha1(filename, sha1_hash):
    """(reference: utils.py:136)"""
    sha1 = hashlib.sha1()
    with open(filename, "rb") as f:
        while True:
            data = f.read(1048576)
            if not data:
                break
            sha1.update(data)
    return sha1.hexdigest() == sha1_hash


def download(url, path=None, overwrite=False, sha1_hash=None):
    """Download a file (reference: utils.py:157). This environment has no
    network egress; only file:// and existing local paths resolve."""
    if path is None:
        fname = url.split("/")[-1]
    elif os.path.isdir(path):
        fname = os.path.join(path, url.split("/")[-1])
    else:
        fname = path
    if os.path.exists(fname) and not overwrite and \
            (not sha1_hash or check_sha1(fname, sha1_hash)):
        return fname
    if url.startswith("file://"):
        import shutil
        shutil.copyfile(url[7:], fname)
        return fname
    raise RuntimeError(
        f"cannot download {url}: no network egress in this environment; "
        "place the file at the target path manually")
