"""Gluon Trainer.

TPU-native rebuild of ``mxnet.gluon.trainer`` (reference:
python/mxnet/gluon/trainer.py — step :156-200, kvstore wiring :94-154,
save/load_states :202-235).

Architectural mapping: the reference pushes gradients to a KVStore
(priority=-i for comm/compute overlap) and pulls averaged weights back. Here
single-process training applies the optimizer directly; data-parallel
gradient averaging happens inside the pjit'd step via ``psum`` (see
``mxnet_tpu.kvstore`` / ``mxnet_tpu.parallel``), where XLA overlaps the
collectives with backward compute automatically — the engine-priority trick
falls out of the dataflow.
"""
from __future__ import annotations

from .. import optimizer as opt
from ..ndarray.ndarray import NDArray
from .parameter import Parameter, ParameterDict

__all__ = ["Trainer"]


class Trainer:
    """Applies an Optimizer to a set of Parameters (reference:
    trainer.py:30)."""

    def __init__(self, params, optimizer, optimizer_params=None, kvstore="device",
                 compression_params=None, update_on_kvstore=None):
        if isinstance(params, (dict, ParameterDict)):
            params = list(params.values())
        if not isinstance(params, (list, tuple)):
            raise ValueError(
                "First argument must be a list or dict of Parameters, "
                f"got {type(params)}.")
        self._params = []
        for param in params:
            if not isinstance(param, Parameter):
                raise ValueError(
                    "First argument must be a list or dict of Parameters, "
                    f"got list of {type(param)}.")
            self._params.append(param)
        self._compression_params = compression_params
        optimizer_params = optimizer_params if optimizer_params else {}
        self._scale = float(optimizer_params.get("rescale_grad", 1.0))
        self._init_optimizer(optimizer, optimizer_params)
        self._kvstore_type = kvstore
        self._kvstore = None
        self._update_on_kvstore = update_on_kvstore
        self._kv_initialized = False

    def _init_optimizer(self, optimizer, optimizer_params):
        param_dict = {i: param for i, param in enumerate(self._params)}
        if isinstance(optimizer, opt.Optimizer):
            assert not optimizer_params, \
                "optimizer_params must be None if optimizer is an Optimizer " \
                "instance"
            self._optimizer = optimizer
            self._optimizer.param_dict = param_dict
        else:
            self._optimizer = opt.create(optimizer, param_dict=param_dict,
                                         **optimizer_params)
        self._updaters = [opt.get_updater(self._optimizer)]

    def _init_kvstore(self):
        """Create the kvstore facade lazily (reference: trainer.py:94)."""
        from .. import kvstore as kvs
        if self._kvstore_type is not None and \
                not isinstance(self._kvstore_type, str):
            self._kvstore = self._kvstore_type
        elif self._kvstore_type:
            self._kvstore = kvs.create(self._kvstore_type)
        # single-logical-device training needs no store round-trip (the mesh
        # handles cross-chip reduction inside the step); the kvstore engages
        # only for dist types or an explicit update_on_kvstore=True
        use_kv = self._kvstore is not None and \
            (self._kvstore.is_distributed or self._update_on_kvstore is True)
        if use_kv:
            if self._update_on_kvstore is not False:
                self._kvstore.set_optimizer(self._optimizer)
                self._update_on_kvstore = True
            else:
                self._update_on_kvstore = False
            for i, param in enumerate(self._params):
                if param.grad_req != "null":
                    self._kvstore.init(i, param.list_data())
        else:
            self._kvstore = None
            self._update_on_kvstore = False
        self._kv_initialized = True

    @property
    def learning_rate(self):
        return self._optimizer.lr if self._optimizer.lr_scheduler is None else \
            self._optimizer.lr_scheduler(self._optimizer.num_update)

    def set_learning_rate(self, lr):
        """(reference: trainer.py:150)"""
        self._optimizer.set_learning_rate(lr)

    def step(self, batch_size, ignore_stale_grad=False):
        """Apply one optimization step with gradients scaled by 1/batch_size
        (reference: trainer.py:156)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._all_reduce_grads()
        self._update(ignore_stale_grad)

    def _all_reduce_grads(self):
        """Cross-device gradient reduction. Single-controller TPU training
        shards the batch inside the jitted step, where psum already averaged
        the grads; multi-process/kvstore mode reduces here via the facade
        (reference: trainer.py:190 — push with priority=-i, pull back)."""
        if self._kvstore is None:
            return
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            self._kvstore.push(i, param.list_grad(), priority=-i)
            if self._update_on_kvstore:
                # server-side optimizer already applied the update; pull the
                # fresh weights (reference: model.py:126 _update_params_on_kvstore)
                self._kvstore.pull(i, param.list_data(), priority=-i)
            else:
                self._kvstore.pull(i, param.list_grad(), priority=-i)

    def _update(self, ignore_stale_grad=False):
        updater = self._updaters[0]
        if not hasattr(self, "_last_grad_seq"):
            self._last_grad_seq = {}
        for i, param in enumerate(self._params):
            if param.grad_req == "null":
                continue
            if not ignore_stale_grad and param.grad_req == "write":
                # backward stamps each written leaf grad with the global
                # backward sequence number; a step that sees the same stamp
                # as last step means backward never touched this parameter
                # (reference semantics: trainer.py:176 _version check)
                data = param._check_and_get()
                seq = getattr(data, "_grad_written_seq", None)
                if seq is None or seq == self._last_grad_seq.get(i):
                    raise UserWarning(
                        f"Gradient of Parameter `{param.name}` has not been "
                        "updated by backward since last `step`. This could "
                        "mean a bug in your model that made it only use a "
                        "subset of the Parameters for the last forward pass. "
                        "Call step with ignore_stale_grad=True to suppress "
                        "this warning and skip updating of Parameters with "
                        "stale gradient")
                self._last_grad_seq[i] = seq
            if self._update_on_kvstore:
                continue  # kvstore applied the update in push
            updater(i, param.grad(), param.data())

    def allreduce_grads(self):
        """Explicit grad reduction without update (reference: trainer.py)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._all_reduce_grads()

    def update(self, batch_size, ignore_stale_grad=False):
        """Apply optimizer update only — for use after allreduce_grads
        (reference: trainer.py update)."""
        if not self._kv_initialized:
            self._init_kvstore()
        self._optimizer.rescale_grad = self._scale / batch_size
        self._update(ignore_stale_grad)

    def save_states(self, fname):
        """Save optimizer/updater states (reference: trainer.py:202)."""
        assert self._optimizer is not None
        from ..base import atomic_write
        with atomic_write(fname) as fout:
            fout.write(self._updaters[0].get_states(dump_optimizer=True))

    def load_states(self, fname):
        """(reference: trainer.py:217)"""
        with open(fname, "rb") as f:
            states = f.read()
        self._updaters[0].set_states(states)
        self._optimizer = self._updaters[0].optimizer
        self._optimizer.param_dict = {
            i: param for i, param in enumerate(self._params)}
