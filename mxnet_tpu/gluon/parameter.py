"""Gluon Parameter / ParameterDict.

TPU-native rebuild of ``mxnet.gluon.parameter`` (reference:
python/mxnet/gluon/parameter.py — Parameter :44, deferred init :44-120,
ParameterDict :509). The reference keeps one NDArray copy per GPU context and
reduces gradients across them via KVStore; here a Parameter holds ONE
functional array, and multi-device is expressed by a ``jax.sharding``
annotation on that single array (data parallelism shards the batch, not the
parameter), which is the idiomatic GSPMD formulation of the same capability.
"""
from __future__ import annotations

from collections import OrderedDict
from typing import Optional

import numpy as np

from .. import autograd, initializer
from ..base import MXNetError
from ..context import Context, cpu, current_context
from ..dtype import resolve_dtype
from ..ndarray import ndarray as _nd_mod
from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["DeferredInitializationError", "Parameter", "Constant",
           "ParameterDict", "tensor_types"]

tensor_types = (NDArray,)


class DeferredInitializationError(MXNetError):
    """Error for unfinished deferred initialization
    (reference: parameter.py:37)."""


class Parameter:
    """A Container holding parameter weight and (optionally) gradient.

    Reference semantics (parameter.py:44-120): shape may contain 0s →
    deferred init completed at first forward via ``_finish_deferred_init``;
    ``grad_req`` in {'write', 'add', 'null'}; ``lr_mult``/``wd_mult`` consumed
    by Trainer/Optimizer.
    """

    def __init__(self, name, grad_req="write", shape=None, dtype="float32",
                 lr_mult=1.0, wd_mult=1.0, init=None, allow_deferred_init=False,
                 differentiable=True, stype="default", grad_stype="default"):
        self._var = None
        self._data: Optional[NDArray] = None
        self._grad: Optional[NDArray] = None
        self._deferred_init = ()
        self._differentiable = differentiable
        self._allow_deferred_init = allow_deferred_init
        self._grad_req = None
        self.name = name
        if isinstance(shape, int):
            shape = (shape,)
        self.shape = tuple(shape) if shape is not None else None
        self.dtype = dtype
        self.lr_mult = lr_mult
        self.wd_mult = wd_mult
        self.grad_req = grad_req
        self.init = init
        if stype not in ("default", "row_sparse", "csr"):
            raise ValueError(f"invalid stype {stype}")
        self._stype = stype
        self._grad_stype = grad_stype

    def __repr__(self):
        return f"Parameter {self.name} (shape={self.shape}, dtype={self.dtype})"

    # -- grad_req ------------------------------------------------------------
    @property
    def grad_req(self):
        return self._grad_req

    @grad_req.setter
    def grad_req(self, req):
        if req not in ("write", "add", "null"):
            raise ValueError(f"grad_req must be write/add/null, got {req}")
        if not self._differentiable:
            req = "null"
        if self._grad_req == req:
            return
        self._grad_req = req
        if req == "null":
            self._grad = None
            if self._data is not None:
                self._data._grad = None
                self._data._require_grad = False
        elif self._data is not None:
            self._init_grad()

    # -- init machinery ------------------------------------------------------
    def initialize(self, init=None, ctx=None, default_init=initializer.Uniform(),
                   force_reinit=False):
        """Initialize parameter arrays (reference: parameter.py:286)."""
        if self._data is not None and not force_reinit:
            return
        if ctx is None:
            ctx = [current_context()]
        if isinstance(ctx, Context):
            ctx = [ctx]
        if init is None:
            init = default_init if self.init is None else self.init
        init = initializer.create(init) or default_init
        if self.shape is None or any(s <= 0 for s in self.shape):
            if self._allow_deferred_init:
                self._deferred_init = (init, ctx, default_init, None)
                return
            raise ValueError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                "invalid shape: {}.".format(self.shape))
        self._deferred_init = (init, ctx, default_init, None)
        self._finish_deferred_init()

    def _finish_deferred_init(self):
        if not self._deferred_init:
            return
        init, ctx, default_init, data = self._deferred_init
        self._deferred_init = ()
        if self.shape is None or any(s <= 0 for s in self.shape):
            raise ValueError(
                f"Cannot initialize Parameter '{self.name}' because it has "
                f"invalid shape: {self.shape}.")
        with autograd.pause():
            if data is None:
                data = _nd_mod.array(
                    np.zeros(self.shape, np.dtype(resolve_dtype(self.dtype))),
                    ctx=ctx[0])
                desc = initializer.InitDesc(self.name)
                init(desc, data)
            self._init_impl(data, ctx)

    def _init_impl(self, data, ctx_list):
        self._ctx_list = list(ctx_list)
        self._data = data if isinstance(data, NDArray) else _nd_mod.array(data)
        if self._grad_req != "null":
            self._init_grad()

    def _init_grad(self):
        import jax.numpy as jnp
        if self._grad_stype != "default":
            # row_sparse grads are densified on TPU: XLA reductions over the
            # batch produce dense grads; sparsity shows up in the optimizer's
            # lazy_update path instead (reference: parameter.py grad_stype)
            pass
        self._data.attach_grad(self._grad_req)
        self._grad = self._data.grad

    def _check_and_get(self, ctx=None):
        if self._data is not None:
            return self._data
        if self._deferred_init:
            raise DeferredInitializationError(
                f"Parameter '{self.name}' has not been initialized yet because "
                "initialization was deferred. Actual initialization happens "
                "during the first forward pass. Please pass one batch of data "
                "through the network before accessing Parameters.")
        raise RuntimeError(
            f"Parameter '{self.name}' has not been initialized. Note that you "
            "should initialize parameters and create Trainer with "
            "Block.collect_params() instead of Block.params because the later "
            "does not include Parameters of nested child Blocks")

    # -- shape inference (deferred init) -------------------------------------
    def _infer_shape(self, known_shape):
        """Complete 0-dims in self.shape from an observed shape."""
        if self.shape is None:
            self.shape = tuple(known_shape)
            return
        if len(known_shape) != len(self.shape):
            raise ValueError(
                f"Parameter {self.name}: rank mismatch {self.shape} vs "
                f"{known_shape}")
        new = []
        for s, k in zip(self.shape, known_shape):
            if s > 0 and k > 0 and s != k:
                raise ValueError(
                    f"Parameter {self.name}: shape mismatch {self.shape} vs "
                    f"{known_shape}")
            new.append(s if s > 0 else k)
        self.shape = tuple(new)

    def shape_is_known(self):
        return self.shape is not None and all(s > 0 for s in self.shape)

    # -- data access ---------------------------------------------------------
    def data(self, ctx=None) -> NDArray:
        """The parameter array (reference: parameter.py:389)."""
        return self._check_and_get(ctx)

    def list_data(self):
        """All per-context copies — exactly one here (sharding replaces
        replication; reference: parameter.py:402)."""
        return [self._check_and_get()]

    def grad(self, ctx=None) -> NDArray:
        d = self._check_and_get(ctx)
        if d.grad is None:
            raise RuntimeError(
                f"Cannot get gradient array for Parameter '{self.name}' "
                "because grad_req='null'")
        return d.grad

    def list_grad(self):
        return [self.grad()]

    def list_ctx(self):
        if self._data is None:
            if self._deferred_init:
                return self._deferred_init[1]
            raise RuntimeError(f"Parameter '{self.name}' has not been "
                               "initialized")
        return getattr(self, "_ctx_list", [self._data.context])

    def zero_grad(self):
        """Set gradient to 0 (reference: parameter.py:447)."""
        if self._grad is None:
            return
        import jax.numpy as jnp
        self._grad._data = jnp.zeros_like(self._grad._data)

    def set_data(self, data):
        """Set this parameter's value everywhere (reference: parameter.py:419)."""
        if isinstance(data, NDArray):
            src = data
        else:
            src = _nd_mod.array(data)
        if self._data is None:
            if self._deferred_init:
                self._infer_shape(src.shape)
                init, ctx, default_init, _ = self._deferred_init
                self._deferred_init = (init, ctx, default_init, src)
                self._finish_deferred_init()
                return
            # loading into a never-initialized parameter: initialize from the
            # data directly (reference: parameter.py _load_init)
            self._infer_shape(src.shape)
            self._init_impl(src.copy(), [current_context()])
            return
        self._infer_shape(src.shape)
        self._data._data = src._data.astype(self._data.dtype) \
            if src.dtype != self._data.dtype else src._data

    def reset_ctx(self, ctx):
        """Re-assign to new devices (reference: parameter.py:431)."""
        if isinstance(ctx, Context):
            ctx = [ctx]
        if self._data is not None:
            self._data = self._data.as_in_context(ctx[0])
            self._ctx_list = list(ctx)
            if self._grad_req != "null":
                self._init_grad()
        elif self._deferred_init:
            init, _, default_init, data = self._deferred_init
            self._deferred_init = (init, ctx, default_init, data)

    def cast(self, dtype):
        """Cast data and gradient (reference: parameter.py:469)."""
        self.dtype = dtype
        if self._data is None:
            return
        with autograd.pause():
            self._data = self._data.astype(dtype)
            if self._grad_req != "null":
                self._init_grad()

    def var(self):
        """The symbolic variable for this parameter (reference:
        parameter.py:497)."""
        if self._var is None:
            from .. import symbol as _sym
            self._var = _sym.var(self.name, shape=self.shape, dtype=self.dtype,
                                 lr_mult=self.lr_mult, wd_mult=self.wd_mult,
                                 init=self.init)
        return self._var


class Constant(Parameter):
    """A constant (non-trained) parameter (reference: parameter.py:600)."""

    def __init__(self, name, value):
        if not isinstance(value, NDArray):
            value = _nd_mod.array(value)
        self.value = value

        class _Init(initializer.Initializer):
            def _init_weight(self2, _, arr):
                arr._data = value._data

            _init_default = _init_weight

        super().__init__(name, grad_req="null", shape=value.shape,
                         dtype=value.dtype, init=_Init(), differentiable=False)


class ParameterDict:
    """A dictionary managing a set of Parameters (reference:
    parameter.py:509+). Supports prefix sharing for nested Blocks."""

    def __init__(self, prefix="", shared=None):
        self._prefix = prefix
        self._params = OrderedDict()
        self._shared = shared

    def __getitem__(self, key):
        return self._params[key]

    def __iter__(self):
        return iter(self._params)

    def __repr__(self):
        name = self._prefix + " " if self._prefix else ""
        body = "\n".join(f"  {v!r}" for v in self.values())
        return f"{name}(\n{body}\n)"

    def items(self):
        return self._params.items()

    def keys(self):
        return self._params.keys()

    def values(self):
        return self._params.values()

    @property
    def prefix(self):
        return self._prefix

    def _get_impl(self, name):
        if name in self._params:
            return self._params[name]
        if self._shared is not None and name in self._shared._params:
            self._params[name] = self._shared._params[name]
            return self._params[name]
        return None

    def get(self, name, **kwargs):
        """Retrieve or create a Parameter named ``prefix+name``
        (reference: parameter.py:557)."""
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            param = Parameter(name, **kwargs)
            self._params[name] = param
        else:
            for k, v in kwargs.items():
                if hasattr(param, k) and getattr(param, k) is not None:
                    existing = getattr(param, k)
                    if k == "shape" and v is not None and existing is not None:
                        v = tuple(v) if not isinstance(v, int) else (v,)
                        # merge partial shapes; conflicting known dims is an
                        # error (reference: parameter.py Parameter shape merge)
                        if len(v) == len(existing):
                            for a, b in zip(existing, v):
                                if a > 0 and b > 0 and a != b:
                                    raise AssertionError(
                                        f"Parameter '{name}' already exists "
                                        f"with shape {existing}, incompatible "
                                        f"with requested {v}")
                            param.shape = tuple(
                                a if a > 0 else b
                                for a, b in zip(existing, v))
                            continue
                    if v is not None and v != existing and k in ("dtype",):
                        param.cast(v)
                elif v is not None:
                    setattr(param, k, v)
        return param

    def get_constant(self, name, value=None):
        name = self._prefix + name
        param = self._get_impl(name)
        if param is None:
            if value is None:
                raise KeyError(f"No constant named '{name}'")
            param = Constant(name, value)
            self._params[name] = param
        return param

    def update(self, other):
        """Copy all Parameters in ``other`` (reference: parameter.py:627)."""
        for k, v in other.items():
            if k in self._params and self._params[k] is not v:
                raise ValueError(
                    f"Cannot update self with other because they have different "
                    f"Parameters with the same name '{k}'")
            self._params[k] = v

    def initialize(self, init=initializer.Uniform(), ctx=None, verbose=False,
                   force_reinit=False):
        for v in self.values():
            v.initialize(None, ctx, init, force_reinit=force_reinit)

    def zero_grad(self):
        for v in self.values():
            v.zero_grad()

    def reset_ctx(self, ctx):
        for v in self.values():
            v.reset_ctx(ctx)

    def setattr(self, name, value):
        for v in self.values():
            setattr(v, name, value)

    # -- (de)serialization ---------------------------------------------------
    def save(self, filename, strip_prefix=""):
        """Save to .params file (reference: parameter.py:713; format is the
        ndarray map save — see mxnet_tpu.ndarray save)."""
        arg_dict = {}
        for param in self.values():
            block = param.list_data()
            if len(block) > 1:
                weight = sum(b.copyto(cpu()) for b in block) / len(block)
            else:
                weight = block[0]
            if not param.name.startswith(strip_prefix):
                raise ValueError(
                    f"Prefix '{strip_prefix}' is to be stripped before saving, "
                    f"but Parameter's name '{param.name}' does not start with "
                    f"'{strip_prefix}'")
            arg_dict[param.name[len(strip_prefix):]] = weight
        from ..ndarray import save as nd_save
        nd_save(filename, arg_dict)

    def load(self, filename, ctx=None, allow_missing=False,
             ignore_extra=False, restore_prefix=""):
        """Load from .params file (reference: parameter.py:740)."""
        from ..ndarray import load as nd_load
        arg_dict = nd_load(filename)
        if restore_prefix:
            arg_dict = {restore_prefix + k: v for k, v in arg_dict.items()}
        # strip arg:/aux: markers from Module-style files
        arg_dict = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                    for k, v in arg_dict.items()}
        if not allow_missing:
            for name in self.keys():
                if name not in arg_dict:
                    raise IOError(
                        f"Parameter '{name}' is missing in file '{filename}'")
        for name, v in arg_dict.items():
            if name not in self._params:
                if not ignore_extra:
                    raise IOError(
                        f"Parameter '{name}' loaded from file '{filename}' is "
                        "not present in ParameterDict")
                continue
            self._params[name].set_data(v)
