"""Gluon Block / HybridBlock.

TPU-native rebuild of ``mxnet.gluon.block`` (reference:
python/mxnet/gluon/block.py — Block :123, HybridBlock :376, SymbolBlock :599,
hybridize :332, cache build ``_build_cache`` :436).

Architectural mapping: the reference's ``hybridize()`` traces the network into
a ``CachedOp`` (an NNVM graph JIT that still dispatches per-op to the engine,
src/imperative/cached_op.cc:342). Here ``hybridize()`` stages the whole
forward into ONE ``jax.jit`` computation — XLA fuses the graph, so the TPU
version is strictly stronger (kernel fusion, not just dispatch removal).
Training state (BatchNorm running stats) and RNG (Dropout) are threaded
functionally through the jitted computation and applied after each call.
"""
from __future__ import annotations

import copy
import re
import threading
from collections import OrderedDict

import numpy as np

from .. import autograd
from .. import ndarray as nd_module
from ..context import Context, current_context
from ..ndarray.ndarray import NDArray, _wrap
from .parameter import (DeferredInitializationError, Parameter, ParameterDict)

__all__ = ["Block", "HybridBlock", "SymbolBlock"]


class _BlockScope:
    """Name manager for Blocks (reference: block.py:30-85)."""

    _current = threading.local()

    def __init__(self, block):
        self._block = block
        self._counter = {}
        self._old_scope = None
        self._name_scope = None

    @staticmethod
    def create(prefix, params, hint):
        """Create prefix and params for a new Block."""
        current = getattr(_BlockScope._current, "value", None)
        if current is None:
            if prefix is None:
                from ..name import NameManager
                prefix = NameManager.current.get(None, hint) + "_"
            if params is None:
                params = ParameterDict(prefix)
            else:
                params = ParameterDict(params.prefix, params)
            return prefix, params
        if prefix is None:
            count = current._counter.get(hint, 0)
            prefix = f"{hint}{count}_"
            current._counter[hint] = count + 1
        if params is None:
            parent = current._block.params
            params = ParameterDict(parent.prefix + prefix, parent._shared)
        else:
            params = ParameterDict(params.prefix, params)
        return current._block.prefix + prefix, params

    def __enter__(self):
        if self._block._empty_prefix:
            return self
        self._old_scope = getattr(_BlockScope._current, "value", None)
        _BlockScope._current.value = self
        self._name_scope = None
        from ..name import Prefix
        self._name_scope = Prefix(self._block.prefix)
        self._name_scope.__enter__()
        return self

    def __exit__(self, ptype, value, trace):
        if self._block._empty_prefix:
            return
        if self._name_scope is not None:
            self._name_scope.__exit__(ptype, value, trace)
            self._name_scope = None
        _BlockScope._current.value = self._old_scope


class Block:
    """Base class for all neural network layers and models
    (reference: block.py:123-374)."""

    def __init__(self, prefix=None, params=None):
        self._empty_prefix = prefix == ""
        self._prefix, self._params = _BlockScope.create(prefix, params,
                                                        self._alias())
        self._name = self._prefix[:-1] if self._prefix.endswith("_") \
            else self._prefix
        self._scope = _BlockScope(self)
        self._children = OrderedDict()
        self._reg_params = {}

    def __repr__(self):
        s = "{name}(\n{modstr}\n)"
        modstr = "\n".join(
            f"  ({key}): {_indent(repr(block), 2)}"
            for key, block in self._children.items())
        return s.format(name=self.__class__.__name__, modstr=modstr)

    def __setattr__(self, name, value):
        """Register parameters and child blocks (reference: block.py:180)."""
        if hasattr(self, name):
            existing = getattr(self, name)
            if isinstance(existing, (Parameter, Block)) and \
                    not isinstance(value, type(existing)) and \
                    not isinstance(existing, type(value)):
                raise TypeError(
                    f"Changing attribute type for {self.name} from "
                    f"{type(existing)} to {type(value)} is not allowed.")
        if isinstance(value, Block):
            self.register_child(value, name)
        elif isinstance(value, Parameter):
            assert name not in self._reg_params or \
                self._reg_params[name] is value, \
                "Overriding Parameter attribute %s is not allowed." % name
            self._reg_params[name] = value
            # directly-assigned Parameters also live in the ParameterDict so
            # sharing via params= sees them (reference: block.py __setattr__)
            self._params._params.setdefault(value.name, value)
        super().__setattr__(name, value)

    def _alias(self):
        return self.__class__.__name__.lower()

    @property
    def prefix(self):
        return self._prefix

    @property
    def name(self):
        return self._name

    def name_scope(self):
        """Returns a name-scope context manager (reference: block.py:237)."""
        return self._scope

    @property
    def params(self):
        """The Block's ParameterDict — the live dict (with its shared-dict
        link intact), not a copy, so ``params=other.collect_params()``
        sharing works (reference: block.py:245)."""
        return self._params

    def collect_params(self, select=None):
        """ParameterDict of this Block and all children
        (reference: block.py:252)."""
        ret = ParameterDict(self._params.prefix)
        if select is None:
            ret.update(self.params)
        else:
            pattern = re.compile(select)
            ret._params.update(
                {name: value for name, value in self.params.items()
                 if pattern.match(name)})
        for child in self._children.values():
            ret.update(child.collect_params(select=select))
        return ret

    def register_child(self, block, name=None):
        """Register a child block (reference: block.py:304)."""
        if name is None:
            name = str(len(self._children))
        self._children[name] = block

    def apply(self, fn):
        """Apply fn recursively to every child and self
        (reference: block.py:318)."""
        for child in self._children.values():
            child.apply(fn)
        fn(self)
        return self

    def initialize(self, init=None, ctx=None, verbose=False,
                   force_reinit=False):
        from .. import initializer as _init
        init = init if init is not None else _init.Uniform()
        self.collect_params().initialize(init, ctx, verbose, force_reinit)

    def cast(self, dtype):
        """Cast parameters and children (reference: block.py:357)."""
        for child in self._children.values():
            child.cast(dtype)
        for _, param in self.params.items():
            param.cast(dtype)

    def hybridize(self, active=True, **kwargs):
        """No-op on plain Blocks; recurses (reference: block.py:348)."""
        for child in self._children.values():
            child.hybridize(active, **kwargs)

    # -- checkpoint ----------------------------------------------------------
    def save_parameters(self, filename):
        """Save parameters to file using *structural* names — portable across
        prefixes (reference: block.py save_parameters)."""
        params = self._collect_params_with_prefix()
        from ..ndarray import save as nd_save
        nd_save(filename, {k: v._check_and_get() for k, v in params.items()})

    def load_parameters(self, filename, ctx=None, allow_missing=False,
                        ignore_extra=False, cast_dtype=False):
        from ..ndarray import load as nd_load
        loaded = nd_load(filename)
        params = self._collect_params_with_prefix()
        if not allow_missing:
            for name in params:
                if name not in loaded:
                    raise IOError(
                        f"Parameter '{name}' is missing in file '{filename}'")
        for name, v in loaded.items():
            if name not in params:
                if not ignore_extra:
                    raise IOError(
                        f"Parameter '{name}' loaded from file '{filename}' is "
                        "not present in this Block")
                continue
            params[name].set_data(v)

    # legacy prefix-keyed forms (reference: block.py save_params/load_params)
    def save_params(self, filename):
        self.collect_params().save(filename, strip_prefix=self.prefix)

    def load_params(self, filename, ctx=None, allow_missing=False,
                    ignore_extra=False):
        self.collect_params().load(filename, ctx, allow_missing, ignore_extra,
                                   self.prefix)

    def _collect_params_with_prefix(self, prefix=""):
        if prefix:
            prefix += "."
        ret = {prefix + k: v for k, v in self._reg_params.items()}
        for name, child in self._children.items():
            ret.update(child._collect_params_with_prefix(prefix + name))
        return ret

    # -- forward -------------------------------------------------------------
    def __call__(self, *args):
        return self.forward(*args)

    def forward(self, *args):
        """Override to implement the computation (reference: block.py:373)."""
        raise NotImplementedError

    def summary(self, *inputs):
        """Print a per-layer summary table (reference: block.py summary)."""
        rows = []

        def hook(block, depth):
            for name, child in block._children.items():
                n_params = sum(
                    int(np.prod(p.shape)) for p in child.params.values()
                    if p.shape_is_known())
                rows.append(("  " * depth + child.__class__.__name__ +
                             f"({child.name})", n_params))
                hook(child, depth + 1)

        total = sum(int(np.prod(p.shape))
                    for p in self.collect_params().values()
                    if p.shape_is_known())
        rows.append((self.__class__.__name__ + f"({self.name})", total))
        hook(self, 1)
        width = max(len(r[0]) for r in rows) + 4
        lines = [f"{'Layer':<{width}}Params", "-" * (width + 8)]
        for name, n in rows:
            lines.append(f"{name:<{width}}{n}")
        print("\n".join(lines))


def _indent(s, num_spaces):
    lines = s.split("\n")
    if len(lines) == 1:
        return s
    first = lines.pop(0)
    return first + "\n" + "\n".join(" " * num_spaces + line for line in lines)


# ---------------------------------------------------------------------------
# Trace-time functional state (BatchNorm running stats, Dropout RNG)
# ---------------------------------------------------------------------------
class _TraceState:
    """Collects parameter writes made during a jit trace so they become
    functional outputs of the compiled graph (the reference mutates aux
    states in-place inside the engine; XLA requires the functional form)."""

    _current = threading.local()

    def __init__(self):
        self.writes = OrderedDict()  # param -> traced value

    @staticmethod
    def active():
        return getattr(_TraceState._current, "value", None)


def stateful_write(param, value):
    """Write an NDArray/array into a Parameter, trace-aware.

    In eager mode this mutates the parameter immediately; inside a
    hybridized (jitted) forward the write is recorded and applied with the
    concrete value after the compiled call returns.
    """
    data = value._data if isinstance(value, NDArray) else value
    tr = _TraceState.active()
    if tr is not None:
        tr.writes[param] = data
    else:
        param._check_and_get()._data = data


_sym_trace_vars = threading.local()


class HybridBlock(Block):
    """A Block that can be staged into a single XLA computation
    (reference: block.py:376-598; CachedOp analog src/imperative/cached_op.cc).
    """

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_graph = {}
        self._cached_param_list = None

    def hybridize(self, active=True, static_alloc=False, static_shape=False,
                  **kwargs):
        """Activate staged (jit) execution (reference: block.py:332).

        static_alloc/static_shape are accepted for API parity; XLA always
        plans memory statically, so they are implied.
        """
        self._active = active
        self._flags = dict(static_alloc=static_alloc,
                           static_shape=static_shape, **kwargs)
        self._clear_cached_op()
        super().hybridize(active, static_alloc=static_alloc,
                          static_shape=static_shape, **kwargs)

    def _clear_cached_op(self):
        self._cached_graph = {}
        self._cached_param_list = None

    def cast(self, dtype):
        self._clear_cached_op()
        super().cast(dtype)

    def register_child(self, block, name=None):
        super().register_child(block, name)
        self._clear_cached_op()

    def infer_shape(self, *args):
        """Infer parameter shapes from inputs for deferred initialization.

        Built-in layers override this; custom HybridBlocks with 0-dim
        parameter shapes must too. (The reference infers via the symbolic
        graph, block.py:470; with XLA the layer-local rule is equivalent and
        avoids a second tracing machinery.)
        """
        raise NotImplementedError(
            f"{self.__class__.__name__} has parameters with unknown shape. "
            "Override infer_shape() to support deferred initialization, or "
            "construct with fully-specified shapes.")

    def infer_type(self, *args):
        for p in self._reg_params.values():
            p.dtype = args[0].dtype

    def _gather_params(self):
        out = {}
        for name, p in self._reg_params.items():
            out[name] = p.data()
        return out

    def _finish_deferred(self, *args):
        self.infer_shape(*args)
        for p in self._reg_params.values():
            if p._deferred_init:
                p._finish_deferred_init()

    def __call__(self, *args):
        from ..symbol.symbol import Symbol as _Sym
        if args and isinstance(args[0], _Sym):
            return self.forward(*args)
        if self._active and _TraceState.active() is None:
            return self._call_cached(*args)
        return self.forward(*args)

    def forward(self, x, *args):
        """Gather this block's params and defer to ``hybrid_forward``
        (reference: block.py:541-560).

        When ``x`` is a Symbol the forward composes the symbolic graph
        instead: parameters become variables named by their full name, with
        grad_req=='null' ones marked auxiliary (the reference builds this
        graph in _get_graph, block.py:468)."""
        from ..symbol.symbol import Symbol as _Sym
        if isinstance(x, _Sym):
            from .. import symbol as sym_module
            from ..symbol.symbol import var as _sym_var
            cache = getattr(_sym_trace_vars, "vars", None)
            if cache is None:
                # direct net(symbol) call outside _trace_symbol: dedupe
                # variables per thread so a Parameter shared by two blocks
                # maps to ONE node (two same-named nodes confuse bind)
                if not hasattr(_sym_trace_vars, "fallback"):
                    _sym_trace_vars.fallback = {}
                cache = _sym_trace_vars.fallback
            params = {}
            for name, p in self._reg_params.items():
                v = cache.get(p.name)
                if v is not None and \
                        bool(v._node.attrs.get("__is_aux__")) != \
                        (p.grad_req == "null"):
                    # grad_req classification changed since the node was
                    # cached: mint a fresh node rather than mutating one
                    # embedded in previously built graphs
                    v = None
                if v is None:
                    v = _sym_var(p.name)
                    if p.grad_req == "null":
                        v._node.attrs["__is_aux__"] = True
                    cache[p.name] = v
                params[name] = v
            return self.hybrid_forward(sym_module, x, *args, **params)
        try:
            params = self._gather_params()
        except DeferredInitializationError:
            self._finish_deferred(x, *args)
            params = self._gather_params()
        return self.hybrid_forward(nd_module, x, *args, **params)

    def hybrid_forward(self, F, x, *args, **kwargs):
        """Override to implement the computation. ``F`` is the op namespace
        (``mxnet_tpu.nd``; the same code also runs under jit tracing because
        every op is a pure jax function)."""
        raise NotImplementedError

    # -- staged execution -----------------------------------------------------
    def _get_param_list(self):
        if self._cached_param_list is None:
            self._cached_param_list = [
                p for _, p in sorted(self.collect_params().items())]
        return self._cached_param_list

    def _build_jit(self, training):
        import jax

        block = self
        param_list = self._get_param_list()

        def staged(pvals, arg_arrays, key):
            from .. import random as _random
            saved = [(p._data._data if p._data is not None else None)
                     for p in param_list]
            tr = _TraceState()
            _TraceState._current.value = tr
            prev_r = autograd.set_recording(False)
            prev_t = autograd.set_training(training)
            _random.push_trace_key(key)
            try:
                for p, v in zip(param_list, pvals):
                    p._data._data = v
                out = block.forward(*[_wrap(a) for a in arg_arrays])
            finally:
                _random.pop_trace_key()
                autograd.set_training(prev_t)
                autograd.set_recording(prev_r)
                _TraceState._current.value = None
                for p, v in zip(param_list, saved):
                    if p._data is not None:
                        p._data._data = v
            outs = out if isinstance(out, tuple) else (out,)
            out_arrays = tuple(o._data for o in outs)
            write_params = list(tr.writes.keys())
            write_vals = tuple(tr.writes[p] for p in write_params)
            staged._write_params = write_params
            return out_arrays, write_vals

        return jax.jit(staged), staged

    def _call_cached(self, *args):
        import jax.numpy as jnp
        from .. import random as _random

        nd_args = [a if isinstance(a, NDArray) else _wrap(jnp.asarray(a))
                   for a in args]
        param_list = self._get_param_list()
        # deferred init: run shape inference against these inputs first
        needs_init = any(p._deferred_init for p in param_list)
        if needs_init:
            try:
                for p in param_list:
                    p._check_and_get()
            except DeferredInitializationError:
                # one eager forward completes all nested deferred inits
                out = self.forward(*nd_args)
                self._cached_param_list = None
                param_list = self._get_param_list()
                return out

        training = autograd.is_training()
        recording = autograd.is_recording()
        cache_key = (training,)
        if cache_key not in self._cached_graph:
            self._cached_graph[cache_key] = self._build_jit(training)
        jitted, raw = self._cached_graph[cache_key]

        pvals = tuple(p.data()._data for p in param_list)
        arg_arrays = tuple(a._data for a in nd_args)
        key = _random.next_key()

        if recording:
            n_p = len(pvals)

            def closed(*flat):
                outs, writes = jitted(flat[:n_p], flat[n_p:], key)
                return outs + tuple(writes)

            import jax
            all_out, vjp_fn = jax.vjp(closed, *(pvals + arg_arrays))
            write_params = getattr(raw, "_write_params", [])
            n_main = len(all_out) - len(write_params)
            out_nds = [_wrap(o) for o in all_out[:n_main]]
            write_nds = [_wrap(o) for o in all_out[n_main:]]
            node = autograd.TapeNode(vjp_fn, param_list + nd_args,
                                     len(all_out), self.name, fn=closed)
            for i, o in enumerate(out_nds + write_nds):
                o._node = node
                o._node_index = i
            node.outputs = out_nds + write_nds
            # TapeNode.parents must be the NDArray wrappers of the inputs
            node.parents = [p.data() for p in param_list] + nd_args
            with autograd.pause():
                for p, w in zip(write_params, write_nds):
                    p._check_and_get()._data = w._data
        else:
            outs, writes = jitted(pvals, arg_arrays, key)
            write_params = getattr(raw, "_write_params", [])
            out_nds = [_wrap(o) for o in outs]
            for p, w in zip(write_params, writes):
                p._check_and_get()._data = w
        return out_nds[0] if len(out_nds) == 1 else tuple(out_nds)

    def export(self, path, epoch=0, num_inputs=1):
        """Export to ``<path>-symbol.json`` + ``<path>-NNNN.params``
        (reference: block.py:590 export — the symbol/params pair that
        Module.load / mx.model.load_checkpoint consumes).

        The graph is traced symbolically (inference mode); parameters are
        classified into ``arg:``/``aux:`` keys via the traced symbol's
        list_arguments/list_auxiliary_states, falling back to the
        grad_req=='null' aux convention for params the trace didn't touch.
        """
        sym = self._trace_symbol(num_inputs=num_inputs)
        sym.save(f"{path}-symbol.json")
        aux_names = set(sym.list_auxiliary_states())
        arg_names = set(sym.list_arguments())
        params = {}
        for name, p in self.collect_params().items():
            if name in aux_names:
                key = "aux:" + name
            elif name in arg_names:
                key = "arg:" + name
            else:
                key = ("aux:" if p.grad_req == "null" else "arg:") + name
            params[key] = p._check_and_get()
        from ..ndarray import save as nd_save
        nd_save(f"{path}-{epoch:04d}.params", params)
        return sym

    def _trace_symbol(self, num_inputs=1):
        """Trace this block into a Symbol graph (inference mode).

        Input variables are named ``data`` (single input) or ``data0..N``,
        matching the reference's export convention."""
        from ..symbol.symbol import var as _sym_var
        if num_inputs == 1:
            inputs = [_sym_var("data")]
        else:
            inputs = [_sym_var(f"data{i}") for i in range(num_inputs)]
        _sym_trace_vars.vars = {}
        prev_t = autograd.set_training(False)
        prev_r = autograd.set_recording(False)
        try:
            out = self.forward(*inputs)
        finally:
            autograd.set_recording(prev_r)
            autograd.set_training(prev_t)
            _sym_trace_vars.vars = None
        if isinstance(out, tuple):
            from ..symbol.symbol import Group
            return Group([o for o in out])
        return out


class SymbolBlock(HybridBlock):
    """Construct a block from a Symbol (reference: block.py:599).

    Implemented with the symbol layer in ``mxnet_tpu.symbol``.
    """

    def __init__(self, outputs, inputs, params=None):
        super().__init__(prefix=None, params=params)
        from .. import symbol as _sym
        from .parameter import ParameterDict
        if isinstance(outputs, (list, tuple)) and len(outputs) == 1:
            outputs = outputs[0]
        self._outputs = outputs
        self._inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        # graph params keep their raw symbol names — no block prefix
        # (reference: block.py SymbolBlock uses the unprefixed shared dict)
        self._params = ParameterDict("", shared=self._params._shared
                                     if params is None else params)
        input_names = {i.name for i in self._inputs}
        for name in outputs.list_arguments():
            if name not in input_names:
                self._reg_params[name] = self.params.get(
                    name, allow_deferred_init=True)
        for name in outputs.list_auxiliary_states():
            self._reg_params[name] = self.params.get(
                name, grad_req="null", allow_deferred_init=True)

    def forward(self, *args):
        arg_dict = {i.name: a for i, a in zip(self._inputs, args)}
        for name, p in self._reg_params.items():
            arg_dict[name] = p.data()
        res = self._outputs.eval_dict(arg_dict)
        return res[0] if len(res) == 1 else tuple(res)

    def hybrid_forward(self, F, *args, **kwargs):
        raise NotImplementedError
