"""Fused RNN layers (reference: python/mxnet/gluon/rnn/rnn_layer.py:32-432).

Each layer keeps per-layer/direction i2h/h2h weights (reference param
naming for checkpoint parity) and concatenates them into the flat
cuDNN-layout vector consumed by the fused ``RNN`` op — one ``lax.scan``
whose body is batched MXU matmuls (the cuDNN-fused-kernel analog,
src/operator/cudnn_rnn-inl.h).
"""
from __future__ import annotations

import numpy as np

from ... import ndarray as F
from ..block import HybridBlock

__all__ = ["RNN", "LSTM", "GRU"]


class _RNNLayer(HybridBlock):
    """(reference: rnn_layer.py:32)"""

    def __init__(self, hidden_size, num_layers, layout, dropout,
                 bidirectional, input_size, i2h_weight_initializer,
                 h2h_weight_initializer, i2h_bias_initializer,
                 h2h_bias_initializer, mode, **kwargs):
        super().__init__(**kwargs)
        assert layout in ("TNC", "NTC"), \
            f"Invalid layout {layout}; must be one of ['TNC' or 'NTC']"
        self._hidden_size = hidden_size
        self._num_layers = num_layers
        self._mode = mode
        self._layout = layout
        self._dropout = dropout
        self._dir = 2 if bidirectional else 1
        self._input_size = input_size
        self._i2h_weight_initializer = i2h_weight_initializer
        self._h2h_weight_initializer = h2h_weight_initializer
        self._i2h_bias_initializer = i2h_bias_initializer
        self._h2h_bias_initializer = h2h_bias_initializer
        self._gates = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4,
                       "gru": 3}[mode]
        ng, ni, nh = self._gates, input_size, hidden_size
        for i in range(num_layers):
            for j in ["l", "r"][:self._dir]:
                self._register_param(f"{j}{i}_i2h_weight",
                                     (ng * nh, ni), i2h_weight_initializer)
                self._register_param(f"{j}{i}_h2h_weight",
                                     (ng * nh, nh), h2h_weight_initializer)
                self._register_param(f"{j}{i}_i2h_bias",
                                     (ng * nh,), i2h_bias_initializer)
                self._register_param(f"{j}{i}_h2h_bias",
                                     (ng * nh,), h2h_bias_initializer)
            ni = nh * self._dir

    def _register_param(self, name, shape, init):
        p = self.params.get(name, shape=shape, init=init,
                            allow_deferred_init=True)
        self._reg_params[name] = p
        object.__setattr__(self, name, p)

    def __repr__(self):
        s = "{name}({mapping}, {_layout}"
        if self._num_layers != 1:
            s += ", num_layers={_num_layers}"
        if self._dropout != 0:
            s += ", dropout={_dropout}"
        if self._dir == 2:
            s += ", bidirectional"
        s += ")"
        shape = self.l0_i2h_weight.shape
        mapping = f"{shape[1] if shape[1] else None} -> " \
            f"{shape[0] // self._gates}"
        return s.format(name=self.__class__.__name__, mapping=mapping,
                        **self.__dict__)

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """Initial recurrent states (reference: rnn_layer.py:166)."""
        from ... import ndarray as nd
        states = []
        for info in self.state_info(batch_size):
            info = {k: v for k, v in info.items() if not k.startswith("__")}
            if func is None:
                states.append(nd.zeros(**info, **kwargs))
            else:
                info.update(kwargs)
                states.append(func(**info))
        return states

    def infer_shape(self, x, *args):
        ni = x.shape[2] if self._layout == "TNC" else x.shape[-1]
        ng, nh = self._gates, self._hidden_size
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                getattr(self, f"{j}{i}_i2h_weight")._infer_shape(
                    (ng * nh, ni))
                getattr(self, f"{j}{i}_h2h_weight")._infer_shape(
                    (ng * nh, nh))
                getattr(self, f"{j}{i}_i2h_bias")._infer_shape((ng * nh,))
                getattr(self, f"{j}{i}_h2h_bias")._infer_shape((ng * nh,))
            ni = nh * self._dir

    def forward(self, inputs, states=None):
        """(reference: rnn_layer.py:183)"""
        batch_size = inputs.shape[self._layout.find("N")]
        skip_states = states is None
        if skip_states:
            states = self.begin_state(batch_size)
        if hasattr(states, "shape"):  # single NDArray
            states = [states]
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except Exception:
            self.infer_shape(inputs)
            for p in self._reg_params.values():
                if p._deferred_init:
                    p._finish_deferred_init()
            params = {k: p.data() for k, p in self._reg_params.items()}
        out = self._forward_kernel(inputs, states, params)
        return out[0] if skip_states else out

    def _flat_params(self, params):
        """Concatenate per-layer params into the cuDNN layout
        (weights for all layers, then all biases — rnn-inl.h)."""
        order = []
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                order.append(params[f"{j}{i}_i2h_weight"])
                order.append(params[f"{j}{i}_h2h_weight"])
        for i in range(self._num_layers):
            for j in ["l", "r"][:self._dir]:
                order.append(params[f"{j}{i}_i2h_bias"])
                order.append(params[f"{j}{i}_h2h_bias"])
        return F.concat(*[p.reshape((-1,)) for p in order], dim=0)

    def _forward_kernel(self, inputs, states, params):
        if self._layout == "NTC":
            inputs = inputs.swapaxes(0, 1)
        flat = self._flat_params(params)
        outputs = F.RNN(inputs, flat, *states, state_size=self._hidden_size,
                        num_layers=self._num_layers,
                        bidirectional=self._dir == 2, p=self._dropout,
                        state_outputs=True, mode=self._mode)
        if self._mode == "lstm":
            outputs, states = outputs[0], [outputs[1], outputs[2]]
        else:
            outputs, states = outputs[0], [outputs[1]]
        if self._layout == "NTC":
            outputs = outputs.swapaxes(0, 1)
        return outputs, states


class RNN(_RNNLayer):
    """Vanilla Elman RNN with relu/tanh (reference: rnn_layer.py:244)."""

    def __init__(self, hidden_size, num_layers=1, activation="relu",
                 layout="TNC", dropout=0, bidirectional=False, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "rnn_" + activation, **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class LSTM(_RNNLayer):
    """(reference: rnn_layer.py:318)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "lstm", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"},
                {"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]


class GRU(_RNNLayer):
    """(reference: rnn_layer.py:398)"""

    def __init__(self, hidden_size, num_layers=1, layout="TNC", dropout=0,
                 bidirectional=False, input_size=0,
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 **kwargs):
        super().__init__(hidden_size, num_layers, layout, dropout,
                         bidirectional, input_size, i2h_weight_initializer,
                         h2h_weight_initializer, i2h_bias_initializer,
                         h2h_bias_initializer, "gru", **kwargs)

    def state_info(self, batch_size=0):
        return [{"shape": (self._num_layers * self._dir, batch_size,
                           self._hidden_size), "__layout__": "LNC"}]
