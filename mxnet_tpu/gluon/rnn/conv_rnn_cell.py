"""Convolutional recurrent cells (ConvRNN / ConvLSTM / ConvGRU).

Capability rebuild of the reference's convolutional cell family
(reference: python/mxnet/rnn/rnn_cell.py — BaseConvRNNCell :1094,
ConvRNNCell :1176, ConvLSTMCell :1253 [Shi et al., NIPS 2015],
ConvGRUCell :1348): the i2h/h2h projections are convolutions over
spatial feature maps instead of dense matmuls, so states carry
(batch, hidden, H, W). Convs lower to ``lax.conv_general_dilated``
on the MXU like every other conv in the framework.
"""
from __future__ import annotations

from .rnn_cell import HybridRecurrentCell

__all__ = ["ConvRNNCell", "ConvLSTMCell", "ConvGRUCell"]


def _pair(x):
    return tuple(x) if isinstance(x, (tuple, list)) else (x, x)


class _BaseConvRNNCell(HybridRecurrentCell):
    """Shared conv-projection machinery (reference: rnn_cell.py:1094)."""

    _num_gates = 1

    def __init__(self, input_shape, hidden_size, i2h_kernel=(3, 3),
                 i2h_stride=(1, 1), i2h_pad=(1, 1), i2h_dilate=(1, 1),
                 h2h_kernel=(3, 3), h2h_dilate=(1, 1),
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 activation="tanh", prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._input_shape = tuple(input_shape)  # (C, H, W)
        self._hidden_size = hidden_size
        self._i2h_kernel = _pair(i2h_kernel)
        self._i2h_stride = _pair(i2h_stride)
        self._i2h_pad = _pair(i2h_pad)
        self._i2h_dilate = _pair(i2h_dilate)
        self._h2h_kernel = _pair(h2h_kernel)
        self._h2h_dilate = _pair(h2h_dilate)
        # h2h padding preserves the state's spatial shape
        # (reference: rnn_cell.py:1147 h2h_pad from dilate*(kernel-1)//2)
        self._h2h_pad = tuple(d * (k - 1) // 2 for k, d in
                              zip(self._h2h_kernel, self._h2h_dilate))
        self._activation = activation
        c, h, w = self._input_shape
        self._state_shape = (
            hidden_size,
            (h + 2 * self._i2h_pad[0] -
             self._i2h_dilate[0] * (self._i2h_kernel[0] - 1) - 1)
            // self._i2h_stride[0] + 1,
            (w + 2 * self._i2h_pad[1] -
             self._i2h_dilate[1] * (self._i2h_kernel[1] - 1) - 1)
            // self._i2h_stride[1] + 1)
        ng = self._num_gates
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(ng * hidden_size, c) + self._i2h_kernel,
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight",
            shape=(ng * hidden_size, hidden_size) + self._h2h_kernel,
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(ng * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(ng * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size,) + self._state_shape,
                 "__layout__": "NCHW"}] * self._num_states

    def _conv_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                      i2h_bias, h2h_bias):
        ng = self._num_gates
        i2h = F_.Convolution(inputs, i2h_weight, i2h_bias,
                             kernel=self._i2h_kernel,
                             stride=self._i2h_stride,
                             pad=self._i2h_pad,
                             dilate=self._i2h_dilate,
                             num_filter=ng * self._hidden_size)
        h2h = F_.Convolution(states[0], h2h_weight, h2h_bias,
                             kernel=self._h2h_kernel,
                             stride=(1, 1),
                             pad=self._h2h_pad,
                             dilate=self._h2h_dilate,
                             num_filter=ng * self._hidden_size)
        return i2h, h2h

    def _act(self, F_, x):
        return F_.Activation(x, act_type=self._activation) \
            if isinstance(self._activation, str) else self._activation(x)


class ConvRNNCell(_BaseConvRNNCell):
    """(reference: rnn_cell.py:1176)"""

    _num_gates = 1
    _num_states = 1

    def _alias(self):
        return "conv_rnn"

    def hybrid_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F_, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        out = self._act(F_, i2h + h2h)
        return out, [out]


class ConvLSTMCell(_BaseConvRNNCell):
    """Convolutional LSTM (Shi et al., NIPS 2015; reference:
    rnn_cell.py:1253)."""

    _num_gates = 4
    _num_states = 2

    def _alias(self):
        return "conv_lstm"

    def hybrid_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F_, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        gates = i2h + h2h
        sg = gates.split(num_outputs=4, axis=1)
        in_gate = F_.Activation(sg[0], act_type="sigmoid")
        forget_gate = F_.Activation(sg[1], act_type="sigmoid")
        in_transform = self._act(F_, sg[2])
        out_gate = F_.Activation(sg[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * self._act(F_, next_c)
        return next_h, [next_h, next_c]


class ConvGRUCell(_BaseConvRNNCell):
    """(reference: rnn_cell.py:1348)"""

    _num_gates = 3
    _num_states = 1

    def _alias(self):
        return "conv_gru"

    def hybrid_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h, h2h = self._conv_forward(F_, inputs, states, i2h_weight,
                                      h2h_weight, i2h_bias, h2h_bias)
        i2h_r, i2h_z, i2h_n = i2h.split(num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = h2h.split(num_outputs=3, axis=1)
        reset = F_.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update = F_.Activation(i2h_z + h2h_z, act_type="sigmoid")
        cand = self._act(F_, i2h_n + reset * h2h_n)
        next_h = (1.0 - update) * cand + update * states[0]
        return next_h, [next_h]
