"""RNN cells (reference: python/mxnet/gluon/rnn/rnn_cell.py and the legacy
symbolic zoo python/mxnet/rnn/rnn_cell.py:536+).

Cells run one timestep; ``unroll`` lays out the timesteps. On TPU prefer
the fused layers in rnn_layer.py (single scan); cells exist for custom
recurrences and parity. ``unroll`` is a Python loop: under hybridize the
whole unrolled graph still compiles to one XLA program.
"""
from __future__ import annotations

from ... import ndarray as F
from ..block import Block, HybridBlock

__all__ = ["RecurrentCell", "HybridRecurrentCell", "RNNCell", "LSTMCell",
           "GRUCell", "SequentialRNNCell", "DropoutCell", "ModifierCell",
           "ZoneoutCell", "ResidualCell", "BidirectionalCell"]


def _cells_state_info(cells, batch_size):
    return sum([c.state_info(batch_size) for c in cells], [])


def _cells_begin_state(cells, **kwargs):
    return sum([c.begin_state(**kwargs) for c in cells], [])


def _format_sequence(length, inputs, layout, merge, in_layout=None):
    """Normalize inputs to a list of per-step arrays or a merged tensor
    (reference: rnn_cell.py:55)."""
    from ...ndarray.ndarray import NDArray
    assert inputs is not None
    axis = layout.find("T")
    batch_axis = layout.find("N")
    if isinstance(inputs, NDArray):
        batch_size = inputs.shape[batch_axis]
        if merge is False:
            assert length is None or length == inputs.shape[axis]
            inputs = [x.squeeze(axis=axis) for x in
                      inputs.split(num_outputs=inputs.shape[axis],
                                   axis=axis, squeeze_axis=False)]
    else:
        assert length is None or len(inputs) == length
        batch_size = inputs[0].shape[0]
        if merge is True:
            inputs = [x.expand_dims(axis=axis) for x in inputs]
            inputs = F.concat(*inputs, dim=axis)
    return inputs, axis, batch_size


class RecurrentCell(Block):
    """Abstract base for RNN cells (reference: rnn_cell.py:93)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._modified = False
        self.reset()

    def reset(self):
        """Reset before re-use (reference: rnn_cell.py:110)."""
        self._init_counter = -1
        self._counter = -1
        for cell in self._children.values():
            if isinstance(cell, RecurrentCell):
                cell.reset()

    def state_info(self, batch_size=0):
        raise NotImplementedError

    def begin_state(self, batch_size=0, func=None, **kwargs):
        """(reference: rnn_cell.py:129)"""
        from ... import ndarray as nd
        assert not self._modified, \
            "After applying modifier cells (e.g. ZoneoutCell) the base cell " \
            "cannot be called directly. Call the modifier cell instead."
        states = []
        for info in self.state_info(batch_size):
            self._init_counter += 1
            info = {k: v for k, v in (info or {}).items()
                    if not k.startswith("__")}
            if func is None:
                states.append(nd.zeros(**info, **kwargs))
            else:
                info.update(kwargs)
                states.append(func(**info))
        return states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        """Unroll the cell over ``length`` steps (reference:
        rnn_cell.py:173)."""
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        outputs = []
        for i in range(length):
            output, new_states = self(inputs[i], states)
            if valid_length is not None:
                # step i is valid for sequences with valid_length > i:
                # mask the output to 0 and FREEZE the state at the last
                # valid step (reference: rnn_cell.py SequenceLast handling)
                mask = (valid_length > float(i)).astype(output.dtype)
                mask_col = mask.reshape((-1, 1))
                output = output * mask_col
                states = [n * mask.reshape((-1,) + (1,) * (n.ndim - 1)) +
                          s * (1 - mask.reshape((-1,) + (1,) * (n.ndim - 1)))
                          for n, s in zip(new_states, states)]
            else:
                states = new_states
            outputs.append(output)
        if merge_outputs:
            outputs = [o.expand_dims(axis=axis) for o in outputs]
            outputs = F.concat(*outputs, dim=axis)
        return outputs, states

    def _get_activation(self, F_, inputs, activation, **kwargs):
        if isinstance(activation, str):
            return F_.Activation(inputs, act_type=activation, **kwargs)
        return activation(inputs, **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        return super().__call__(inputs, states)

    def forward(self, inputs, states):
        raise NotImplementedError


class HybridRecurrentCell(RecurrentCell, HybridBlock):
    """(reference: rnn_cell.py:245)"""

    def __init__(self, prefix=None, params=None):
        RecurrentCell.__init__(self, prefix=prefix, params=params)
        self._active = False
        self._flags = {}
        self._cached_graph = {}
        self._cached_param_list = None

    def forward(self, x, *args):
        try:
            params = {k: p.data() for k, p in self._reg_params.items()}
        except Exception:
            self.infer_shape(x)
            for p in self._reg_params.values():
                if p._deferred_init:
                    p._finish_deferred_init()
            params = {k: p.data() for k, p in self._reg_params.items()}
        return self.hybrid_forward(F, x, *args, **params)

    def hybrid_forward(self, F_, x, *args, **kwargs):
        raise NotImplementedError


class RNNCell(HybridRecurrentCell):
    """Elman cell (reference: rnn_cell.py:270)."""

    def __init__(self, hidden_size, activation="tanh",
                 i2h_weight_initializer=None, h2h_weight_initializer=None,
                 i2h_bias_initializer="zeros", h2h_bias_initializer="zeros",
                 input_size=0, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._activation = activation
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(hidden_size,), init=i2h_bias_initializer,
            allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(hidden_size,), init=h2h_bias_initializer,
            allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "rnn"

    def infer_shape(self, x):
        self.i2h_weight._infer_shape((self._hidden_size, x.shape[-1]))
        self.h2h_weight._infer_shape((self._hidden_size, self._hidden_size))
        self.i2h_bias._infer_shape((self._hidden_size,))
        self.h2h_bias._infer_shape((self._hidden_size,))

    def hybrid_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F_.FullyConnected(inputs, i2h_weight, i2h_bias,
                                num_hidden=self._hidden_size)
        h2h = F_.FullyConnected(states[0], h2h_weight, h2h_bias,
                                num_hidden=self._hidden_size)
        output = self._get_activation(F_, i2h + h2h, self._activation)
        return output, [output]


class LSTMCell(HybridRecurrentCell):
    """(reference: rnn_cell.py:343)"""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(4 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(4 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(4 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(4 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"},
                {"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "lstm"

    def infer_shape(self, x):
        self.i2h_weight._infer_shape((4 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._infer_shape((4 * self._hidden_size,
                                      self._hidden_size))
        self.i2h_bias._infer_shape((4 * self._hidden_size,))
        self.h2h_bias._infer_shape((4 * self._hidden_size,))

    def hybrid_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        i2h = F_.FullyConnected(inputs, i2h_weight, i2h_bias,
                                num_hidden=4 * self._hidden_size)
        h2h = F_.FullyConnected(states[0], h2h_weight, h2h_bias,
                                num_hidden=4 * self._hidden_size)
        gates = i2h + h2h
        slice_gates = gates.split(num_outputs=4, axis=1)
        in_gate = F_.Activation(slice_gates[0], act_type="sigmoid")
        forget_gate = F_.Activation(slice_gates[1], act_type="sigmoid")
        in_transform = F_.Activation(slice_gates[2], act_type="tanh")
        out_gate = F_.Activation(slice_gates[3], act_type="sigmoid")
        next_c = forget_gate * states[1] + in_gate * in_transform
        next_h = out_gate * F_.Activation(next_c, act_type="tanh")
        return next_h, [next_h, next_c]


class GRUCell(HybridRecurrentCell):
    """(reference: rnn_cell.py:437)"""

    def __init__(self, hidden_size, i2h_weight_initializer=None,
                 h2h_weight_initializer=None, i2h_bias_initializer="zeros",
                 h2h_bias_initializer="zeros", input_size=0, prefix=None,
                 params=None):
        super().__init__(prefix=prefix, params=params)
        self._hidden_size = hidden_size
        self._input_size = input_size
        self.i2h_weight = self.params.get(
            "i2h_weight", shape=(3 * hidden_size, input_size),
            init=i2h_weight_initializer, allow_deferred_init=True)
        self.h2h_weight = self.params.get(
            "h2h_weight", shape=(3 * hidden_size, hidden_size),
            init=h2h_weight_initializer, allow_deferred_init=True)
        self.i2h_bias = self.params.get(
            "i2h_bias", shape=(3 * hidden_size,),
            init=i2h_bias_initializer, allow_deferred_init=True)
        self.h2h_bias = self.params.get(
            "h2h_bias", shape=(3 * hidden_size,),
            init=h2h_bias_initializer, allow_deferred_init=True)

    def state_info(self, batch_size=0):
        return [{"shape": (batch_size, self._hidden_size),
                 "__layout__": "NC"}]

    def _alias(self):
        return "gru"

    def infer_shape(self, x):
        self.i2h_weight._infer_shape((3 * self._hidden_size, x.shape[-1]))
        self.h2h_weight._infer_shape((3 * self._hidden_size,
                                      self._hidden_size))
        self.i2h_bias._infer_shape((3 * self._hidden_size,))
        self.h2h_bias._infer_shape((3 * self._hidden_size,))

    def hybrid_forward(self, F_, inputs, states, i2h_weight, h2h_weight,
                       i2h_bias, h2h_bias):
        prev_state_h = states[0]
        i2h = F_.FullyConnected(inputs, i2h_weight, i2h_bias,
                                num_hidden=3 * self._hidden_size)
        h2h = F_.FullyConnected(prev_state_h, h2h_weight, h2h_bias,
                                num_hidden=3 * self._hidden_size)
        i2h_r, i2h_z, i2h_n = i2h.split(num_outputs=3, axis=1)
        h2h_r, h2h_z, h2h_n = h2h.split(num_outputs=3, axis=1)
        reset_gate = F_.Activation(i2h_r + h2h_r, act_type="sigmoid")
        update_gate = F_.Activation(i2h_z + h2h_z, act_type="sigmoid")
        next_h_tmp = F_.Activation(i2h_n + reset_gate * h2h_n,
                                   act_type="tanh")
        next_h = (1.0 - update_gate) * next_h_tmp + \
            update_gate * prev_state_h
        return next_h, [next_h]


class SequentialRNNCell(RecurrentCell):
    """Stack cells (reference: rnn_cell.py:518)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, cell):
        self.register_child(cell)

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def __call__(self, inputs, states):
        self._counter += 1
        next_states = []
        p = 0
        for cell in self._children.values():
            assert not isinstance(cell, BidirectionalCell)
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell(inputs, state)
            next_states.append(state)
        return inputs, sum(next_states, [])

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        num_cells = len(self._children)
        if begin_state is None:
            inputs_first, _, batch_size = _format_sequence(
                length, inputs, layout, None)
            begin_state = self.begin_state(batch_size=batch_size)
        p = 0
        states = begin_state
        next_states = []
        for i, cell in enumerate(self._children.values()):
            n = len(cell.state_info())
            state = states[p:p + n]
            p += n
            inputs, state = cell.unroll(
                length, inputs, begin_state=state, layout=layout,
                merge_outputs=None if i < num_cells - 1 else merge_outputs,
                valid_length=valid_length)
            next_states.extend(state)
        return inputs, next_states

    def __getitem__(self, i):
        return list(self._children.values())[i]

    def __len__(self):
        return len(self._children)

    def forward(self, *args):
        raise NotImplementedError


class DropoutCell(HybridRecurrentCell):
    """(reference: rnn_cell.py:611)"""

    def __init__(self, rate, axes=(), prefix=None, params=None):
        super().__init__(prefix, params)
        assert isinstance(rate, float)
        self._rate = rate
        self._axes = axes

    def state_info(self, batch_size=0):
        return []

    def _alias(self):
        return "dropout"

    def hybrid_forward(self, F_, inputs, states):
        if self._rate > 0:
            inputs = F_.Dropout(inputs, p=self._rate, axes=self._axes)
        return inputs, states

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, _, _ = _format_sequence(length, inputs, layout,
                                        merge_outputs)
        if hasattr(inputs, "shape"):
            return self.hybrid_forward(F, inputs, begin_state or [])
        return super().unroll(length, inputs, begin_state=begin_state,
                              layout=layout, merge_outputs=merge_outputs,
                              valid_length=valid_length)


class ModifierCell(HybridRecurrentCell):
    """Base for cells that modify another cell (reference:
    rnn_cell.py:672)."""

    def __init__(self, base_cell):
        assert not base_cell._modified, \
            "Cell %s is already modified. One cell cannot be modified " \
            "twice" % base_cell.name
        base_cell._modified = True
        super().__init__(prefix=base_cell.prefix + self._alias(),
                         params=None)
        self.base_cell = base_cell

    @property
    def params(self):
        return self.base_cell.params

    def state_info(self, batch_size=0):
        return self.base_cell.state_info(batch_size)

    def begin_state(self, func=None, **kwargs):
        assert not self._modified
        self.base_cell._modified = False
        begin = self.base_cell.begin_state(func=func, **kwargs)
        self.base_cell._modified = True
        return begin

    def hybrid_forward(self, F_, inputs, states):
        raise NotImplementedError


class ZoneoutCell(ModifierCell):
    """(reference: rnn_cell.py:731)"""

    def __init__(self, base_cell, zoneout_outputs=0.0, zoneout_states=0.0):
        assert not isinstance(base_cell, BidirectionalCell), \
            "BidirectionalCell doesn't support zoneout. Apply zoneout to " \
            "the cells underneath instead."
        super().__init__(base_cell)
        self.zoneout_outputs = zoneout_outputs
        self.zoneout_states = zoneout_states
        self._prev_output = None

    def _alias(self):
        return "zoneout"

    def reset(self):
        super().reset()
        self._prev_output = None

    def hybrid_forward(self, F_, inputs, states):
        cell = self.base_cell
        p_outputs, p_states = self.zoneout_outputs, self.zoneout_states
        next_output, next_states = cell(inputs, states)

        def mask(p, like):
            keep = F_.Dropout(F_.ones_like(like), p=p)
            return keep

        prev_output = self._prev_output
        if prev_output is None:
            prev_output = F_.zeros_like(next_output)
        output = F_.where(mask(p_outputs, next_output), next_output,
                          prev_output) if p_outputs != 0.0 else next_output
        new_states = [F_.where(mask(p_states, new_s), new_s, old_s)
                      for new_s, old_s in zip(next_states, states)] \
            if p_states != 0.0 else next_states
        self._prev_output = output
        return output, new_states


class ResidualCell(ModifierCell):
    """(reference: rnn_cell.py:800)"""

    def _alias(self):
        return "residual"

    def hybrid_forward(self, F_, inputs, states):
        output, states = self.base_cell(inputs, states)
        output = output + inputs
        return output, states


class BidirectionalCell(HybridRecurrentCell):
    """(reference: rnn_cell.py:852)"""

    def __init__(self, l_cell, r_cell, output_prefix="bi_"):
        super().__init__(prefix="", params=None)
        self.register_child(l_cell, "l_cell")
        self.register_child(r_cell, "r_cell")
        self._output_prefix = output_prefix

    def __call__(self, inputs, states):
        raise NotImplementedError(
            "Bidirectional cannot be stepped. Please use unroll")

    def state_info(self, batch_size=0):
        return _cells_state_info(self._children.values(), batch_size)

    def begin_state(self, **kwargs):
        assert not self._modified
        return _cells_begin_state(self._children.values(), **kwargs)

    def unroll(self, length, inputs, begin_state=None, layout="NTC",
               merge_outputs=None, valid_length=None):
        self.reset()
        inputs, axis, batch_size = _format_sequence(length, inputs, layout,
                                                    False)
        if begin_state is None:
            begin_state = self.begin_state(batch_size=batch_size)
        states = begin_state
        l_cell, r_cell = self._children.values()
        n_l = len(l_cell.state_info(batch_size))
        l_outputs, l_states = l_cell.unroll(
            length, inputs=inputs, begin_state=states[:n_l], layout=layout,
            merge_outputs=False, valid_length=valid_length)
        r_outputs, r_states = r_cell.unroll(
            length, inputs=list(reversed(inputs)),
            begin_state=states[n_l:], layout=layout, merge_outputs=False,
            valid_length=valid_length)
        outputs = [F.concat(l_o, r_o, dim=1)
                   for l_o, r_o in zip(l_outputs, reversed(r_outputs))]
        if merge_outputs:
            outputs = [o.expand_dims(axis=axis) for o in outputs]
            outputs = F.concat(*outputs, dim=axis)
        states = l_states + r_states
        return outputs, states
