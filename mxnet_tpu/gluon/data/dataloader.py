"""DataLoader with multiprocess workers.

TPU-native rebuild of ``mxnet.gluon.data.dataloader`` (reference:
python/mxnet/gluon/data/dataloader.py:35-200).

The reference rebuilds NDArrays over POSIX shared memory between workers
(cpu_shared_storage_manager.h); here workers return numpy arrays over
multiprocessing pipes and the main process device_puts the assembled batch —
host→TPU transfer is the same single DMA either way, and JAX's async
dispatch overlaps it with compute.
"""
from __future__ import annotations

import multiprocessing
import pickle

import numpy as np

from ... import ndarray as nd
from ...ndarray.ndarray import NDArray
from . import sampler as _sampler

__all__ = ["DataLoader", "default_batchify_fn"]


def default_batchify_fn(data):
    """Stack samples into a batch (reference: dataloader.py:82)."""
    if isinstance(data[0], NDArray):
        return nd.stack(*data)
    if isinstance(data[0], tuple):
        data = zip(*data)
        return [default_batchify_fn(i) for i in data]
    out = np.asarray(data)
    return nd.array(out)


def _np_batchify(data):
    """numpy-only batchify for workers: no JAX device ops in the forked
    child (the parent's JAX runtime is multi-threaded; device work in a
    forked child can deadlock)."""
    first = data[0]
    if isinstance(first, NDArray):
        return np.stack([np.asarray(d.asnumpy()) for d in data])
    if isinstance(first, tuple):
        return [_np_batchify(list(col)) for col in zip(*data)]
    return np.asarray(data)


def _reopen_record_files(obj, _depth=0):
    """Reopen RecordIO handles after fork: dup'd fds share one file offset
    across processes, so concurrent seek/read would race (the reference
    avoids this with per-worker handles via pickling, recordio.py:87)."""
    from ... import recordio as _recordio
    if _depth > 4:
        return
    if isinstance(obj, _recordio.MXRecordIO):
        if obj.is_open:
            obj.close()
            obj.open()
        return
    for attr in ("_record", "_data", "_dataset"):
        child = getattr(obj, attr, None)
        if child is not None:
            _reopen_record_files(child, _depth + 1)


def _worker_loop(dataset, key_queue, data_queue, batchify_fn):
    """(reference: dataloader.py:104)"""
    _reopen_record_files(dataset)
    while True:
        idx, samples = key_queue.get()
        if idx is None:
            break
        try:
            if batchify_fn is default_batchify_fn:
                batch = _np_batchify([dataset[i] for i in samples])
            else:
                batch = batchify_fn([dataset[i] for i in samples])
                if isinstance(batch, NDArray):
                    batch = batch.asnumpy()
                elif isinstance(batch, (list, tuple)):
                    batch = [b.asnumpy() if isinstance(b, NDArray) else b
                             for b in batch]
            data_queue.put((idx, batch, None))
        except Exception as e:  # surface worker errors to the main process
            data_queue.put((idx, None, str(e)))


class DataLoader:
    """Loads data from a Dataset in mini-batches (reference:
    dataloader.py:35)."""

    def __init__(self, dataset, batch_size=None, shuffle=False, sampler=None,
                 last_batch=None, batch_sampler=None, batchify_fn=None,
                 num_workers=0):
        self._dataset = dataset
        if batch_sampler is None:
            if batch_size is None:
                raise ValueError("batch_size must be specified unless "
                                 "batch_sampler is specified")
            if sampler is None:
                if shuffle:
                    sampler = _sampler.RandomSampler(len(dataset))
                else:
                    sampler = _sampler.SequentialSampler(len(dataset))
            elif shuffle:
                raise ValueError("shuffle must not be specified if sampler "
                                 "is specified")
            batch_sampler = _sampler.BatchSampler(
                sampler, batch_size, last_batch if last_batch else "keep")
        elif batch_size is not None or shuffle or sampler is not None or \
                last_batch is not None:
            raise ValueError("batch_size, shuffle, sampler and last_batch "
                             "must not be specified if batch_sampler is "
                             "specified.")
        self._batch_sampler = batch_sampler
        self._num_workers = max(0, num_workers)
        self._batchify_fn = batchify_fn if batchify_fn is not None \
            else default_batchify_fn

    def __iter__(self):
        if self._num_workers == 0:
            for batch in self._batch_sampler:
                yield self._batchify_fn([self._dataset[i] for i in batch])
            return
        yield from self._multi_worker_iter()

    def _multi_worker_iter(self):
        """Pipelined workers: keep 2x workers batches in flight, yield in
        order (reference: dataloader.py:143 _MultiWorkerIter)."""
        ctx = multiprocessing.get_context("fork")
        key_queue = ctx.Queue()
        data_queue = ctx.Queue(2 * self._num_workers)
        workers = []
        for _ in range(self._num_workers):
            w = ctx.Process(target=_worker_loop,
                            args=(self._dataset, key_queue, data_queue,
                                  self._batchify_fn), daemon=True)
            w.start()
            workers.append(w)
        try:
            batches = list(self._batch_sampler)
            sent = 0
            rcvd = 0
            buf = {}
            for i in range(min(2 * self._num_workers, len(batches))):
                key_queue.put((i, batches[i]))
                sent += 1
            while rcvd < len(batches):
                while rcvd not in buf:
                    idx, batch, err = data_queue.get()
                    if err is not None:
                        raise RuntimeError(f"DataLoader worker error: {err}")
                    buf[idx] = batch
                batch = buf.pop(rcvd)
                rcvd += 1
                if sent < len(batches):
                    key_queue.put((sent, batches[sent]))
                    sent += 1
                if isinstance(batch, np.ndarray):
                    yield nd.array(batch)
                elif isinstance(batch, (list, tuple)):
                    yield [nd.array(b) if isinstance(b, np.ndarray) else b
                           for b in batch]
                else:
                    yield batch
        finally:
            for _ in workers:
                key_queue.put((None, None))
            for w in workers:
                w.join(timeout=5)
                if w.is_alive():
                    w.terminate()

    def __len__(self):
        return len(self._batch_sampler)
