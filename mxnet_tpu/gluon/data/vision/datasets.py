"""Vision datasets (reference: python/mxnet/gluon/data/vision/datasets.py).

MNIST/FashionMNIST/CIFAR read the standard binary formats from a local
root (no network egress; point ``root`` at existing files or use
``SyntheticImageDataset`` for smoke tests).
"""
from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile

import numpy as np

from .... import ndarray as nd
from ....base import MXNetError
from .. import dataset

__all__ = ["MNIST", "FashionMNIST", "CIFAR10", "CIFAR100",
           "ImageRecordDataset", "ImageFolderDataset",
           "SyntheticImageDataset"]


class _DownloadedDataset(dataset.Dataset):
    """(reference: datasets.py:45)"""

    def __init__(self, root, transform):
        self._transform = transform
        self._data = None
        self._label = None
        self._root = os.path.expanduser(root)
        self._get_data()

    def __getitem__(self, idx):
        if self._transform is not None:
            return self._transform(self._data[idx], self._label[idx])
        return self._data[idx], self._label[idx]

    def __len__(self):
        return len(self._label)

    def _get_data(self):
        raise NotImplementedError


class MNIST(_DownloadedDataset):
    """MNIST from the standard idx-ubyte files (reference: datasets.py:60)."""

    _train_data = ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz")
    _test_data = ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz")

    def __init__(self, root="~/.mxnet_tpu/datasets/mnist", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _get_data(self):
        images, labels = self._train_data if self._train else self._test_data
        img_path = os.path.join(self._root, images)
        lbl_path = os.path.join(self._root, labels)
        for p in (img_path, lbl_path):
            if not os.path.exists(p) and not os.path.exists(p[:-3]):
                raise MXNetError(
                    f"MNIST file {p} not found; this environment has no "
                    "network egress — place the standard MNIST files under "
                    f"{self._root} (gzip or raw)")

        def opener(p):
            if os.path.exists(p):
                return gzip.open(p, "rb")
            return open(p[:-3], "rb")

        with opener(lbl_path) as fin:
            struct.unpack(">II", fin.read(8))
            label = np.frombuffer(fin.read(), dtype=np.uint8)\
                .astype(np.int32)
        with opener(img_path) as fin:
            _, num, rows, cols = struct.unpack(">IIII", fin.read(16))
            data = np.frombuffer(fin.read(), dtype=np.uint8)
            data = data.reshape(num, rows, cols, 1)
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class FashionMNIST(MNIST):
    """(reference: datasets.py:103)"""

    def __init__(self, root="~/.mxnet_tpu/datasets/fashion-mnist",
                 train=True, transform=None):
        super().__init__(root, train, transform)


class CIFAR10(_DownloadedDataset):
    """CIFAR-10 from the python pickle batches (reference:
    datasets.py:130)."""

    def __init__(self, root="~/.mxnet_tpu/datasets/cifar10", train=True,
                 transform=None):
        self._train = train
        super().__init__(root, transform)

    def _load_batches(self, names):
        data, label = [], []
        base = self._root
        # accept either extracted dir or the tar.gz
        tar = os.path.join(base, "cifar-10-python.tar.gz")
        if os.path.exists(tar):
            with tarfile.open(tar) as tf:
                for n in names:
                    with tf.extractfile(
                            f"cifar-10-batches-py/{n}") as f:
                        d = pickle.load(f, encoding="bytes")
                    data.append(d[b"data"])
                    label.append(d[b"labels"])
        else:
            for n in names:
                p = os.path.join(base, "cifar-10-batches-py", n)
                if not os.path.exists(p):
                    p = os.path.join(base, n)
                if not os.path.exists(p):
                    raise MXNetError(
                        f"CIFAR-10 batch {n} not found under {base}; place "
                        "the python-version batches there (no network "
                        "egress)")
                with open(p, "rb") as f:
                    d = pickle.load(f, encoding="bytes")
                data.append(d[b"data"])
                label.append(d[b"labels"])
        data = np.concatenate(data).reshape(-1, 3, 32, 32)\
            .transpose(0, 2, 3, 1)
        label = np.concatenate(label).astype(np.int32)
        return data, label

    def _get_data(self):
        if self._train:
            names = [f"data_batch_{i}" for i in range(1, 6)]
        else:
            names = ["test_batch"]
        data, label = self._load_batches(names)
        self._data = nd.array(data, dtype="uint8")
        self._label = label


class CIFAR100(CIFAR10):
    """(reference: datasets.py:171)"""

    def __init__(self, root="~/.mxnet_tpu/datasets/cifar100",
                 fine_label=False, train=True, transform=None):
        self._fine_label = fine_label
        super().__init__(root, train, transform)

    def _get_data(self):
        name = "train" if self._train else "test"
        p = os.path.join(self._root, "cifar-100-python", name)
        if not os.path.exists(p):
            p = os.path.join(self._root, name)
        if not os.path.exists(p):
            raise MXNetError(f"CIFAR-100 file {name} not found under "
                             f"{self._root}")
        with open(p, "rb") as f:
            d = pickle.load(f, encoding="bytes")
        data = d[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)
        key = b"fine_labels" if self._fine_label else b"coarse_labels"
        self._data = nd.array(data, dtype="uint8")
        self._label = np.asarray(d[key], np.int32)


class ImageRecordDataset(dataset.RecordFileDataset):
    """Images + labels from a .rec file (reference: datasets.py:217)."""

    def __init__(self, filename, flag=1, transform=None):
        super().__init__(filename)
        self._flag = flag
        self._transform = transform

    def __getitem__(self, idx):
        from .... import image as img_mod
        from .... import recordio
        record = super().__getitem__(idx)
        header, img = recordio.unpack(record)
        img = img_mod.imdecode(img, self._flag)
        label = header.label
        if self._transform is not None:
            return self._transform(img, label)
        return img, label


class ImageFolderDataset(dataset.Dataset):
    """root/category/image.jpg layout (reference: datasets.py:248)."""

    def __init__(self, root, flag=1, transform=None):
        self._root = os.path.expanduser(root)
        self._flag = flag
        self._transform = transform
        self._exts = [".jpg", ".jpeg", ".png"]
        self._list_images(self._root)

    def _list_images(self, root):
        self.synsets = []
        self.items = []
        for folder in sorted(os.listdir(root)):
            path = os.path.join(root, folder)
            if not os.path.isdir(path):
                continue
            label = len(self.synsets)
            self.synsets.append(folder)
            for filename in sorted(os.listdir(path)):
                filename = os.path.join(path, filename)
                ext = os.path.splitext(filename)[1]
                if ext.lower() not in self._exts:
                    continue
                self.items.append((filename, label))

    def __getitem__(self, idx):
        from .... import image as img_mod
        img = img_mod.imread(self.items[idx][0], self._flag)
        label = self.items[idx][1]
        if self._transform is not None:
            return self._transform(img, label)
        return img, label

    def __len__(self):
        return len(self.items)


class SyntheticImageDataset(dataset.Dataset):
    """Deterministic synthetic images for tests/benchmarks (TPU-rebuild
    extra — the environment has no dataset downloads)."""

    def __init__(self, num_samples=1000, shape=(3, 224, 224), classes=1000,
                 seed=0):
        self._n = num_samples
        self._shape = shape
        self._classes = classes
        self._seed = seed

    def __len__(self):
        return self._n

    def __getitem__(self, idx):
        rng = np.random.RandomState(self._seed + idx)
        img = rng.randint(0, 256, (self._shape[1], self._shape[2],
                                   self._shape[0])).astype(np.uint8)
        label = int(rng.randint(self._classes))
        return nd.array(img, dtype="uint8"), label
