"""Vision transforms (reference:
python/mxnet/gluon/data/vision/transforms.py)."""
from __future__ import annotations

import numpy as np

from .... import ndarray as nd
from ....ndarray.ndarray import NDArray
from ...block import Block, HybridBlock
from ...nn import Sequential, HybridSequential

__all__ = ["Compose", "Cast", "ToTensor", "Normalize", "Resize",
           "CenterCrop", "RandomResizedCrop", "RandomFlipLeftRight",
           "RandomFlipTopBottom", "RandomBrightness", "RandomContrast",
           "RandomSaturation", "RandomHue", "RandomColorJitter",
           "RandomLighting"]


class Compose(Sequential):
    """Sequentially compose transforms (reference: transforms.py:33)."""

    def __init__(self, transforms):
        super().__init__()
        for t in transforms:
            self.add(t)


class Cast(HybridBlock):
    """(reference: transforms.py:70)"""

    def __init__(self, dtype="float32"):
        super().__init__()
        self._dtype = dtype

    def hybrid_forward(self, F, x):
        return x.astype(self._dtype)


class ToTensor(HybridBlock):
    """(H, W, C) uint8 [0,255] → (C, H, W) float32 [0,1]
    (reference: transforms.py:90)."""

    def hybrid_forward(self, F, x):
        return x.astype("float32").transpose((2, 0, 1)) / 255.0


class Normalize(HybridBlock):
    """(reference: transforms.py:121)"""

    def __init__(self, mean, std):
        super().__init__()
        self._mean = np.asarray(mean, np.float32).reshape(-1, 1, 1)
        self._std = np.asarray(std, np.float32).reshape(-1, 1, 1)
        self._mean_nd = None
        self._std_nd = None

    def hybrid_forward(self, F, x):
        if self._mean_nd is None:
            # cache device constants (one transfer, not two per image)
            self._mean_nd = nd.array(self._mean)
            self._std_nd = nd.array(self._std)
        return (x - self._mean_nd) / self._std_nd


class Resize(Block):
    """(reference: transforms.py:279)"""

    def __init__(self, size, keep_ratio=False, interpolation=1):
        super().__init__()
        self._size = size
        self._keep = keep_ratio
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image as img_mod
        if isinstance(self._size, int):
            if self._keep:
                return img_mod.resize_short(x, self._size,
                                            self._interpolation)
            return img_mod.imresize(x, self._size, self._size,
                                    self._interpolation)
        return img_mod.imresize(x, self._size[0], self._size[1],
                                self._interpolation)


class CenterCrop(Block):
    """(reference: transforms.py:225)"""

    def __init__(self, size, interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.center_crop(x, self._size, self._interpolation)[0]


class RandomResizedCrop(Block):
    """(reference: transforms.py:252)"""

    def __init__(self, size, scale=(0.08, 1.0), ratio=(3.0 / 4.0, 4.0 / 3.0),
                 interpolation=1):
        super().__init__()
        self._size = (size, size) if isinstance(size, int) else tuple(size)
        self._scale = scale
        self._ratio = ratio
        self._interpolation = interpolation

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.random_size_crop(x, self._size, self._scale,
                                        self._ratio,
                                        self._interpolation)[0]


class RandomFlipLeftRight(Block):
    """(reference: transforms.py:312)"""

    def forward(self, x):
        import random as pyrandom
        if pyrandom.random() < 0.5:
            x = nd.array(x.asnumpy()[:, ::-1])
        return x


class RandomFlipTopBottom(Block):
    """(reference: transforms.py:327)"""

    def forward(self, x):
        import random as pyrandom
        if pyrandom.random() < 0.5:
            x = nd.array(x.asnumpy()[::-1])
        return x


class RandomBrightness(Block):
    def __init__(self, brightness):
        super().__init__()
        self._args = brightness

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.BrightnessJitterAug(self._args)(x)


class RandomContrast(Block):
    def __init__(self, contrast):
        super().__init__()
        self._args = contrast

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.ContrastJitterAug(self._args)(x)


class RandomSaturation(Block):
    def __init__(self, saturation):
        super().__init__()
        self._args = saturation

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.SaturationJitterAug(self._args)(x)


class RandomHue(Block):
    def __init__(self, hue):
        super().__init__()
        self._args = hue

    def forward(self, x):
        from .... import image as img_mod
        return img_mod.HueJitterAug(self._args)(x)


class RandomColorJitter(Block):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        super().__init__()
        self._args = (brightness, contrast, saturation)
        self._hue = hue

    def forward(self, x):
        from .... import image as img_mod
        x = img_mod.ColorJitterAug(*self._args)(x)
        if self._hue:
            x = img_mod.HueJitterAug(self._hue)(x)
        return x


class RandomLighting(Block):
    """(reference: transforms.py:423)"""

    def __init__(self, alpha):
        super().__init__()
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        self._aug = None
        self._params = (alpha, eigval, eigvec)

    def forward(self, x):
        from .... import image as img_mod
        if self._aug is None:
            self._aug = img_mod.LightingAug(*self._params)
        return self._aug(x)
