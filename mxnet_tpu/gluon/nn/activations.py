"""Activation layers (reference: python/mxnet/gluon/nn/activations.py)."""
from __future__ import annotations

from ..block import HybridBlock

__all__ = ["Activation", "LeakyReLU", "PReLU", "ELU", "SELU", "Swish"]


class Activation(HybridBlock):
    """Applies an activation: relu/sigmoid/tanh/softrelu/softsign
    (reference: activations.py:24)."""

    def __init__(self, activation, **kwargs):
        self._act_type = activation
        super().__init__(**kwargs)

    def _alias(self):
        return self._act_type

    def hybrid_forward(self, F, x):
        return F.Activation(x, act_type=self._act_type)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._act_type})"


class LeakyReLU(HybridBlock):
    """(reference: activations.py:55)"""

    def __init__(self, alpha, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="leaky", slope=self._alpha)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._alpha})"


class PReLU(HybridBlock):
    """Parametric leaky relu with learned slope (reference: activations.py:88)."""

    def __init__(self, alpha_initializer=None, **kwargs):
        super().__init__(**kwargs)
        from ... import initializer as _init
        with self.name_scope():
            self.alpha = self.params.get(
                "alpha", shape=(1,),
                init=alpha_initializer or _init.Constant(0.25))

    def hybrid_forward(self, F, x, alpha):
        return F.LeakyReLU(x, alpha, act_type="prelu")

    def infer_shape(self, x):
        pass


class ELU(HybridBlock):
    """(reference: activations.py:123)"""

    def __init__(self, alpha=1.0, **kwargs):
        super().__init__(**kwargs)
        self._alpha = alpha

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="elu", slope=self._alpha)


class SELU(HybridBlock):
    """(reference: activations.py:152)"""

    def hybrid_forward(self, F, x):
        return F.LeakyReLU(x, act_type="selu")


class Swish(HybridBlock):
    """x * sigmoid(beta*x) (reference: activations.py:176)."""

    def __init__(self, beta=1.0, **kwargs):
        super().__init__(**kwargs)
        self._beta = beta

    def hybrid_forward(self, F, x):
        return x * F.sigmoid(self._beta * x)
