"""Basic Gluon layers (reference: python/mxnet/gluon/nn/basic_layers.py).

Dense/Dropout/BatchNorm/Embedding/... — thin parameterized wrappers over the
op library; all compute lowers to XLA (matmuls hit the MXU directly).
"""
from __future__ import annotations

import numpy as np

from ... import autograd
from ..block import Block, HybridBlock, stateful_write
from .activations import Activation

__all__ = ["Sequential", "HybridSequential", "Dense", "Dropout", "Embedding",
           "BatchNorm", "InstanceNorm", "LayerNorm", "Flatten", "Lambda",
           "HybridLambda", "HybridConcurrent", "Concurrent", "Identity"]


class Sequential(Block):
    """Stacks Blocks sequentially (reference: basic_layers.py:29)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())

    def hybridize(self, active=True, **kwargs):
        """Sequential (non-hybrid) only hybridizes children
        (reference: basic_layers.py:76)."""
        super().hybridize(active, **kwargs)


class HybridSequential(HybridBlock):
    """Stacks HybridBlocks; hybridizable as one XLA program
    (reference: basic_layers.py:92)."""

    def __init__(self, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        for block in self._children.values():
            x = block(x)
        return x

    def forward(self, x):
        for block in self._children.values():
            x = block(x)
        return x

    def __len__(self):
        return len(self._children)

    def __getitem__(self, key):
        layers = list(self._children.values())[key]
        if isinstance(layers, list):
            net = type(self)(prefix=self._prefix)
            with net.name_scope():
                net.add(*layers)
            return net
        return layers

    def __iter__(self):
        return iter(self._children.values())


class Dense(HybridBlock):
    """Fully-connected layer: ``act(dot(x, W^T) + b)``
    (reference: basic_layers.py:128). Weight layout (units, in_units) matches
    the reference so checkpoints interchange."""

    def __init__(self, units, activation=None, use_bias=True, flatten=True,
                 dtype="float32", weight_initializer=None,
                 bias_initializer="zeros", in_units=0, **kwargs):
        super().__init__(**kwargs)
        self._flatten = flatten
        self._units = units
        self._in_units = in_units
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(units, in_units), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)
            if use_bias:
                self.bias = self.params.get(
                    "bias", shape=(units,), dtype=dtype,
                    init=bias_initializer, allow_deferred_init=True)
            else:
                self.bias = None
            if activation is not None:
                self.act = Activation(activation, prefix=activation + "_")
            else:
                self.act = None

    def infer_shape(self, x):
        in_units = int(np.prod(x.shape[1:])) if self._flatten else x.shape[-1]
        self.weight._infer_shape((self._units, in_units))
        if self.bias is not None:
            self.bias._infer_shape((self._units,))

    def hybrid_forward(self, F, x, weight, bias=None):
        out = F.FullyConnected(x, weight, bias, no_bias=bias is None,
                               num_hidden=self._units, flatten=self._flatten)
        if self.act is not None:
            out = self.act(out)
        return out

    def __repr__(self):
        shape = self.weight.shape
        return (f"{self.__class__.__name__}"
                f"({shape[1] if len(shape) > 1 and shape[1] else None} -> "
                f"{shape[0]}, "
                f"{'linear' if self.act is None else self.act._act_type})")


class Dropout(HybridBlock):
    """(reference: basic_layers.py:219)"""

    def __init__(self, rate, axes=(), **kwargs):
        super().__init__(**kwargs)
        self._rate = rate
        self._axes = axes

    def hybrid_forward(self, F, x):
        return F.Dropout(x, p=self._rate, axes=self._axes)

    def __repr__(self):
        return f"{self.__class__.__name__}(p = {self._rate}, axes={self._axes})"


class BatchNorm(HybridBlock):
    """Batch normalization with running-average stats
    (reference: basic_layers.py:262; op src/operator/nn/batch_norm.cc).

    Functional-state design: the batch mean/var computed inside the (possibly
    jitted) forward are threaded out via ``stateful_write`` and folded into
    the running stats — the XLA-native analog of the reference's in-place aux
    state mutation.
    """

    def __init__(self, axis=1, momentum=0.9, epsilon=1e-5, center=True,
                 scale=True, use_global_stats=False, beta_initializer="zeros",
                 gamma_initializer="ones", running_mean_initializer="zeros",
                 running_variance_initializer="ones", in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._kwargs = {"axis": axis, "eps": epsilon, "momentum": momentum,
                        "fix_gamma": not scale,
                        "use_global_stats": use_global_stats}
        self._axis = axis
        self._momentum = momentum
        self.in_channels = in_channels
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True, differentiable=scale)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True, differentiable=center)
            self.running_mean = self.params.get(
                "running_mean", grad_req="null", shape=(in_channels,),
                init=running_mean_initializer, allow_deferred_init=True,
                differentiable=False)
            self.running_var = self.params.get(
                "running_var", grad_req="null", shape=(in_channels,),
                init=running_variance_initializer, allow_deferred_init=True,
                differentiable=False)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        for p in (self.gamma, self.beta, self.running_mean, self.running_var):
            p._infer_shape((c,))

    def cast(self, dtype):
        if np.dtype(dtype).name == "float16":
            dtype = "float32"
        super().cast(dtype)

    def hybrid_forward(self, F, x, gamma, beta, running_mean, running_var):
        training = autograd.is_training()
        out, batch_mean, batch_var = F.BatchNorm(
            x, gamma, beta, running_mean, running_var,
            training=training, **self._kwargs)
        if training and not self._kwargs["use_global_stats"]:
            m = self._momentum
            stateful_write(self.running_mean,
                           running_mean * m + batch_mean * (1 - m))
            stateful_write(self.running_var,
                           running_var * m + batch_var * (1 - m))
        return out

    def __repr__(self):
        in_channels = self.gamma.shape[0] if self.gamma.shape else None
        return (f"{self.__class__.__name__}(axis={self._axis}, "
                f"eps={self._kwargs['eps']}, momentum={self._momentum}, "
                f"in_channels={in_channels})")


class InstanceNorm(HybridBlock):
    """(reference: basic_layers.py:415)"""

    def __init__(self, axis=1, epsilon=1e-5, center=True, scale=False,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._epsilon = epsilon
        self._axis = axis
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma._infer_shape((c,))
        self.beta._infer_shape((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        if self._axis != 1:
            x = x.swapaxes(1, self._axis)
        out = F.InstanceNorm(x, gamma, beta, eps=self._epsilon)
        return out if self._axis == 1 else out.swapaxes(1, self._axis)


class LayerNorm(HybridBlock):
    """(reference: basic_layers.py:497)"""

    def __init__(self, axis=-1, epsilon=1e-5, center=True, scale=True,
                 beta_initializer="zeros", gamma_initializer="ones",
                 in_channels=0, **kwargs):
        super().__init__(**kwargs)
        self._axis = axis
        self._epsilon = epsilon
        with self.name_scope():
            self.gamma = self.params.get(
                "gamma", grad_req="write" if scale else "null",
                shape=(in_channels,), init=gamma_initializer,
                allow_deferred_init=True)
            self.beta = self.params.get(
                "beta", grad_req="write" if center else "null",
                shape=(in_channels,), init=beta_initializer,
                allow_deferred_init=True)

    def infer_shape(self, x):
        c = x.shape[self._axis]
        self.gamma._infer_shape((c,))
        self.beta._infer_shape((c,))

    def hybrid_forward(self, F, x, gamma, beta):
        return F.LayerNorm(x, gamma, beta, axis=self._axis, eps=self._epsilon)


class Embedding(HybridBlock):
    """Index → dense vector lookup (reference: basic_layers.py:565;
    op src/operator/tensor/indexing_op.cc Embedding).

    On TPU the lookup is an XLA gather; sharding the (large) table over the
    mesh is handled by the parallel layer."""

    def __init__(self, input_dim, output_dim, dtype="float32",
                 weight_initializer=None, sparse_grad=False, **kwargs):
        super().__init__(**kwargs)
        self._input_dim = input_dim
        self._output_dim = output_dim
        self._kwargs = {"input_dim": input_dim, "output_dim": output_dim,
                        "dtype": dtype, "sparse_grad": sparse_grad}
        with self.name_scope():
            self.weight = self.params.get(
                "weight", shape=(input_dim, output_dim), dtype=dtype,
                init=weight_initializer, allow_deferred_init=True)

    def infer_shape(self, x):
        pass

    def hybrid_forward(self, F, x, weight):
        return F.Embedding(x, weight, **self._kwargs)

    def __repr__(self):
        return (f"{self.__class__.__name__}({self._input_dim} -> "
                f"{self._output_dim}, {self._kwargs['dtype']})")


class Flatten(HybridBlock):
    """(reference: basic_layers.py:629)"""

    def hybrid_forward(self, F, x):
        return x.reshape((0, -1))

    def __repr__(self):
        return self.__class__.__name__


class HybridConcurrent(HybridBlock):
    """Run children on the same input and concat their outputs
    (reference: python/mxnet/gluon/contrib/nn/basic_layers.py
    HybridConcurrent; used by squeezenet/densenet/inception)."""

    def __init__(self, axis=-1, prefix=None, params=None):
        super().__init__(prefix=prefix, params=params)
        self.axis = axis

    def add(self, *blocks):
        for block in blocks:
            self.register_child(block)

    def hybrid_forward(self, F, x):
        out = [block(x) for block in self._children.values()]
        return F.concat(*out, dim=self.axis)


class Concurrent(HybridConcurrent):
    """Non-hybrid alias (reference: contrib/nn Concurrent)."""


class Identity(HybridBlock):
    """(reference: contrib/nn Identity)"""

    def hybrid_forward(self, F, x):
        return x


class Lambda(Block):
    """Wraps a function as a Block (reference: basic_layers.py:647)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            if not hasattr(F, function):
                raise ValueError(f"Function name {function} is not found in "
                                 "ndarray namespace")
            self._func_impl = getattr(F, function)
            self._func_name = function
        elif callable(function):
            self._func_impl = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def forward(self, *args):
        return self._func_impl(*args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"


class HybridLambda(HybridBlock):
    """Wraps a function as a HybridBlock (reference: basic_layers.py:694)."""

    def __init__(self, function, prefix=None):
        super().__init__(prefix=prefix)
        if isinstance(function, str):
            from ... import ndarray as F
            if not hasattr(F, function):
                raise ValueError(f"Function name {function} is not found in "
                                 "ndarray namespace")
            fname = function
            self._func = lambda F_, *args: getattr(F_, fname)(*args)
            self._func_name = function
        elif callable(function):
            self._func = function
            self._func_name = function.__name__
        else:
            raise ValueError("Unrecognized function in lambda: "
                             f"{function} of type {type(function)}")

    def hybrid_forward(self, F, x, *args):
        return self._func(F, x, *args)

    def __repr__(self):
        return f"{self.__class__.__name__}({self._func_name})"
