"""Model zoo (reference: python/mxnet/gluon/model_zoo/__init__.py)."""
from . import vision
from .model_store import get_model_file, purge
