"""Pretrained model file store (reference:
python/mxnet/gluon/model_zoo/model_store.py).

The reference downloads sha1-verified .params files from S3. This
environment has no network egress; models are resolved from a local root
(``MXNET_TPU_MODEL_ZOO`` env or ``~/.mxnet_tpu/models``) so users can drop
converted checkpoints in place.
"""
from __future__ import annotations

import os

from ..utils import check_sha1

__all__ = ["get_model_file", "purge", "check_sha1"]

# name -> sha1 of the .params artifact. The reference ships a static
# table and verifies every download (model_store.py:30-60); here the
# table covers vendored/converted artifacts and a ``{name}.sha1``
# sidecar next to the file extends it per-root.
_model_sha1 = {}


def get_model_root():
    return os.path.expanduser(
        os.environ.get("MXNET_TPU_MODEL_ZOO", "~/.mxnet_tpu/models"))


def get_model_file(name, root=None):
    """Return the path of a pretrained model parameters file, sha1-
    verified when a checksum is known (reference: model_store.py:68 —
    the download step is replaced by a local root since this environment
    has no egress)."""
    root = root or get_model_root()
    file_path = os.path.join(root, f"{name}.params")
    if not os.path.exists(file_path):
        raise FileNotFoundError(
            f"Pretrained model file {file_path} is not found. This "
            "environment has no network egress; place a converted "
            "checkpoint at that path (see tools/convert_params.py) or "
            "construct the model with pretrained=False.")
    sha1_hash = _model_sha1.get(name)
    sidecar = file_path + ".sha1"
    if sha1_hash is None and os.path.exists(sidecar):
        with open(sidecar) as f:
            parts = f.read().split()
        sha1_hash = parts[0] if parts else None
    if sha1_hash and not check_sha1(file_path, sha1_hash):
        raise ValueError(
            f"sha1 mismatch for {file_path}: the artifact is corrupted "
            "or was replaced (reference model_store re-downloads here; "
            "restore the checkpoint or remove the stale file)")
    return file_path


def purge(root=None):
    """Remove cached pretrained models (reference: model_store.py:97)."""
    root = root or get_model_root()
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
