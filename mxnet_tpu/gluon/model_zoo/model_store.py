"""Pretrained model file store (reference:
python/mxnet/gluon/model_zoo/model_store.py).

The reference downloads sha1-verified .params files from S3. This
environment has no network egress; models are resolved from a local root
(``MXNET_TPU_MODEL_ZOO`` env or ``~/.mxnet_tpu/models``) so users can drop
converted checkpoints in place.
"""
from __future__ import annotations

import os

__all__ = ["get_model_file", "purge"]

_model_sha1 = {}  # name -> sha1, populated as checkpoints are converted


def get_model_root():
    return os.path.expanduser(
        os.environ.get("MXNET_TPU_MODEL_ZOO", "~/.mxnet_tpu/models"))


def get_model_file(name, root=None):
    """Return the path of a pretrained model parameters file
    (reference: model_store.py:68)."""
    root = root or get_model_root()
    file_path = os.path.join(root, f"{name}.params")
    if os.path.exists(file_path):
        return file_path
    raise FileNotFoundError(
        f"Pretrained model file {file_path} is not found. This environment "
        "has no network egress; place a converted checkpoint at that path "
        "(see tools/convert_params.py) or construct the model with "
        "pretrained=False.")


def purge(root=None):
    """Remove cached pretrained models (reference: model_store.py:97)."""
    root = root or get_model_root()
    if os.path.isdir(root):
        for f in os.listdir(root):
            if f.endswith(".params"):
                os.remove(os.path.join(root, f))
