"""Attribute scoping for symbols (reference: python/mxnet/attribute.py).

``with mx.AttrScope(ctx_group='dev1'):`` annotates symbols created inside;
the reference's PlaceDevice pass reads ``__ctx_group__`` for model
parallelism (graph_executor.cc:406) — here the annotation maps to sharding
hints consumed by the parallel layer.
"""
from __future__ import annotations

import threading

__all__ = ["AttrScope", "current_attrs"]


class AttrScope:
    """(reference: attribute.py:27)"""

    _current = threading.local()

    def __init__(self, **kwargs):
        self._old_scope = None
        for value in kwargs.values():
            if not isinstance(value, str):
                raise ValueError("Attributes need to be a string")
        self._attr = {f"__{k}__" if not k.startswith("__") else k: v
                      for k, v in kwargs.items()}

    def get(self, attr=None):
        if attr:
            ret = self._attr.copy()
            ret.update(attr)
            return ret
        return self._attr.copy()

    def __enter__(self):
        self._old_scope = getattr(AttrScope._current, "value", None)
        attr = self._attr.copy()
        if self._old_scope is not None:
            merged = self._old_scope._attr.copy()
            merged.update(attr)
            self._attr = merged
        AttrScope._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        AttrScope._current.value = self._old_scope


def apply_scope_attrs(node):
    """Merge the active AttrScope's attributes into a graph node's
    user_attrs (single definition for ops and variables — reference:
    symbol creation + Variable both consult AttrScope.current)."""
    scope_attrs = current_attrs()
    if scope_attrs:
        merged = dict(scope_attrs)
        merged.update(node.user_attrs)  # explicit attrs win over scope
        node.user_attrs = merged


def current_attrs():
    scope = getattr(AttrScope._current, "value", None)
    return scope._attr.copy() if scope is not None else {}
