"""Device context abstraction over ``jax.devices()``.

TPU-native rebuild of ``mxnet.context`` (reference: python/mxnet/context.py,
include/mxnet/base.h:133-160). The reference's device types {cpu, gpu,
cpu_pinned, cpu_shared} map here to {cpu, tpu (accelerator), cpu (host
staging is implicit in JAX's transfer machinery)}. ``gpu()`` is kept as an
alias for the accelerator so reference scripts run unmodified.
"""
from __future__ import annotations

import threading
from typing import Optional

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "current_context", "num_gpus", "num_tpus"]


class Context:
    """A device context.

    Usable as a ``with`` scope like the reference (context.py:98):

        with mx.tpu(0):
            x = mx.nd.zeros((2, 2))
    """

    # device type codes kept numerically compatible with the reference
    # (include/mxnet/base.h:135-139) plus a new kTPU.
    devtype2str = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 5: "cpu_shared", 6: "tpu"}
    devstr2type = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "cpu_shared": 5, "tpu": 6}

    _default_ctx = threading.local()

    def __init__(self, device_type: str, device_id: int = 0):
        if isinstance(device_type, Context):
            self.device_typeid = device_type.device_typeid
            self.device_id = device_type.device_id
        else:
            self.device_typeid = Context.devstr2type[device_type]
            self.device_id = device_id
        self._old_ctx: Optional["Context"] = None

    @property
    def device_type(self) -> str:
        return Context.devtype2str[self.device_typeid]

    def __hash__(self):
        return hash((self.device_typeid, self.device_id))

    def __eq__(self, other):
        return (
            isinstance(other, Context)
            and self.device_typeid == other.device_typeid
            and self.device_id == other.device_id
        )

    def __str__(self):
        return f"{self.device_type}({self.device_id})"

    def __repr__(self):
        return self.__str__()

    def __enter__(self):
        self._old_ctx = getattr(Context._default_ctx, "value", None)
        Context._default_ctx.value = self
        return self

    def __exit__(self, ptype, value, trace):
        Context._default_ctx.value = self._old_ctx

    # -- JAX mapping ---------------------------------------------------------
    @property
    def jax_device(self):
        """The ``jax.Device`` this context denotes."""
        dt = self.device_type
        if dt in ("cpu", "cpu_pinned", "cpu_shared"):
            devs = [d for d in jax.devices() if d.platform == "cpu"]
            if not devs:
                devs = jax.devices("cpu")
        else:  # gpu / tpu → whatever accelerator backs this process
            devs = [d for d in jax.devices() if d.platform != "cpu"]
            if not devs:  # CPU-only process: alias accelerator ctx to cpu
                devs = jax.devices()
        return devs[self.device_id % len(devs)]

    def empty_cache(self):
        """Reference API parity (context.py:161); XLA owns the allocator, so
        this is a best-effort hint."""
        for d in jax.devices():
            try:
                d.memory_stats()
            except Exception:
                pass


def cpu(device_id: int = 0) -> Context:
    return Context("cpu", device_id)


def gpu(device_id: int = 0) -> Context:
    """Alias for the process accelerator. Reference scripts that say
    ``mx.gpu(i)`` transparently get TPU chip *i*."""
    return Context("gpu", device_id)


def tpu(device_id: int = 0) -> Context:
    return Context("tpu", device_id)


def num_gpus() -> int:
    """Number of accelerator chips visible (reference: context.py:242)."""
    return len([d for d in jax.devices() if d.platform != "cpu"])


def num_tpus() -> int:
    return num_gpus()


def current_context() -> Context:
    """The active default context (reference: context.py:216)."""
    ctx = getattr(Context._default_ctx, "value", None)
    if ctx is not None:
        return ctx
    # default to the accelerator if present, else cpu
    return Context("tpu", 0) if num_gpus() else Context("cpu", 0)
