"""Image IO and augmentation (reference: python/mxnet/image/__init__.py)."""
from .image import *  # noqa: F401,F403
from . import detection  # noqa: F401
from .detection import *  # noqa: F401,F403
