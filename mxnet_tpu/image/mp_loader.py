"""Multiprocess RecordIO image pipeline.

TPU-native analog of the reference's multithreaded decode+augment iterator
(reference: src/io/iter_image_recordio_2.cc:727 — ImageRecordIOParser2
decodes JPEGs on an OpenCV thread pool into pinned batch buffers). Python
threads can't parallelize cv2.imdecode-bound work past the GIL for the
numpy glue around it, so the TPU rebuild uses worker *processes* feeding
preallocated shared-memory batch slots:

    parent: ring of K shared-memory slots ──▶ NDArray batches
    worker[i]: owns 1/N of the record index; loop:
        take free slot → read+decode+augment a full batch into it → ready

Each worker builds whole batches from its own index shard (the same
record-sharding the reference applies across its decode threads and across
``num_parts`` distributed workers), so no cross-process assembly is needed
and a slot is written by exactly one process at a time.

Epoch semantics: every epoch each worker reshuffles its shard with
seed=(seed, epoch) when ``shuffle``; the parent raises StopIteration after
the fixed per-epoch batch count. Partial per-shard tail batches are padded
by wraparound with the pad count reported on ``DataBatch.pad`` (the
reference's round_batch behavior) so metrics can ignore padded records and
no record is silently dropped.

Shuffle scope (documented deviation): shards are a fixed round-robin
split of the record index, so each batch mixes records from ONE worker's
shard only — weaker than the reference's global shuffle. The shard split
is stride-based (r::nworkers over the on-disk order), which decorrelates
any on-disk grouping across shards; per-epoch within-shard shuffles then
vary batch composition. Redistributing shards across persistent worker
processes each epoch would serialize the whole key list through IPC per
epoch for marginal mixing gain; use more workers (smaller shards) if
batch-level mixing matters for your data.
"""
from __future__ import annotations

import multiprocessing as mp
import os
from multiprocessing import shared_memory

import numpy as np

from .. import recordio
from ..io import DataBatch, DataDesc, DataIter

__all__ = ["MPImageRecordIter"]


def _fast_augment(img, out_hw, rand_crop, rand_mirror, resize, rng,
                  interp):
    """numpy/cv2 augment fast path: resize-shorter-side, crop, mirror.
    Matches CreateAugmenter(resize, rand_crop, rand_mirror) semantics
    (reference: image.py:877) without per-op NDArray round trips."""
    import cv2
    h, w = img.shape[:2]
    oh, ow = out_hw
    if resize:
        # resize shorter side to `resize`, keep aspect
        if h < w:
            nh, nw = resize, max(ow, int(w * resize / h))
        else:
            nh, nw = max(oh, int(h * resize / w)), resize
        img = cv2.resize(img, (nw, nh), interpolation=interp)
        h, w = nh, nw
    if h < oh or w < ow:
        img = cv2.resize(img, (max(w, ow), max(h, oh)),
                         interpolation=interp)
        h, w = img.shape[:2]
    if rand_crop:
        y0 = rng.randint(0, h - oh + 1)
        x0 = rng.randint(0, w - ow + 1)
    else:
        y0, x0 = (h - oh) // 2, (w - ow) // 2
    img = img[y0:y0 + oh, x0:x0 + ow]
    if rand_mirror and rng.randint(2):
        img = img[:, ::-1]
    return img


def _native_decoder(path_imgrec, idx_keys, shard_keys, interp, c):
    """(lib, handle, key->position map FOR THIS SHARD) for the in-native
    decode path (native/recordio.cc rio_decode_batch), or None when
    unavailable / not applicable (non-RGB, non-linear interp). The
    offset->position mapping is one bulk C call + a vectorized
    searchsorted over the shard's keys only — no per-record ctypes round
    trips and no whole-dataset dict per worker."""
    import ctypes
    import cv2
    if c != 3 or interp != cv2.INTER_LINEAR:
        return None
    if os.environ.get("MXNET_TPU_NATIVE_DECODE", "1") == "0":
        return None
    try:
        from .. import native as native_mod
        lib = native_mod.get_lib()
        if lib is None or not hasattr(lib, "rio_decode_batch"):
            return None
        h = lib.rio_open(path_imgrec.encode())
        if not h:
            return None
        n = int(lib.rio_count(h))
        offsets = np.empty(n, np.int64)
        if hasattr(lib, "rio_record_offsets"):
            lib.rio_record_offsets(
                h, offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        else:
            # prebuilt library that predates the bulk call (rebuild
            # toolchain unavailable): per-record round trips still beat
            # losing native decode entirely
            for p in range(n):
                offsets[p] = lib.rio_record_offset(h, p)
        order = np.argsort(offsets, kind="stable")
        sorted_off = offsets[order]
        want_off = np.array([int(idx_keys[int(k)]) for k in shard_keys],
                            np.int64)
        slots = np.searchsorted(sorted_off, want_off)
        if (slots >= n).any() or (sorted_off[np.minimum(slots, n - 1)]
                                  != want_off).any():
            lib.rio_close(h)
            return None
        pos = order[slots]
        key2pos = {int(k): int(p) for k, p in zip(shard_keys, pos)}
        return lib, h, key2pos
    except Exception:
        return None


def _worker(rank, path_imgrec, path_imgidx, keys, batch_size, data_shape,
            label_width, shuffle, seed, rand_crop, rand_mirror, resize,
            mean, std, out_dtype, shm_name, lbl_shm_name, nslots,
            free_q, ready_q, interp, fast_decode=False):
    """Worker main: decode+augment its shard into shared-memory slots."""
    # never let a stray jax use in a child grab the TPU the parent owns
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import ctypes
    import cv2
    cv2.setNumThreads(0)  # one process = one core; don't oversubscribe
    c, oh, ow = data_shape
    rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
    native = _native_decoder(path_imgrec, rec.idx, keys, interp, c)
    shm = shared_memory.SharedMemory(name=shm_name)
    lbl_shm = shared_memory.SharedMemory(name=lbl_shm_name)
    slot_shape = (nslots, batch_size, c, oh, ow)
    data_buf = np.ndarray(slot_shape, dtype=out_dtype, buffer=shm.buf)
    lbl_buf = np.ndarray((nslots, batch_size, label_width), np.float32,
                         buffer=lbl_shm.buf)
    normalize = out_dtype != np.uint8 and (mean is not None
                                           or std is not None)
    mean_a = None if mean is None else np.asarray(
        mean, np.float32).reshape(1, 1, -1)
    std_a = None if std is None else np.asarray(
        std, np.float32).reshape(1, 1, -1)
    keys = np.asarray(keys)
    # tail batch wraps around the shard and reports pad, like the
    # reference's round_batch behavior (iter_image_recordio_2.cc) — padded
    # records are ignored by metrics via DataBatch.pad
    nbatch = -(-len(keys) // batch_size)
    epoch = 0
    try:
        while True:
            order = keys.copy()
            if shuffle:
                np.random.RandomState((seed, rank, epoch)).shuffle(order)
            rng = np.random.RandomState((seed + 1, rank, epoch))
            for b in range(nbatch):
                slot = free_q.get()
                if slot is None:
                    return
                idxs = order[b * batch_size:(b + 1) * batch_size]
                pad = batch_size - len(idxs)
                if pad:
                    # wraparound pad; np.resize tiles when the whole
                    # shard is smaller than one batch (tiny num_parts
                    # partitions), so no slot row is left uninitialized
                    idxs = np.concatenate([idxs, np.resize(order, pad)])
                if native is not None:
                    # whole-batch decode+augment inside the native
                    # library (iter_image_recordio_2.cc analog)
                    lib, nh, key2pos = native
                    pos = np.array([key2pos[int(k)] for k in idxs],
                                   np.int64)
                    seeds = rng.randint(
                        1, 2 ** 62, size=len(idxs)).astype(np.uint64)
                    hwc = np.empty((len(idxs), oh, ow, 3), np.uint8)
                    rc = lib.rio_decode_batch(
                        nh, pos.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_int64)),
                        len(idxs), oh, ow, int(resize or 0),
                        int(bool(rand_crop)), int(bool(rand_mirror)),
                        int(bool(fast_decode)),
                        seeds.ctypes.data_as(
                            ctypes.POINTER(ctypes.c_uint64)),
                        hwc.ctypes.data_as(ctypes.c_void_p), 1)
                    if rc != 0:
                        # not JPEG (e.g. PNG-packed records) or a
                        # corrupt stream: drop to the cv2 path for the
                        # rest of the run
                        native = None
                    else:
                        batch = hwc.transpose(0, 3, 1, 2)
                        if normalize:
                            batch = batch.astype(np.float32)
                            if mean_a is not None:
                                batch = batch - mean_a.reshape(
                                    1, -1, 1, 1)
                            if std_a is not None:
                                batch = batch / std_a.reshape(
                                    1, -1, 1, 1)
                        data_buf[slot] = batch
                        labs = np.zeros((len(idxs), label_width),
                                        np.float32)
                        for i, p in enumerate(pos):
                            lib.rio_record_label(
                                nh, int(p),
                                labs[i].ctypes.data_as(
                                    ctypes.POINTER(ctypes.c_float)),
                                label_width)
                        lbl_buf[slot, :len(idxs)] = labs
                        ready_q.put(("ok", rank, slot, epoch, pad))
                        continue
                for i, k in enumerate(idxs):
                    header, raw = recordio.unpack(rec.read_idx(int(k)))
                    img = cv2.imdecode(np.frombuffer(raw, np.uint8),
                                       cv2.IMREAD_COLOR)
                    if img is None:
                        raise ValueError(
                            f"cannot decode image record {int(k)} in "
                            f"{path_imgrec}")
                    img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
                    img = _fast_augment(img, (oh, ow), rand_crop,
                                        rand_mirror, resize, rng, interp)
                    if normalize:
                        img = img.astype(np.float32)
                        if mean_a is not None:
                            img = img - mean_a
                        if std_a is not None:
                            img = img / std_a
                    # HWC -> CHW into the slot (dtype cast happens here)
                    data_buf[slot, i] = img.transpose(2, 0, 1)
                    lab = np.atleast_1d(np.asarray(header.label,
                                                   np.float32))
                    lbl_buf[slot, i] = lab[:label_width]
                ready_q.put(("ok", rank, slot, epoch, pad))
            epoch += 1
    except (KeyboardInterrupt, EOFError, BrokenPipeError):
        pass
    except Exception as e:  # surface the failure instead of hanging the job
        import traceback
        traceback.print_exc()
        try:
            ready_q.put(("error", rank, f"{type(e).__name__}: {e}"))
        except Exception:
            pass
    finally:
        shm.close()
        lbl_shm.close()


class MPImageRecordIter(DataIter):
    """Multiprocess ImageRecordIter (see module docstring).

    Parameters mirror ``io.ImageRecordIter``; ``preprocess_threads`` is the
    worker *process* count (the reference's arg drives its decode thread
    pool: src/io/iter_image_recordio_2.cc:727).
    """

    def __init__(self, path_imgrec, data_shape, batch_size, label_width=1,
                 preprocess_threads=4, prefetch_buffer=2, shuffle=False,
                 seed=0, rand_crop=False, rand_mirror=False, resize=0,
                 mean=None, std=None, dtype="float32", num_parts=1,
                 part_index=0, data_name="data",
                 label_name="softmax_label", path_imgidx=None,
                 inter_method=1, as_numpy=False, fast_decode=False):
        super().__init__(batch_size)
        if path_imgidx is None:
            path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
        if not os.path.isfile(path_imgidx):
            raise IOError(
                f"MPImageRecordIter needs an index file ({path_imgidx}); "
                "build one with tools/im2rec.py")
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.data_name = data_name
        self.label_name = label_name
        self._as_numpy = as_numpy
        self._dtype = np.dtype(dtype)
        nworkers = max(1, int(preprocess_threads))

        idx_rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
        keys = list(idx_rec.keys)
        idx_rec.close()
        if num_parts > 1:
            # round-robin partition: disjoint, and no remainder records
            # are dropped (contiguous-slice partitioning loses up to
            # num_parts-1 records every epoch)
            keys = keys[part_index::num_parts]
        if self._dtype == np.uint8 and (mean is not None
                                        or std is not None):
            raise ValueError(
                "dtype='uint8' cannot carry mean/std normalization "
                "(fold it into an on-device preprocess, or use "
                "dtype='float32')")
        nworkers = min(nworkers, max(1, len(keys) // batch_size))
        shards = [keys[r::nworkers] for r in range(nworkers)]
        # fixed epoch length: per-shard batch counts, tail batches padded
        # by wraparound (reference round_batch semantics)
        self._batches_per_epoch = sum(
            -(-len(s) // batch_size) for s in shards)
        if self._batches_per_epoch == 0:
            raise ValueError(
                f"dataset too small: {len(keys)} records, "
                f"batch {batch_size} x {nworkers} workers")

        c, h, w = self.data_shape
        # each worker owns a private pool of slots so one fast worker can't
        # hoard the ring and run ahead while another still owes batches for
        # the current epoch (the parent re-orders cross-epoch arrivals via
        # the epoch tag; private pools bound each worker's run-ahead, which
        # also makes the epoch-stash deadlock-free)
        per_worker = max(2, 1 + int(prefetch_buffer))
        nslots = nworkers * per_worker
        itemsize = self._dtype.itemsize
        self._shm = shared_memory.SharedMemory(
            create=True, size=nslots * batch_size * c * h * w * itemsize)
        self._lbl_shm = shared_memory.SharedMemory(
            create=True, size=nslots * batch_size * label_width * 4)
        self._data_view = np.ndarray(
            (nslots, batch_size, c, h, w), self._dtype, buffer=self._shm.buf)
        self._lbl_view = np.ndarray(
            (nslots, batch_size, label_width), np.float32,
            buffer=self._lbl_shm.buf)

        # forkserver: children fork from a clean server process — no
        # re-import of __main__ (spawn breaks under REPL/stdin scripts)
        # and no unsafe fork of the jax-initialized parent. The preload
        # makes the server import this module once so each worker forks
        # ready-to-run instead of paying the package import.
        try:
            ctx = mp.get_context("forkserver")
            mp.set_forkserver_preload(["mxnet_tpu.image.mp_loader"])
        except (ValueError, AttributeError):  # non-POSIX fallback
            ctx = mp.get_context("spawn")
        self._free_qs = [ctx.Queue() for _ in range(nworkers)]
        self._ready_q = ctx.Queue()
        for r in range(nworkers):
            for s in range(r * per_worker, (r + 1) * per_worker):
                self._free_qs[r].put(s)
        self._procs = []
        # multiprocessing's child bootstrap re-imports __main__ from its
        # __file__; for stdin/REPL sessions that "file" is '<stdin>' and
        # the child crashes before reaching the worker. Hide a non-file
        # __main__.__file__ for the duration of process start so the
        # bootstrap skips the main-module fixup.
        import sys as _sys
        main_mod = _sys.modules.get("__main__")
        saved_file = getattr(main_mod, "__file__", None)
        hide = saved_file is not None and not os.path.isfile(saved_file)
        if hide:
            del main_mod.__file__
        try:
            for r in range(nworkers):
                p = ctx.Process(
                    target=_worker,
                    args=(r, path_imgrec, path_imgidx, shards[r],
                          batch_size, self.data_shape, label_width,
                          shuffle, seed, rand_crop, rand_mirror, resize,
                          mean, std, self._dtype, self._shm.name,
                          self._lbl_shm.name, nslots, self._free_qs[r],
                          self._ready_q, inter_method, fast_decode),
                    daemon=True)
                p.start()
                self._procs.append(p)
        finally:
            if hide:
                main_mod.__file__ = saved_file
        self._cursor = 0
        self._epoch = 0
        self._pending = {}  # epoch -> [(rank, slot), ...] arrived early
        self._closed = False
        # weakref-based: lets un-closed iterators be garbage collected
        # (an atexit.register(self.close) would pin self alive forever)
        import weakref
        self._finalizer = weakref.finalize(
            self, _shutdown, self._procs, self._free_qs,
            (self._shm, self._lbl_shm))

    @property
    def provide_data(self):
        return [DataDesc(self.data_name,
                         (self.batch_size,) + self.data_shape,
                         self._dtype)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [DataDesc(self.label_name, shape)]

    def reset(self):
        """Start the next epoch. At a normal epoch boundary this is free;
        mid-epoch it SKIPS the remainder (stale batches are discarded as
        they arrive — no blocking on decode work), and before anything was
        consumed it is a no-op."""
        if self._cursor == 0:
            return
        old = self._epoch
        self._epoch += 1
        self._cursor = 0
        for (rank, slot, _pad) in self._pending.pop(old, []):
            self._free_qs[rank].put(slot)

    def _take_current_epoch(self):
        """Next (rank, slot, pad) belonging to the parent's current epoch.
        Later-epoch arrivals are stashed (bounded by each worker's private
        slot pool); stale-epoch arrivals (after a mid-epoch reset) are
        freed immediately; a dead or erroring worker raises instead of
        hanging the job."""
        import queue as _queue
        stash = self._pending.get(self._epoch)
        if stash:
            return stash.pop(0)
        while True:
            try:
                msg = self._ready_q.get(timeout=5.0)
            except _queue.Empty:
                dead = [r for r, p in enumerate(self._procs)
                        if not p.is_alive()]
                if dead:
                    raise RuntimeError(
                        f"data worker process(es) {dead} died "
                        "unexpectedly; see stderr for the traceback")
                continue
            if msg[0] == "error":
                raise RuntimeError(
                    f"data worker {msg[1]} failed: {msg[2]}")
            _, rank, slot, ep, pad = msg
            if ep == self._epoch:
                return rank, slot, pad
            if ep < self._epoch:      # skipped by a mid-epoch reset
                self._free_qs[rank].put(slot)
                continue
            self._pending.setdefault(ep, []).append((rank, slot, pad))

    def next(self):
        if self._cursor >= self._batches_per_epoch:
            raise StopIteration
        self._cursor += 1
        rank, slot, pad = self._take_current_epoch()
        data = np.array(self._data_view[slot], copy=True)
        label = np.array(self._lbl_view[slot], copy=True)
        self._free_qs[rank].put(slot)
        if self.label_width == 1:
            label = label[:, 0]
        if self._as_numpy:
            return DataBatch([data], [label], pad=pad)
        from .. import ndarray as nd
        return DataBatch([nd.array(data, dtype=str(self._dtype))],
                         [nd.array(label)], pad=pad)

    def close(self):
        if self._closed:
            return
        self._closed = True
        del self._data_view, self._lbl_view
        self._finalizer()  # stop workers + unlink shm (idempotent)
        for shm in (self._shm, self._lbl_shm):
            try:
                shm.close()
            except BufferError:
                pass


def _shutdown(procs, free_qs, shms):
    """Finalizer for MPImageRecordIter (module-level: must not hold a
    reference to the iterator, or it could never be collected)."""
    for q in free_qs:
        try:
            q.put(None)
        except Exception:
            pass
    for p in procs:
        p.join(timeout=2)
        if p.is_alive():
            p.terminate()
    for shm in shms:
        try:
            shm.unlink()
        except Exception:
            pass
