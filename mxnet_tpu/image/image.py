"""Image loading, augmentation, and the ImageIter pipeline.

TPU-native rebuild of ``mxnet.image`` (reference: python/mxnet/image/
image.py; native path src/io/iter_image_recordio_2.cc:727 + augmenters
image_aug_default.cc).

Decode/augment run on host CPU (cv2) like the reference's OpenCV path; the
batch is handed to the device as one contiguous array so the transfer
overlaps compute via JAX async dispatch (+ PrefetchingIter for pipelining).
"""
from __future__ import annotations

import logging
import os
import random as pyrandom

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from .. import recordio
from ..base import MXNetError
from ..ndarray.ndarray import NDArray

__all__ = ["imread", "imdecode", "imresize", "scale_down", "resize_short",
           "fixed_crop", "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "Augmenter", "SequentialAug", "RandomOrderAug",
           "ResizeAug", "ForceResizeAug", "RandomCropAug",
           "RandomSizedCropAug", "CenterCropAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "RandomGrayAug", "HorizontalFlipAug", "CastAug",
           "CreateAugmenter", "ImageIter"]


def imread(filename, flag=1, to_rgb=True):
    """Read an image file to (H, W, C) NDArray (reference: image.py:78)."""
    import cv2
    img = cv2.imread(filename, flag)
    if img is None:
        raise MXNetError(f"cannot read image {filename}")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img, dtype="uint8")


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image from bytes (reference: image.py:147; native
    image_io.cc)."""
    import cv2
    if isinstance(buf, NDArray):
        buf = buf.asnumpy().astype(np.uint8)
    img = cv2.imdecode(np.frombuffer(bytes(buf), np.uint8), flag)
    if img is None:
        raise MXNetError("cannot decode image")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    return nd.array(img, dtype="uint8")


def imresize(src, w, h, interp=1):
    import cv2
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    return nd.array(cv2.resize(arr, (w, h), interpolation=interp),
                    dtype=str(arr.dtype))


def scale_down(src_size, size):
    """Scale target size to fit in src (reference: image.py:209)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge is ``size`` (reference: image.py:245)."""
    import cv2
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return nd.array(cv2.resize(arr, (new_w, new_h), interpolation=interp),
                    dtype=str(arr.dtype))


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """(reference: image.py:279)"""
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        import cv2
        out = cv2.resize(out, size, interpolation=interp)
    return nd.array(out, dtype=str(arr.dtype))


def random_crop(src, size, interp=2):
    """(reference: image.py:312)"""
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    """(reference: image.py:363)"""
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    """(reference: image.py:409)"""
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, area, ratio, interp=2):
    """Random crop by area fraction + aspect ratio (reference:
    image.py:433; inception-style augmentation)."""
    import math
    arr = src.asnumpy() if isinstance(src, NDArray) else src
    h, w = arr.shape[:2]
    src_area = h * w
    if isinstance(area, (int, float)):
        area = (area, 1.0)
    for _ in range(10):
        target_area = pyrandom.uniform(area[0], area[1]) * src_area
        log_ratio = (math.log(ratio[0]), math.log(ratio[1]))
        new_ratio = math.exp(pyrandom.uniform(*log_ratio))
        new_w = int(round(math.sqrt(target_area * new_ratio)))
        new_h = int(round(math.sqrt(target_area / new_ratio)))
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


# ---------------------------------------------------------------------------
# Augmenters (reference: image.py:505-877)
# ---------------------------------------------------------------------------
class Augmenter:
    """Image augmenter base (reference: image.py:505)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        for k, v in kwargs.items():
            if isinstance(v, NDArray):
                kwargs[k] = v.asnumpy().tolist()

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src):
        raise NotImplementedError


class SequentialAug(Augmenter):
    """(reference: image.py:536)"""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        for aug in self.ts:
            src = aug(src)
        return src


class RandomOrderAug(Augmenter):
    """(reference: image.py:556)"""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        ts = list(self.ts)
        pyrandom.shuffle(ts)
        for t in ts:
            src = t(src)
        return src


class ResizeAug(Augmenter):
    """Resize shorter edge (reference: image.py:582)."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """(reference: image.py:602)"""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, area, ratio, interp=2):
        super().__init__(size=size, area=area, ratio=ratio, interp=interp)
        self.size = size
        self.area = area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return random_size_crop(src, self.size, self.area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return center_crop(src, self.size, self.interp)[0]


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return src.astype("float32") * alpha


class ContrastJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self.coef).sum() * (3.0 / arr.size)
        return nd.array(arr * alpha + gray * (1.0 - alpha))


class SaturationJitterAug(Augmenter):
    coef = np.array([[[0.299, 0.587, 0.114]]], np.float32)

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self.coef).sum(axis=2, keepdims=True)
        return nd.array(arr * alpha + gray * (1.0 - alpha))


class HueJitterAug(Augmenter):
    """(reference: image.py:729)"""

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue
        self.tyiq = np.array([[0.299, 0.587, 0.114],
                              [0.596, -0.274, -0.321],
                              [0.211, -0.523, 0.311]], np.float32)
        self.ityiq = np.array([[1.0, 0.956, 0.621],
                               [1.0, -0.272, -0.647],
                               [1.0, -1.107, 1.705]], np.float32)

    def __call__(self, src):
        import math
        alpha = pyrandom.uniform(-self.hue, self.hue)
        u = math.cos(alpha * np.pi)
        w = math.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0], [0.0, u, -w], [0.0, w, u]],
                      np.float32)
        t = np.dot(np.dot(self.ityiq, bt), self.tyiq).T
        arr = src.asnumpy().astype(np.float32)
        return nd.array(np.dot(arr, t))


class ColorJitterAug(RandomOrderAug):
    """(reference: image.py:767)"""

    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super().__init__(ts)


class LightingAug(Augmenter):
    """PCA lighting noise (reference: image.py:795)."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, np.float32)
        self.eigvec = np.asarray(eigvec, np.float32)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return src.astype("float32") + nd.array(rgb)


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__(mean=mean, std=std)
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return color_normalize(src.astype("float32"), self.mean, self.std)


class RandomGrayAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p
        self.mat = nd.array([[0.21, 0.21, 0.21], [0.72, 0.72, 0.72],
                             [0.07, 0.07, 0.07]])

    def __call__(self, src):
        if pyrandom.random() < self.p:
            src = nd.dot(src.astype("float32"), self.mat)
        return src


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            src = nd.array(src.asnumpy()[:, ::-1])
        return src


class CastAug(Augmenter):
    def __init__(self, typ="float32"):
        super().__init__(type=typ)
        self.typ = typ

    def __call__(self, src):
        return src.astype(self.typ)


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, hue=0, pca_noise=0,
                    rand_gray=0, inter_method=2):
    """Build the standard augmentation pipeline (reference: image.py:877)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, (0.08, 1.0),
                                          (3.0 / 4.0, 4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if hue:
        auglist.append(HueJitterAug(hue))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if rand_gray > 0:
        auglist.append(RandomGrayAug(rand_gray))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(io_mod.DataIter):
    """Image iterator over .rec files or image lists with augmentation
    (reference: image.py:1017; native iter_image_recordio_2.cc:727).

    Supports path_imgrec (RecordIO) or path_imglist/imglist + path_root.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root="",
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", dtype="float32",
                 last_batch_handle="pad", **kwargs):
        super().__init__()
        assert path_imgrec or path_imglist or isinstance(imglist, list)
        self.imgrec = None
        self.imglist = None
        self.seq = None
        if path_imgrec:
            if path_imgidx is None and os.path.isfile(
                    os.path.splitext(path_imgrec)[0] + ".idx"):
                path_imgidx = os.path.splitext(path_imgrec)[0] + ".idx"
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.seq = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
        elif path_imglist:
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in fin:
                    line = line.strip().split("\t")
                    label = np.array(line[1:-1], np.float32)
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
            self.imglist = imglist
            self.seq = imgkeys
        else:
            import numbers
            result = {}
            imgkeys = []
            for i, img in enumerate(imglist):
                label = np.array([img[0]], np.float32) \
                    if isinstance(img[0], numbers.Number) \
                    else np.array(img[0], np.float32)
                result[i] = (label, img[1])
                imgkeys.append(i)
            self.imglist = result
            self.seq = imgkeys
        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast",
                         "saturation", "hue", "pca_noise", "rand_gray",
                         "inter_method")})
        else:
            self.auglist = aug_list
        self.cur = 0
        self._cache = None
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.reset()

    @property
    def provide_data(self):
        return [io_mod.DataDesc(self.data_name,
                                (self.batch_size,) + self.data_shape)]

    @property
    def provide_label(self):
        shape = (self.batch_size,) if self.label_width == 1 else \
            (self.batch_size, self.label_width)
        return [io_mod.DataDesc(self.label_name, shape)]

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None and self.seq is None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        """Returns (label, decoded image NDArray)
        (reference: image.py:1167)."""
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                return header.label, imdecode(img)
            label, fname = self.imglist[idx]
            return label, imread(os.path.join(self.path_root, fname))
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, imdecode(img)

    def next(self):
        """(reference: image.py:1190)"""
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, h, w, c), np.float32)
        batch_label = np.zeros((batch_size, self.label_width), np.float32)
        i = 0
        try:
            while i < batch_size:
                label, data = self.next_sample()
                for aug in self.auglist:
                    data = aug(data)
                arr = data.asnumpy() if isinstance(data, NDArray) else data
                if arr.ndim == 2:
                    arr = arr[:, :, None]
                batch_data[i] = arr
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        data_nd = nd.array(batch_data.transpose(0, 3, 1, 2),
                           dtype=self.dtype)
        label_nd = nd.array(batch_label.squeeze(-1)
                            if self.label_width == 1 else batch_label)
        return io_mod.DataBatch([data_nd], [label_nd], pad=pad)
