"""Detection-specific image augmentation
(reference: python/mxnet/image/detection.py; native
src/io/image_det_aug_default.cc, iter_image_det_recordio.cc:582).

Labels are (N, 5+) arrays [class, xmin, ymin, xmax, ymax, ...] with
normalized coordinates; augmenters transform image + boxes together.
"""
from __future__ import annotations

import random as pyrandom

import numpy as np

from .. import io as io_mod
from .. import ndarray as nd
from ..ndarray.ndarray import NDArray
from . import image as img_mod

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter:
    """(reference: detection.py:41)"""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        import json
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Wrap a classification augmenter that doesn't move pixels relative to
    boxes (reference: detection.py:68)."""

    def __init__(self, augmenter):
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src), label


class DetRandomSelectAug(DetAugmenter):
    """(reference: detection.py:89)"""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = aug_list
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if pyrandom.random() < self.skip_prob:
            return src, label
        aug = pyrandom.choice(self.aug_list)
        return aug(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """(reference: detection.py:118)"""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if pyrandom.random() < self.p:
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            src = nd.array(arr[:, ::-1])
            label = np.array(label, copy=True)
            tmp = 1.0 - label[:, 1].copy()
            label[:, 1] = 1.0 - label[:, 3]
            label[:, 3] = tmp
        return src, label


class DetRandomCropAug(DetAugmenter):
    """IoU-constrained random crop (reference: detection.py:142; SSD
    data augmentation, Liu et al.)."""

    def __init__(self, min_object_covered=0.1, aspect_ratio_range=(0.75,
                 1.33), area_range=(0.05, 1.0), min_eject_coverage=0.3,
                 max_attempts=50):
        super().__init__()
        self.min_object_covered = min_object_covered
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.min_eject_coverage = min_eject_coverage
        self.max_attempts = max_attempts

    def _check_satisfy(self, rect, boxes):
        l, t, r, b = rect
        ious = []
        for box in boxes:
            ix = max(0.0, min(r, box[3]) - max(l, box[1]))
            iy = max(0.0, min(b, box[4]) - max(t, box[2]))
            inter = ix * iy
            union = (r - l) * (b - t) + \
                (box[3] - box[1]) * (box[4] - box[2]) - inter
            ious.append(inter / union if union > 0 else 0.0)
        return ious and max(ious) >= self.min_object_covered

    def _update_labels(self, label, crop):
        l, t, r, b = crop
        w, h = r - l, b - t
        out = []
        for obj in label:
            cx = (obj[1] + obj[3]) / 2
            cy = (obj[2] + obj[4]) / 2
            if not (l <= cx <= r and t <= cy <= b):
                continue
            nl = (max(obj[1], l) - l) / w
            nt = (max(obj[2], t) - t) / h
            nr = (min(obj[3], r) - l) / w
            nb = (min(obj[4], b) - t) / h
            coverage = max(0.0, nr - nl) * max(0.0, nb - nt) * w * h / \
                max((obj[3] - obj[1]) * (obj[4] - obj[2]), 1e-12)
            if coverage < self.min_eject_coverage:
                continue
            out.append([obj[0], nl, nt, nr, nb] + list(obj[5:]))
        return np.asarray(out, np.float32) if out else None

    def __call__(self, src, label):
        import math
        label = np.asarray(label)
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            ratio = math.exp(pyrandom.uniform(
                math.log(self.aspect_ratio_range[0]),
                math.log(self.aspect_ratio_range[1])))
            w = min(1.0, math.sqrt(area * ratio))
            h = min(1.0, math.sqrt(area / ratio))
            l = pyrandom.uniform(0, 1 - w)
            t = pyrandom.uniform(0, 1 - h)
            rect = (l, t, l + w, t + h)
            if not self._check_satisfy(rect, label):
                continue
            new_label = self._update_labels(label, rect)
            if new_label is None:
                continue
            arr = src.asnumpy() if isinstance(src, NDArray) else src
            H, W = arr.shape[:2]
            x0, y0 = int(l * W), int(t * H)
            x1, y1 = int((l + w) * W), int((t + h) * H)
            return nd.array(arr[y0:y1, x0:x1]), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Zoom-out padding (reference: detection.py:285)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=50,
                 pad_val=(127, 127, 127)):
        super().__init__()
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = pad_val

    def __call__(self, src, label):
        import math
        label = np.asarray(label)
        arr = src.asnumpy() if isinstance(src, NDArray) else src
        H, W = arr.shape[:2]
        for _ in range(self.max_attempts):
            area = pyrandom.uniform(*self.area_range)
            if area < 1.0:
                continue
            ratio = math.exp(pyrandom.uniform(
                math.log(self.aspect_ratio_range[0]),
                math.log(self.aspect_ratio_range[1])))
            nw = int(W * math.sqrt(area * ratio))
            nh = int(H * math.sqrt(area / ratio))
            if nw < W or nh < H:
                continue
            x0 = pyrandom.randint(0, nw - W)
            y0 = pyrandom.randint(0, nh - H)
            canvas = np.full((nh, nw, arr.shape[2]), self.pad_val,
                             arr.dtype)
            canvas[y0:y0 + H, x0:x0 + W] = arr
            new_label = np.array(label, copy=True)
            new_label[:, 1] = (label[:, 1] * W + x0) / nw
            new_label[:, 3] = (label[:, 3] * W + x0) / nw
            new_label[:, 2] = (label[:, 2] * H + y0) / nh
            new_label[:, 4] = (label[:, 4] * H + y0) / nh
            return nd.array(canvas), new_label
        return src, label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_gray=0, rand_mirror=False, mean=None, std=None,
                       brightness=0, contrast=0, saturation=0, pca_noise=0,
                       hue=0, inter_method=2, min_object_covered=0.1,
                       aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.05, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """(reference: detection.py:611)"""
    auglist = []
    if resize > 0:
        auglist.append(DetBorrowAug(img_mod.ResizeAug(resize, inter_method)))
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered, aspect_ratio_range,
                                (area_range[0], min(1.0, area_range[1])),
                                min_eject_coverage, max_attempts)
        auglist.append(DetRandomSelectAug([crop], 1 - rand_crop))
    if rand_mirror:
        auglist.append(DetHorizontalFlipAug(0.5))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range,
                              (max(1.0, area_range[0]), area_range[1]),
                              max_attempts, pad_val)
        auglist.append(DetRandomSelectAug([pad], 1 - rand_pad))
    auglist.append(DetBorrowAug(img_mod.ForceResizeAug(
        (data_shape[2], data_shape[1]), inter_method)))
    auglist.append(DetBorrowAug(img_mod.CastAug()))
    if brightness or contrast or saturation:
        auglist.append(DetBorrowAug(img_mod.ColorJitterAug(
            brightness, contrast, saturation)))
    if hue:
        auglist.append(DetBorrowAug(img_mod.HueJitterAug(hue)))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(DetBorrowAug(img_mod.LightingAug(pca_noise, eigval,
                                                        eigvec)))
    if rand_gray > 0:
        auglist.append(DetBorrowAug(img_mod.RandomGrayAug(rand_gray)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(DetBorrowAug(img_mod.ColorNormalizeAug(mean, std)))
    return auglist


class ImageDetIter(img_mod.ImageIter):
    """Detection iterator: labels are (N, 5+) box arrays padded to a fixed
    object count per batch (reference: detection.py:751)."""

    def __init__(self, batch_size, data_shape, path_imgrec=None,
                 path_imglist=None, path_root="", path_imgidx=None,
                 shuffle=False, part_index=0, num_parts=1, aug_list=None,
                 imglist=None, data_name="data", label_name="label",
                 last_batch_handle="pad", **kwargs):
        if aug_list is None:
            aug_list = CreateDetAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_pad", "rand_gray",
                         "rand_mirror", "mean", "std", "brightness",
                         "contrast", "saturation", "pca_noise", "hue",
                         "inter_method")})
        super().__init__(batch_size=batch_size, data_shape=data_shape,
                         path_imgrec=path_imgrec, path_imglist=path_imglist,
                         path_root=path_root, path_imgidx=path_imgidx,
                         shuffle=shuffle, part_index=part_index,
                         num_parts=num_parts, aug_list=[], imglist=imglist,
                         data_name=data_name, label_name=label_name,
                         **kwargs)
        self.det_auglist = aug_list
        self.max_objects = kwargs.get("max_objects", 50)
        self.label_obj_width = kwargs.get("label_width", 5)

    @property
    def provide_label(self):
        return [io_mod.DataDesc(
            self.label_name,
            (self.batch_size, self.max_objects, self.label_obj_width))]

    def _parse_label(self, label):
        """Header label → (N, 5) boxes (reference: detection.py:845)."""
        raw = np.asarray(label).ravel()
        if raw.size >= 2 and raw[0] == 2:  # [2, obj_width, ...boxes]
            obj_width = int(raw[1])
            body = raw[2:]
            return body.reshape(-1, obj_width)
        return raw.reshape(-1, self.label_obj_width)

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, h, w, c), np.float32)
        batch_label = np.full(
            (batch_size, self.max_objects, self.label_obj_width), -1.0,
            np.float32)
        i = 0
        try:
            while i < batch_size:
                raw_label, data = self.next_sample()
                boxes = self._parse_label(raw_label)
                for aug in self.det_auglist:
                    data, boxes = aug(data, boxes)
                arr = data.asnumpy() if isinstance(data, NDArray) else data
                if arr.ndim == 2:
                    arr = arr[:, :, None]
                batch_data[i] = arr
                n = min(len(boxes), self.max_objects)
                if n:
                    batch_label[i, :n] = boxes[:n, :self.label_obj_width]
                i += 1
        except StopIteration:
            if i == 0:
                raise
        pad = batch_size - i
        return io_mod.DataBatch(
            [nd.array(batch_data.transpose(0, 3, 1, 2))],
            [nd.array(batch_label)], pad=pad)
