"""Automatic naming of Symbols/Blocks.

Reference: python/mxnet/name.py — ``NameManager`` (counter-based auto names)
and ``Prefix`` (prepend a prefix within a scope).
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class NameManager:
    """Assigns unique names per op-type hint (reference: name.py:25)."""

    _current_tls = threading.local()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        if name:
            return name
        if hint not in self._counter:
            self._counter[hint] = 0
        name = f"{hint}{self._counter[hint]}"
        self._counter[hint] += 1
        return name

    def __enter__(self):
        self._old_manager = getattr(NameManager._current_tls, "value", None)
        NameManager._current_tls.value = self
        return self

    def __exit__(self, ptype, value, trace):
        NameManager._current_tls.value = self._old_manager


class _CurrentProxy:
    """``NameManager.current`` — the active manager (thread-local)."""

    def get(self, name, hint):
        mgr = getattr(NameManager._current_tls, "value", None)
        if mgr is None:
            mgr = NameManager()
            NameManager._current_tls.value = mgr
        return mgr.get(name, hint)


NameManager.current = _CurrentProxy()


class Prefix(NameManager):
    """Auto-names with a fixed prefix (reference: name.py:70)."""

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
