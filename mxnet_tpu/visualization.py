"""Network visualization (reference: python/mxnet/visualization.py —
plot_network via graphviz, print_summary table).

``plot_network`` emits Graphviz DOT. If the ``graphviz`` package is
importable the reference-compatible ``graphviz.Digraph`` is returned;
otherwise a ``DotGraph`` with the same ``.source``/``.render()`` surface is
returned so the capability works without the dependency (zero-egress image).
"""
from __future__ import annotations

from typing import Dict, Optional

__all__ = ["plot_network", "print_summary"]

_NODE_STYLE = {
    "null": ("#8dd3c7", "oval"),
    "FullyConnected": ("#fb8072", "box"),
    "Convolution": ("#fb8072", "box"),
    "Activation": ("#ffffb3", "box"),
    "BatchNorm": ("#bebada", "box"),
    "Pooling": ("#80b1d3", "box"),
    "Concat": ("#fdb462", "box"),
    "Flatten": ("#fdb462", "box"),
    "SoftmaxOutput": ("#b3de69", "box"),
}


class DotGraph:
    """Minimal stand-in for graphviz.Digraph (same source/render API)."""

    def __init__(self, name="plot"):
        self.name = name
        self._lines = [f'digraph "{name}" {{',
                       "node [fontsize=10];", "edge [fontsize=10];"]
        self._closed = False

    def node(self, name, label, **attrs):
        a = "".join(f' {k}="{v}"' for k, v in attrs.items())
        self._lines.append(f'"{name}" [label="{label}"{a}];')

    def edge(self, src, dst, **attrs):
        a = "".join(f' {k}="{v}"' for k, v in attrs.items())
        self._lines.append(f'"{src}" -> "{dst}" [{a.strip()}];')

    @property
    def source(self):
        return "\n".join(self._lines + ["}"])

    def render(self, filename=None, format="dot", cleanup=False):
        filename = filename or self.name
        path = f"{filename}.{format}" if not filename.endswith(f".{format}") \
            else filename
        with open(path, "w") as f:
            f.write(self.source)
        return path

    def _repr_svg_(self):  # pragma: no cover - notebook nicety
        return None


def _iter_nodes(symbol):
    """Topological (creation-order) node list of a Symbol graph."""
    seen = []
    seen_ids = set()

    def walk(node):
        if id(node) in seen_ids:
            return
        for parent, _ in node.inputs:
            walk(parent)
        seen_ids.add(id(node))
        seen.append(node)

    syms = getattr(symbol, "_group", None) or [symbol]
    for out in syms:
        walk(out._node)
    return seen


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a DOT graph of a Symbol (reference: visualization.py
    plot_network)."""
    import shutil
    try:
        from graphviz import Digraph
        have_pkg = True
    except ImportError:
        have_pkg = False
    if have_pkg and shutil.which("dot"):
        dot = Digraph(name=title)
    else:
        # needs both the python pkg and the dot executable for render();
        # otherwise use the self-contained DOT emitter
        dot = DotGraph(name=title)
    nodes = _iter_nodes(symbol)
    arg_like = {".weight", "_weight", ".bias", "_bias", "_gamma", "_beta",
                "_moving_mean", "_moving_var", "_running_mean",
                "_running_var"}
    hidden = set()
    for n in nodes:
        if n.op is None and hide_weights and \
                any(n.name.endswith(s) for s in arg_like):
            hidden.add(id(n))
            continue
        op = n.op or "null"
        color, nshape = _NODE_STYLE.get(op, ("#d9d9d9", "box"))
        label = n.name if n.op is None else f"{n.op}\\n{n.name}"
        attrs = {"fillcolor": color, "shape": nshape, "style": "filled"}
        attrs.update(node_attrs or {})
        dot.node(n.name, label, **attrs)
    for n in nodes:
        if id(n) in hidden:
            continue
        for parent, _ in n.inputs:
            if id(parent) in hidden:
                continue
            dot.edge(parent.name, n.name)
    return dot


def print_summary(symbol, shape=None, line_length=120, positions=None):
    """Print a layer table: name, output shape where inferable, params
    (reference: visualization.py print_summary)."""
    positions = positions or [0.44, 0.64, 0.74, 1.0]
    cols = [int(line_length * p) for p in positions]
    heads = ["Layer (type)", "Output Shape", "Param #", "Previous Layer"]

    shapes = {}
    if shape is not None:
        try:
            arg_shapes, out_shapes, _ = symbol.infer_shape(**shape)
            for name, s in zip(symbol.list_arguments(), arg_shapes):
                shapes[name] = s
        except Exception:
            pass

    def fmt_row(fields):
        line = ""
        for f, c in zip(fields, cols):
            line = (line + str(f))[:c - 1].ljust(c)
        return line

    print("=" * line_length)
    print(fmt_row(heads))
    print("=" * line_length)
    total_params = 0
    import numpy as np
    nodes = _iter_nodes(symbol)
    node_params = {}
    for n in nodes:
        if n.op is None:
            continue
        layer_params = 0
        prevs = []
        for parent, _ in n.inputs:
            if parent.op is None and parent.name != "data" and \
                    not parent.name.endswith("label"):
                s = shapes.get(parent.name)
                if s:
                    layer_params += int(np.prod(s))
            else:
                prevs.append(parent.name)
        total_params += layer_params
        out_shape = ""
        print(fmt_row([f"{n.name} ({n.op})", out_shape, layer_params,
                       ", ".join(prevs)]))
        node_params[n.name] = layer_params
    print("=" * line_length)
    print(f"Total params: {total_params}")
    print("=" * line_length)
    return total_params
