"""Multi-process (multi-host) data parallelism.

TPU-native replacement for the reference's parameter-server stack
(reference: src/kvstore/kvstore_dist.h:44, kvstore_dist_server.h:113,
3rdparty/ps-lite, tools/launch.py + dmlc-tracker bootstrap).

Architectural mapping:
- bootstrap: ``ps::StartAsync`` + scheduler rendezvous → ``init()`` /
  ``jax.distributed.initialize`` (env: COORDINATOR_ADDRESS, NUM_PROCESSES,
  PROCESS_ID — replacing DMLC_PS_ROOT_URI/DMLC_ROLE).
- worker push/pull of float buffers over ZMQ → an all-reduce across
  processes over DCN/ICI via a global mesh ``psum``.
- server-side optimizer ("update_on_kvstore", kvstore_dist_server.h:187)
  → every process applies the same optimizer to the all-reduced gradient;
  there is no server role.
- ``dist_async`` (no inter-worker barrier) has no XLA analog — collectives
  are cooperative. It is emulated as sync (documented deviation; the
  reference's own docs recommend sync for convergence).
"""
from __future__ import annotations

import os

import numpy as np

from ..kvstore import KVStore
from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["DistKVStore", "init", "barrier", "allreduce"]

_initialized = [False]


def init(coordinator=None, num_processes=None, process_id=None):
    """Bootstrap multi-process JAX (reference analog: tools/launch.py +
    ps-lite rendezvous, kvstore_dist.h:51-53)."""
    import jax
    if _initialized[0] or jax.process_count() > 1:
        _initialized[0] = True
        return
    coordinator = coordinator or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator is None:
        # single-process: nothing to bootstrap
        _initialized[0] = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes or
                          os.environ.get("NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("PROCESS_ID", 0)))
    _initialized[0] = True


def barrier():
    """Global barrier (reference: ps Barrier, kvstore_dist.h:108)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("mxnet_tpu_barrier")


_reduce_cache = {}
_mesh_cache = {}


def _global_mesh():
    import jax
    from jax.sharding import Mesh
    key = tuple(id(d) for d in jax.devices())
    mesh = _mesh_cache.get(key)
    if mesh is None:
        devs = np.array(jax.devices()).reshape(jax.process_count(), -1)
        mesh = Mesh(devs, ("proc", "local"))
        _mesh_cache.clear()          # device topology changes invalidate all
        _mesh_cache[key] = mesh
    return mesh


def _reduce_jit(mesh):
    """One compiled cross-process sum over a (procs, n) buffer — the
    collective rides DCN/ICI inside XLA, replacing a per-key host
    round-trip. One jit wrapper per mesh; jit's own cache re-specializes
    per input shape/dtype."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    key = id(mesh)
    fn = _reduce_cache.get(key)
    if fn is None:
        _reduce_cache.clear()
        fn = jax.jit(lambda x: x.sum(axis=0),
                     out_shardings=NamedSharding(mesh, P()))
        _reduce_cache[key] = fn
    return fn


def allreduce(array):
    """Sum an array across all processes (returns the global sum)."""
    arrays = allreduce_batch([array])
    return arrays[0]


def allreduce_batch(arrays):
    """Sum a *list* of arrays across all processes with ONE device
    collective: everything is flattened into a single buffer, reduced as
    one XLA computation, and split back (reference analog: the server
    merging all keys of a push round, kvstore_dist_server.h:189 — but as a
    batched allreduce instead of per-key RPCs)."""
    import jax
    import jax.numpy as jnp
    if jax.process_count() == 1:
        return list(arrays)
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    arrays = [jnp.asarray(a) for a in arrays]
    shapes = [a.shape for a in arrays]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    dtype = jnp.result_type(*arrays) if arrays else jnp.float32
    flat = jnp.concatenate([a.astype(dtype).ravel() for a in arrays]) \
        if arrays else jnp.zeros((0,), dtype)
    mesh = _global_mesh()
    global_buf = multihost_utils.host_local_array_to_global_array(
        flat[None], mesh, P("proc"))
    summed = _reduce_jit(mesh)(global_buf)
    local = multihost_utils.global_array_to_host_local_array(
        summed, mesh, P())
    out, pos = [], 0
    for a, shape, size in zip(arrays, shapes, sizes):
        out.append(local[pos:pos + size].reshape(shape).astype(a.dtype))
        pos += size
    return out


class DistKVStore(KVStore):
    """dist_sync / dist_device_sync / dist_async kvstore types.

    Push sums gradients across every process (the reference's server-side
    merge across NumWorkers() pushes, kvstore_dist_server.h:189); pull
    returns the merged value or the optimizer-updated weight.
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        init()
        if kv_type == "dist_async":
            import warnings
            warnings.warn(
                "dist_async is emulated as synchronous data parallelism on "
                "TPU (XLA collectives are cooperative); convergence "
                "semantics match dist_sync")

    @property
    def is_distributed(self):
        return True

    def push(self, key, value, priority=0):
        keys, values = [key], [value]
        if isinstance(key, (list, tuple)):
            keys, values = list(key), list(value)
        local = []
        for k, v in zip(keys, values):
            vals = v if isinstance(v, (list, tuple)) else [v]
            agg = vals[0]
            for extra in vals[1:]:
                agg = agg + extra
            # row sets differ per process: densify sparse grads for the
            # uniform-shape collective (the reference instead re-encodes
            # row keys per server, kvstore_dist.h EncodeRowSparseKey)
            if getattr(agg, "stype", "default") != "default":
                agg = agg.todense()
            # worker-side 2-bit quantize with error feedback before the
            # wire (reference: kvstore_dist.h:343-353)
            agg = self._apply_compression(k, agg)
            local.append((k, agg))
        # one batched cross-process reduction for the whole push round
        # (≙ server merge across NumWorkers() pushes)
        reduced = allreduce_batch([a._data for _, a in local])
        for (k, _), rdata in zip(local, reduced):
            agg = _wrap(rdata)
            if self._updater is not None:
                if k not in self._data:
                    raise ValueError(f"key {k} not initialized")
                self._updater(_key_int(k), agg, self._data[k])
            else:
                self._merged = getattr(self, "_merged", {})
                self._merged[k] = agg


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k
