"""Multi-process (multi-host) data parallelism.

TPU-native replacement for the reference's parameter-server stack
(reference: src/kvstore/kvstore_dist.h:44, kvstore_dist_server.h:113,
3rdparty/ps-lite, tools/launch.py + dmlc-tracker bootstrap).

Architectural mapping:
- bootstrap: ``ps::StartAsync`` + scheduler rendezvous → ``init()`` /
  ``jax.distributed.initialize`` (env: COORDINATOR_ADDRESS, NUM_PROCESSES,
  PROCESS_ID — replacing DMLC_PS_ROOT_URI/DMLC_ROLE).
- worker push/pull of float buffers over ZMQ → an all-reduce across
  processes over DCN/ICI via a global mesh ``psum``.
- server-side optimizer ("update_on_kvstore", kvstore_dist_server.h:187)
  → every process applies the same optimizer to the all-reduced gradient;
  there is no server role.
- ``dist_async`` (no inter-worker barrier) has no XLA analog — collectives
  are cooperative. It is emulated as sync (documented deviation; the
  reference's own docs recommend sync for convergence).
"""
from __future__ import annotations

import os

import numpy as np

from ..kvstore import KVStore
from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["DistKVStore", "init", "barrier", "allreduce"]

_initialized = [False]


def init(coordinator=None, num_processes=None, process_id=None):
    """Bootstrap multi-process JAX (reference analog: tools/launch.py +
    ps-lite rendezvous, kvstore_dist.h:51-53)."""
    import jax
    if _initialized[0] or jax.process_count() > 1:
        _initialized[0] = True
        return
    coordinator = coordinator or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator is None:
        # single-process: nothing to bootstrap
        _initialized[0] = True
        return
    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=int(num_processes or
                          os.environ.get("NUM_PROCESSES", 1)),
        process_id=int(process_id or os.environ.get("PROCESS_ID", 0)))
    _initialized[0] = True


def barrier():
    """Global barrier (reference: ps Barrier, kvstore_dist.h:108)."""
    import jax
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices("mxnet_tpu_barrier")


def allreduce(array):
    """Sum an array across all processes (returns the global sum)."""
    import jax
    import jax.numpy as jnp
    if jax.process_count() == 1:
        return array
    from jax.experimental import multihost_utils
    gathered = multihost_utils.process_allgather(np.asarray(array))
    return jnp.asarray(np.sum(gathered, axis=0))


class DistKVStore(KVStore):
    """dist_sync / dist_device_sync / dist_async kvstore types.

    Push sums gradients across every process (the reference's server-side
    merge across NumWorkers() pushes, kvstore_dist_server.h:189); pull
    returns the merged value or the optimizer-updated weight.
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        init()
        if kv_type == "dist_async":
            import warnings
            warnings.warn(
                "dist_async is emulated as synchronous data parallelism on "
                "TPU (XLA collectives are cooperative); convergence "
                "semantics match dist_sync")

    @property
    def is_distributed(self):
        return True

    def push(self, key, value, priority=0):
        keys, values = [key], [value]
        if isinstance(key, (list, tuple)):
            keys, values = list(key), list(value)
        for k, v in zip(keys, values):
            vals = v if isinstance(v, (list, tuple)) else [v]
            agg = vals[0]
            for extra in vals[1:]:
                agg = agg + extra
            # cross-process reduction (≙ server merge)
            agg = _wrap(allreduce(agg._data))
            if self._updater is not None:
                if k not in self._data:
                    raise ValueError(f"key {k} not initialized")
                self._updater(_key_int(k), agg, self._data[k])
            else:
                self._merged = getattr(self, "_merged", {})
                self._merged[k] = agg


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k
