"""Multi-process (multi-host) data parallelism.

TPU-native replacement for the reference's parameter-server stack
(reference: src/kvstore/kvstore_dist.h:44, kvstore_dist_server.h:113,
3rdparty/ps-lite, tools/launch.py + dmlc-tracker bootstrap).

Architectural mapping:
- bootstrap: ``ps::StartAsync`` + scheduler rendezvous → ``init()`` /
  ``jax.distributed.initialize`` (env: COORDINATOR_ADDRESS, NUM_PROCESSES,
  PROCESS_ID — replacing DMLC_PS_ROOT_URI/DMLC_ROLE).
- worker push/pull of float buffers over ZMQ → an all-reduce across
  processes over DCN/ICI via a global mesh ``psum``.
- server-side optimizer ("update_on_kvstore", kvstore_dist_server.h:187)
  → every process applies the same optimizer to the all-reduced gradient;
  there is no server role.
- ``dist_async`` (no inter-worker barrier) has no XLA analog — collectives
  are cooperative. It is emulated as sync (documented deviation; the
  reference's own docs recommend sync for convergence).
"""
from __future__ import annotations

import os
import time
import warnings

import numpy as np

from .. import fault
from ..base import MXNetError
from ..kvstore import KVStore
from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["DistKVStore", "init", "barrier", "allreduce", "rank",
           "world_size", "process_identity", "notify_world_changed"]

_initialized = [False]
_host_fallback = [False]    # sticky: backend lacks multiproc collectives
_fallback_world = [0]       # ...but only for the world that proved it
_host_seq = [0]             # per-process collective ordinal (SPMD-matched)
_barrier_seq = [0]


def _fallback_active():
    """Is the sticky host-transport fallback still valid? The stickiness
    is keyed to the world size that PROVED the backend limitation: after
    an elastic re-form the device set and fabric are different, so the
    old world's evidence no longer applies — reset and re-probe the fast
    path instead of degrading the new mesh forever (round 17)."""
    if not _host_fallback[0]:
        return False
    if _fallback_world[0] != world_size():
        _host_fallback[0] = False
        _fallback_world[0] = 0
        return False
    return True


def _ft_cfg():
    from .. import config
    return (int(config.get("MXTPU_FT_DIST_RETRIES")),
            float(config.get("MXTPU_FT_DIST_BACKOFF")),
            float(config.get("MXTPU_FT_DIST_DEADLINE")))


def _retry(fn, what):
    """Run ``fn`` with exponential backoff + a wall-clock deadline —
    transient transport failures (coordinator not yet listening, slow
    rendezvous, injected faults) degrade to retries instead of killing
    the job (reference analog: ps-lite's van resends; SURVEY §5)."""
    retries, backoff, deadline = _ft_cfg()
    t0 = time.monotonic()
    attempt = 0
    while True:
        try:
            return fn()
        except Exception as e:
            attempt += 1
            elapsed = time.monotonic() - t0
            if attempt > retries or elapsed >= deadline:
                raise MXNetError(
                    f"dist {what} failed after {attempt} attempt(s) / "
                    f"{elapsed:.1f}s (retries={retries}, "
                    f"deadline={deadline}s): {e}") from e
            fault.count(f"dist.{what}_retries")
            from .. import profiler
            with profiler.Domain("ft").new_task(f"dist_retry_{what}"):
                time.sleep(min(backoff * (2 ** (attempt - 1)),
                               max(0.0, deadline - elapsed)))


def init(coordinator=None, num_processes=None, process_id=None):
    """Bootstrap multi-process JAX (reference analog: tools/launch.py +
    ps-lite rendezvous, kvstore_dist.h:51-53). Retries with backoff —
    workers racing the coordinator's bind no longer die on attempt 1."""
    import jax
    if _initialized[0] or jax.process_count() > 1:
        _initialized[0] = True
        return
    coordinator = coordinator or os.environ.get("COORDINATOR_ADDRESS")
    if coordinator is None:
        # single-process: nothing to bootstrap
        _initialized[0] = True
        return

    def _do_init():
        from .. import faultinject
        if faultinject.fire("dist_init"):
            raise faultinject.FaultInjected("dist_init")
        jax.distributed.initialize(
            coordinator_address=coordinator,
            num_processes=int(num_processes or
                              os.environ.get("NUM_PROCESSES", 1)),
            process_id=int(process_id or os.environ.get("PROCESS_ID", 0)))

    _retry(_do_init, "init")
    _initialized[0] = True


def rank():
    """This process's index in the job (0 when single-process / before
    the backend initializes). The per-host shard selector the data
    pipeline's ``RecordIOSource`` defaults to (reference analog: the
    ``part_index`` DMLC rank every C++ iterator took)."""
    import jax
    try:
        return int(jax.process_index())
    except Exception:
        return 0


def world_size():
    """Number of processes in the job (1 when single-process) — the
    ``num_parts`` default for per-host input sharding."""
    import jax
    try:
        return max(1, int(jax.process_count()))
    except Exception:
        return 1


def process_identity():
    """``(rank, world_size)`` in one call — the selector behind the
    telemetry exporter's ``rank-<r>/`` directory layout (telemetry/
    export.py): multi-process runs split their event logs, snapshots
    and traces per rank; single-process runs stay flat."""
    return rank(), world_size()


def _kv_client():
    """The jax coordination-service client (the process rendezvous that
    ``jax.distributed.initialize`` already established) — the host-level
    transport under the fallback collective and barrier."""
    from jax._src import distributed
    client = getattr(distributed.global_state, "client", None)
    if client is None:
        raise MXNetError(
            "host-level fallback collective needs jax.distributed to be "
            "initialized (no coordination-service client)")
    return client


def barrier():
    """Global barrier (reference: ps Barrier, kvstore_dist.h:108). Uses
    the XLA device barrier when the backend supports multi-process
    computations; otherwise the coordination-service barrier (CPU
    backend, degraded transport)."""
    import jax
    if jax.process_count() <= 1:
        return
    _barrier_seq[0] += 1
    seq = _barrier_seq[0]
    if not _fallback_active():
        try:
            from jax.experimental import multihost_utils
            multihost_utils.sync_global_devices(f"mxnet_tpu_barrier_{seq}")
            return
        except Exception as e:
            if not _collective_unsupported(e):
                raise
            _note_fallback(e)
    _, _, deadline = _ft_cfg()
    _retry(lambda: _kv_client().wait_at_barrier(
        f"mxtpu_b_{seq}", int(deadline * 1000)), "barrier")


_reduce_cache = {}
_mesh_cache = {}


def _global_mesh():
    import jax
    from jax.sharding import Mesh
    key = tuple(id(d) for d in jax.devices())
    mesh = _mesh_cache.get(key)
    if mesh is None:
        devs = np.array(jax.devices()).reshape(jax.process_count(), -1)
        mesh = Mesh(devs, ("proc", "local"))
        _mesh_cache.clear()          # device topology changes invalidate all
        _mesh_cache[key] = mesh
    return mesh


def _reduce_jit(mesh):
    """One compiled cross-process sum over a (procs, n) buffer — the
    collective rides DCN/ICI inside XLA, replacing a per-key host
    round-trip. One jit wrapper per mesh; jit's own cache re-specializes
    per input shape/dtype."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    key = id(mesh)
    fn = _reduce_cache.get(key)
    if fn is None:
        _reduce_cache.clear()
        fn = jax.jit(lambda x: x.sum(axis=0),
                     out_shardings=NamedSharding(mesh, P()))
        _reduce_cache[key] = fn
    return fn


def allreduce(array):
    """Sum an array across all processes (returns the global sum)."""
    arrays = allreduce_batch([array])
    return arrays[0]


def _collective_unsupported(e):
    """Does this error mean "the backend cannot run multi-process XLA
    computations" (CPU backend, partial-fabric degradation) — i.e. the
    host-level fallback applies — rather than a real program bug?"""
    from .. import faultinject
    if isinstance(e, faultinject.FaultInjected):
        return True
    msg = str(e)
    return ("Multiprocess computations aren't implemented" in msg
            or "not implemented on the CPU backend" in msg
            or "UNIMPLEMENTED" in msg)


def _note_fallback(e):
    if not _host_fallback[0]:
        _host_fallback[0] = True
        _fallback_world[0] = world_size()
        fault.count("dist.collective_fallbacks")
        warnings.warn(
            "backend cannot run multi-process collectives "
            f"({str(e).splitlines()[0][:120]}); degrading to the "
            "host-level allgather-sum over the jax coordination service "
            "— correct but slower (parallel/dist.py)")


def notify_world_changed():
    """Reset every piece of per-world collective state after an elastic
    mesh re-form (parallel/elastic.py): the global-mesh and
    reduce-program caches (keyed on the dead world's device set), the
    SPMD collective/barrier ordinals (a re-formed job starts its
    sequence from zero on every survivor, or ordinals would disagree
    across ranks that joined at different generations), the sticky
    host-transport fallback, and the init latch. Barrier re-entry
    during the re-form runs under the same
    ``MXTPU_FT_DIST_RETRIES/BACKOFF/DEADLINE`` policy as any other
    degraded transport — a survivor blocks at most ``deadline`` seconds
    for peers that never arrive, then fails with a diagnosable
    ``MXNetError`` instead of hanging the fleet."""
    _mesh_cache.clear()
    _reduce_cache.clear()
    _host_seq[0] = 0
    _barrier_seq[0] = 0
    _host_fallback[0] = False
    _fallback_world[0] = 0
    _initialized[0] = False


def allreduce_batch(arrays):
    """Sum a *list* of arrays across all processes with ONE device
    collective: everything is flattened into a single buffer, reduced as
    one XLA computation, and split back (reference analog: the server
    merging all keys of a push round, kvstore_dist_server.h:189 — but as a
    batched allreduce instead of per-key RPCs).

    When the backend can't run multi-process computations (the CPU
    backend; injected transport faults), the SAME semantics degrade to a
    host-level allgather-sum over the coordination-service KV store —
    the job keeps training instead of hard-failing (sticky per process;
    every process hits the identical backend limitation at the same
    SPMD call, so the fleet degrades together).
    """
    import jax
    import jax.numpy as jnp
    if jax.process_count() == 1:
        return list(arrays)

    arrays = [jnp.asarray(a) for a in arrays]
    shapes = [a.shape for a in arrays]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    dtype = jnp.result_type(*arrays) if arrays else jnp.float32
    flat = jnp.concatenate([a.astype(dtype).ravel() for a in arrays]) \
        if arrays else jnp.zeros((0,), dtype)

    if not _fallback_active():
        try:
            summed = _allreduce_device(flat)
        except Exception as e:
            if not _collective_unsupported(e):
                raise
            _note_fallback(e)
    if _host_fallback[0]:
        summed = _allreduce_host_flat(np.asarray(flat))
    out, pos = [], 0
    for a, shape, size in zip(arrays, shapes, sizes):
        out.append(jnp.asarray(summed[pos:pos + size]).reshape(shape)
                   .astype(a.dtype))
        pos += size
    return out


def _allreduce_device(flat):
    """The XLA cross-process sum (one compiled collective over DCN/ICI)."""
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P
    from .. import faultinject
    if faultinject.fire("dist_drop"):
        raise faultinject.FaultInjected("dist_drop")
    mesh = _global_mesh()
    global_buf = multihost_utils.host_local_array_to_global_array(
        flat[None], mesh, P("proc"))
    summed = _reduce_jit(mesh)(global_buf)
    return multihost_utils.global_array_to_host_local_array(
        summed, mesh, P())


def _allreduce_host_flat(flat):
    """Host-level allgather-sum of one flat numpy buffer through the
    coordination-service KV store: publish local bytes, barrier, fetch
    every rank's buffer, sum, barrier, clean up own key. O(n·procs)
    traffic through the coordinator — the degraded-mode transport, not
    the fast path."""
    import jax
    from .. import faultinject
    # the same transport fault site as _allreduce_device: on backends
    # already in host fallback (CPU), ``dist_drop:call=K:action=kill``
    # is the kill-rank-mid-collective drill the elastic supervisor
    # recovers from (parallel/elastic.py); a plain raise here is a
    # hard transport error — there is no further fallback below this
    if faultinject.fire("dist_drop"):
        raise faultinject.FaultInjected("dist_drop")
    client = _kv_client()
    _, _, deadline = _ft_cfg()
    tmo = int(deadline * 1000)
    rank = jax.process_index()
    nproc = jax.process_count()
    _host_seq[0] += 1
    seq = _host_seq[0]
    base = f"mxtpu_ar/{seq}"
    client.key_value_set_bytes(f"{base}/{rank}",
                               np.ascontiguousarray(flat).tobytes())
    client.wait_at_barrier(f"{base}/ready", tmo)
    total = np.zeros_like(flat)
    for r in range(nproc):
        raw = client.blocking_key_value_get_bytes(f"{base}/{r}", tmo)
        total += np.frombuffer(raw, flat.dtype).reshape(flat.shape)
    # every rank must have READ all buffers before anyone deletes
    client.wait_at_barrier(f"{base}/done", tmo)
    try:
        client.key_value_delete(f"{base}/{rank}")
    except Exception:
        pass  # cleanup is best-effort; keys are seq-namespaced
    fault.count("dist.host_collectives")
    return total


class DistKVStore(KVStore):
    """dist_sync / dist_device_sync / dist_async kvstore types.

    Push sums gradients across every process (the reference's server-side
    merge across NumWorkers() pushes, kvstore_dist_server.h:189); pull
    returns the merged value or the optimizer-updated weight.
    """

    def __init__(self, kv_type="dist_sync"):
        super().__init__(kv_type)
        init()
        if kv_type == "dist_async":
            import warnings
            warnings.warn(
                "dist_async is emulated as synchronous data parallelism on "
                "TPU (XLA collectives are cooperative); convergence "
                "semantics match dist_sync")

    @property
    def is_distributed(self):
        return True

    def push(self, key, value, priority=0):
        keys, values = [key], [value]
        if isinstance(key, (list, tuple)):
            keys, values = list(key), list(value)
        local = []
        for k, v in zip(keys, values):
            vals = v if isinstance(v, (list, tuple)) else [v]
            agg = vals[0]
            for extra in vals[1:]:
                agg = agg + extra
            # row sets differ per process: densify sparse grads for the
            # uniform-shape collective (the reference instead re-encodes
            # row keys per server, kvstore_dist.h EncodeRowSparseKey)
            if getattr(agg, "stype", "default") != "default":
                agg = agg.todense()
            # worker-side 2-bit quantize with error feedback before the
            # wire (reference: kvstore_dist.h:343-353)
            agg = self._apply_compression(k, agg)
            local.append((k, agg))
        # one batched cross-process reduction for the whole push round
        # (≙ server merge across NumWorkers() pushes)
        reduced = allreduce_batch([a._data for _, a in local])
        for (k, _), rdata in zip(local, reduced):
            agg = _wrap(rdata)
            if self._updater is not None:
                if k not in self._data:
                    raise ValueError(f"key {k} not initialized")
                self._updater(_key_int(k), agg, self._data[k])
            else:
                self._merged = getattr(self, "_merged", {})
                self._merged[k] = agg


def _key_int(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k
