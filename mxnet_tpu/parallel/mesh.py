"""Device mesh management.

The mesh is the TPU-native "cluster": axes name parallelism dimensions
(data/model/pipeline/seq/expert). The reference's notion of "device group"
(ctx lists in Module, kvstore device lists) maps to mesh axes.
"""
from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "current_mesh", "set_default_mesh", "replicated",
           "batch_sharded", "P", "NamedSharding"]

_default_mesh = [None]


def make_mesh(axes: Optional[dict] = None, devices=None) -> Mesh:
    """Create a Mesh from {axis_name: size}.

    ``make_mesh({'data': 8})`` or ``make_mesh({'data': 4, 'model': 2})``.
    Sizes may use -1 once to absorb the remaining devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    if not axes:
        axes = {"data": len(devices)}
    names = list(axes.keys())
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    total = int(np.prod(sizes))
    if total > len(devices):
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                         f"devices, only {len(devices)} available")
    arr = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(arr, names)


def set_default_mesh(mesh: Optional[Mesh]):
    _default_mesh[0] = mesh


def current_mesh() -> Optional[Mesh]:
    return _default_mesh[0]


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def batch_sharded(mesh: Mesh, axis: str = "data", ndim: int = 2,
                  batch_dim: int = 0) -> NamedSharding:
    spec = [None] * ndim
    spec[batch_dim] = axis
    return NamedSharding(mesh, P(*spec))
