"""Parallelism layer: device meshes, sharded training steps, collectives.

This package is the TPU-native replacement for the reference's entire
distribution stack (SURVEY.md §2.2): KVStore comm strategies
(src/kvstore/comm.h), NCCL (kvstore_nccl.h), the ps-lite parameter server
(kvstore_dist.h), and the engine's copy threads all collapse into XLA
collectives over a ``jax.sharding.Mesh``:

- data parallelism   → batch sharded on the 'data' mesh axis; gradient psum
  inserted by GSPMD (≙ kvstore push/pull + NCCL allreduce)
- tensor parallelism → parameters sharded on 'model' (exceeds reference)
- optimizer sharding → optimizer state sharded on 'data' (ZeRO-style; ≙ the
  parameter server holding the optimizer, kvstore_dist_server.h:187)
- multi-host        → jax.distributed + the same mesh spanning hosts
"""
from .mesh import make_mesh, current_mesh, set_default_mesh
from .step import TrainStep
from .ring import ring_attention, sequence_shard

__all__ = ["make_mesh", "current_mesh", "set_default_mesh", "TrainStep",
           "ring_attention", "sequence_shard"]
