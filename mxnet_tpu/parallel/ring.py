"""Ring attention: sequence/context parallelism over the device mesh.

This capability EXCEEDS the reference — MXNet ~1.1 has no attention op at
all and no sequence parallelism (SURVEY.md §5 long-context: its tools were
BucketingModule, sequence ops, and a fused RNN). On TPU, long sequences
shard over a mesh axis and attention walks the ring:

- each device holds a sequence block of Q, K, V;
- at every step it computes blockwise attention of its Q against the
  K/V block currently resident, accumulating with the numerically stable
  running-max/denominator recurrence (flash-attention style), then
  rotates K/V one hop around the ring with ``lax.ppermute`` — the
  collective rides ICI neighbor links, never gathering the full sequence
  on any chip;
- total memory per chip stays O(T/P), enabling contexts P× longer.

Public surface: ``ring_attention`` (shard_map'd full attention) and
``sequence_shard``/mesh helpers. Causal masking is computed from global
block offsets, and fully masked blocks are skipped numerically (their
contribution multiplies in as exp(-inf) = 0).
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["ring_attention", "local_attention_block", "sequence_shard"]

_NEG = -1e30


def local_attention_block(q, k, v, bias=None, scale=None):
    """Dense softmax attention for one (q-block, kv-block) pair.

    q: (B, Tq, H, D); k/v: (B, Tk, H, D). Returns (out, row_max, row_sum)
    for the stable-accumulation recurrence."""
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / (d ** 0.5)
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1)                          # (B, H, Tq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)                          # (B, H, Tq)
    o = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return o, m, l


def _ring_attention_sharded(q, k, v, axis_name, causal, scale):
    """Per-device body under shard_map: q/k/v are local sequence blocks
    (B, T_local, H, D)."""
    p_size = jax.lax.psum(1, axis_name)
    my = jax.lax.axis_index(axis_name)
    B, T, H, D = q.shape

    q_pos = my * T + jnp.arange(T)                   # global q positions

    def step(carry, i):
        k_blk, v_blk, o, m, l = carry
        # the block resident at step i originated on rank (my + i) % P
        src = (my + i) % p_size
        if causal:
            k_pos = src * T + jnp.arange(T)
            bias = jnp.where(q_pos[:, None] >= k_pos[None, :], 0.0, _NEG)
            bias = bias[None, None, :, :]            # (1, 1, Tq, Tk)
        else:
            bias = None
        o_i, m_i, l_i = local_attention_block(q, k_blk, v_blk, bias, scale)
        # stable accumulation (flash recurrence)
        m_new = jnp.maximum(m, m_i)
        alpha = jnp.exp(m - m_new)
        beta = jnp.exp(m_i - m_new)
        l_new = l * alpha + l_i * beta
        o_new = o * alpha.transpose(0, 2, 1)[..., None] \
            + o_i * beta.transpose(0, 2, 1)[..., None]
        # rotate K/V one hop: rank r sends to r-1 (so blocks advance +1)
        perm = [(r, (r - 1) % p_size) for r in range(p_size)]
        k_blk = jax.lax.ppermute(k_blk, axis_name, perm)
        v_blk = jax.lax.ppermute(v_blk, axis_name, perm)
        return (k_blk, v_blk, o_new, m_new, l_new), None

    o0 = jnp.zeros_like(q, jnp.float32)
    # derive the running stats from q so they carry exactly q's varying
    # manual axes (required for the scan carry to type-check under
    # shard_map, whatever combination of mesh axes is in use)
    zero_bht = q.astype(jnp.float32).sum(-1).transpose(0, 2, 1) * 0.0
    m0 = zero_bht + _NEG
    l0 = zero_bht
    (k_f, v_f, o, m, l), _ = jax.lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(p_size))
    out = o / jnp.maximum(l, 1e-30).transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(query, key, value, mesh: Mesh, seq_axis: str = "sp",
                   batch_axis: Optional[str] = None, causal: bool = False,
                   scale: Optional[float] = None):
    """Multi-head attention with the sequence axis sharded over ``seq_axis``.

    query/key/value: (B, T, H, D) arrays (global view). T must divide the
    size of ``seq_axis``. The result equals dense softmax attention to
    numerical accuracy while no device ever holds more than T/P of the
    sequence.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    qspec = P(batch_axis, seq_axis, None, None)
    body = functools.partial(_ring_attention_sharded, axis_name=seq_axis,
                             causal=causal, scale=scale)
    fn = shard_map(body, mesh=mesh, in_specs=(qspec, qspec, qspec),
                   out_specs=qspec)
    with mesh:
        return fn(jnp.asarray(query), jnp.asarray(key), jnp.asarray(value))


def sequence_shard(array, mesh: Mesh, seq_axis: str = "sp", axis: int = 1,
                   batch_axis: Optional[str] = None):
    """Place an array with its sequence dimension sharded over the mesh."""
    spec = [None] * array.ndim
    spec[axis] = seq_axis
    if batch_axis is not None:
        spec[0] = batch_axis
    return jax.device_put(jnp.asarray(array), NamedSharding(mesh, P(*spec)))
