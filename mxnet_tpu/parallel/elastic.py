"""Elastic training recovery: survive worker loss, re-form, continue.

The fault-tolerance layer up to round 16 could survive every failure
EXCEPT losing a worker process: checkpoints are atomic (checkpoint.py),
collectives retry under a deadline (dist.py ``_retry``), but a
SIGKILLed rank left the survivors blocked in a barrier until the
``MXTPU_FT_DIST_DEADLINE`` expired and then the whole job died — the
documented "no elastic rejoin" gap in docs/faq/failure_recovery.md.
This module closes it, with the same health model the serving
FleetRouter (serving/fleet.py) uses for replicas:

- **detection** — every rank renews a heartbeat *lease* in the jax
  coordination-service KV store (:class:`HeartbeatLease`, renewed every
  ``MXTPU_FLEET_HEARTBEAT_S``, stale after ``MXTPU_FLEET_LEASE_S``).
  Survivors notice a lost peer from its stale lease — usually BEFORE
  the next collective would block on it — and raise
  :class:`WorldChanged` at a batch boundary (:class:`ElasticGuard`).
  A collective that does block on the dead rank fails within the
  ``MXTPU_FT_DIST_*`` deadline; the guard classifies that failure the
  same way. The ``heartbeat_miss`` fault site drills detection without
  an actual kill (suppressed renewals → peers see a stale lease).
- **re-form** — jax pins the process count at ``distributed.initialize``
  time, so the mesh cannot shrink in place: a survivor exits with
  :data:`REFORM_EXIT` (75) and the :class:`ElasticSupervisor` relaunches
  the survivors as a NEW generation at the new world size, on a fresh
  coordinator port (``dist.notify_world_changed()`` covers the
  in-process state for single-process tests and future in-place
  backends).
- **recovery** — the relaunched generation restores params + optimizer
  state from the newest checkpoint (rank 0 writes them via
  :class:`ElasticCheckpointManager`, which stamps ``world``/``rank``/
  ``generation`` into the checkpoint's ``extra``); data shards are
  recomputed from ``(rank, world)``. Same world size → the r9 data
  cursor restores too and resume is **bit-exact**; changed world → the
  cursor (recorded under the dead world's sharding) is discarded with a
  warning and the epoch re-shards from its start
  (:func:`prepare_resume`).
- **rejoin** — a later generation launched at a larger world is just
  another re-form; the rejoining rank AOT-loads its programs from the
  shared persistent compile cache (``MXTPU_COMPILE_CACHE_DIR``) and
  catches up without a single fresh XLA compile.

Scope: :class:`ElasticSupervisor` relaunches on ONE host (the
multi-process drill topology); rank 0 doubles as coordinator host, so
its loss takes the coordination service with it — a cluster
scheduler's restart policy owns that case (documented in
failure_recovery.md).

Round 20 adds the MULTI-HOST half of the contract:
:class:`SupervisorSpec` pins down, as files under a shared workdir,
exactly what a per-host supervisor must agree on with its peers —
generation counter, world size, coordinator address, and a per-host
rank file — and :class:`HostSupervisor` is the per-host agent that
speaks it: host 0 computes membership from the alive leases and
publishes ``control.json`` per generation, every host launches only
its own ranks with the handshake env
(:meth:`SupervisorSpec.handshake_env`), and a WHOLE-host loss (its
alive lease goes stale, its exit codes never land) shrinks the next
generation just like a single lost rank does. Workers machine-check
the handshake with :meth:`SupervisorSpec.check_env` — a worker whose
env disagrees with its host's published rank file fails fast with the
mismatch named, instead of joining the wrong mesh and corrupting a
collective. The 2-host drill (tests/test_autoscale.py) SIGKILLs one
whole "host" (a subprocess tree) mid-generation and pins the re-form.
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import warnings

from ..base import MXNetError
from ..checkpoint import CheckpointManager

__all__ = ["REFORM_EXIT", "WorldChanged", "HeartbeatLease",
           "ElasticGuard", "ElasticCheckpointManager", "prepare_resume",
           "ElasticSupervisor", "SupervisorSpec", "HostSupervisor",
           "generation_from_env", "exit_for_reform"]

# exit code a survivor uses to ask its supervisor for a mesh re-form
# (chosen clear of shell/signal codes: 0=done, 1=error, 128+N=signal)
REFORM_EXIT = 75


def exit_for_reform():
    """Exit this worker with :data:`REFORM_EXIT` — via ``os._exit``, NOT
    ``sys.exit``. A plain exit runs the interpreter's atexit hooks,
    and jax.distributed registers a shutdown barrier there: with a dead
    peer that barrier blocks for the full coordination-service timeout
    (minutes) and then SIGABRTs the process, so the supervisor would see
    a crash instead of a re-form request. Streams are flushed first."""
    try:
        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:        # noqa: BLE001 - nothing useful to do mid-exit
        pass
    os._exit(REFORM_EXIT)


class WorldChanged(MXNetError):
    """A peer's heartbeat lease went stale (or a collective failed on a
    dead rank): the world this process initialized with no longer
    exists. Raised at a batch boundary so the training loop can exit
    cleanly with :data:`REFORM_EXIT`."""

    def __init__(self, lost, world):
        super().__init__(
            f"elastic: lost rank(s) {sorted(lost)} of world {world}; "
            "mesh re-form required")
        self.lost = sorted(lost)
        self.world = world


def _cfg():
    from .. import config
    return (float(config.get("MXTPU_FLEET_HEARTBEAT_S")),
            float(config.get("MXTPU_FLEET_LEASE_S")))


def _hb_key(generation, rank):
    return f"mxtpu_el/g{generation}/hb/{rank}"


class HeartbeatLease:
    """Renew this rank's liveness lease and watch every peer's.

    One daemon thread per process: each tick it (1) re-publishes its
    own key (``mxtpu_el/g<gen>/hb/<rank>`` → a wall-clock timestamp)
    unless the ``heartbeat_miss`` fault site eats the renewal, and (2)
    reads every peer's key, marking a peer lost once its timestamp is
    older than the lease TTL (``MXTPU_FLEET_LEASE_S``) or the key has
    repeatedly failed to materialize. Lost peers are sticky — a rank
    that died stays dead for this generation; the re-formed generation
    starts a fresh key namespace.

    Timestamps compare across processes on the same host (the supervisor
    topology); cross-host deployment assumes clocks synchronized well
    within the lease TTL (NTP is orders of magnitude tighter).
    """

    def __init__(self, rank=None, world=None, generation=0,
                 heartbeat_s=None, lease_s=None):
        from . import dist
        self.rank = dist.rank() if rank is None else int(rank)
        self.world = dist.world_size() if world is None else int(world)
        self.generation = int(generation)
        hb, lease = _cfg()
        self.heartbeat_s = float(heartbeat_s or hb)
        self.lease_s = float(lease_s or lease)
        self._client = dist._kv_client()
        self._lost = set()
        self._strikes = {}     # peer rank -> consecutive failed reads
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.renewals = 0
        self.missed = 0

    # -- lease publishing ------------------------------------------------------
    def _publish(self):
        from .. import faultinject
        if faultinject.fire("heartbeat_miss", rank=self.rank):
            self.missed += 1
            return
        key = _hb_key(self.generation, self.rank)
        val = f"{time.time():.6f}".encode()
        try:
            self._client.key_value_set_bytes(key, val,
                                             allow_overwrite=True)
        except TypeError:      # older client: no allow_overwrite kwarg
            try:
                self._client.key_value_delete(key)
            except Exception:                  # noqa: BLE001
                pass
            self._client.key_value_set_bytes(key, val)
        self.renewals += 1

    def _check_peer(self, peer):
        try:
            raw = self._client.blocking_key_value_get_bytes(
                _hb_key(self.generation, peer),
                max(50, int(self.heartbeat_s * 1000)))
        except Exception:                      # noqa: BLE001
            # key absent within the wait: strike (a peer that never
            # published within a full lease worth of ticks is lost too)
            self._strikes[peer] = self._strikes.get(peer, 0) + 1
            return self._strikes[peer] * self.heartbeat_s >= \
                self.lease_s
        self._strikes[peer] = 0
        try:
            age = time.time() - float(raw.decode())
        except ValueError:
            return False
        return age > self.lease_s

    def _loop(self):
        while not self._stop.is_set():
            try:
                self._publish()
                for peer in range(self.world):
                    if peer == self.rank:
                        continue
                    with self._lock:
                        if peer in self._lost:
                            continue
                    if self._check_peer(peer):
                        with self._lock:
                            self._lost.add(peer)
            except Exception:                  # noqa: BLE001
                pass   # transport hiccups must not kill the monitor
            self._stop.wait(self.heartbeat_s)

    # -- lifecycle -------------------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._publish()        # lease exists before any peer checks it
        self._thread = threading.Thread(
            target=self._loop, name=f"hb-lease-r{self.rank}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.heartbeat_s * 4 + 2)
            self._thread = None
        try:
            self._client.key_value_delete(
                _hb_key(self.generation, self.rank))
        except Exception:                      # noqa: BLE001
            pass

    def lost_peers(self):
        with self._lock:
            return sorted(self._lost)


class ElasticGuard:
    """Training-loop wrapper that turns peer loss into a clean
    :class:`WorldChanged` at a batch boundary::

        with elastic.ElasticGuard(generation=gen) as guard:
            try:
                mod.fit(..., batch_end_callback=guard.batch_end_callback)
            except Exception as e:
                if guard.should_reform(e):
                    elastic.exit_for_reform()
                raise

    ``batch_end_callback`` raises as soon as the lease monitor flags a
    peer; a collective that failed FIRST (it blocked on the dead rank
    until the ``MXTPU_FT_DIST_DEADLINE``) reaches ``should_reform``,
    which re-checks the leases to distinguish "peer died" (re-form)
    from a genuine program error (re-raise). Single-process worlds need
    no lease and never re-form."""

    def __init__(self, generation=0, lease=None):
        from . import dist
        self.world = dist.world_size()
        self.generation = int(generation)
        self._lease = lease
        if self._lease is None and self.world > 1:
            self._lease = HeartbeatLease(generation=generation)

    def __enter__(self):
        if self._lease is not None:
            self._lease.start()
        return self

    def __exit__(self, *exc):
        if self._lease is not None:
            self._lease.stop()
        return False

    def lost_peers(self):
        return self._lease.lost_peers() if self._lease else []

    def batch_end_callback(self, param=None):
        lost = self.lost_peers()
        if lost:
            raise WorldChanged(lost, self.world)

    def should_reform(self, error):
        """Did ``error`` mean "the world changed"? True for
        :class:`WorldChanged` itself and for any failure observed while
        a peer's lease is stale (the collective found out the hard
        way). Waits one extra heartbeat before deciding: the collective
        deadline usually fires before the lease does."""
        if isinstance(error, WorldChanged):
            return True
        if self._lease is None:
            return False
        if not self.lost_peers():
            time.sleep(self._lease.lease_s)
        return bool(self.lost_peers())


class ElasticCheckpointManager(CheckpointManager):
    """CheckpointManager that stamps the elastic identity —
    ``{"world", "rank", "generation"}`` — into every checkpoint's
    ``extra`` (the fit loop's epoch-end save passes no ``extra`` of its
    own, so the stamp must live in the manager). ``prepare_resume``
    reads it back to decide between bit-exact cursor restore and an
    epoch-granularity re-shard."""

    def __init__(self, directory, world=None, rank=None, generation=0,
                 **kw):
        super().__init__(directory, **kw)
        from . import dist
        self.world = dist.world_size() if world is None else int(world)
        self.rank = dist.rank() if rank is None else int(rank)
        self.generation = int(generation)

    def save_module(self, module, epoch, nbatch=0, eval_metric=None,
                    extra=None, data_state=None):
        extra = dict(extra or {})
        extra["elastic"] = {"world": self.world, "rank": self.rank,
                            "generation": self.generation}
        return super().save_module(module, epoch, nbatch=nbatch,
                                   eval_metric=eval_metric, extra=extra,
                                   data_state=data_state)


def _check_reshard(module, old_world, world):
    """Fail-fast half of the changed-world re-shard: checkpointed
    params/optimizer state are *logical* (gathered) arrays, and the
    re-formed generation's bind already recorded partition specs for
    the NEW mesh — ``fit``'s auto-resume loads the checkpoint THROUGH
    those specs (module/fused.py ``load_params``/``set_states`` re-
    ``device_put`` every array, and the ZeRO-1 flat pack re-pads to the
    new replica count). The one thing that can still go wrong is a
    partition rule that divided at the old world but not the new one —
    caught here with the parameter's name, instead of as a GSPMD shape
    complaint deep inside the first post-resume compile."""
    fused = getattr(module, "_fused", None) or module
    mesh = getattr(fused, "mesh", None)
    rules = getattr(fused, "partition_rules", None)
    from ..telemetry import registry as _treg
    _treg.counter("elastic::reshard").inc()
    if mesh is None or not rules:
        return
    try:
        arg_params, _ = module.get_params()
    except Exception:                          # noqa: BLE001
        return   # params not initialized yet; bind will validate
    from . import partition as _partition
    shapes = {n: tuple(v.shape) for n, v in arg_params.items()}
    specs = _partition.match_partition_rules(rules, shapes, strict=False)
    _partition.validate_specs(mesh, specs, shapes)


def prepare_resume(manager, train_data, world=None, rank=None,
                   module=None):
    """Pre-``fit`` resume policy for an elastic generation: load the
    newest checkpoint's elastic stamp and decide what the data iterator
    may restore.

    Same world size as the checkpoint → nothing to do: ``fit``'s
    auto-resume restores params, optimizer state AND the r9 data cursor
    — the relaunched generation replays the exact surviving schedule
    (bit-exact resume, pinned by the chaos drill).

    Different world size → the saved cursor describes the DEAD world's
    ``(rank, world)`` sharding; restoring it would skip or double-read
    rows. The cursor restore is disabled (``train_data.set_state`` is
    shadowed with ``None`` on the *instance* — ``fit`` checks
    ``callable(...)`` and skips silently) and the epoch re-shards from
    its start under the new world, which is the correct
    epoch-granularity recovery. Mesh-partitioned state re-shards
    automatically: the checkpoint holds logical (gathered) arrays and
    the new generation's bind loads them through the partition specs it
    recorded for its OWN mesh — including the ZeRO-1 optimizer shards,
    which re-pad and re-split at the new replica count
    (module/fused.py). Pass ``module`` (bound at the new world) to also
    validate up front that every partition rule still divides at the
    re-formed mesh, with the parameter's name in the error.

    Returns the :class:`~mxnet_tpu.checkpoint.CheckpointState` (or None
    when there is nothing to resume from)."""
    from . import dist
    world = dist.world_size() if world is None else int(world)
    rank = dist.rank() if rank is None else int(rank)
    state = manager.load_latest()
    if state is None:
        return None
    stamp = (state.extra or {}).get("elastic") or {}
    old_world = stamp.get("world")
    if old_world is not None and int(old_world) != world:
        warnings.warn(
            f"elastic resume: checkpoint '{state.path}' was written at "
            f"world={old_world}, resuming at world={world} — data "
            "cursor discarded, epoch re-shards from its start "
            f"(rank {rank}/{world})")
        try:
            train_data.set_state = None
        except Exception:                      # noqa: BLE001
            pass
        if module is not None:
            _check_reshard(module, int(old_world), world)
    return state


class ElasticSupervisor:
    """Single-host supervisor: launch one worker process per rank, and
    when ranks die (SIGKILL, ``dist_drop:action=kill``) or ask for a
    re-form (:data:`REFORM_EXIT`), relaunch the survivors as the next
    generation at the shrunken world size — each generation on a fresh
    coordinator port with a fresh heartbeat namespace. A ``rejoin``
    schedule grows a later generation back (the recovered host): the
    relaunch is identical, only the world is larger.

    ``argv_fn(rank, world, generation, coordinator)`` builds one
    worker's command line; the supervisor additionally exports
    ``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES`` / ``PROCESS_ID`` /
    ``MXTPU_ELASTIC_GENERATION`` into its environment, so a worker can
    use either surface."""

    def __init__(self, argv_fn, world, min_world=1, max_generations=6,
                 env=None, timeout_s=240, port_fn=None, logger=None,
                 fault=None, fault_rank=0, fault_generation=0):
        self.argv_fn = argv_fn
        self.world = int(world)
        self.min_world = int(min_world)
        self.max_generations = int(max_generations)
        self.env = dict(env) if env else dict(os.environ)
        self.timeout_s = float(timeout_s)
        self._port_fn = port_fn or self._free_port
        # arm a MXTPU_FAULT_INJECT spec on exactly ONE (rank, generation)
        # — the drill victim; every other worker runs clean
        self.fault = fault
        self.fault_rank = int(fault_rank)
        self.fault_generation = int(fault_generation)
        import logging
        self.logger = logger or logging.getLogger("mxnet_tpu.elastic")
        self.history = []    # one record per generation

    @staticmethod
    def _free_port():
        import socket
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    def _launch(self, rank, world, generation, coordinator):
        env = dict(self.env)
        env.pop("MXTPU_FAULT_INJECT", None)
        if self.fault and rank == self.fault_rank and \
                generation == self.fault_generation:
            env["MXTPU_FAULT_INJECT"] = self.fault
        env["COORDINATOR_ADDRESS"] = coordinator
        env["NUM_PROCESSES"] = str(world)
        env["PROCESS_ID"] = str(rank)
        env["MXTPU_ELASTIC_GENERATION"] = str(generation)
        argv = self.argv_fn(rank, world, generation, coordinator)
        return subprocess.Popen(argv, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT)

    def run(self, rejoin=None):
        """Drive generations until a generation where EVERY rank exits
        0 (training finished) or limits are hit. ``rejoin`` maps
        ``generation -> world size`` overrides (e.g. ``{2: 3}``: the
        third generation launches 3 ranks regardless of survivor
        count). Returns ``self.history`` — per generation: world, exit
        codes, lost ranks, outcome."""
        rejoin = dict(rejoin or {})
        world = self.world
        for gen in range(self.max_generations):
            world = int(rejoin.get(gen, world))
            if world < self.min_world:
                raise MXNetError(
                    f"elastic: world shrank to {world} < min_world="
                    f"{self.min_world} at generation {gen}")
            coordinator = f"127.0.0.1:{self._port_fn()}"
            self.logger.info("elastic gen %d: launching world=%d (%s)",
                             gen, world, coordinator)
            procs = [self._launch(r, world, gen, coordinator)
                     for r in range(world)]
            codes, logs = [], []
            deadline = time.monotonic() + self.timeout_s
            for p in procs:
                try:
                    out, _ = p.communicate(
                        timeout=max(1.0, deadline - time.monotonic()))
                except subprocess.TimeoutExpired:
                    p.kill()
                    out, _ = p.communicate()
                codes.append(p.returncode)
                logs.append((out or b"").decode(errors="replace"))
            lost = [r for r, c in enumerate(codes)
                    if c not in (0, REFORM_EXIT)]
            record = {"generation": gen, "world": world,
                      "coordinator": coordinator, "codes": codes,
                      "lost": lost, "logs": logs}
            self.history.append(record)
            if all(c == 0 for c in codes):
                record["outcome"] = "done"
                return self.history
            if not any(c == REFORM_EXIT for c in codes) and not lost \
                    and (gen + 1) not in rejoin:
                record["outcome"] = "failed"
                raise MXNetError(
                    f"elastic gen {gen}: workers failed without "
                    f"requesting re-form (codes={codes});\n"
                    + "\n".join(logs))
            record["outcome"] = "reform"
            world = world - len(lost)
        raise MXNetError(
            f"elastic: no generation finished within "
            f"{self.max_generations} re-forms")


def generation_from_env(default=0):
    """The generation stamp the supervisor exported for this worker."""
    try:
        return int(os.environ.get("MXTPU_ELASTIC_GENERATION", default))
    except ValueError:
        return int(default)


# -- multi-host supervisor contract (round 20) --------------------------------

class SupervisorSpec:
    """The machine-checked contract between per-host supervisors and
    their workers, pinned down as files under ``<workdir>/supervisor``:

    - ``control.json`` — host 0 publishes it once per generation
      (atomic tmp+rename): ``{"generation", "world", "coordinator",
      "ranks": {host_id: [ranks]}, "status": run|done|failed}``,
    - ``host<id>.alive`` — each host's liveness lease, re-touched every
      ``lease_s / 3``; a lease older than ``3 x lease_s`` means the
      WHOLE host (and every rank on it) is lost,
    - ``g<gen>/host<id>.ranks.json`` — the per-host rank file: exactly
      the ranks this host launched this generation. Workers validate
      their env against it via :meth:`check_env`,
    - ``g<gen>/host<id>.codes.json`` — the host's exit codes, how
      host 0 gathers a generation's outcome.

    The handshake env a worker receives is :meth:`handshake_env`: the
    four single-host vars (``COORDINATOR_ADDRESS`` / ``NUM_PROCESSES``
    / ``PROCESS_ID`` / ``MXTPU_ELASTIC_GENERATION``) plus
    ``MXTPU_SUPERVISOR_DIR`` and ``MXTPU_SUPERVISOR_HOST`` so
    :meth:`check_env` can find the contract and the worker's host."""

    def __init__(self, workdir, hosts=2, procs_per_host=1,
                 lease_s=None):
        from .. import config
        self.workdir = str(workdir)
        self.hosts = int(hosts)
        self.procs_per_host = int(procs_per_host)
        self.lease_s = float(
            lease_s if lease_s is not None
            else config.get("MXTPU_FLEET_LEASE_S", 10.0))
        self.root = os.path.join(self.workdir, "supervisor")
        os.makedirs(self.root, exist_ok=True)

    # -- paths -----------------------------------------------------------------
    @property
    def control_path(self):
        return os.path.join(self.root, "control.json")

    def alive_path(self, host_id):
        return os.path.join(self.root, f"host{host_id}.alive")

    def gen_dir(self, generation):
        return os.path.join(self.root, f"g{generation}")

    def ranks_path(self, generation, host_id):
        return os.path.join(self.gen_dir(generation),
                            f"host{host_id}.ranks.json")

    def codes_path(self, generation, host_id):
        return os.path.join(self.gen_dir(generation),
                            f"host{host_id}.codes.json")

    # -- contract I/O ----------------------------------------------------------
    @staticmethod
    def _write_json(path, obj):
        import json
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(obj, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)

    @staticmethod
    def _read_json(path):
        import json
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def write_control(self, control):
        self._write_json(self.control_path, control)

    def read_control(self):
        return self._read_json(self.control_path)

    def touch_alive(self, host_id):
        path = self.alive_path(host_id)
        with open(path, "a"):
            os.utime(path, None)

    def host_alive(self, host_id):
        """Fresh-enough alive lease? Stale past ``3 x lease_s`` (or
        never touched) means the whole host is lost."""
        try:
            age = time.time() - os.path.getmtime(self.alive_path(host_id))
        except OSError:
            return False
        return age <= 3.0 * self.lease_s

    def write_ranks(self, generation, host_id, ranks, world,
                    coordinator):
        self._write_json(self.ranks_path(generation, host_id),
                         {"generation": int(generation),
                          "world": int(world),
                          "coordinator": coordinator,
                          "ranks": [int(r) for r in ranks]})

    def write_codes(self, generation, host_id, codes):
        self._write_json(self.codes_path(generation, host_id),
                         {"codes": [int(c) for c in codes]})

    def read_codes(self, generation, host_id):
        obj = self._read_json(self.codes_path(generation, host_id))
        return None if obj is None else obj.get("codes")

    # -- worker handshake ------------------------------------------------------
    def handshake_env(self, rank, world, generation, coordinator,
                      host_id):
        return {
            "COORDINATOR_ADDRESS": coordinator,
            "NUM_PROCESSES": str(int(world)),
            "PROCESS_ID": str(int(rank)),
            "MXTPU_ELASTIC_GENERATION": str(int(generation)),
            "MXTPU_SUPERVISOR_DIR": self.workdir,
            "MXTPU_SUPERVISOR_HOST": str(int(host_id)),
        }

    @staticmethod
    def check_env(environ=None):
        """Worker-side machine check of the supervisor handshake.

        No-op (returns None) when not running under a
        :class:`HostSupervisor` (``MXTPU_SUPERVISOR_DIR`` unset).
        Otherwise validates this worker's env against its host's
        published rank file — generation, world size, coordinator, and
        rank membership must all agree — and raises :class:`MXNetError`
        naming the first mismatch. Returns the validated identity dict
        ``{"rank", "world", "generation", "host", "coordinator"}``."""
        environ = os.environ if environ is None else environ
        workdir = environ.get("MXTPU_SUPERVISOR_DIR")
        if not workdir:
            return None
        spec = SupervisorSpec(workdir)
        host = int(environ.get("MXTPU_SUPERVISOR_HOST", -1))
        rank = int(environ.get("PROCESS_ID", -1))
        world = int(environ.get("NUM_PROCESSES", -1))
        gen = int(environ.get("MXTPU_ELASTIC_GENERATION", -1))
        coord = environ.get("COORDINATOR_ADDRESS")
        rec = spec._read_json(spec.ranks_path(gen, host))
        if rec is None:
            raise MXNetError(
                f"supervisor handshake: no rank file for host {host} "
                f"generation {gen} under {spec.root}")
        for field, got, want in (
                ("generation", gen, rec.get("generation")),
                ("world", world, rec.get("world")),
                ("coordinator", coord, rec.get("coordinator"))):
            if got != want:
                raise MXNetError(
                    f"supervisor handshake mismatch: env {field}={got!r}"
                    f" but host {host}'s rank file says {want!r}")
        if rank not in rec.get("ranks", []):
            raise MXNetError(
                f"supervisor handshake mismatch: rank {rank} not in "
                f"host {host}'s rank file {rec.get('ranks')} for "
                f"generation {gen}")
        return {"rank": rank, "world": world, "generation": gen,
                "host": host, "coordinator": coord}


class HostSupervisor:
    """Per-host agent of the :class:`SupervisorSpec` contract: the
    multi-host twin of :class:`ElasticSupervisor`.

    Every host renews its alive lease and launches ONLY its own ranks
    each generation. Host 0 is additionally the controller: it computes
    membership from the alive leases (a stale lease = whole-host loss,
    all its ranks gone at once), assigns contiguous ranks across live
    hosts, publishes ``control.json``, gathers per-host exit codes, and
    decides done / re-form / failed exactly like the single-host
    supervisor — REFORM_EXIT or lost ranks shrink the next generation;
    a clean sweep of zeros finishes.

    ``argv_fn(rank, world, generation, coordinator)`` builds one
    worker's command line (same signature as
    :class:`ElasticSupervisor`)."""

    def __init__(self, spec, host_id, argv_fn, env=None, timeout_s=240,
                 max_generations=6, min_world=1, port_fn=None,
                 logger=None):
        self.spec = spec
        self.host_id = int(host_id)
        self.argv_fn = argv_fn
        self.env = dict(env) if env else dict(os.environ)
        self.timeout_s = float(timeout_s)
        self.max_generations = int(max_generations)
        self.min_world = int(min_world)
        self._port_fn = port_fn or ElasticSupervisor._free_port
        import logging
        self.logger = logger or logging.getLogger("mxnet_tpu.elastic")
        self.history = []
        self._dead = set()      # hosts declared lost (no rejoin here)
        self._stop_lease = threading.Event()
        self._lease_thread = None

    # -- alive lease -----------------------------------------------------------
    def _lease_loop(self):
        while not self._stop_lease.wait(self.spec.lease_s / 3.0):
            try:
                self.spec.touch_alive(self.host_id)
            except OSError:
                pass

    def _start_lease(self):
        self.spec.touch_alive(self.host_id)
        self._lease_thread = threading.Thread(
            target=self._lease_loop,
            name=f"host{self.host_id}-alive", daemon=True)
        self._lease_thread.start()

    def _stop_lease_thread(self):
        self._stop_lease.set()
        if self._lease_thread is not None:
            self._lease_thread.join(timeout=self.spec.lease_s)
            self._lease_thread = None

    # -- worker launch ---------------------------------------------------------
    def _run_ranks(self, ctrl):
        gen = ctrl["generation"]
        world = ctrl["world"]
        coordinator = ctrl["coordinator"]
        ranks = ctrl["ranks"].get(str(self.host_id),
                                  ctrl["ranks"].get(self.host_id, []))
        self.spec.write_ranks(gen, self.host_id, ranks, world,
                              coordinator)
        procs = []
        for rank in ranks:
            env = dict(self.env)
            env.pop("MXTPU_FAULT_INJECT", None)
            env.update(self.spec.handshake_env(
                rank, world, gen, coordinator, self.host_id))
            procs.append(subprocess.Popen(
                self.argv_fn(rank, world, gen, coordinator), env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT))
        codes, logs = [], []
        deadline = time.monotonic() + self.timeout_s
        for p in procs:
            try:
                out, _ = p.communicate(
                    timeout=max(1.0, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()
                out, _ = p.communicate()
            codes.append(p.returncode)
            logs.append((out or b"").decode(errors="replace"))
        self.spec.write_codes(gen, self.host_id, codes)
        return ranks, codes, logs

    # -- controller (host 0) ---------------------------------------------------
    def _live_hosts(self):
        return [h for h in range(self.spec.hosts)
                if h not in self._dead
                and (h == self.host_id or self.spec.host_alive(h))]

    def _gather_codes(self, gen, member_hosts, own_codes):
        """Wait for every member host's codes file; a host whose file
        never lands AND whose alive lease went stale is a whole-host
        loss — its ranks all count as lost."""
        got = {self.host_id: own_codes}
        lost_hosts = []
        deadline = time.monotonic() + self.timeout_s
        pending = [h for h in member_hosts if h != self.host_id]
        while pending and time.monotonic() < deadline:
            for h in list(pending):
                codes = self.spec.read_codes(gen, h)
                if codes is not None:
                    got[h] = codes
                    pending.remove(h)
                elif not self.spec.host_alive(h):
                    lost_hosts.append(h)
                    pending.remove(h)
            if pending:
                time.sleep(0.1)
        lost_hosts.extend(pending)     # deadline: treat as lost
        return got, sorted(set(lost_hosts))

    def _run_controller(self):
        assert self.host_id == 0, "only host 0 controls the fleet"
        world = None
        for gen in range(self.max_generations):
            # membership from alive leases; give stragglers one lease
            # to publish theirs on the first generation
            if gen == 0:
                deadline = time.monotonic() + 3.0 * self.spec.lease_s
                while len(self._live_hosts()) < self.spec.hosts and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
            hosts = self._live_hosts()
            ranks, nxt = {}, 0
            for h in hosts:
                ranks[str(h)] = list(range(
                    nxt, nxt + self.spec.procs_per_host))
                nxt += self.spec.procs_per_host
            world = nxt
            if world < self.min_world:
                raise MXNetError(
                    f"supervisor: world shrank to {world} < min_world="
                    f"{self.min_world} at generation {gen}")
            coordinator = f"127.0.0.1:{self._port_fn()}"
            ctrl = {"generation": gen, "world": world,
                    "coordinator": coordinator, "ranks": ranks,
                    "status": "run"}
            self.spec.write_control(ctrl)
            self.logger.info(
                "supervisor gen %d: hosts=%s world=%d (%s)",
                gen, hosts, world, coordinator)
            _, own_codes, logs = self._run_ranks(ctrl)
            codes_by_host, lost_hosts = self._gather_codes(
                gen, hosts, own_codes)
            self._dead.update(lost_hosts)
            all_codes = [c for h in sorted(codes_by_host)
                         for c in codes_by_host[h]]
            dead = [r for h in lost_hosts for r in ranks[str(h)]]
            for h, cs in codes_by_host.items():
                for i, c in enumerate(cs):
                    if c not in (0, REFORM_EXIT):
                        dead.append(ranks[str(h)][i])
            lost_ranks = sorted(set(dead))
            record = {"generation": gen, "world": world,
                      "hosts": hosts, "ranks": ranks,
                      "codes": {h: codes_by_host.get(h)
                                for h in hosts},
                      "lost_hosts": lost_hosts,
                      "lost_ranks": lost_ranks, "logs": logs}
            self.history.append(record)
            if codes_by_host and not lost_hosts and \
                    all(c == 0 for c in all_codes):
                record["outcome"] = "done"
                ctrl["status"] = "done"
                self.spec.write_control(ctrl)
                return self.history
            if not lost_hosts and not lost_ranks and \
                    not any(c == REFORM_EXIT for c in all_codes):
                record["outcome"] = "failed"
                ctrl["status"] = "failed"
                self.spec.write_control(ctrl)
                raise MXNetError(
                    f"supervisor gen {gen}: workers failed without "
                    f"requesting re-form (codes={codes_by_host});\n"
                    + "\n".join(logs))
            record["outcome"] = "reform"
        raise MXNetError(
            f"supervisor: no generation finished within "
            f"{self.max_generations} re-forms")

    # -- follower (host > 0) ---------------------------------------------------
    def _run_follower(self):
        seen = -1
        deadline = time.monotonic() + \
            self.timeout_s * self.max_generations
        while time.monotonic() < deadline:
            ctrl = self.spec.read_control()
            if ctrl is None or ctrl["generation"] <= seen:
                time.sleep(0.05)
                continue
            if ctrl.get("status") in ("done", "failed"):
                return self.history
            seen = ctrl["generation"]
            if str(self.host_id) not in ctrl["ranks"]:
                # not a member this generation (we were declared lost);
                # keep the lease warm so a future rejoin can include us
                time.sleep(0.05)
                continue
            ranks, codes, logs = self._run_ranks(ctrl)
            self.history.append(
                {"generation": seen, "ranks": ranks, "codes": codes,
                 "logs": logs})
        return self.history

    def run(self):
        """Drive this host's half of the contract until the fleet
        finishes (host 0 returns the full history; followers return
        their own launch records)."""
        self._start_lease()
        try:
            if self.host_id == 0:
                return self._run_controller()
            return self._run_follower()
        finally:
            self._stop_lease_thread()
