"""Regex-rule parameter partitioning (ROADMAP item 1).

``match_partition_rules`` maps an ordered list of ``(regex,
PartitionSpec)`` rules over a named parameter tree — the established
idiom for declaring tensor-parallel layouts over large named trees
(SNIPPETS.md [2]): first ``re.search`` match wins, scalars are always
replicated, and in strict mode an unmatched parameter is an ERROR, not
a silent replication — a partitioning that quietly skips a parameter is
exactly the kind of wrong that only shows up as an OOM three models
later.

Rules also come from the environment (``MXTPU_PARTITION_RULES``) in a
flat text form so launch scripts can flip layouts without code:

    MXTPU_PARTITION_RULES="fc.*_weight=model,None;.*=replicated"

Each clause is ``regex=spec`` (``;``-separated); a spec is a
``,``-separated PartitionSpec — axis names partition the matching
dimension, ``None`` (or ``*``) replicates it, and the whole-spec words
``replicated``/``rep`` mean ``P()``. The parsed rules feed
``FusedSymbolStep`` / ``TrainStep`` parameter layouts and
``rules_fingerprint`` is compile-key material (compile/key.py): two
processes resolving different partition regimes trace different
programs and must never share a cached executable.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Sequence, Tuple

from ..base import MXNetError

__all__ = ["parse_rules", "match_partition_rules", "spec_for",
           "rules_fingerprint", "env_rules", "shard_params",
           "validate_specs"]


def _parse_spec(text: str):
    """One spec clause -> PartitionSpec. ``replicated``/``rep``/empty
    mean P(); otherwise a ``,``-list of axis names with ``None``/``*``
    as the replicated-dimension placeholder."""
    from jax.sharding import PartitionSpec as P
    t = text.strip()
    if t.lower() in ("", "replicated", "rep", "p()"):
        return P()
    parts = []
    for tok in t.split(","):
        tok = tok.strip()
        if tok.lower() in ("none", "*", ""):
            parts.append(None)
        else:
            parts.append(tok)
    return P(*parts)


def parse_rules(text: str) -> List[tuple]:
    """``MXTPU_PARTITION_RULES`` text -> ordered ``[(regex, spec)]``.
    Invalid clauses raise MXNetError at parse time (a bad rule fails the
    bind that consulted it, never silently trains mis-partitioned)."""
    rules = []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise MXNetError(
                f"MXTPU_PARTITION_RULES clause {clause!r} is not "
                "'regex=spec'")
        pat, spec = clause.split("=", 1)
        try:
            rx = re.compile(pat.strip())
        except re.error as e:
            raise MXNetError(
                f"MXTPU_PARTITION_RULES regex {pat!r} invalid: {e}")
        rules.append((rx.pattern, _parse_spec(spec)))
    return rules


def env_rules() -> List[tuple]:
    """Rules from ``MXTPU_PARTITION_RULES`` ([] when unset)."""
    from .. import config as _config
    return parse_rules(str(_config.get("MXTPU_PARTITION_RULES", "") or ""))


def spec_for(rules: Sequence[tuple], name: str, ndim: Optional[int] = None,
             strict: bool = False):
    """First matching rule's PartitionSpec for ``name`` (re.search, in
    order). Rank-0 values are always replicated. No match -> P() (or
    MXNetError when ``strict``)."""
    from jax.sharding import PartitionSpec as P
    if ndim == 0:
        return P()
    for pat, spec in rules or ():
        if re.search(pat, name):
            if ndim is not None and len(spec) > ndim:
                raise MXNetError(
                    f"partition rule {pat!r} -> {spec} has more "
                    f"dimensions than parameter '{name}' (ndim={ndim})")
            return spec
    if strict:
        raise MXNetError(
            f"no partition rule matches parameter '{name}' — add a "
            "catch-all '.*=replicated' clause (strict matching refuses "
            "to silently replicate)")
    return P()


def match_partition_rules(rules: Sequence[tuple], params: Dict[str, object],
                          strict: bool = True) -> Dict[str, object]:
    """Resolve a whole named tree: ``{name: array-or-shape}`` ->
    ``{name: PartitionSpec}`` (SNIPPETS.md [2] semantics — ordered
    first-match-wins, scalars replicated, unmatched raises in strict
    mode)."""
    out = {}
    for name, v in params.items():
        shape = tuple(getattr(v, "shape", v if isinstance(v, (tuple, list))
                              else ()))
        out[name] = spec_for(rules, name, ndim=len(shape), strict=strict)
    return out


def validate_specs(mesh, specs: Dict[str, object],
                   shapes: Dict[str, tuple]) -> None:
    """Every partitioned dimension must divide by its mesh-axis size —
    checked up front with the parameter's NAME in the error instead of
    a deep GSPMD shape complaint at compile time."""
    for name, spec in specs.items():
        shape = shapes.get(name)
        if shape is None:
            continue
        for d, ax in enumerate(tuple(spec)):
            if ax is None:
                continue
            axes = ax if isinstance(ax, tuple) else (ax,)
            size = 1
            for a in axes:
                size *= int(mesh.shape[a])
            if d < len(shape) and int(shape[d]) % size:
                raise MXNetError(
                    f"parameter '{name}' dim {d} (={shape[d]}) does not "
                    f"divide mesh axes {axes} (size {size}) — pad the "
                    "parameter or change the rule")


def shard_params(mesh, rules: Sequence[tuple], params: Dict[str, object],
                 strict: bool = False) -> Dict[str, object]:
    """device_put every value under its matched rule's NamedSharding
    (convenience for tests/tools; the fused step applies shardings
    through its own buffer plumbing)."""
    import jax
    from jax.sharding import NamedSharding
    specs = match_partition_rules(rules, params, strict=strict)
    validate_specs(mesh, specs,
                   {n: tuple(getattr(v, "shape", ())) for n, v
                    in params.items()})
    return {n: jax.device_put(v, NamedSharding(mesh, specs[n]))
            for n, v in params.items()}


def rules_fingerprint(rules: Sequence[tuple]) -> Optional[list]:
    """Canonical key material for a rule list (compile/key.py): the
    ordered (regex, spec-as-strings) pairs. None for no rules, so keys
    stay byte-identical with pre-partition builds when the feature is
    off."""
    if not rules:
        return None
    return [(pat, [str(a) for a in tuple(spec)]) for pat, spec in rules]
