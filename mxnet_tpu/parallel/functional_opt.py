"""Functional optimizer rules for the fused training step.

The eager ``mxnet_tpu.optimizer`` classes mutate NDArrays and keep their
update count in Python — correct for the per-parameter Updater loop, but
wrong inside one traced XLA step (the count would be baked in at trace
time). This module provides the pure counterpart: for every registered
optimizer name, ``create(name, **kwargs)`` returns a rule with

    init(param)                    -> state tuple (jnp leaves)
    update(param, grad, state, lr, t, wd, key=None)
                                   -> (new_param, new_state)

where ``t`` is the TRACED 1-based update count (a device scalar advancing
inside the compiled step) and ``lr``/``wd`` are per-call values so the
caller can apply schedules and per-parameter lr_mult/wd_mult. The math
mirrors ops/optimizer_ops.py (reference: src/operator/optimizer_op.cc)
and the eager classes in optimizer.py (reference: python/mxnet/optimizer.py).

Used by parallel.step.TrainStep (gluon path) so that ANY ``--optimizer X``
runs inside the single fused fwd+bwd+update XLA program — no eager
per-parameter fallback.
"""
from __future__ import annotations

from typing import Callable, Dict

import jax
import jax.numpy as jnp

__all__ = ["create", "from_optimizer", "supported", "row_supported",
           "FunctionalOptimizer"]


class FunctionalOptimizer:
    """A pure optimizer rule: closures over static hyperparameters.

    ``elementwise`` declares that ``update`` is purely per-element given
    (lr, wd) — i.e. running it on a flat concatenation of parameters with
    per-element lr/wd vectors is exact. Only per-tensor-norm rules opt
    out (lbsgd/lars with warmup_strategy='lars'); the fused Module step
    uses the flag to gate small-parameter packing (module/fused.py).
    State leaves from ``init`` may be parameter-shaped or scalar
    (pack-shared, e.g. nadam's m_schedule) — any other shape would break
    the packed state IO.

    ``row_update`` (sgd/adam) is the lazy row-sparse rule matching the
    reference's ``lazy_update=True`` semantics (optimizer.py SGD/Adam
    with row_sparse grads): ``row_update(p, uids, rows, s, lr, t, wd)``
    updates ONLY the rows named by ``uids`` — momentum decay, moment
    EMAs and weight decay all advance on touch, untouched rows are
    bit-frozen. Ids read with clip and written with drop, so padded
    sentinel ids (and out-of-shard ids under shard_map rebasing —
    sparse/sharding.py) are structural no-ops. None on rules without a
    lazy form.
    """

    def __init__(self, name, init_fn, update_fn, needs_key=False,
                 elementwise=True, row_update_fn=None):
        self.name = name
        self.init = init_fn            # p -> state tuple
        self._update = update_fn       # (p, g, s, lr, t, wd, key) -> (p, s)
        self.needs_key = needs_key
        self.elementwise = elementwise
        # (p, uids, rows, s, lr, t, wd) -> (p, s); None = no lazy form
        self.row_update = row_update_fn

    def update(self, p, g, s, lr, t, wd=0.0, key=None):
        return self._update(p, g, s, lr, t, wd, key)


_FACTORIES: Dict[str, Callable] = {}


def _factory(*names):
    def deco(fn):
        for n in names:
            _FACTORIES[n] = fn
        return fn
    return deco


def supported():
    return sorted(_FACTORIES)


def row_supported():
    """Optimizer names with a lazy row-sparse rule."""
    return sorted(n for n in _FACTORIES
                  if create(n).row_update is not None)


# hyperparameter names each rule accepts (plus the common prologue keys);
# create() rejects anything else so a misspelled optimizer_param fails fast
# instead of silently training with defaults
_COMMON_KEYS = {"rescale_grad", "clip_gradient"}
_PARAM_KEYS = {
    "sgd": {"momentum", "lazy_update"},
    "nag": {"momentum"},
    "lbsgd": {"momentum", "eta", "warmup_strategy", "warmup_epochs",
              "updates_per_epoch", "batch_scale", "begin_epoch",
              "num_epochs", "multi_precision"},
    "lars": {"momentum", "eta", "warmup_strategy", "warmup_epochs",
             "updates_per_epoch", "batch_scale"},
    "adam": {"beta1", "beta2", "epsilon", "lazy_update"},
    "adamax": {"beta1", "beta2"},
    "nadam": {"beta1", "beta2", "epsilon", "schedule_decay"},
    "ftml": {"beta1", "beta2", "epsilon"},
    "adagrad": {"eps"},
    "rmsprop": {"gamma1", "gamma2", "epsilon", "centered", "clip_weights"},
    "adadelta": {"rho", "epsilon"},
    "ftrl": {"lamda1", "beta"},
    "signsgd": set(),
    "signum": {"momentum", "wd_lh"},
    "sgld": set(),
    "dcasgd": {"momentum", "lamda"},
    "test": set(),
}


def create(name, **kwargs) -> FunctionalOptimizer:
    name = name.lower()
    if name not in _FACTORIES:
        raise ValueError(
            f"no functional rule for optimizer '{name}'; supported: "
            f"{supported()}")
    unknown = set(kwargs) - _PARAM_KEYS[name] - _COMMON_KEYS
    if unknown:
        raise TypeError(
            f"optimizer '{name}' got unexpected parameters {sorted(unknown)}"
            f"; accepted: {sorted(_PARAM_KEYS[name] | _COMMON_KEYS)}")
    return _FACTORIES[name](kwargs)


def _g32(g, p, kw):
    """Common gradient preprocessing: f32, rescale, clip (the reference's
    KERNEL_ASSIGN prologue in optimizer_op-inl.h)."""
    g = g.astype(jnp.float32) * kw.get("rescale_grad", 1.0)
    clip = kw.get("clip_gradient")
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


def _g32_wd_then_clip(g, p, kw, wd):
    """Variant where weight decay is folded in BEFORE clipping — the
    adamax/nadam/ftml ordering in the eager classes (optimizer.py:609,
    640; ftml_update optimizer_ops.py:122)."""
    g = g.astype(jnp.float32) * kw.get("rescale_grad", 1.0) + wd * p
    clip = kw.get("clip_gradient")
    if clip is not None and clip > 0:
        g = jnp.clip(g, -clip, clip)
    return g


def _zeros(p):
    return jnp.zeros_like(p, dtype=jnp.float32)


# -- sgd / nag / lbsgd --------------------------------------------------------

@_factory("sgd")
def _make_sgd(kw):
    momentum = kw.get("momentum", 0.0)
    lazy = kw.get("lazy_update", True)

    def init(p):
        return (_zeros(p),) if momentum else ()

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw) + wd * p
        if momentum:
            (mom,) = s
            mom = momentum * mom - lr * g
            return p + mom, (mom,)
        return p - lr * g, ()

    def row_update(p, uids, rows, s, lr, t, wd):
        # lazy SGD (reference: optimizer.py SGD lazy_update): only the
        # touched rows advance — weight decay applies on touch, the
        # momentum of untouched rows stays frozen
        pr = jnp.take(p, uids, axis=0, mode="clip").astype(jnp.float32)
        g = _g32(rows, pr, kw) + wd * pr
        if momentum:
            (mom,) = s
            mr = jnp.take(mom, uids, axis=0, mode="clip")
            mr = momentum * mr - lr * g
            p = p.at[uids].add(mr.astype(p.dtype), mode="drop")
            mom = mom.at[uids].set(mr, mode="drop")
            return p, (mom,)
        return p.at[uids].add((-lr * g).astype(p.dtype), mode="drop"), ()

    return FunctionalOptimizer("sgd", init, update,
                               row_update_fn=row_update if lazy else None)


@_factory("nag")
def _make_nag(kw):
    momentum = kw.get("momentum", 0.0)

    def init(p):
        return (_zeros(p),)

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw) + wd * p
        (mom,) = s
        mom = momentum * mom + g
        return p - lr * (g + momentum * mom), (mom,)

    return FunctionalOptimizer("nag", init, update)


@_factory("lbsgd")
def _make_lbsgd(kw):
    """Large-batch SGD (reference: optimizer.py LBSGD). Defaults mirror the
    eager class: warmup_strategy='linear', raw trust ratio (no eta factor)
    for strategy='lars'. The 'lars' alias below keeps TrainStep's historic
    eta-scaled semantics. Scheduled strategies use the traced count."""
    momentum = kw.get("momentum", 0.9)
    eta = kw.get("eta", 1.0)
    strategy = kw.get("warmup_strategy", "linear")
    warmup_epochs = kw.get("warmup_epochs", 5)
    updates_per_epoch = kw.get("updates_per_epoch", 32)
    batch_scale = float(kw.get("batch_scale", 1))

    def init(p):
        return (_zeros(p),)

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        if strategy == "lars":
            w_norm = jnp.linalg.norm(p.ravel())
            g_norm = jnp.linalg.norm(g.ravel())
            mult = jnp.where((w_norm > 0) & (g_norm > 0),
                             eta * w_norm / (g_norm + wd * w_norm + 1e-9),
                             1.0)
        else:
            nwup = float(warmup_epochs * updates_per_epoch)
            nup = t.astype(jnp.float32)
            if nwup <= 1:
                mult = batch_scale
            elif strategy == "linear":
                mult = 1.0 + (batch_scale - 1) * nup / nwup
            elif strategy == "power2":
                mult = 1.0 + (batch_scale - 1) * (nup * nup) / (nwup * nwup)
            elif strategy == "sqrt":
                mult = 1.0 + (batch_scale - 1) * jnp.sqrt(nup / nwup)
            else:
                mult = 1.0
            mult = jnp.minimum(mult, batch_scale)
        lr = lr * mult
        (mom,) = s
        mom = momentum * mom + lr * (g + wd * p)
        return p - mom, (mom,)

    # the 'lars' strategy is per-tensor-norm based — not elementwise
    return FunctionalOptimizer("lbsgd", init, update,
                               elementwise=(strategy != "lars"))


@_factory("lars")
def _make_lars(kw):
    """TrainStep's 'lars' name: LBSGD with trust-ratio warmup and the
    conventional eta=0.001 LARS coefficient (You et al.; the eager LBSGD
    folds eta into the base lr instead)."""
    kw = dict(kw)
    kw.setdefault("warmup_strategy", "lars")
    kw.setdefault("eta", 0.001)
    return _make_lbsgd(kw)


# -- adam family --------------------------------------------------------------

@_factory("adam")
def _make_adam(kw):
    beta1 = kw.get("beta1", 0.9)
    beta2 = kw.get("beta2", 0.999)
    epsilon = kw.get("epsilon", 1e-8)
    lazy = kw.get("lazy_update", True)

    def init(p):
        return (_zeros(p), _zeros(p))

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw) + wd * p
        mean, var = s
        mean = beta1 * mean + (1 - beta1) * g
        var = beta2 * var + (1 - beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        lr_t = lr * jnp.sqrt(1 - beta2 ** tf) / (1 - beta1 ** tf)
        return p - lr_t * mean / (jnp.sqrt(var) + epsilon), (mean, var)

    def row_update(p, uids, rows, s, lr, t, wd):
        # lazy Adam (reference: optimizer.py Adam lazy_update): moment
        # EMAs advance only for touched rows; bias correction uses the
        # GLOBAL step count (the reference's documented approximation —
        # exact vs dense when every row is touched every step)
        pr = jnp.take(p, uids, axis=0, mode="clip").astype(jnp.float32)
        g = _g32(rows, pr, kw) + wd * pr
        mean, var = s
        mr = jnp.take(mean, uids, axis=0, mode="clip")
        vr = jnp.take(var, uids, axis=0, mode="clip")
        mr = beta1 * mr + (1 - beta1) * g
        vr = beta2 * vr + (1 - beta2) * jnp.square(g)
        tf = t.astype(jnp.float32)
        lr_t = lr * jnp.sqrt(1 - beta2 ** tf) / (1 - beta1 ** tf)
        delta = -lr_t * mr / (jnp.sqrt(vr) + epsilon)
        p = p.at[uids].add(delta.astype(p.dtype), mode="drop")
        mean = mean.at[uids].set(mr, mode="drop")
        var = var.at[uids].set(vr, mode="drop")
        return p, (mean, var)

    return FunctionalOptimizer("adam", init, update,
                               row_update_fn=row_update if lazy else None)


@_factory("adamax")
def _make_adamax(kw):
    beta1 = kw.get("beta1", 0.9)
    beta2 = kw.get("beta2", 0.999)

    def init(p):
        return (_zeros(p), _zeros(p))

    def update(p, g, s, lr, t, wd, key):
        g = _g32_wd_then_clip(g, p, kw, wd)
        m, u = s
        m = beta1 * m + (1 - beta1) * g
        u = jnp.maximum(beta2 * u, jnp.abs(g))
        lr_t = lr / (1 - beta1 ** t.astype(jnp.float32))
        return p - lr_t * m / (u + 1e-8), (m, u)

    return FunctionalOptimizer("adamax", init, update)


@_factory("nadam")
def _make_nadam(kw):
    beta1 = kw.get("beta1", 0.9)
    beta2 = kw.get("beta2", 0.999)
    epsilon = kw.get("epsilon", 1e-8)
    decay = kw.get("schedule_decay", 0.004)

    def init(p):
        # m_schedule is carried as state — the eager class accumulates it
        # in Python (optimizer.py Nadam.m_schedule), which cannot live
        # across traced steps
        return (_zeros(p), _zeros(p), jnp.ones((), jnp.float32))

    def update(p, g, s, lr, t, wd, key):
        g = _g32_wd_then_clip(g, p, kw, wd)
        m, v, m_sched = s
        tf = t.astype(jnp.float32)
        mom_t = beta1 * (1.0 - 0.5 * 0.96 ** (tf * decay))
        mom_t1 = beta1 * (1.0 - 0.5 * 0.96 ** ((tf + 1) * decay))
        m_sched = m_sched * mom_t
        m_sched_next = m_sched * mom_t1
        m = beta1 * m + (1 - beta1) * g
        v = beta2 * v + (1 - beta2) * jnp.square(g)
        g_prime = g / (1 - m_sched)
        m_prime = m / (1 - m_sched_next)
        v_prime = v / (1 - beta2 ** tf)
        m_bar = (1 - mom_t) * g_prime + mom_t1 * m_prime
        return p - lr * m_bar / (jnp.sqrt(v_prime) + epsilon), \
            (m, v, m_sched)

    return FunctionalOptimizer("nadam", init, update)


@_factory("ftml")
def _make_ftml(kw):
    beta1 = kw.get("beta1", 0.6)
    beta2 = kw.get("beta2", 0.999)
    epsilon = kw.get("epsilon", 1e-8)

    def init(p):
        return (_zeros(p), _zeros(p), _zeros(p))

    def update(p, g, s, lr, t, wd, key):
        g = _g32_wd_then_clip(g, p, kw, wd)
        d, v, z = s
        tf = t.astype(jnp.float32)
        v = beta2 * v + (1 - beta2) * jnp.square(g)
        d_new = (1 - beta1 ** tf) / lr * (
            jnp.sqrt(v / (1 - beta2 ** tf)) + epsilon)
        sigma = d_new - beta1 * d
        z = beta1 * z + (1 - beta1) * g - sigma * p
        return -z / d_new, (d_new, v, z)

    return FunctionalOptimizer("ftml", init, update)


# -- adaptive-rate family -----------------------------------------------------

@_factory("adagrad")
def _make_adagrad(kw):
    eps = kw.get("eps", 1e-7)

    def init(p):
        return (_zeros(p),)

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        (h,) = s
        h = h + jnp.square(g)
        return p - lr * (g / jnp.sqrt(h + eps) + wd * p), (h,)

    return FunctionalOptimizer("adagrad", init, update)


@_factory("rmsprop")
def _make_rmsprop(kw):
    gamma1 = kw.get("gamma1", 0.9)
    gamma2 = kw.get("gamma2", 0.9)
    epsilon = kw.get("epsilon", 1e-8)
    centered = kw.get("centered", False)
    clip_weights = kw.get("clip_weights")

    def init(p):
        return (_zeros(p), _zeros(p), _zeros(p)) if centered else (_zeros(p),)

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw) + wd * p
        if not centered:
            (n,) = s
            n = gamma1 * n + (1 - gamma1) * jnp.square(g)
            w = p - lr * g / jnp.sqrt(n + epsilon)
            st = (n,)
        else:
            n, gbar, delta = s
            n = gamma1 * n + (1 - gamma1) * jnp.square(g)
            gbar = gamma1 * gbar + (1 - gamma1) * g
            delta = gamma2 * delta - lr * g / jnp.sqrt(
                n - jnp.square(gbar) + epsilon)
            w = p + delta
            st = (n, gbar, delta)
        if clip_weights is not None and clip_weights > 0:
            w = jnp.clip(w, -clip_weights, clip_weights)
        return w, st

    return FunctionalOptimizer("rmsprop", init, update)


@_factory("adadelta")
def _make_adadelta(kw):
    rho = kw.get("rho", 0.90)
    epsilon = kw.get("epsilon", 1e-5)

    def init(p):
        return (_zeros(p), _zeros(p))

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        acc_g, acc_d = s
        acc_g = rho * acc_g + (1 - rho) * jnp.square(g)
        cur = jnp.sqrt(acc_d + epsilon) / jnp.sqrt(acc_g + epsilon) * g
        acc_d = rho * acc_d + (1 - rho) * jnp.square(cur)
        return p - cur - wd * p, (acc_g, acc_d)

    return FunctionalOptimizer("adadelta", init, update)


@_factory("ftrl")
def _make_ftrl(kw):
    lamda1 = kw.get("lamda1", 0.01)
    beta = kw.get("beta", 1.0)

    def init(p):
        return (_zeros(p), _zeros(p))

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        z, n = s
        n_new = n + jnp.square(g)
        sigma = (jnp.sqrt(n_new) - jnp.sqrt(n)) / lr
        z = z + g - sigma * p
        w = jnp.where(
            jnp.abs(z) <= lamda1, jnp.zeros_like(p),
            (jnp.sign(z) * lamda1 - z) / ((beta + jnp.sqrt(n_new)) / lr + wd))
        return w, (z, n_new)

    return FunctionalOptimizer("ftrl", init, update)


# -- sign / noise / delay-compensated family ----------------------------------

@_factory("signsgd")
def _make_signsgd(kw):
    def init(p):
        return ()

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        return p - lr * (jnp.sign(g) + wd * p), ()

    return FunctionalOptimizer("signsgd", init, update)


@_factory("signum")
def _make_signum(kw):
    momentum = kw.get("momentum", 0.9)
    wd_lh = kw.get("wd_lh", 0.0)

    def init(p):
        return (_zeros(p),) if momentum != 0.0 else ()

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        if momentum == 0.0:
            # the eager class dispatches to signsgd_update here
            # (optimizer.py Signum.update state=None branch)
            return p - lr * (jnp.sign(g) + wd * p), ()
        (mom,) = s
        mom = momentum * mom - (1 - momentum) * (g + wd * p)
        return (1 - lr * wd_lh) * p + lr * jnp.sign(mom), (mom,)

    return FunctionalOptimizer("signum", init, update)


@_factory("sgld")
def _make_sgld(kw):
    def init(p):
        return ()

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        noise = jax.random.normal(key, p.shape, jnp.float32) * jnp.sqrt(lr)
        return p - lr / 2 * (g + wd * p) + noise, ()

    return FunctionalOptimizer("sgld", init, update, needs_key=True)


@_factory("dcasgd")
def _make_dcasgd(kw):
    """Delay-compensated async SGD (reference: optimizer.py DCASGD). In the
    fused synchronous step the delay is zero, but the variance-control term
    is kept for numeric parity with the eager class."""
    momentum = kw.get("momentum", 0.0)
    lamda = kw.get("lamda", 0.04)

    def init(p):
        return (_zeros(p), jnp.array(p, dtype=jnp.float32))

    def update(p, g, s, lr, t, wd, key):
        g = _g32(g, p, kw)
        mom, prev_w = s
        mon = g + wd * p + lamda * g * g * (p - prev_w)
        mom = momentum * mom - lr * mon
        # previous_weight tracks the PRE-update weight (optimizer.py:360)
        return p + mom, (mom, p.astype(jnp.float32))

    return FunctionalOptimizer("dcasgd", init, update)


@_factory("test")
def _make_test(kw):
    def init(p):
        return (_zeros(p),)

    def update(p, g, s, lr, t, wd, key):
        w = p - _g32(g, p, kw)
        return w, (w,)

    return FunctionalOptimizer("test", init, update)


# -- bridging from eager Optimizer objects ------------------------------------

# attrs each eager class carries, keyed by its registered (lowercase) name;
# every entry also pulls rescale_grad/clip_gradient from the base class
_ATTR_MAP = {
    "sgd": ("momentum", "lazy_update"),
    "nag": ("momentum",),
    "lbsgd": ("momentum", "warmup_strategy", "warmup_epochs",
              "updates_per_epoch", "batch_scale"),
    "adam": ("beta1", "beta2", "epsilon", "lazy_update"),
    "adamax": ("beta1", "beta2"),
    "nadam": ("beta1", "beta2", "epsilon", "schedule_decay"),
    "ftml": ("beta1", "beta2", "epsilon"),
    "adagrad": (),
    "rmsprop": ("gamma1", "gamma2", "epsilon", "centered", "clip_weights"),
    "adadelta": ("rho", "epsilon"),
    "ftrl": ("lamda1", "beta"),
    "signsgd": (),
    "signum": ("momentum", "wd_lh"),
    "sgld": (),
    "dcasgd": ("momentum", "lamda"),
    "test": (),
}


def from_optimizer(opt) -> FunctionalOptimizer:
    """Build a functional rule mirroring an eager Optimizer instance.

    Hyperparameters are read off the instance; lr/wd stay per-call so the
    caller applies opt's schedule and lr_mult/wd_mult itself.
    """
    name = type(opt).__name__.lower()
    if name not in _ATTR_MAP:
        raise ValueError(
            f"no functional rule for optimizer class {type(opt).__name__}; "
            f"supported: {supported()}")
    kw = {}
    for a in _ATTR_MAP[name]:
        if hasattr(opt, a):
            kw[a] = getattr(opt, a)
    if name == "adagrad":
        kw["eps"] = getattr(opt, "float_stable_eps", 1e-7)
    kw["rescale_grad"] = getattr(opt, "rescale_grad", 1.0)
    kw["clip_gradient"] = getattr(opt, "clip_gradient", None)
    return create(name, **kw)
