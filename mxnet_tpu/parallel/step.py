"""Fused, sharded training step.

This is the TPU-native replacement for the reference's steady-state hot loop
(SURVEY.md §3.2): GraphExecutor::RunOps pushing cached per-op engine
operations + KVStore push/pull per layer. Here the ENTIRE training step —
forward, backward, gradient reduction across the mesh, optimizer update, and
BatchNorm running-stat fold — is one XLA computation: compiled once, fully
fused, with parameter/optimizer buffers donated (zero-copy in-place update)
and cross-chip gradient reductions (psum) inserted by GSPMD exactly where
the dataflow needs them, overlapping backward compute the way the
reference's priority-ordered engine pushes did (trainer.py:190 priority=-i).
"""
from __future__ import annotations

from typing import Callable, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ndarray.ndarray import NDArray, _wrap

__all__ = ["TrainStep", "softmax_ce_loss", "l2_loss"]


def softmax_ce_loss(logits, labels):
    """Mean softmax cross entropy with integer labels (the train_imagenet
    objective; reference op: SoftmaxOutput src/operator/softmax_output.cc)."""
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    picked = jnp.take_along_axis(
        logp, labels.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.mean(picked)


def l2_loss(pred, target):
    return 0.5 * jnp.mean(jnp.square(pred - target.reshape(pred.shape)))


_LOSSES = {"softmax_ce": softmax_ce_loss, "l2": l2_loss}


def _remat_staged(staged):
    """Wrap the staged forward in jax.checkpoint. The inner function
    records ``_write_params`` on itself AT TRACE TIME (block.py:484), so
    the wrapper keeps a reference for the BatchNorm fold to read."""
    wrapped = jax.checkpoint(staged)
    wrapped._inner = staged
    return wrapped


class TrainStep:
    """One-XLA-computation training step for a HybridBlock.

    Usage::

        step = TrainStep(net, loss="softmax_ce", optimizer="sgd",
                         optimizer_params={"momentum": 0.9}, mesh=mesh)
        loss = step(x, y)          # NDArray/ndarray in, scalar out

    With a mesh, the batch is sharded over the 'data' axis and parameters
    are replicated (data parallelism); pass ``param_spec_fn`` for
    tensor-parallel parameter layouts.
    """

    def __init__(self, net, loss="softmax_ce", optimizer="sgd",
                 optimizer_params=None, mesh: Optional[Mesh] = None,
                 data_axis="data", compute_dtype=None, lr=0.01,
                 lr_schedule: Optional[Callable[[int], float]] = None,
                 param_spec_fn=None, partition_rules=None, preprocess=None,
                 remat=None):
        """``preprocess``: optional on-device fn applied to the data batch
        inside the compiled step (e.g. uint8 decode -> normalize). Keeps the
        host->device transfer small — the TPU analog of the reference doing
        mean-subtract inside the C++ iterator (iter_normalize.h).

        ``remat``: recompute activations during backward (jax.checkpoint),
        trading FLOPs for HBM — the reference's gradient mirroring
        (MXNET_BACKWARD_DO_MIRROR, graph_executor.cc mirror fn). Default
        comes from that env var via mxnet_tpu.config."""
        self.net = net
        self.preprocess = preprocess
        self.loss_fn = _LOSSES[loss] if isinstance(loss, str) else loss
        optimizer_params = dict(optimizer_params or {})
        self.lr = optimizer_params.pop("learning_rate", lr)
        self.lr_schedule = lr_schedule
        self.wd = optimizer_params.pop("wd", 0.0)
        # any registered optimizer runs inside the fused step — the pure
        # rules live in functional_opt (the traced analog of optimizer.py)
        from . import functional_opt
        self._fopt = functional_opt.create(optimizer, **optimizer_params)
        self._opt_init = self._fopt.init
        self.mesh = mesh
        self.data_axis = data_axis
        self.compute_dtype = compute_dtype
        self._num_update = 0

        if remat is None:
            from .. import config as _config
            remat = _config.get("MXNET_BACKWARD_DO_MIRROR")
        self.remat = bool(remat)

        self.param_list = net._get_param_list()
        self._trainable = [p.grad_req != "null" for p in self.param_list]
        # staged forward in training mode: fn(pvals, args, key)->(outs,writes)
        _, self._staged = net._build_jit(training=True)
        if self.remat:
            self._staged = _remat_staged(self._staged)
        self._pvals = None
        self._opt_state = None
        self._step_jit = None
        # declarative alternative to param_spec_fn: regex -> PartitionSpec
        # rules (parallel/partition.py). Explicit param_spec_fn wins; with
        # neither, rules come from MXTPU_PARTITION_RULES.
        if param_spec_fn is None and mesh is not None:
            from . import partition as _partition
            rules = (_partition.parse_rules(partition_rules)
                     if isinstance(partition_rules, str)
                     else partition_rules)
            if rules is None:
                rules = _partition.env_rules()
            if rules:
                def param_spec_fn(p, _rules=tuple(rules)):
                    shape = getattr(p, "shape", None)
                    ndim = len(shape) if shape else None
                    return _partition.spec_for(_rules, p.name, ndim=ndim)
        self._param_spec_fn = param_spec_fn

    # -- state ----------------------------------------------------------------
    def _init_state(self):
        import jax.numpy as jnp
        # copy the buffers: the step donates its param arrays, which would
        # otherwise invalidate the net's live Parameter buffers
        pvals = tuple(jnp.array(p.data()._data, copy=True)
                      for p in self.param_list)
        opt_state = tuple(
            self._opt_init(v) if t else ()
            for v, t in zip(pvals, self._trainable))
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            if self._param_spec_fn is not None:
                shard = [NamedSharding(self.mesh,
                                       self._param_spec_fn(p) or P())
                         for p in self.param_list]
            else:
                shard = [rep] * len(pvals)
            pvals = tuple(jax.device_put(v, s)
                          for v, s in zip(pvals, shard))
            # state leaves only inherit the param's sharding when they have
            # the param's shape; scalar leaves (e.g. adam's step counter t)
            # are replicated — a non-empty spec on a rank-0 array is invalid
            opt_state = tuple(
                tuple(jax.device_put(
                          x, s if getattr(x, "shape", None) == v.shape
                          else rep)
                      if hasattr(x, "shape") else x
                      for x in st)
                for st, s, v in zip(opt_state, shard, pvals))
        self._pvals = pvals
        self._opt_state = opt_state
        t0 = jnp.zeros((), jnp.uint32)
        if self.mesh is not None:
            t0 = jax.device_put(t0, NamedSharding(self.mesh, P()))
        self._t_dev = t0
        self._lr_cache = None

    def _build_step(self):
        staged = self._staged
        loss_fn = self.loss_fn
        fopt = self._fopt
        trainable = self._trainable
        compute_dtype = self.compute_dtype
        param_objs = self.param_list
        wd_base = self.wd
        # per-parameter multipliers are static (gluon Parameter.lr_mult /
        # wd_mult — reference: gluon/parameter.py), baked into the trace
        lr_mults = [getattr(p, "lr_mult", 1.0) for p in param_objs]
        wd_mults = [getattr(p, "wd_mult", 1.0) for p in param_objs]

        preprocess = self.preprocess

        # RNG: one base key captured at build; per-step keys are folded in
        # from the update counter INSIDE the compiled step — an eager
        # jax.random.split per step would cost a host->device dispatch
        # round trip (expensive when the chip is reached over a network)
        from .. import random as _random_mod
        base_key = _random_mod.next_key()

        def step_fn(pvals, opt_state, x, y, t, lr):
            key = jax.random.fold_in(base_key, t)
            if preprocess is not None:
                x = preprocess(x)

            def fwd(pv):
                pv_c = pv
                if compute_dtype is not None:
                    pv_c = tuple(
                        v.astype(compute_dtype)
                        if v.dtype == jnp.float32 else v for v in pv)
                    x_c = x.astype(compute_dtype) \
                        if x.dtype == jnp.float32 else x
                else:
                    x_c = x
                outs, writes = staged(pv_c, (x_c,), key)
                return loss_fn(outs[0], y), writes

            (loss, writes), grads = jax.value_and_grad(
                fwd, has_aux=True)(pvals)
            # optimizer update on trainable params only
            new_p, new_s = [], []
            for i, (p, g, s, tr) in enumerate(
                    zip(pvals, grads, opt_state, trainable)):
                if tr:
                    # salt the optimizer stream: fold_in(key, i) for small i
                    # coincides with split(key)[i], which is exactly what the
                    # staged forward's dropout chain consumes
                    pkey = jax.random.fold_in(
                        jax.random.fold_in(key, 0x6F707469), i) \
                        if fopt.needs_key else None
                    np_, ns_ = fopt.update(p, g, s, lr * lr_mults[i],
                                           t + 1, wd_base * wd_mults[i],
                                           key=pkey)
                    new_p.append(np_.astype(p.dtype))
                    new_s.append(ns_)
                else:
                    new_p.append(p)
                    new_s.append(s)
            # fold BatchNorm running-stat writes (identified at trace time)
            write_params = getattr(
                getattr(staged, "_inner", staged), "_write_params", [])
            if write_params:
                idx = {id(p): i for i, p in enumerate(param_objs)}
                for wp, wv in zip(write_params, writes):
                    i = idx.get(id(wp))
                    if i is not None:
                        new_p[i] = wv.astype(new_p[i].dtype)
            # the update counter lives ON DEVICE and advances inside the
            # step: feeding it from the host would cost one tiny transfer
            # (a full RPC when the chip is tunneled) every step
            return tuple(new_p), tuple(new_s), t + 1, loss

        donate = (0, 1, 4)
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            batch1 = NamedSharding(self.mesh, P(self.data_axis))
            # param shardings mirror _init_state
            if self._param_spec_fn is not None:
                pshard = tuple(NamedSharding(self.mesh,
                                             self._param_spec_fn(p) or P())
                               for p in self.param_list)
            else:
                pshard = tuple(rep for _ in self.param_list)
            sshard = tuple(
                tuple(ps if getattr(leaf, "shape", None)
                      == getattr(pv, "shape", None) else rep
                      for leaf in st) if st else ()
                for ps, st, pv in zip(pshard, self._opt_state, self._pvals))
            in_shardings = (pshard, sshard, batch1, batch1, rep, rep)
            # pin outputs to the same layout: without this GSPMD may pick a
            # different sharding for the updated params, forcing a reshard
            # of every parameter on every step's input boundary
            out_shardings = (pshard, sshard, rep, rep)
            self._step_jit = jax.jit(step_fn, donate_argnums=donate,
                                     in_shardings=in_shardings,
                                     out_shardings=out_shardings)
        else:
            self._step_jit = jax.jit(step_fn, donate_argnums=donate)

    # -- public ---------------------------------------------------------------
    def __call__(self, x, y):
        if self._pvals is None:
            # ensure deferred params are materialized (one eager fwd if needed)
            try:
                for p in self.param_list:
                    p._check_and_get()
            except Exception:
                import numpy as _np
                from .. import autograd as _ag
                xa = x._data if isinstance(x, NDArray) else jnp.asarray(x)
                xa1 = xa[:1]
                if self.preprocess is not None:
                    # the eager materialization forward must see the same
                    # dtype/layout the compiled step computes on
                    xa1 = self.preprocess(xa1)
                with _ag.train_mode():
                    self.net.forward(_wrap(xa1))
                self.param_list = self.net._get_param_list()
                self._trainable = [p.grad_req != "null"
                                   for p in self.param_list]
            self._init_state()
        if self._step_jit is None:
            self._build_step()
        xa = x._data if isinstance(x, NDArray) else jnp.asarray(x)
        ya = y._data if isinstance(y, NDArray) else jnp.asarray(y)
        if self.mesh is not None:
            batch = NamedSharding(self.mesh, P(self.data_axis))
            xa = jax.device_put(xa, batch)
            ya = jax.device_put(ya, batch)
        lr = self.lr if self.lr_schedule is None \
            else self.lr_schedule(self._num_update)
        # cache the lr device scalar (it changes rarely; shipping a fresh
        # host scalar per step costs a transfer round trip)
        if self._lr_cache is None or self._lr_cache[0] != lr:
            self._lr_cache = (lr, jnp.asarray(lr, jnp.float32))
        self._pvals, self._opt_state, self._t_dev, loss = self._step_jit(
            self._pvals, self._opt_state, xa, ya, self._t_dev,
            self._lr_cache[1])
        self._num_update += 1
        return _wrap(loss)

    def sync_params(self):
        """Write the step's parameter buffers back into the net's Parameters
        (copies — the step's own buffers get donated on the next call)."""
        import jax.numpy as jnp
        if self._pvals is None:
            return
        for p, v in zip(self.param_list, self._pvals):
            p._check_and_get()._data = jnp.array(v, copy=True)

    @property
    def num_update(self):
        return self._num_update
