"""Define-by-run autograd on top of ``jax.vjp``.

TPU-native rebuild of the reference's imperative autograd
(reference: src/imperative/imperative.cc:86-420, python/mxnet/autograd.py).

Design: the reference records an NNVM graph node per imperative op and runs an
NNVM ``Gradient`` pass at ``backward()`` time. Here every recorded op eagerly
captures its VJP closure via ``jax.vjp`` (XLA keeps residuals on device), and
``backward()`` is a reverse walk over the recorded tape. Leaves are NDArrays
with ``attach_grad()`` / ``mark_variables`` (reference: autograd.py:197).

Differences from the reference, by design:
- No NNVM pass: JAX's tracing is the graph IR.
- ``record()`` + hybridized blocks produce a *single* tape node whose VJP is
  the XLA-compiled backward of the whole block (reference analog: CachedOp
  backward, src/imperative/cached_op.cc:434).
- Higher-order gradients go through ``create_graph=True`` which re-records the
  backward ops (same contract as imperative.cc:331).
"""
from __future__ import annotations

import contextlib
import itertools
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

__all__ = [
    "record",
    "pause",
    "train_mode",
    "predict_mode",
    "is_recording",
    "is_training",
    "set_recording",
    "set_training",
    "mark_variables",
    "backward",
    "grad",
    "get_symbol",
    "Function",
]

_state = threading.local()
_node_counter = itertools.count()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
    return _state


def is_recording() -> bool:
    """Whether autograd recording is on (reference: autograd.py:86)."""
    return _st().recording


def is_training() -> bool:
    """Whether train-mode (dropout active etc.) is on (reference: autograd.py:93)."""
    return _st().training


def set_recording(is_rec: bool) -> bool:
    st = _st()
    prev, st.recording = st.recording, is_rec
    return prev


def set_training(train: bool) -> bool:
    st = _st()
    prev, st.training = st.training, train
    return prev


@contextlib.contextmanager
def record(train_mode: bool = True):
    """Record ops for autograd (reference: autograd.py:122)."""
    prev_r = set_recording(True)
    prev_t = set_training(train_mode)
    try:
        yield
    finally:
        set_recording(prev_r)
        set_training(prev_t)


@contextlib.contextmanager
def pause(train_mode: bool = False):
    """Stop recording inside a ``record()`` scope (reference: autograd.py:146)."""
    prev_r = set_recording(False)
    prev_t = set_training(train_mode)
    try:
        yield
    finally:
        set_recording(prev_r)
        set_training(prev_t)


@contextlib.contextmanager
def train_mode():
    prev_t = set_training(True)
    try:
        yield
    finally:
        set_training(prev_t)


@contextlib.contextmanager
def predict_mode():
    prev_t = set_training(False)
    try:
        yield
    finally:
        set_training(prev_t)


class TapeNode:
    """One recorded op: VJP closure + links to parent arrays.

    The analog of the reference's per-op NNVM node + ``AGInfo``
    (include/mxnet/imperative.h:59-95).

    ``fn`` (when present) is the pure jax function over the differentiable
    inputs — kept so ``grad(create_graph=True)`` can re-differentiate the
    backward (the reference records backward ops into the graph via
    Imperative::Backward's create_graph flag, imperative.cc:485).
    """

    __slots__ = ("seq", "vjp_fn", "parents", "n_out", "op_name", "outputs",
                 "fn")

    def __init__(self, vjp_fn, parents, n_out, op_name="", fn=None):
        self.seq = next(_node_counter)
        self.vjp_fn = vjp_fn
        self.parents = parents  # list of NDArray (the *differentiable* inputs)
        self.n_out = n_out
        self.op_name = op_name
        self.fn = fn
        self.outputs: List[Any] = []  # weak-ish: set by record_op


def record_op(op_name: str, fn: Callable, inputs: Sequence, raw_inputs: Sequence,
              out_arrays: Sequence):
    """Attach a tape node for an executed op.

    ``fn(*arrays) -> tuple(arrays)`` is the pure function over the
    differentiable inputs only; ``raw_inputs`` are the NDArray wrappers for
    those inputs (leaves or intermediates); ``out_arrays`` the output NDArrays.
    """
    primals = [x.data if hasattr(x, "data") else x for x in inputs]
    _, vjp_fn = jax.vjp(fn, *primals)
    node = TapeNode(vjp_fn, list(raw_inputs), len(out_arrays), op_name, fn=fn)
    for i, o in enumerate(out_arrays):
        o._node = node
        o._node_index = i
    node.outputs = list(out_arrays)
    return node


def mark_variables(variables, gradients, grad_reqs="write"):
    """Mark NDArrays as autograd leaves (reference: autograd.py:197)."""
    if not isinstance(variables, (list, tuple)):
        variables = [variables]
        gradients = [gradients]
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._require_grad = req != "null"


def _collect_graph(out_nodes):
    """Reachable tape nodes from the given outputs, reverse-topological by seq."""
    seen = {}
    stack = list(out_nodes)
    while stack:
        node = stack.pop()
        if node is None or node.seq in seen:
            continue
        seen[node.seq] = node
        for p in node.parents:
            pn = getattr(p, "_node", None)
            if pn is not None and pn.seq not in seen:
                stack.append(pn)
    return [seen[s] for s in sorted(seen, reverse=True)]


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    """Compute gradients of ``heads`` w.r.t. marked variables.

    Reference semantics: Imperative::Backward (src/imperative/imperative.cc:358)
    — default head gradient is ones; gradients accumulate into ``.grad``
    according to each leaf's ``grad_req`` ('write' overwrites, 'add'
    accumulates; src/executor docs for kAddTo).
    """
    from .ndarray.ndarray import NDArray, _wrap  # local import to avoid cycle

    _backward_seq[0] += 1
    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    # cotangent buffers keyed by (node.seq, out_index); leaf grads keyed by id
    cotangents: Dict[tuple, Any] = {}
    out_nodes = []
    for h, hg in zip(heads, head_grads):
        node = getattr(h, "_node", None)
        g = hg.data if hasattr(hg, "data") else (
            jnp.ones(h.shape, h.dtype) if hg is None else jnp.asarray(hg))
        if node is None:
            # head is itself a leaf
            if getattr(h, "_require_grad", False):
                _accumulate_leaf(h, g)
            continue
        key = (node.seq, h._node_index)
        cotangents[key] = cotangents.get(key, 0) + g
        out_nodes.append(node)

    for node in _collect_graph(out_nodes):
        cts = []
        any_ct = False
        for i, o in enumerate(node.outputs):
            ct = cotangents.pop((node.seq, i), None)
            if ct is None:
                ct = jnp.zeros(o.shape, o.dtype)
            else:
                any_ct = True
            cts.append(ct)
        if not any_ct:
            continue
        if node.vjp_fn is None:
            raise RuntimeError(
                f"backward through op '{node.op_name}' a second time, but its "
                "residuals were freed; call backward(retain_graph=True) the "
                "first time")
        in_grads = node.vjp_fn(tuple(cts))
        for p, g in zip(node.parents, in_grads):
            if g is None:
                continue
            pn = getattr(p, "_node", None)
            if pn is not None:
                key = (pn.seq, p._node_index)
                prev = cotangents.get(key)
                cotangents[key] = g if prev is None else prev + g
            if getattr(p, "_require_grad", False):
                _accumulate_leaf(p, g)
        if not retain_graph:
            node.vjp_fn = None  # free residuals


def _accumulate_leaf(leaf, g):
    req = getattr(leaf, "_grad_req", "write")
    if req == "null" or leaf._grad is None:
        return
    if getattr(g, "stype", "default") == "row_sparse":
        # sparse gradient (e.g. from sparse.dot): keep it sparse so the
        # optimizer's lazy_update row-scatter path can run
        # (reference: grad_stype='row_sparse', sparse.py / optimizer_op.cc)
        from .ndarray.sparse import RowSparseNDArray, add as _rsp_add
        accumulate = req == "add" or \
            getattr(leaf, "_grad_written_seq", None) == _backward_seq[0]
        prev = leaf._grad
        if accumulate and isinstance(prev, RowSparseNDArray):
            leaf._grad = _rsp_add(prev, g)
        elif accumulate:
            prev._data = prev._data + g.todense()._data
        else:
            leaf._grad = g.copy()
            leaf._grad_written_seq = _backward_seq[0]
        return
    g = jnp.asarray(g, leaf._grad.dtype)
    if getattr(leaf._grad, "stype", "default") != "default":
        # dense cotangent into a sparse grad buffer (e.g. the leaf also feeds
        # a dense op like an L2 penalty): fall back to a dense grad — the
        # reference's cast_storage fallback semantics
        from .ndarray.ndarray import _wrap as _wrap_nd
        accumulate = req == "add" or \
            getattr(leaf, "_grad_written_seq", None) == _backward_seq[0]
        prev = leaf._grad.todense()._data if accumulate else None
        leaf._grad = _wrap_nd(g if prev is None else prev + g)
        leaf._grad_written_seq = _backward_seq[0]
        return
    if req == "add":
        leaf._grad._data = leaf._grad._data + g
    else:  # write — but within one backward pass multiple paths accumulate
        if getattr(leaf, "_grad_written_seq", None) == _backward_seq[0]:
            leaf._grad._data = leaf._grad._data + g
        else:
            leaf._grad._data = g
            leaf._grad_written_seq = _backward_seq[0]


_backward_seq = [0]


def _backward_graph(heads, head_grads, variables, train_mode=True):
    """Backward pass that RECORDS itself: every VJP application runs as a
    taped eager op (vjp-of-vjp via jax), so the returned gradients are
    differentiable again — true ``create_graph=True`` semantics (reference:
    Imperative::Backward with create_graph, src/imperative/imperative.cc:485,
    exposed through autograd.grad's create_graph flag, autograd.py:270).

    Returns a list of NDArray gradients aligned with ``variables`` (new
    arrays; ``.grad`` buffers are not touched — reference docstring: grads
    are "returned as new NDArrays instead of stored into variable.grad").
    """
    from .ndarray.ndarray import NDArray, _wrap, _invoke_fn

    if isinstance(heads, NDArray):
        heads = [heads]
        if head_grads is not None and not isinstance(head_grads, (list, tuple)):
            head_grads = [head_grads]
    if head_grads is None:
        head_grads = [None] * len(heads)

    var_idx = {id(v): i for i, v in enumerate(variables)}
    leaf_grads: Dict[int, Any] = {}
    cotangents: Dict[tuple, Any] = {}
    out_nodes = []

    def accum(store, key, g):
        prev = store.get(key)
        store[key] = g if prev is None else prev + g  # recorded add

    with record(train_mode=train_mode):
        for h, hg in zip(heads, head_grads):
            g = hg if isinstance(hg, NDArray) else _wrap(
                jnp.ones(h.shape, h.dtype) if hg is None
                else jnp.asarray(hg))
            node = getattr(h, "_node", None)
            if node is None:
                if id(h) in var_idx:
                    accum(leaf_grads, id(h), g)
                continue
            accum(cotangents, (node.seq, h._node_index), g)
            out_nodes.append(node)

        for node in _collect_graph(out_nodes):
            cts, any_ct = [], False
            for i, o in enumerate(node.outputs):
                ct = cotangents.pop((node.seq, i), None)
                if ct is None:
                    ct = _wrap(jnp.zeros(o.shape, o.dtype))
                else:
                    any_ct = True
                cts.append(ct)
            if not any_ct:
                continue
            if node.fn is None:
                raise RuntimeError(
                    f"create_graph=True through op '{node.op_name}': this op "
                    "does not support higher-order gradients (no stored "
                    "forward; the reference has the same restriction for ops "
                    "without backward-of-backward definitions)")
            nparents = len(node.parents)
            fwd = node.fn

            def bwd(*args, _fwd=fwd, _np=nparents):
                prim, cts_ = args[:_np], args[_np:]
                _, vjp = jax.vjp(_fwd, *prim)
                return tuple(vjp(tuple(cts_)))

            res = _invoke_fn(f"_backward_{node.op_name}", bwd,
                             list(node.parents) + cts)
            if not isinstance(res, tuple):
                res = (res,)
            for p, g in zip(node.parents, res):
                if g is None:
                    continue
                pn = getattr(p, "_node", None)
                if pn is not None:
                    accum(cotangents, (pn.seq, p._node_index), g)
                if id(p) in var_idx:
                    accum(leaf_grads, id(p), g)

    return [leaf_grads.get(id(v)) if leaf_grads.get(id(v)) is not None
            else _wrap(jnp.zeros(v.shape, v.dtype))
            for v in variables]


def grad(heads, variables, head_grads=None, retain_graph=None, create_graph=False,
         train_mode=True):
    """Return gradients of heads w.r.t. variables (reference: autograd.py:270).

    ``create_graph=True`` records the backward pass itself onto the tape
    (see ``_backward_graph``), so the returned grads support ``.backward()``
    / further ``grad()`` calls to arbitrary order.
    """
    from .ndarray.ndarray import NDArray, _wrap

    single = isinstance(variables, NDArray)
    if single:
        variables = [variables]
    if create_graph:
        grads = _backward_graph(heads, head_grads, variables,
                                train_mode=train_mode)
        return grads[0] if single else grads
    saved = [(getattr(v, "_grad", None), getattr(v, "_grad_req", "null"),
              getattr(v, "_require_grad", False)) for v in variables]
    for v in variables:
        v._grad = _wrap(jnp.zeros(v.shape, v.dtype), v.context)
        v._grad_req = "add"
        v._require_grad = True
    try:
        backward(heads, head_grads, retain_graph=bool(retain_graph) or create_graph,
                 train_mode=train_mode)
        grads = [v._grad for v in variables]
    finally:
        for v, (g, req, rg) in zip(variables, saved):
            v._grad, v._grad_req, v._require_grad = g, req, rg
    return grads[0] if single else grads


def get_symbol(x):  # pragma: no cover - compat
    """Reference API (autograd.py:304) returns the recorded symbol; here the
    recorded program is a tape of XLA computations, not a serializable symbol."""
    raise NotImplementedError(
        "get_symbol: recorded graphs are XLA computations in mxnet_tpu; "
        "use hybridize()/Symbol for serializable graphs")


class Function:
    """Customized differentiable function (reference: autograd.py:364).

    Subclass and override ``forward`` and ``backward``. Both run eagerly on
    NDArrays; the backward is registered on the tape as an opaque VJP.
    """

    def __init__(self):
        self._used = False

    def forward(self, *inputs):
        raise NotImplementedError

    def backward(self, *output_grads):
        raise NotImplementedError

    def save_for_backward(self, *args):
        self._saved = args

    @property
    def saved_tensors(self):
        return getattr(self, "_saved", ())

    def __call__(self, *inputs):
        from .ndarray.ndarray import NDArray, _wrap

        with pause():
            outputs = self.forward(*inputs)
        single = not isinstance(outputs, (list, tuple))
        outs = [outputs] if single else list(outputs)
        if is_recording():
            func = self

            class _CustomNode(TapeNode):
                pass

            def _vjp(cts):
                cts = cts if isinstance(cts, tuple) else (cts,)
                with pause():
                    gs = func.backward(*[_wrap(c) for c in cts])
                if not isinstance(gs, (list, tuple)):
                    gs = [gs]
                return [g.data if hasattr(g, "data") else g for g in gs]

            node = TapeNode(_vjp, [x for x in inputs if isinstance(x, NDArray)],
                            len(outs), type(self).__name__)
            for i, o in enumerate(outs):
                o._node = node
                o._node_index = i
            node.outputs = outs
        return outs[0] if single else outs
