"""Predictor: a frozen, bucketed, compiled inference program.

The reference's C Predict API (c_predict_api.cc) freezes symbol+params
and binds one executor per input shape; BucketingModule shares params
across per-bucket executors. This class is both at once, TPU-native:
ONE jitted inference function whose XLA cache is keyed by the padded
batch bucket, parameters staged on device once (optionally cast to
bf16), the ``MXTPU_PALLAS_FUSION`` graph rewrite applied to the predict
program, and the request's (donated) input buffer the only per-call
host↔device traffic.

Bucketing: arbitrary request sizes pad up to the nearest configured
bucket, so the set of compiled programs is small and fixed — a mixed
stream of request sizes compiles each bucket exactly once
(``retraces`` counts actual traces; tests pin it). Oversized inputs
split into largest-bucket chunks.
"""
from __future__ import annotations

import threading

import numpy as np

from .. import config
from ..base import MXNetError
from . import _register_predictor

__all__ = ["Predictor", "default_buckets"]


def default_buckets():
    """Bucket set from MXTPU_SERVING_BUCKETS (ascending, deduped)."""
    raw = str(config.get("MXTPU_SERVING_BUCKETS", "1,8,64"))
    try:
        buckets = sorted({int(x) for x in raw.replace(" ", "").split(",")
                          if x})
    except ValueError:
        raise MXNetError(
            f"MXTPU_SERVING_BUCKETS={raw!r} is not a comma-separated "
            "integer list")
    if not buckets or buckets[0] < 1:
        raise MXNetError(
            f"MXTPU_SERVING_BUCKETS={raw!r} must name positive batch "
            "sizes")
    return tuple(buckets)


class Predictor:
    """Inference-only compiled program over a frozen symbol+params.

    Parameters
    ----------
    symbol : Symbol
        The model graph (output heads as trained; SoftmaxOutput & co
        evaluate in inference mode — no labels consumed).
    arg_params / aux_params : dict name -> NDArray (or array)
        Trained parameter/aux values; staged on device once.
    data_names : tuple of str
        Input argument names fed per request (everything else in
        ``list_arguments`` must be in the params or is zero-filled —
        e.g. a ``softmax_label`` head argument).
    data_shapes : dict name -> per-row feature shape (no batch dim)
        Required for every data name; buckets supply the batch dim.
    buckets : tuple of int, optional
        Ascending batch buckets (default: MXTPU_SERVING_BUCKETS).
    compute_dtype : str/dtype, optional
        e.g. "bfloat16": float32 params are cast ONCE at staging and
        inputs in-program; outputs return float32.
    apply_fusion : bool, optional
        Force the MXTPU_PALLAS_FUSION predict-program rewrite on/off
        (default: the flag's own resolution).
    """

    def __init__(self, symbol, arg_params, aux_params=None,
                 data_names=("data",), data_shapes=None, buckets=None,
                 compute_dtype=None, apply_fusion=None):
        import jax
        import jax.numpy as jnp

        self.symbol = symbol
        self.data_names = list(data_names)
        self.buckets = tuple(sorted(set(buckets))) if buckets \
            else default_buckets()
        if data_shapes is None:
            raise MXNetError(
                "Predictor needs data_shapes={name: per-row feature "
                "shape} — the batch dim comes from the buckets")
        self.data_shapes = {n: tuple(s) for n, s in data_shapes.items()}
        for n in self.data_names:
            if n not in self.data_shapes:
                raise MXNetError(f"data_shapes missing entry for '{n}'")
        self._cdt = jnp.dtype(compute_dtype) \
            if compute_dtype is not None else None

        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        aux_params = aux_params or {}
        self.param_names = [n for n in arg_names
                            if n not in self.data_names]
        self.output_names = symbol.list_outputs()

        # infer the full argument/output shape sets at TWO batch sizes:
        # comparing them identifies what actually TRACKS the batch —
        # which non-param args are label-head inputs to zero-fill per
        # bucket, and which outputs carry a batch axis to trim/split
        # (a coincidental leading dim equal to the bucket must not
        # count: a conv weight with num_filter == bucket is a missing
        # PARAM, and a fixed-shape aux output must never be sliced).
        # The largest-bucket shapes also feed the fusion pass's tile
        # bail-outs (batch-independent, so one bucket suffices).
        top = self.buckets[-1]

        def _infer(b):
            shape_kwargs = {n: (b,) + self.data_shapes[n]
                            for n in self.data_names}
            a, o, x = symbol.infer_shape(**shape_kwargs)
            return (dict(zip(arg_names, a)), list(o),
                    dict(zip(aux_names, x)))

        arg_shape_map, out_shapes, aux_shape_map = _infer(top)
        arg_alt, out_alt, _ = _infer(top + 1)

        def _tracks_batch(s_top, s_alt, b_top=top):
            return bool(s_top) and s_top[0] == b_top \
                and s_alt[0] == b_top + 1

        self.out_batched = [_tracks_batch(s, sa)
                            for s, sa in zip(out_shapes, out_alt)]

        # non-param, non-data args whose leading dim tracks the batch
        # (e.g. a softmax_label head argument, unused in inference) are
        # zero-filled per bucket; everything else must come from params
        self._zero_args = []
        missing = []
        for n in self.param_names:
            if n in arg_params:
                continue
            if _tracks_batch(arg_shape_map[n], arg_alt[n]):
                self._zero_args.append(n)
            else:
                missing.append(n)
        if missing:
            raise MXNetError(f"Predictor missing parameters {missing}")
        for n in aux_names:
            if n not in aux_params:
                raise MXNetError(f"Predictor missing aux state '{n}'")

        self._arg_shape_map = arg_shape_map
        self._aux_shape_map = aux_shape_map
        self._aux_names = aux_names
        self._pvals = {n: self._stage_value(arg_params[n],
                                            arg_shape_map[n], n)
                       for n in self.param_names
                       if n not in self._zero_args}

        # predict-program rewrite pipeline (symbol/passes/): the same
        # fusion rewrites the train step gets, plus the serving-only BN
        # constant-fold — eval-mode moving stats are constants, so
        # matched Conv→BN BatchNorms disappear from the compiled
        # predict program entirely. ``apply_fusion`` forces the pallas
        # pass on/off; the other passes follow their MXTPU_PASS_*
        # flags. Applicability uses the largest-bucket bound shapes.
        import contextlib
        run_sym = symbol
        self.fusion_report = None
        self.pass_report = None
        from ..symbol import passes as _passes
        shapes = dict(arg_shape_map)
        shapes.update(aux_shape_map)
        force = contextlib.nullcontext()
        if apply_fusion is not None:
            force = config.override("MXTPU_PALLAS_FUSION",
                                    "1" if apply_fusion else "0")
        with force:
            fused_sym, self.pass_report = _passes.apply_pipeline(
                symbol, {n: tuple(s) for n, s in shapes.items()},
                tag="predictor", mode="serving",
                compute_dtype=self._cdt,
                data_names=set(self.data_names) | set(self._zero_args))
        self.fusion_report = _passes.legacy_fusion_entry(
            self.pass_report)
        self._passes_material = _passes.pipeline_key_material(
            self.pass_report)
        if fused_sym is not None:
            run_sym = fused_sym

        from .. import compile as compile_mod
        from ..symbol.passes import hoist as _hoist
        run_arg_names = run_sym.list_arguments()
        run_aux_names = run_sym.list_auxiliary_states()
        self._arg_names = arg_names
        key = jax.random.PRNGKey(0)
        cdt = self._cdt
        zero_args = set(self._zero_args)
        # parameter-expression hoisting (symbol/passes/hoist.py): a
        # rewrite pass may leave weight-sized arithmetic in the graph
        # (the BN fold's w·s, a bf16 weight cast). Frozen params make
        # those subgraphs constants, so evaluate them ONCE here and
        # feed the results as precomputed program arguments — the
        # serving program reads the folded weight directly and the BN
        # (plus its four parameter vectors) vanishes from the compiled
        # program's byte traffic, not just its op count.
        hoist_keys, live_vars = _hoist.hoist_plan(
            run_sym, set(self.data_names) | zero_args)
        staged_aux = {n: self._stage_value(aux_params[n],
                                           aux_shape_map[n], n)
                      for n in aux_names}
        if hoist_keys:
            amap = dict(self._pvals)
            amap.update(staged_aux)
            self._hvals = tuple(
                jax.device_put(v)
                for v in _hoist.hoist_values(run_sym, hoist_keys, amap))
        else:
            self._hvals = ()
        hoist_ids = [(id(n), i) for n, i in hoist_keys]
        # parameters are explicit ARGUMENTS of the compiled program (in
        # the traced graph's arg order), not closure constants: baked-in
        # values would bloat every executable with the full weight set
        # and — worse — let a persistent-cache hit replay stale weights.
        # As arguments (hoisted values included: they recompute from the
        # current params at staging), the executable is
        # weight-independent and the program key only covers
        # shapes/dtypes.
        self._pval_names = [n for n in run_arg_names
                            if n in self._pvals and n in live_vars]
        self._pvals_t = tuple(self._pvals[n] for n in self._pval_names)
        pval_names = list(self._pval_names)
        live_aux_names = [n for n in run_aux_names if n in live_vars]
        self._avals = tuple(staged_aux[n] for n in live_aux_names)
        # restage() needs the staging plan after __init__: which symbol
        # actually runs, which param expressions were hoisted, and
        # which aux names the program consumes
        self._run_sym = run_sym
        self._hoist_keys = hoist_keys
        self._live_aux_names = live_aux_names

        def infer_fn(pvals_t, data_vals, avals, hvals):
            amap = dict(zip(pval_names, pvals_t))
            amap.update(zip(live_aux_names, avals))
            bsz = data_vals[0].shape[0]
            for n, v in zip(self.data_names, data_vals):
                if cdt is not None and v.dtype == jnp.float32:
                    v = v.astype(cdt)
                amap[n] = v
            for n in zero_args:
                s = (bsz,) + tuple(arg_shape_map[n][1:])
                amap[n] = jnp.zeros(s, jnp.float32)
            outs, _ = run_sym.eval_arrays_ex(
                amap, training=False, rng_key=key,
                preset=dict(zip(hoist_ids, hvals)))
            return tuple(o.astype(jnp.float32)
                         if cdt is not None and o.dtype == cdt else o
                         for o in outs)

        # donate the request buffers: they are fresh padded arrays each
        # call, so XLA may reuse them for outputs (donation_supported is
        # the compile subsystem's one home for the CPU-can't-donate
        # policy — the old per-Predictor workaround for the per-compile
        # backend warning)
        donate = {"donate_argnums": (1,)} \
            if compile_mod.donation_supported() else {}
        self._infer_jit = jax.jit(infer_fn, **donate)
        self._donate = bool(donate)
        self._programs = {}     # (bucket, dtypes) -> compiled program
        self._program_costs = {}  # (bucket, dtypes) -> XLA cost dict
        self._program_exes = {}   # (bucket, dtypes) -> raw executable
        self._program_memory = {}  # (bucket, dtypes) -> memory dict
        self._materialized = 0  # fresh traces taken BY this instance
        self._cache_loads = 0   # bucket programs AOT-loaded from disk
        self._faulted = False   # replica_drop fired: permanently dead
        self._lock = threading.Lock()
        # per-bucket counters: calls, rows served, pad rows wasted
        self._bucket_calls = {b: 0 for b in self.buckets}
        self._bucket_rows = {b: 0 for b in self.buckets}
        self._bucket_pad_rows = {b: 0 for b in self.buckets}
        _register_predictor(self)

    # -- construction helpers -------------------------------------------------
    @classmethod
    def from_module(cls, module, **kwargs):
        """Freeze a trained (bound+initialized) Module. Data feature
        shapes come from the module's bound data_shapes; params are
        synced from device."""
        arg_params, aux_params = module.get_params()
        kwargs.setdefault("data_names", list(module.data_names))
        kwargs.setdefault("data_shapes", {
            n: tuple(s[1:]) for n, s in module.data_shapes})
        return cls(module.symbol, arg_params, aux_params, **kwargs)

    # -- parameter staging ----------------------------------------------------
    def _stage_value(self, v, want_shape, name):
        """Shape-check one param/aux value and put it on device (cast
        to the compute dtype when configured) — the single staging rule
        __init__ and restage share."""
        import jax
        import jax.numpy as jnp
        a = np.asarray(getattr(v, "_data", getattr(v, "data", v)))
        if tuple(a.shape) != tuple(want_shape):
            raise MXNetError(
                f"Predictor param '{name}' has shape {a.shape}, "
                f"inferred {tuple(want_shape)}")
        x = jnp.asarray(a)
        if self._cdt is not None and x.dtype == jnp.float32:
            x = x.astype(self._cdt)
        return jax.device_put(x)

    def restage(self, arg_params, aux_params=None):
        """Swap in a new checkpoint's parameter values WITHOUT touching
        the compiled programs (the weight-hot-swap primitive,
        ``FleetRouter.swap_weights`` drives it replica-by-replica).

        Parameters are program *arguments* — the program key covers
        shapes/dtypes/passes, never values — so staging new values and
        recomputing the hoisted parameter expressions is the complete
        swap: zero retraces, and the next micro-batch computes exactly
        what a fresh Predictor on the new checkpoint would. Staging and
        hoist evaluation happen OUTSIDE the run lock; the final pointer
        swap takes it, so an in-flight micro-batch finishes on the old
        weights and the swap is atomic per micro-batch."""
        import jax
        aux_params = aux_params or {}
        missing = [n for n in self.param_names
                   if n not in self._zero_args and n not in arg_params]
        if missing:
            raise MXNetError(f"restage missing parameters {missing}")
        for n in self._aux_names:
            if n not in aux_params:
                raise MXNetError(f"restage missing aux state '{n}'")
        new_pvals = {n: self._stage_value(arg_params[n],
                                          self._arg_shape_map[n], n)
                     for n in self.param_names
                     if n not in self._zero_args}
        new_aux = {n: self._stage_value(aux_params[n],
                                        self._aux_shape_map[n], n)
                   for n in self._aux_names}
        if self._hoist_keys:
            from ..symbol.passes import hoist as _hoist
            amap = dict(new_pvals)
            amap.update(new_aux)
            new_hvals = tuple(
                jax.device_put(v) for v in _hoist.hoist_values(
                    self._run_sym, self._hoist_keys, amap))
        else:
            new_hvals = ()
        with self._lock:
            self._pvals = new_pvals
            self._pvals_t = tuple(new_pvals[n]
                                  for n in self._pval_names)
            self._avals = tuple(new_aux[n]
                                for n in self._live_aux_names)
            self._hvals = new_hvals

    # -- bucketing ------------------------------------------------------------
    @property
    def max_batch(self):
        return self.buckets[-1]

    def bucket_for(self, n):
        """Smallest bucket >= n, or the largest bucket (callers chunk)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    @property
    def retraces(self):
        """Number of XLA traces this predictor took (compile-registry
        accounting) — at most one per bucket after warmup, tests pin
        this; ZERO when every bucket program AOT-loaded from a warm
        ``MXTPU_COMPILE_CACHE_DIR``."""
        return self._materialized

    # -- compile registry / AOT cache (compile/ package) ----------------------
    def _program_key(self, bucket, dtypes):
        from .. import compile as compile_mod
        from .. import config as _config
        if not hasattr(self, "_symbol_sha"):
            self._symbol_sha = compile_mod.symbol_digest(self.symbol)
        sigs = tuple(
            (n, (bucket,) + tuple(self.data_shapes[n]), dt)
            for n, dt in zip(self.data_names, dtypes))
        fusion = {"flag": str(_config.get("MXTPU_PALLAS_FUSION")),
                  "sites": len(self.fusion_report["sites"])
                  if self.fusion_report else 0}
        extra = {
            "compute_dtype": str(self._cdt),
            "donate": self._donate,
            "zero_args": sorted(self._zero_args),
            "hoisted": len(self._hvals),
        }
        return compile_mod.program_key(
            "predictor", f"predictor:{self.symbol.name}:b{bucket}",
            symbol_sha=self._symbol_sha, input_sigs=sigs, fusion=fusion,
            passes=self._passes_material, extra=extra)

    def _acquire_program(self, bucket, args):
        """One compiled program per (bucket, request dtypes), acquired
        through the compile registry: a warm persistent cache turns
        warmup's per-bucket compile storm into file loads. Failures of
        the AOT machinery degrade to the plain jit."""
        from .. import compile as compile_mod
        dtypes = tuple(str(a.dtype) for a in args[1])
        try:
            key = self._program_key(bucket, dtypes)
            exe, source = compile_mod.load_or_compile(
                key, lambda: self._infer_jit.lower(*args))
            compile_mod.note_entry_point(
                key.name, key, compile_mod.arg_signature(args[1]))
        except Exception as e:
            import logging
            logging.getLogger("mxnet_tpu.compile").warning(
                "predictor AOT compile path failed (%s); using the "
                "plain jit", e)
            from .. import fault as _fault
            _fault.count("compile.aot_fallback")
            self._materialized += 1
            return self._infer_jit
        self._note_cost(bucket, dtypes, exe)
        if source == "cache":
            self._cache_loads += 1
            jit_fn = self._infer_jit

            def _reject():
                self._programs[(bucket, dtypes)] = jit_fn
                self._materialized += 1
            return compile_mod.guarded_loaded_program(
                exe, jit_fn, "predictor", on_reject=_reject)
        self._materialized += 1
        return exe

    def _note_cost(self, bucket, dtypes, exe):
        """Record XLA cost analysis of an acquired bucket program
        (bytes accessed is the serving-program currency too: the BN
        constant-fold exists to shrink it), and of its memory analysis
        (telemetry.memory — per-bucket HBM next to the cost record).
        Best-effort — some backends/AOT loads expose none."""
        self._program_exes[(bucket, dtypes)] = exe
        try:
            cost = exe.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            self._program_costs[(bucket, dtypes)] = dict(cost) \
                if cost else {}
        except Exception:
            self._program_costs[(bucket, dtypes)] = {}
        try:
            from ..telemetry import memory as _tmem
            self._program_memory[(bucket, dtypes)] = _tmem.analyze(exe)
        except Exception:
            self._program_memory[(bucket, dtypes)] = {}

    def program_cost(self, bucket=None):
        """XLA cost dict of one bucket's compiled program (largest
        bucket by default; {} when not yet materialized or
        unavailable). bench.py pins the BN-folded serving program's
        bytes-accessed strictly below the unfolded one through here."""
        b = self.buckets[-1] if bucket is None else bucket
        for (bk, _dt), cost in self._program_costs.items():
            if bk == b and cost:
                return dict(cost)
        return {}

    def program_memory(self, bucket=None):
        """``memory_analysis()`` dict of one bucket's compiled program
        (largest bucket by default; {} when not yet materialized or the
        backend exposes none) — recorded at acquisition, same rule as
        :meth:`program_cost`: never a second compile."""
        b = self.buckets[-1] if bucket is None else bucket
        for (bk, _dt), mem in self._program_memory.items():
            if bk == b and mem:
                return dict(mem)
        return {}

    # -- execution ------------------------------------------------------------
    def _run_bucket(self, arrays, rows, bucket):
        """Pad name-ordered request arrays to ``bucket`` rows and run
        the compiled program. Returns trimmed numpy outputs."""
        import jax.numpy as jnp
        from .. import faultinject
        # ``replica_drop``: the serving-replica loss drill. ``call=N``
        # (or ``replica=<telemetry id>``) picks the victim micro-batch;
        # ``action=kill`` SIGKILLs the process, ``action=sleep:ms=N``
        # stretches the batch (the straggler-replica drill), and a
        # plain raise marks THIS replica permanently dead — an
        # in-process stand-in for a killed replica the FleetRouter must
        # drain and replace without dropping a request.
        if faultinject.fire("replica_drop", replica=self.telemetry_id):
            if (faultinject.active("replica_drop") or
                    {}).get("action") != "sleep":
                self._faulted = True
                raise faultinject.FaultInjected(
                    "replica_drop", replica=self.telemetry_id)
        if self._faulted:
            raise MXNetError(
                f"predictor {self.telemetry_id} is dead (replica_drop)")
        padded = []
        for a in arrays:
            if rows != bucket:
                pad = np.zeros((bucket - rows,) + a.shape[1:], a.dtype)
                a = np.concatenate([a, pad], axis=0)
            padded.append(jnp.asarray(a))
        from ..telemetry import trace as _trace
        with self._lock, _trace.span(
                f"serving:bucket{bucket}", cat="serving",
                args={"predictor": self.telemetry_id, "rows": rows,
                      "pad_rows": bucket - rows}):
            args = (self._pvals_t, tuple(padded), self._avals,
                    self._hvals)
            pkey = (bucket, tuple(str(a.dtype) for a in padded))
            fn = self._programs.get(pkey)
            if fn is None:
                fn = self._acquire_program(bucket, args)
                self._programs[pkey] = fn
            outs = fn(*args)
            self._bucket_calls[bucket] += 1
            self._bucket_rows[bucket] += rows
            self._bucket_pad_rows[bucket] += bucket - rows
        return [np.asarray(o)[:rows] if batched else np.asarray(o)
                for o, batched in zip(outs, self.out_batched)]

    def normalize_request(self, data):
        """Validate one request and return ``(arrays, rows)``: numpy
        arrays ordered by ``data_names``. The single input-contract
        check shared by ``predict`` and ``DynamicBatcher.submit`` —
        the two serving surfaces must reject identically."""
        if not isinstance(data, dict):
            data = {self.data_names[0]: data}
        arrays = []
        for n in self.data_names:
            if n not in data:
                raise MXNetError(f"request missing data input '{n}'")
            a = np.asarray(getattr(data[n], "_data", data[n]))
            if tuple(a.shape[1:]) != self.data_shapes[n]:
                raise MXNetError(
                    f"request input '{n}' rows have shape "
                    f"{tuple(a.shape[1:])}, expected "
                    f"{self.data_shapes[n]}")
            arrays.append(a)
        n_rows = arrays[0].shape[0]
        if n_rows < 1:
            raise MXNetError("got an empty (0-row) request")
        if any(a.shape[0] != n_rows for a in arrays):
            raise MXNetError("request inputs disagree on batch size")
        return arrays, n_rows

    def predict(self, data):
        """Run inference on one request. ``data``: array (single data
        input) or dict name -> array, any leading batch size; oversized
        requests chunk through the largest bucket. Returns one numpy
        array (single output) or a list — same shape contract as
        ``DynamicBatcher.predict``."""
        arrays, n_rows = self.normalize_request(data)
        chunks = []
        start = 0
        while start < n_rows:
            rows = min(n_rows - start, self.max_batch)
            bucket = self.bucket_for(rows)
            chunks.append(self._run_bucket(
                [a[start:start + rows] for a in arrays], rows, bucket))
            start += rows
        if len(chunks) == 1:
            outs = chunks[0]
        else:
            outs = [np.concatenate([c[i] for c in chunks], axis=0)
                    if batched else chunks[0][i]
                    for i, batched in enumerate(self.out_batched)]
        return outs[0] if len(outs) == 1 else outs

    def warmup(self):
        """Materialize every bucket program up front (serving must not
        pay a trace on a live request): AOT-loaded from the persistent
        compile cache when a valid entry exists (``compile::load``
        spans), freshly compiled otherwise (``compile::compile`` spans
        — warmup cost is visible in ``mx.profiler`` dumps either way).
        Returns the retrace (fresh trace) count — 0 on a warm cache."""
        for b in self.buckets:
            arrays = [np.zeros((b,) + self.data_shapes[n], np.float32)
                      for n in self.data_names]
            self._run_bucket(arrays, b, b)
        return self.retraces

    # -- observability --------------------------------------------------------
    def report(self, reset=False):
        with self._lock:
            out = {
                "id": self.telemetry_id,
                "buckets": list(self.buckets),
                "retraces": self._materialized,
                "compile_cache_loads": self._cache_loads,
                "faulted": self._faulted,
                "per_bucket": {
                    b: {"calls": self._bucket_calls[b],
                        "rows": self._bucket_rows[b],
                        "pad_rows": self._bucket_pad_rows[b]}
                    for b in self.buckets},
                "fused_sites": len(self.fusion_report["sites"])
                if self.fusion_report else 0,
                "pass_sites": {
                    e["pass"]: len(e["sites"])
                    for e in (self.pass_report or {}).get("passes", ())
                    if e["status"] == "applied"},
                "bytes_accessed": float(self.program_cost().get(
                    "bytes accessed", 0.0)) or None,
                "compute_dtype": str(self._cdt) if self._cdt else None,
            }
            if reset:
                for b in self.buckets:
                    self._bucket_calls[b] = 0
                    self._bucket_rows[b] = 0
                    self._bucket_pad_rows[b] = 0
        return out
