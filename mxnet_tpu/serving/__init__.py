"""Inference serving: bucketed compiled predictors + dynamic batching.

The training subsystems (fused Module step, fusion pass, device metrics)
make the hot TRAINING loop one XLA program; this package does the same
for serving. The reference's inference story was ``Module.predict``'s
eager per-batch loop plus the C Predict API (reference:
src/c_api/c_predict_api.cc — frozen symbol + params, one executor per
input shape); TVM's deployment stack showed that ahead-of-time compiled,
cached artifacts are what serving throughput actually comes from. Here:

- ``Predictor`` (predictor.py) freezes a trained Module/Symbol into an
  inference-only jitted program — params staged on device once, the
  ``MXTPU_PALLAS_FUSION`` graph rewrite applied to the predict program,
  optional bf16 compute, donated input buffers — behind a
  shape-bucketed compile cache: requests pad to a small set of batch
  buckets (bucketing_module-style), so arbitrary request sizes never
  retrace.
- ``DynamicBatcher`` (batcher.py) coalesces concurrent requests into
  bucket-sized micro-batches (``max_batch``/``max_wait_us``), splits
  results back per request, enforces per-request deadlines, and sheds
  load past a queue bound with an explicit ``Overloaded`` error instead
  of hanging.
- ``serving_report()`` aggregates per-bucket latency percentiles, queue
  depth, batch occupancy, and retrace counters from every live
  Predictor/DynamicBatcher; the same spans also feed the
  ``mxnet_tpu.profiler`` aggregate table under the ``serving`` domain.

Knobs default from ``MXTPU_SERVING_*`` env vars (mxnet_tpu/config.py,
docs/faq/env_var.md).
"""
from __future__ import annotations

import weakref

from ..base import MXNetError

__all__ = ["Predictor", "DynamicBatcher", "FleetRouter", "TenantSpec",
           "FleetAutoscaler", "ServingError", "Overloaded",
           "DeadlineExceeded", "Cancelled", "serving_report", "decode"]


class ServingError(MXNetError):
    """Base class for serving-path failures."""


class Overloaded(ServingError):
    """Request rejected at admission: the batcher queue is at its bound.

    Load-shedding semantics: raised IMMEDIATELY at submit() — an
    overloaded server must fail fast so the client can back off or
    retry elsewhere, never queue unboundedly or hang."""


class DeadlineExceeded(ServingError):
    """The request's deadline expired before its micro-batch ran."""


class Cancelled(ServingError):
    """The server stopped while this request was in flight.

    Decode serving introduced *partial* in-flight work (a generation
    mid-stream when ``stop(drain=False)`` lands): already-streamed
    tokens stay delivered, the stream then terminates with this error —
    a future is always completed, never left hanging."""


# live Predictor/DynamicBatcher instances; serving_report() walks these.
# WeakSets so a dropped server never pins device buffers. Every
# instance gets a stable process-unique id at registration (fleet
# readiness: two Predictor replicas in one process must never merge
# into an anonymous pool — ROADMAP item 3's router aggregates
# per-replica by this id).
import itertools as _itertools

_PREDICTORS: "weakref.WeakSet" = weakref.WeakSet()
_BATCHERS: "weakref.WeakSet" = weakref.WeakSet()
_DECODERS: "weakref.WeakSet" = weakref.WeakSet()
_ROUTERS: "weakref.WeakSet" = weakref.WeakSet()
_PRED_SEQ = _itertools.count()
_BATCH_SEQ = _itertools.count()
_DECODE_SEQ = _itertools.count()
_ROUTER_SEQ = _itertools.count()


def _register_predictor(p):
    p.telemetry_id = f"{p.symbol.name or 'predictor'}#{next(_PRED_SEQ)}"
    _PREDICTORS.add(p)
    # the id is process-unique, so every serving::<id>::… registry
    # series belongs to exactly this replica — drop them when it dies,
    # or replica churn (model reloads) grows the registry and every
    # report/scrape without bound
    from ..telemetry import registry as treg
    weakref.finalize(p, treg.remove, f"serving::{p.telemetry_id}::")


def _register_batcher(b):
    b.telemetry_id = f"{b.name}#{next(_BATCH_SEQ)}"
    _BATCHERS.add(b)


def _register_decoder(d):
    """DecodePredictor registration (serving/decode/engine.py): same
    stable-id + registry-cleanup contract as predictors, separate
    report section — decode programs count tokens and KV-cache bytes,
    not padded rows."""
    d.telemetry_id = f"{d.name or 'decode'}#{next(_DECODE_SEQ)}"
    _DECODERS.add(d)
    from ..telemetry import registry as treg
    weakref.finalize(d, treg.remove, f"serving::{d.telemetry_id}::")


def _register_router(r):
    """FleetRouter registration (serving/fleet.py): stable id + cleanup
    of its ``fleet::<id>::…`` registry series when the router dies."""
    r.telemetry_id = f"{r.name or 'fleet'}#{next(_ROUTER_SEQ)}"
    _ROUTERS.add(r)
    from ..telemetry import registry as treg
    weakref.finalize(r, treg.remove, f"fleet::{r.telemetry_id}::")


def _collect(reset: bool = False) -> dict:
    """Aggregate serving observability: one entry per live Predictor
    (per-bucket compile/call/pad counters, retraces) and per live
    DynamicBatcher (per-bucket p50/p99 latency, queue depth, batch
    occupancy, shed/deadline counters), each tagged with its stable
    ``id`` and sorted by it (WeakSet iteration order is arbitrary —
    reads must be correlatable across time and replicas).
    ``reset=True`` clears the latency windows and counters after
    reading (each instance snapshot-and-clears under its own lock),
    including the per-predictor ``serving::…`` registry series — one
    reset, every serving surface starts a fresh window."""
    out = {
        "predictors": sorted(
            (p.report(reset=reset) for p in list(_PREDICTORS)),
            key=lambda r: r["id"]),
        "batchers": sorted(
            (b.report(reset=reset) for b in list(_BATCHERS)),
            key=lambda r: r["id"]),
        "decoders": sorted(
            (d.report(reset=reset) for d in list(_DECODERS)),
            key=lambda r: r["id"]),
        "routers": sorted(
            (r.report(reset=reset) for r in list(_ROUTERS)),
            key=lambda r: r["id"]),
        "clients": loadgen.client_report(reset=reset),
    }
    if reset:
        _treg.reset(prefix="serving::")
    return out


from ..telemetry import registry as _treg  # noqa: E402

serving_report = _treg.collector_view("serving", _collect)


from .predictor import Predictor           # noqa: E402
from .batcher import DynamicBatcher        # noqa: E402
from . import loadgen                      # noqa: E402
from . import decode                       # noqa: E402
from .fleet import FleetRouter             # noqa: E402
from .tenancy import TenantSpec            # noqa: E402
from .autoscale import FleetAutoscaler     # noqa: E402
