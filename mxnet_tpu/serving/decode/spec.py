"""Speculative decoding: a draft transformer proposes, the target
verifies — bytes-per-token amortized over k tokens (round 21).

Each plain decode step moves the WHOLE model + KV-cache through HBM to
emit ONE token per lane; on a bandwidth-bound machine that traffic is
the decode-path cost (ROADMAP item 3). :class:`SpecDecodePredictor`
amortizes it: a small draft model (fewer layers/heads — build one with
:func:`make_draft_spec`, train it with :func:`distill_draft` on the
target's own greedy rollouts) proposes up to ``k`` tokens per lane, and
the target checks ALL of them in ONE multi-token verify program
(``model.verify_step``; width ``k+1`` is compile-key material through
the r10 registry exactly like a prefill bucket).

Accept-prefix semantics keep the stream BIT-IDENTICAL to solo greedy
decode: feeding ``[last, d_1..d_k]`` yields the target's argmax after
each fed token, so ``out[0]`` is exactly what the plain decode step
would emit; draft ``d_j`` is accepted iff it equals ``out[j-1]`` (the
token greedy decode WOULD have produced), and the first disagreement
emits the target's own token instead. Every round therefore commits
1..k+1 tokens, all of them the greedy stream — the draft's quality
moves THROUGHPUT (acceptance rate), never output. Rejected drafts'
cache rows simply go stale behind the committed position
(``seek_slot``): attention masks beyond the live position, and the
next write overwrites — the same no-scrub discipline ``release`` has
always documented.

Continuous batching composes per lane: a lane can join or leave
mid-flight, and plain (non-speculative) lanes ride the SAME verify
launch with a width-1 feed — degenerate speculative decode IS plain
decode, which is also the degrade path: a divergence storm (windowed
acceptance below ``MXTPU_SPEC_DISABLE_BELOW``, or the ``spec_verify``
fault site firing) drops to plain decode for ``MXTPU_SPEC_PROBE_STEPS``
rounds, then probes again. Never a corrupted stream, at worst plain
speed.
"""
from __future__ import annotations

import collections
import threading

import numpy as np

from ... import config
from ...base import MXNetError
from .engine import DecodePredictor
from .model import TransformerLMSpec

__all__ = ["SpecDecodePredictor", "make_draft_spec", "distill_draft"]

# a degrade decision needs this many speculative rounds of evidence in
# the window before the rate is trusted (one unlucky round is not a
# storm)
_MIN_DECIDE_ROUNDS = 8


def make_draft_spec(spec, num_layers=1, shrink=2, name=None):
    """A draft-sized sibling of ``spec``: same vocab and ``max_seq``
    (the draft must address every position the target can), embed and
    heads divided by ``shrink`` (head_dim is preserved: ``d/h`` is
    invariant under dividing both), ``num_layers`` layers. The point is
    a model whose decode step moves genuinely fewer bytes — a draft as
    big as the target can never win bytes-per-accepted-token no matter
    how often it is right."""
    if spec.num_heads % shrink or spec.num_embed % shrink:
        raise MXNetError(
            f"shrink={shrink} must divide num_heads={spec.num_heads} "
            f"and num_embed={spec.num_embed}")
    return TransformerLMSpec(
        vocab_size=spec.vocab_size,
        num_embed=spec.num_embed // shrink,
        num_heads=spec.num_heads // shrink,
        num_layers=int(num_layers),
        max_seq=spec.max_seq,
        name=name or f"{spec.name}-draft")


def distill_draft(target, draft_spec, prompts=None, rollout=40,
                  seq_len=16, num_epoch=8, batch_size=16, lr=3e-3,
                  seed=0):
    """Train ``draft_spec`` weights to imitate ``target``'s GREEDY
    rollouts — distillation on exactly the distribution speculation
    pays for (the target's own argmax stream, not held-out text).

    ``target`` is a :class:`DecodePredictor`; its solo ``generate``
    oracle produces the training stream. Returns the trained param
    dict, ready for :class:`SpecDecodePredictor`.
    """
    import mxnet_tpu as mx
    rs = np.random.RandomState(seed)
    if prompts is None:
        prompts = [rs.randint(target.spec.vocab_size,
                              size=n).astype(np.int32)
                   for n in (4, 6, 8, 5, 7, 3)]
    seqs = []
    for p in prompts:
        p = np.asarray(p, np.int32)
        lim = target.gen_limit(p.shape[0], rollout)
        toks = list(p) + list(target.generate(p, max_new_tokens=lim))
        seqs.append(np.asarray(toks, np.int32))
    ids = np.concatenate(seqs)
    n = len(ids) - seq_len - 1
    if n < batch_size:
        raise MXNetError(
            f"distill_draft: only {n} training windows from the "
            f"rollouts; lower seq_len/batch_size or raise rollout")
    data = np.stack([ids[i:i + seq_len] for i in range(n)])
    label = np.stack([ids[i + 1:i + seq_len + 1]
                      for i in range(n)]).astype(np.float32)
    from .model import build_symbol
    train_iter = mx.io.NDArrayIter(data.astype(np.float32), label,
                                   batch_size, shuffle=True,
                                   last_batch_handle="discard")
    mod = mx.mod.Module(symbol=build_symbol(draft_spec, seq_len),
                        data_names=("data",),
                        label_names=("softmax_label",),
                        context=mx.cpu())
    mod.fit(train_iter, num_epoch=num_epoch, optimizer="adam",
            optimizer_params={"learning_rate": lr},
            initializer=mx.init.Xavier(),
            eval_metric=mx.metric.Accuracy(axis=2, name="distill_acc"))
    arg_params, _aux = mod.get_params()
    return dict(arg_params)


class SpecDecodePredictor(DecodePredictor):
    """A :class:`DecodePredictor` whose lanes advance up to ``k+1``
    tokens per round through draft-then-verify.

    Parameters beyond the base class:

    draft_spec, draft_params :
        The proposal model (same vocab/max_seq; see
        :func:`make_draft_spec` / :func:`distill_draft`). It runs as
        its own ``DecodePredictor`` with the SAME slot count — lane
        ``i`` of the draft mirrors lane ``i`` of the target, so join/
        leave bookkeeping is one slot id.
    k : int, optional
        Speculation depth — drafts proposed per lane per round
        (default ``MXTPU_SPEC_K``). The single verify width ``k+1``
        is declared in ``verify_widths`` so warmup materializes it and
        serving performs zero fresh verify traces.
    draft_kv_dtype : str
        Draft cache dtype (default float32 — the draft cache is small;
        its layout never fingerprints into the target's handoff).
    disable_below / probe_steps / window :
        Degrade policy knobs (defaults ``MXTPU_SPEC_DISABLE_BELOW``,
        ``MXTPU_SPEC_PROBE_STEPS``, ``MXTPU_SPEC_WINDOW``): when the
        windowed acceptance rate over ``window`` speculative rounds
        drops below ``disable_below``, speculation turns OFF for
        ``probe_steps`` rounds (plain decode program — true
        degradation, not width-1 verify), then probes again.
    """

    def __init__(self, spec, params, draft_spec, draft_params, k=None,
                 slots=None, seq_buckets=None, name=None, kv_dtype=None,
                 draft_kv_dtype="float32", disable_below=None,
                 probe_steps=None, window=None):
        if draft_spec.vocab_size != spec.vocab_size:
            raise MXNetError(
                f"draft vocab {draft_spec.vocab_size} != target vocab "
                f"{spec.vocab_size}")
        if draft_spec.max_seq < spec.max_seq:
            raise MXNetError(
                f"draft max_seq {draft_spec.max_seq} < target max_seq "
                f"{spec.max_seq} — the draft must reach every position")
        super().__init__(spec, params, slots=slots,
                         seq_buckets=seq_buckets, name=name,
                         kv_dtype=kv_dtype)
        self.spec_k = int(k) if k is not None \
            else int(config.get("MXTPU_SPEC_K", 4))
        if self.spec_k < 1:
            raise MXNetError(f"speculation depth k={self.spec_k} "
                             "must be >= 1")
        self.verify_widths = (self.spec_k + 1,)
        self.disable_below = float(disable_below) \
            if disable_below is not None \
            else float(config.get("MXTPU_SPEC_DISABLE_BELOW", 0.125))
        self.probe_steps = int(probe_steps) if probe_steps is not None \
            else int(config.get("MXTPU_SPEC_PROBE_STEPS", 64))
        window = int(window) if window is not None \
            else int(config.get("MXTPU_SPEC_WINDOW", 32))
        self.draft = DecodePredictor(
            draft_spec, draft_params, slots=self.slots,
            seq_buckets=self.buckets, name=f"{self.name}-draft",
            kv_dtype=draft_kv_dtype)
        self._spec_lock = threading.Lock()
        self._spec_rounds = 0        # every spec_step call
        self._plain_until = 0        # degrade: rounds <= this are plain
        self._degrade_events = 0
        self._win = collections.deque(maxlen=window)
        # cumulative over VERIFY rounds (the measured-bytes surfaces)
        self._emit_verify = 0        # tokens committed by verify rounds
        self._lane_rounds = 0        # lane participations in verify
        self._drafts_offered = 0
        self._drafts_accepted = 0
        # per-slot (pos, token) rows the DRAFT cache is missing — the
        # full-accept hole (the k-th draft is proposed but its own K/V
        # row is never written) and any tokens committed by plain
        # rounds; replayed through the draft before the next rollout so
        # proposal quality doesn't decay with stream length. Bounded:
        # beyond maxlen the oldest rows stay stale (quality-only).
        self._draft_backlog = [
            collections.deque(maxlen=2 * (self.spec_k + 1))
            for _ in range(self.slots)]
        from ...telemetry import registry as treg
        pid = self.telemetry_id
        self._aps_g = treg.gauge(f"serving::{pid}::accepted_per_step")
        self._rate_g = treg.gauge(f"serving::{pid}::acceptance_rate")

    # -- lifecycle ------------------------------------------------------------
    def prefill(self, slot, prompt):
        """Prefill BOTH engines' lane ``slot`` (one admission path for
        target and draft keeps their caches position-consistent);
        returns the target's token #1 — the draft's is discarded, it
        only seeds the draft cache."""
        tok = super().prefill(slot, prompt)
        self.draft.prefill(slot, prompt)
        self._draft_backlog[slot].clear()
        return tok

    def warmup(self):
        self.draft.warmup()
        return super().warmup()

    def import_lane(self, slot, lane, prompt=None):
        """Adopt a handed-off TARGET lane; the draft cache (not part of
        the transfer — it is proposal state, reconstructible) is
        re-prefilled from the prompt when given, else left stale with
        positions aligned (quality-only: stale draft context lowers
        acceptance, never correctness)."""
        super().import_lane(slot, lane)
        if prompt is not None:
            self.draft.prefill(slot, prompt)
        self.draft.seek_slot(slot, int(lane["pos"]))
        self._draft_backlog[slot].clear()

    # -- the speculative round ------------------------------------------------
    def spec_step(self, lanes):
        """Advance every lane one ROUND: ``{slot: (last_token, budget,
        speculative)}`` -> ``{slot: [token, ...]}`` with 1..k+1 tokens
        per lane, every token exactly what solo greedy decode would
        stream. ``budget`` caps tokens this lane may still emit (the
        generation's remaining limit); ``speculative=False`` lanes ride
        the same launch with a width-1 feed.

        One round = (optional) draft rollout of up to k small-model
        steps + ONE target verify launch; commit via ``seek_slot`` on
        both engines. Degraded rounds (windowed acceptance below the
        disable threshold, or every lane plain) use the plain decode
        program instead. The ``spec_verify`` fault site fires per
        speculative round (``round`` ordinal): a hit simulates a
        divergence storm — proposals are replaced with deliberately
        wrong tokens, the verify path runs for real, acceptance goes to
        zero, the stream stays exact."""
        if not lanes:
            return {}
        from ... import faultinject
        with self._spec_lock:
            self._spec_rounds += 1
            ordinal = self._spec_rounds
            speculating = ordinal > self._plain_until
        vocab = self.spec.vocab_size
        bases = {s: self.slot_pos(s) for s in lanes}
        depths = {}
        for slot, (last, budget, want_spec) in lanes.items():
            nd = min(self.spec_k, int(budget) - 1,
                     self.spec.max_seq - bases[slot] - 1)
            if speculating and want_spec and nd > 0:
                depths[slot] = nd

        storm = False
        if depths:
            storm = faultinject.fire("spec_verify", round=ordinal)

        proposals = {s: [] for s in lanes}
        if depths and not storm:
            self._draft_sync(depths)
            cur = {s: int(lanes[s][0]) for s in depths}
            for s in depths:
                self.draft.seek_slot(s, bases[s])
            for step in range(max(depths.values())):
                live = {s: cur[s] for s, nd in depths.items()
                        if step < nd}
                if not live:
                    break
                nxt = self.draft.decode(live)
                for s, t in nxt.items():
                    proposals[s].append(int(t))
                    cur[s] = int(t)
        elif depths:
            # storm: keep the verify path honest — feed proposals that
            # are (near-)guaranteed wrong instead of skipping the
            # launch, so "never corrupts a stream" is exercised, not
            # assumed. (An accidental match is still the greedy token —
            # accept-prefix is unconditionally exact.)
            for s, nd in depths.items():
                last = int(lanes[s][0])
                proposals[s] = [(last + 1 + j) % vocab
                                for j in range(nd)]

        if not depths:
            # every lane plain this round: true degradation — the
            # PLAIN decode program (advances positions + counters
            # itself)
            out = {s: [int(t)] for s, t in self.decode(
                {s: int(lanes[s][0]) for s in lanes}).items()}
            self._note_round(out, offered=0, accepted=0,
                             verify_round=False)
            return out

        feed = {s: [int(lanes[s][0])] + proposals[s] for s in lanes}
        res = self.verify(feed)
        out, offered, accepted = {}, 0, 0
        for s, fed in feed.items():
            o = res[s]
            emitted = [int(o[0])]
            for j in range(1, len(fed)):
                if fed[j] != int(o[j - 1]):
                    break
                emitted.append(int(o[j]))
            offered += len(fed) - 1
            accepted += len(emitted) - 1
            out[s] = emitted
            m = len(emitted)
            self.seek_slot(s, bases[s] + m)
            self.draft.seek_slot(s, bases[s] + m)
            # rows the draft rollout did NOT validly write for this
            # lane's newly committed positions (position base+i holds
            # the token fed there: ``last`` at i=0, emitted[i-1] after)
            nd_written = len(proposals[s]) if s in depths \
                and not storm else 0
            toks = [int(lanes[s][0])] + emitted[:-1]
            for i in range(min(nd_written, m), m):
                self._draft_backlog[s].append((bases[s] + i, toks[i]))
        ntok = sum(len(v) for v in out.values())
        with self._lock:
            self._tokens += ntok
        self._tokens_c.inc(ntok)
        self._note_round(out, offered, accepted, verify_round=True)
        return out

    def _draft_sync(self, depths):
        """Replay each lane's backlog of committed-but-unwritten rows
        through the draft (lockstep across lanes, positions are
        contiguous per lane) so the next rollout conditions on the real
        stream. Proposals from replay steps are discarded — the tokens
        are already committed."""
        backlogs = {s: list(self._draft_backlog[s]) for s in depths
                    if self._draft_backlog[s]}
        if not backlogs:
            return
        for s, bl in backlogs.items():
            self.draft.seek_slot(s, bl[0][0])
        for i in range(max(len(bl) for bl in backlogs.values())):
            fed = {s: bl[i][1] for s, bl in backlogs.items()
                   if i < len(bl)}
            if fed:
                self.draft.decode(fed)
        for s in backlogs:
            self._draft_backlog[s].clear()

    def _note_round(self, out, offered, accepted, verify_round):
        with self._spec_lock:
            if verify_round:
                self._emit_verify += sum(len(v) for v in out.values())
                self._lane_rounds += len(out)
                self._drafts_offered += offered
                self._drafts_accepted += accepted
            self._win.append((len(out),
                              sum(len(v) for v in out.values()),
                              offered, accepted))
            lanes = sum(w[0] for w in self._win)
            toks = sum(w[1] for w in self._win)
            off = sum(w[2] for w in self._win)
            acc = sum(w[3] for w in self._win)
            aps = toks / lanes if lanes else 0.0
            rate = acc / off if off else 0.0
            decide = sum(1 for w in self._win if w[2] > 0)
            if offered and rate < self.disable_below and \
                    decide >= _MIN_DECIDE_ROUNDS:
                # divergence storm: speculation off for probe_steps
                # rounds, window cleared so the probe gets a fresh vote
                self._plain_until = self._spec_rounds + self.probe_steps
                self._degrade_events += 1
                self._win.clear()
        self._aps_g.set(aps)
        self._rate_g.set(rate)

    # -- measured-gate surfaces ----------------------------------------------
    def spec_bytes_per_accepted_token(self):
        """MEASURED bytes per committed token on the speculative path:
        (verify launches x verify-program bytes + ALL draft decode
        launches x draft-step bytes, replay included) / tokens
        committed by verify rounds. XLA cost-analysis ground truth on
        both programs; ``None`` before any verify round or where the
        backend reports no costs. The r21 gate pins this STRICTLY below
        ``decode_bytes_per_token()`` — amortization must beat the
        plain step per token actually kept, not per token proposed."""
        vb = float(self.program_cost(
            "verify", self.spec_k + 1).get("bytes accessed", 0.0))
        db = float(self.draft.program_cost("decode").get(
            "bytes accessed", 0.0))
        with self._spec_lock:
            emitted = self._emit_verify
        if not vb or not db or not emitted:
            return None
        with self._lock:
            vsteps = self._verify_steps
        with self.draft._lock:
            dsteps = self.draft._decode_steps
        return (vsteps * vb + dsteps * db) / emitted

    # -- observability --------------------------------------------------------
    @property
    def degraded(self):
        """True while a divergence storm has speculation switched off
        (plain-decode rounds until the probe)."""
        with self._spec_lock:
            return self._spec_rounds < self._plain_until

    def report(self, reset=False):
        out = super().report(reset=reset)
        with self._spec_lock:
            lanes = sum(w[0] for w in self._win)
            off = sum(w[2] for w in self._win)
            out["spec"] = {
                "k": self.spec_k,
                "draft_id": self.draft.telemetry_id,
                "rounds": self._spec_rounds,
                "accepted_per_step":
                    (self._emit_verify / self._lane_rounds)
                    if self._lane_rounds else None,
                "acceptance_rate":
                    (self._drafts_accepted / self._drafts_offered)
                    if self._drafts_offered else None,
                "windowed_accepted_per_step":
                    (sum(w[1] for w in self._win) / lanes)
                    if lanes else None,
                "windowed_acceptance_rate":
                    (sum(w[3] for w in self._win) / off)
                    if off else None,
                "degraded": self._spec_rounds < self._plain_until,
                "degrade_events": self._degrade_events,
                "bytes_per_accepted_token": None,
            }
            if reset:
                self._emit_verify = 0
                self._lane_rounds = 0
                self._drafts_offered = 0
                self._drafts_accepted = 0
                self._win.clear()
        out["spec"]["bytes_per_accepted_token"] = \
            self.spec_bytes_per_accepted_token() if not reset else None
        return out
