"""Autoregressive decode serving: KV-cache programs + continuous batching.

The dynamic batcher (serving/batcher.py) coalesces WHOLE requests; an
autoregressive LM produces one token per program step and would pay a
full-prompt recompute for every one of them. This package serves
generation instead:

- ``model`` — a causal transformer LM as symbols/pure functions:
  ``build_symbol`` (trainable graph for Module.fit), ``prefill_step``
  (fills a lane of the KV-cache from a prompt), ``decode_step`` (one
  token for EVERY active lane against the cache), ``reprefill_step``
  (the cacheless baseline the bytes-accessed gate measures against).
- ``engine.DecodePredictor`` — two program kinds per model in the
  compile registry: per-bucket prefill + ONE single-token decode whose
  KV-cache is donated device state. Cache layout, slot count and
  ``max_seq`` are compile-key material; the cache itself is a
  ``decode_state`` row in ``memory_report()``.
- ``batcher.DecodeBatcher`` — continuous batching: requests join and
  leave the in-flight decode batch per TOKEN, freed lanes backfill
  mid-flight, and ``submit()`` returns a :class:`StreamFuture` that
  streams tokens as they decode. TTFT and inter-token latency feed
  ``serving::<pid>::ttft_ms`` / ``::inter_token_ms`` histograms.
  Round 21: the batcher also speaks the disaggregated roles
  (``role="prefill"`` hands freshly filled KV lanes to a sink,
  ``adopt()`` receives them) and steps speculative predictors through
  ``spec_step`` (multiple tokens per round, bit-identical streams).
- ``spec.SpecDecodePredictor`` — speculative decoding (round 21): a
  small distilled draft proposes up to ``k`` tokens per lane, ONE
  batched verify program checks them all, the accepted prefix commits.
  ``make_draft_spec`` / ``distill_draft`` build and train the draft.

Config: ``MXTPU_DECODE_SLOTS``, ``MXTPU_DECODE_SEQ_BUCKETS``,
``MXTPU_DECODE_MAX_WAIT_US``, ``MXTPU_DECODE_MAX_QUEUE``,
``MXTPU_SPEC_K``, ``MXTPU_SPEC_DISABLE_BELOW``,
``MXTPU_SPEC_PROBE_STEPS``, ``MXTPU_SPEC_WINDOW``.
"""
from . import model
from .model import TransformerLMSpec, build_symbol, init_params
from .engine import DecodePredictor
from .batcher import DecodeBatcher, StreamFuture
from .spec import SpecDecodePredictor, make_draft_spec, distill_draft

__all__ = ["model", "TransformerLMSpec", "build_symbol", "init_params",
           "DecodePredictor", "DecodeBatcher", "StreamFuture",
           "SpecDecodePredictor", "make_draft_spec", "distill_draft"]
