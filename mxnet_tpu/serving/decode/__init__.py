"""Autoregressive decode serving: KV-cache programs + continuous batching.

The dynamic batcher (serving/batcher.py) coalesces WHOLE requests; an
autoregressive LM produces one token per program step and would pay a
full-prompt recompute for every one of them. This package serves
generation instead:

- ``model`` — a causal transformer LM as symbols/pure functions:
  ``build_symbol`` (trainable graph for Module.fit), ``prefill_step``
  (fills a lane of the KV-cache from a prompt), ``decode_step`` (one
  token for EVERY active lane against the cache), ``reprefill_step``
  (the cacheless baseline the bytes-accessed gate measures against).
- ``engine.DecodePredictor`` — two program kinds per model in the
  compile registry: per-bucket prefill + ONE single-token decode whose
  KV-cache is donated device state. Cache layout, slot count and
  ``max_seq`` are compile-key material; the cache itself is a
  ``decode_state`` row in ``memory_report()``.
- ``batcher.DecodeBatcher`` — continuous batching: requests join and
  leave the in-flight decode batch per TOKEN, freed lanes backfill
  mid-flight, and ``submit()`` returns a :class:`StreamFuture` that
  streams tokens as they decode. TTFT and inter-token latency feed
  ``serving::<pid>::ttft_ms`` / ``::inter_token_ms`` histograms.

Config: ``MXTPU_DECODE_SLOTS``, ``MXTPU_DECODE_SEQ_BUCKETS``,
``MXTPU_DECODE_MAX_WAIT_US``, ``MXTPU_DECODE_MAX_QUEUE``.
"""
from . import model
from .model import TransformerLMSpec, build_symbol, init_params
from .engine import DecodePredictor
from .batcher import DecodeBatcher, StreamFuture

__all__ = ["model", "TransformerLMSpec", "build_symbol", "init_params",
           "DecodePredictor", "DecodeBatcher", "StreamFuture"]
