"""DecodePredictor: KV-cached autoregressive serving programs + slots.

The Predictor freezes one symbol into per-bucket one-shot programs;
this engine freezes a ``TransformerLMSpec`` weight set into the TWO
program families iterative decode needs (model.py):

- one PREFILL program per prompt-length bucket — batch-1 per request,
  fills the request's slot rows of the KV-cache, emits token #1. Every
  admission runs the identical program whether the server is idle or
  saturated, which is half of the bit-identity guarantee;
- ONE DECODE program — advances all ``slots`` lanes a single token
  against the cache. Lanes are data-independent, so a lane's output
  doesn't depend on which other slots are occupied: the other half;
- per-width VERIFY programs (round 21, ``verify_widths``) — advance
  all lanes up to K tokens in one launch for speculative decoding
  (model.verify_step); width is compile-key material like the prefill
  buckets, and warmup materializes every declared width so serving
  performs zero fresh verify traces.

The KV-cache is DONATED device state: ``2 * num_layers`` buffers of
``(slots, max_seq, heads, head_dim)`` float32 — or, under
``MXTPU_DECODE_KV_DTYPE=int8``, ``4 * num_layers`` int8 value +
per-row f32 scale buffers (model.py, round 19) — threaded through
every call (donated back to XLA where the backend supports donation —
``compile.donation_supported()``), never copied to host. Cache layout
AND dtype, ``max_seq`` and ``slots`` are compile-key material, and the
accounted cache footprint is recorded in ``mx.memory_report()`` next
to the per-program peaks so cache sizing is driven by measured HBM
headroom — under int8 the decode_state row drops to ~0.31× f32, which
is the "roughly double the slots per chip" capacity lever.

Programs go through the r10 compile registry (``load_or_compile`` +
``note_entry_point``): AOT persistent-cache warm starts, retrace
guards, and ``compile_report()`` pinning — a full serving run performs
zero fresh compiles beyond the per-bucket prefill programs plus the one
decode program (tests pin this).

The slot allocator lives here (under the engine lock): ``prefill`` into
a free slot, per-slot positions advance per ``decode`` call, ``release``
returns the slot for mid-flight backfill. ``generate()`` is the solo
streaming surface over the same programs — also the oracle the
continuous-batching drill compares against.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ... import config
from ...base import MXNetError
from . import model as _model
from .. import _register_decoder

__all__ = ["DecodePredictor", "default_seq_buckets"]


def default_seq_buckets(max_seq):
    """Prompt-length buckets from MXTPU_DECODE_SEQ_BUCKETS, clipped to
    ``max_seq`` (which is always a bucket: any prompt the spec admits
    has a program)."""
    raw = str(config.get("MXTPU_DECODE_SEQ_BUCKETS", "16,64"))
    try:
        buckets = sorted({int(x) for x in raw.replace(" ", "").split(",")
                          if x})
    except ValueError:
        raise MXNetError(
            f"MXTPU_DECODE_SEQ_BUCKETS={raw!r} is not a comma-separated "
            "integer list")
    if buckets and buckets[0] < 1:
        raise MXNetError(
            f"MXTPU_DECODE_SEQ_BUCKETS={raw!r} must name positive "
            "prompt lengths")
    buckets = [b for b in buckets if b <= max_seq]
    if not buckets or buckets[-1] != max_seq:
        buckets.append(max_seq)
    return tuple(buckets)


class DecodePredictor:
    """KV-cached decode serving over a frozen transformer LM.

    Parameters
    ----------
    spec : TransformerLMSpec
    params : dict name -> array/NDArray
        Trained weights matching ``spec.param_shapes()`` (e.g.
        ``Module.get_params()[0]`` of the ``build_symbol`` graph).
    slots : int, optional
        Concurrent generation lanes (default MXTPU_DECODE_SLOTS).
    seq_buckets : tuple of int, optional
        Prompt-length buckets (default MXTPU_DECODE_SEQ_BUCKETS,
        clipped to ``spec.max_seq`` which is always included).
    name : str, optional
        Label for programs/telemetry (default ``spec.name``).
    kv_dtype : str, optional
        Cache storage dtype, ``"float32"`` or ``"int8"`` (default
        ``MXTPU_DECODE_KV_DTYPE``). int8 stores per-row absmax scales
        and dequantizes at f32 compute (model.py); the layout is
        compile-key material, so flipping it is a program miss.
    """

    def __init__(self, spec, params, slots=None, seq_buckets=None,
                 name=None, kv_dtype=None):
        import jax
        import jax.numpy as jnp
        from ... import compile as compile_mod

        self.spec = spec
        self.name = name or spec.name
        self.slots = int(slots) if slots is not None \
            else int(config.get("MXTPU_DECODE_SLOTS", 4))
        if self.slots < 1:
            raise MXNetError(f"slots={self.slots} must be >= 1")
        self.kv_dtype = _model.check_kv_dtype(
            kv_dtype if kv_dtype is not None
            else config.get("MXTPU_DECODE_KV_DTYPE", "float32"))
        self.buckets = tuple(sorted(set(
            int(b) for b in seq_buckets))) if seq_buckets \
            else default_seq_buckets(spec.max_seq)
        if self.buckets[-1] > spec.max_seq:
            raise MXNetError(
                f"seq bucket {self.buckets[-1]} exceeds "
                f"spec.max_seq={spec.max_seq}")

        shapes = spec.param_shapes()
        missing = [n for n in shapes if n not in params]
        if missing:
            raise MXNetError(f"DecodePredictor missing params {missing}")
        pvals = {}
        for n, want in shapes.items():
            a = np.asarray(getattr(params[n], "_data",
                                   getattr(params[n], "data", params[n])),
                           dtype=np.float32)
            if tuple(a.shape) != tuple(want):
                raise MXNetError(
                    f"param '{n}' has shape {a.shape}, spec wants "
                    f"{tuple(want)}")
            pvals[n] = jax.device_put(jnp.asarray(a))
        self._pnames = spec.param_names()
        self._pvals_t = tuple(pvals[n] for n in self._pnames)

        self._caches = tuple(
            jax.device_put(c) for c in _model.init_caches(
                spec, self.slots, kv_dtype=self.kv_dtype))

        pnames = list(self._pnames)
        kv_dtype_s = self.kv_dtype

        def prefill_fn(pvals_t, caches, tokens, length, slot):
            p = dict(zip(pnames, pvals_t))
            return _model.prefill_step(spec, p, caches, tokens, length,
                                       slot, kv_dtype=kv_dtype_s)

        def decode_fn(pvals_t, caches, tokens, positions, active):
            p = dict(zip(pnames, pvals_t))
            return _model.decode_step(spec, p, caches, tokens,
                                      positions, active,
                                      kv_dtype=kv_dtype_s)

        def verify_fn(pvals_t, caches, tokens, positions, n_tokens,
                      active):
            p = dict(zip(pnames, pvals_t))
            return _model.verify_step(spec, p, caches, tokens,
                                      positions, n_tokens, active,
                                      kv_dtype=kv_dtype_s)

        def reprefill_fn(pvals_t, tokens, length):
            p = dict(zip(pnames, pvals_t))
            return _model.reprefill_step(spec, p, tokens, length)

        donate = {"donate_argnums": (1,)} \
            if compile_mod.donation_supported() else {}
        self._donate = bool(donate)
        self._prefill_jit = jax.jit(prefill_fn, **donate)
        self._decode_jit = jax.jit(decode_fn, **donate)
        self._verify_jit = jax.jit(verify_fn, **donate)
        self._reprefill_jit = jax.jit(reprefill_fn)
        # multi-token verify widths warmup should materialize; empty on
        # a plain engine (verify still compiles lazily at any width the
        # caller asks for), set by SpecDecodePredictor to (k+1,)
        self.verify_widths = ()

        self._lock = threading.RLock()
        self._programs = {}       # ("prefill", b) / ("decode",) / ...
        self._program_costs = {}
        self._program_memory = {}
        self._materialized = 0
        self._cache_loads = 0
        self._free = list(range(self.slots))      # LIFO slot allocator
        self._slot_pos = [0] * self.slots         # next write position
        self._decode_steps = 0
        self._verify_steps = 0
        self._prefills = 0
        self._tokens = 0

        _register_decoder(self)
        from ...telemetry import registry as treg
        self._tokens_c = treg.counter(
            f"serving::{self.telemetry_id}::tokens")
        treg.gauge(f"serving::{self.telemetry_id}::kv_cache_bytes").set(
            self.kv_cache_bytes())
        # the cache is persistent device STATE, not a per-program temp:
        # give it its own memory_report() row so HBM headroom math sees
        # it next to the program peaks
        from ...telemetry import memory as _tmem
        kv = self.kv_cache_bytes()
        _tmem.record(
            f"decode:{self.telemetry_id}:kv_cache", "decode_state",
            f"kv:{self.telemetry_id}",
            {"argument_bytes": kv, "output_bytes": kv,
             "alias_bytes": kv, "peak_bytes": kv,
             "donation_saved_bytes": kv if self._donate else 0})

    # -- construction helpers -------------------------------------------------
    @classmethod
    def from_module(cls, module, spec, **kwargs):
        """Freeze a trained (bound+initialized) Module of the
        ``build_symbol(spec, ...)`` graph — param names ARE the
        contract, no translation layer."""
        arg_params, _aux = module.get_params()
        return cls(spec, arg_params, **kwargs)

    # -- bucketing / capacity -------------------------------------------------
    @property
    def max_batch(self):
        """Decode lanes (the DecodeBatcher's concurrency bound)."""
        return self.slots

    @property
    def retraces(self):
        return self._materialized

    def bucket_for(self, n):
        for b in self.buckets:
            if n <= b:
                return b
        raise MXNetError(
            f"prompt of {n} tokens exceeds the largest seq bucket "
            f"({self.buckets[-1]})")

    def gen_limit(self, prompt_len, max_new_tokens=None):
        """Max tokens producible for a prompt: the cache holds positions
        ``[0, max_seq)`` so generation is capped at
        ``max_seq - prompt_len + 1`` (token #1 costs no cache row; each
        further token writes one). Solo ``generate`` and the batcher
        clamp through HERE — identical limits are part of bit-identity.
        """
        cap = self.spec.max_seq - prompt_len + 1
        if max_new_tokens is None:
            return cap
        return max(1, min(int(max_new_tokens), cap))

    def check_prompt(self, prompt):
        """Validate/convert one prompt to a 1-D int32 numpy array."""
        a = np.asarray(getattr(prompt, "_data", prompt))
        if a.ndim != 1 or a.shape[0] < 1:
            raise MXNetError(
                f"prompt must be a non-empty 1-D token sequence, got "
                f"shape {tuple(a.shape)}")
        if a.shape[0] > self.spec.max_seq:
            raise MXNetError(
                f"prompt of {a.shape[0]} tokens exceeds "
                f"max_seq={self.spec.max_seq}")
        return a.astype(np.int32)

    # -- compile registry -----------------------------------------------------
    def _program_key(self, kind, bucket=None):
        from ... import compile as compile_mod
        extra = dict(self.spec.key_material())
        layout = ("slot-major:int8+f32scale" if self.kv_dtype == "int8"
                  else "slot-major:f32")
        extra.update({
            "slots": self.slots,
            "cache_layout": layout if kind != "reprefill" else "none",
            "donate": self._donate and kind != "reprefill",
        })
        if kind == "verify":
            # ``bucket`` is the verify WIDTH (max fed tokens per lane):
            # width is program-shape material exactly like a prefill's
            # seq bucket, so each width is its own registry entry
            sigs = (("tokens", (self.slots, bucket), "int32"),)
            label = f"decode:{self.name}:verify:k{bucket}"
        else:
            sigs = ((("tokens", (1, bucket), "int32"),)
                    if bucket is not None
                    else (("tokens", (self.slots,), "int32"),))
            label = f"decode:{self.name}:{kind}" + \
                (f":s{bucket}" if bucket is not None else "")
        return compile_mod.program_key(
            "decode", label, input_sigs=sigs, extra=extra)

    def _acquire(self, pkey_id, kind, bucket, jit_fn, args):
        """Acquire one compiled program through the compile registry
        (AOT cache, retrace guard), mirroring Predictor._acquire_program
        including the degrade-to-plain-jit fallback."""
        from ... import compile as compile_mod
        try:
            key = self._program_key(kind, bucket)
            exe, source = compile_mod.load_or_compile(
                key, lambda: jit_fn.lower(*args))
            compile_mod.note_entry_point(
                key.name, key, compile_mod.arg_signature(args[1]))
        except Exception as e:
            import logging
            logging.getLogger("mxnet_tpu.compile").warning(
                "decode AOT compile path failed (%s); using the plain "
                "jit", e)
            from ... import fault as _fault
            _fault.count("compile.aot_fallback")
            self._materialized += 1
            return jit_fn
        self._note_cost(pkey_id, key, exe)
        if source == "cache":
            self._cache_loads += 1

            def _reject():
                self._programs[pkey_id] = jit_fn
                self._materialized += 1
            return compile_mod.guarded_loaded_program(
                exe, jit_fn, "decode", on_reject=_reject)
        self._materialized += 1
        return exe

    def _note_cost(self, pkey_id, key, exe):
        try:
            cost = exe.cost_analysis()
            if isinstance(cost, (list, tuple)):
                cost = cost[0] if cost else {}
            self._program_costs[pkey_id] = dict(cost) if cost else {}
        except Exception:
            self._program_costs[pkey_id] = {}
        try:
            from ...telemetry import memory as _tmem
            self._program_memory[pkey_id] = _tmem.analyze(exe)
            _tmem.record(f"decode:{self.telemetry_id}:" +
                         ":".join(str(x) for x in pkey_id), "decode",
                         key.digest, exe)
        except Exception:
            self._program_memory[pkey_id] = {}

    def _run(self, pkey_id, kind, bucket, jit_fn, args):
        fn = self._programs.get(pkey_id)
        if fn is None:
            fn = self._acquire(pkey_id, kind, bucket, jit_fn, args)
            self._programs[pkey_id] = fn
        return fn(*args)

    # -- slot allocator (call under self._lock) -------------------------------
    def alloc_slot(self):
        """Claim a free decode lane, or None when saturated (the
        batcher's signal to leave work queued)."""
        with self._lock:
            return self._free.pop() if self._free else None

    def release(self, slot):
        """Return a lane to the pool (stale cache rows need no scrub:
        the next prefill overwrites its rows and attention masks beyond
        the live position with an exact-zero contribution)."""
        with self._lock:
            if slot not in self._free:
                self._free.append(slot)

    @property
    def free_slots(self):
        with self._lock:
            return len(self._free)

    def slot_pos(self, slot):
        """A lane's committed write position (next row index)."""
        with self._lock:
            return self._slot_pos[slot]

    def seek_slot(self, slot, pos):
        """Set a lane's committed position explicitly. The speculative
        layer commits an accepted prefix through here (``verify`` never
        advances positions itself — rows written for rejected drafts
        simply go stale behind the new position), and a KV-lane import
        lands its transferred position the same way."""
        if not 0 <= int(pos) <= self.spec.max_seq:
            raise MXNetError(
                f"seek_slot position {pos} outside [0, "
                f"{self.spec.max_seq}]")
        with self._lock:
            self._slot_pos[slot] = int(pos)

    # -- execution ------------------------------------------------------------
    def prefill(self, slot, prompt):
        """Fill ``slot`` from a validated prompt; returns token #1."""
        prompt = self.check_prompt(prompt)
        plen = prompt.shape[0]
        bucket = self.bucket_for(plen)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :plen] = prompt
        with self._lock:
            args = (self._pvals_t, self._caches, padded,
                    np.int32(plen), np.int32(slot))
            new_caches, nxt = self._run(
                ("prefill", bucket), "prefill", bucket,
                self._prefill_jit, args)
            self._caches = tuple(new_caches)
            self._slot_pos[slot] = plen
            self._prefills += 1
            self._tokens += 1
        self._tokens_c.inc()
        return int(nxt)

    def decode(self, slot_tokens):
        """One decode step: ``{slot: previous_token}`` for every active
        lane -> ``{slot: next_token}``. Consults the ``decode_step``
        fault site (1-based ``token`` ordinal) BEFORE touching device
        state, so an injected raise/kill leaves the cache un-advanced.
        """
        if not slot_tokens:
            return {}
        from ... import faultinject
        with self._lock:
            ordinal = self._decode_steps + 1
            if faultinject.fire("decode_step", token=ordinal):
                armed = faultinject.active("decode_step") or {}
                if armed.get("action") != "sleep":
                    raise faultinject.FaultInjected("decode_step",
                                                    token=ordinal)
                # sleep-armed: the slow-decode straggler drill (mirrors
                # replica_drop's sleep semantics) — fire() already
                # stretched this step, the program still runs
            tokens = np.zeros(self.slots, np.int32)
            positions = np.zeros(self.slots, np.int32)
            active = np.zeros(self.slots, bool)
            for slot, tok in slot_tokens.items():
                tokens[slot] = tok
                positions[slot] = self._slot_pos[slot]
                active[slot] = True
            args = (self._pvals_t, self._caches, tokens, positions,
                    active)
            new_caches, nxt = self._run(
                ("decode",), "decode", None, self._decode_jit, args)
            self._caches = tuple(new_caches)
            nxt = np.asarray(nxt)
            self._decode_steps += 1
            for slot in slot_tokens:
                self._slot_pos[slot] += 1
            self._tokens += len(slot_tokens)
        self._tokens_c.inc(len(slot_tokens))
        return {slot: int(nxt[slot]) for slot in slot_tokens}

    def _verify_width_for(self, n):
        for w in self.verify_widths:
            if n <= w:
                return w
        return n

    def verify(self, slot_feed):
        """One multi-token verify step: ``{slot: fed_tokens}`` — each
        lane's fed list is its last COMMITTED token followed by draft
        proposals — to ``{slot: np.int32 array}`` of the target's
        argmax after each fed token. Pads every lane to the smallest
        declared ``verify_widths`` bucket that fits (padding writes
        nowhere). Positions are NOT advanced: the caller decides the
        accepted prefix and commits it via ``seek_slot`` — which is
        what keeps a rejected draft's cache rows harmlessly stale
        instead of corrupting the lane."""
        if not slot_feed:
            return {}
        counts = {s: len(f) for s, f in slot_feed.items()}
        if min(counts.values()) < 1:
            raise MXNetError("verify needs at least the committed "
                             "token per lane")
        width = self._verify_width_for(max(counts.values()))
        with self._lock:
            tokens = np.zeros((self.slots, width), np.int32)
            positions = np.zeros(self.slots, np.int32)
            n_tok = np.ones(self.slots, np.int32)
            active = np.zeros(self.slots, bool)
            for slot, fed in slot_feed.items():
                n = counts[slot]
                tokens[slot, :n] = fed
                positions[slot] = self._slot_pos[slot]
                n_tok[slot] = n
                active[slot] = True
            args = (self._pvals_t, self._caches, tokens, positions,
                    n_tok, active)
            new_caches, outs = self._run(
                ("verify", width), "verify", width, self._verify_jit,
                args)
            self._caches = tuple(new_caches)
            outs = np.asarray(outs)
            self._verify_steps += 1
        return {slot: outs[slot, :counts[slot]].copy()
                for slot in slot_feed}

    # -- KV-lane handoff (disaggregated prefill/decode, round 21) -------------
    def lane_fingerprint(self):
        """Layout key a lane must match to transfer between engines:
        spec material + cache layout. Slots COUNT is deliberately not
        part of it — a prefill replica with 4 lanes hands off to a
        decode replica with 16."""
        layout = ("slot-major:int8+f32scale" if self.kv_dtype == "int8"
                  else "slot-major:f32")
        return dict(self.spec.key_material(), cache_layout=layout)

    def export_lane(self, slot):
        """Snapshot one lane's cache rows + committed position as a
        host-transportable dict — the prefill side of the KV-lane
        handoff. Under int8 KV the rows are the QUANTIZED buffers, so
        the handoff moves ~0.31× the f32 bytes (the r19 capacity lever
        doubling as a transfer-bytes lever)."""
        with self._lock:
            rows = [np.asarray(c[slot]) for c in self._caches]
            pos = self._slot_pos[slot]
        return {
            "fingerprint": self.lane_fingerprint(),
            "pos": int(pos),
            "rows": rows,
            "bytes": int(sum(r.nbytes for r in rows)),
        }

    def import_lane(self, slot, lane, prompt=None):
        """Land an exported lane into a free local slot — the decode
        side of the handoff. Refuses a fingerprint mismatch (two specs
        or two cache layouts must never silently mix rows). ``prompt``
        (the lane's committed tokens) is unused here but part of the
        contract: subclasses with auxiliary per-lane state — the
        speculative predictor's DRAFT cache — rebuild it from the
        prompt on import."""
        if lane["fingerprint"] != self.lane_fingerprint():
            raise MXNetError(
                f"KV-lane fingerprint mismatch: exporter "
                f"{lane['fingerprint']} vs importer "
                f"{self.lane_fingerprint()} — handoff requires "
                "identical spec + cache layout")
        import jax.numpy as jnp
        rows = lane["rows"]
        with self._lock:
            if len(rows) != len(self._caches):
                raise MXNetError(
                    f"KV-lane has {len(rows)} buffers, cache has "
                    f"{len(self._caches)}")
            self._caches = tuple(
                c.at[slot].set(jnp.asarray(r))
                for c, r in zip(self._caches, rows))
            self._slot_pos[slot] = int(lane["pos"])

    def generate(self, prompt, max_new_tokens=None, stop_token=None):
        """Stream tokens for ONE prompt (a generator): the solo surface
        over the same slot allocator and compiled programs the
        continuous batcher drives — which is why batched streams can be
        (and are, tests pin it) bit-identical to this.

        Yields ints; includes ``stop_token`` (generation halts after
        yielding it). Stops at ``max_new_tokens`` or when the cache is
        full (``gen_limit``)."""
        prompt = self.check_prompt(prompt)
        limit = self.gen_limit(prompt.shape[0], max_new_tokens)
        slot = self.alloc_slot()
        if slot is None:
            raise MXNetError(
                f"no free decode slot ({self.slots} busy); generate() "
                "is the solo surface — use DecodeBatcher for "
                "concurrent load")
        try:
            tok = self.prefill(slot, prompt)
            produced = 1
            yield tok
            while produced < limit and \
                    (stop_token is None or tok != stop_token):
                tok = self.decode({slot: tok})[slot]
                produced += 1
                yield tok
        finally:
            self.release(slot)

    def warmup(self):
        """Materialize every program (per-bucket prefill + the decode
        step) before live traffic; slot 0's scratch writes are harmless
        (release() doc). Returns the fresh-trace count — a full serving
        run after warmup performs ZERO further compiles."""
        with self._lock:
            for b in self.buckets:
                if ("prefill", b) not in self._programs:
                    padded = np.zeros((1, b), np.int32)
                    args = (self._pvals_t, self._caches, padded,
                            np.int32(1), np.int32(0))
                    new_caches, _ = self._run(
                        ("prefill", b), "prefill", b,
                        self._prefill_jit, args)
                    self._caches = tuple(new_caches)
            if ("decode",) not in self._programs:
                args = (self._pvals_t, self._caches,
                        np.zeros(self.slots, np.int32),
                        np.zeros(self.slots, np.int32),
                        np.zeros(self.slots, bool))
                new_caches, _ = self._run(
                    ("decode",), "decode", None, self._decode_jit, args)
                self._caches = tuple(new_caches)
            for w in self.verify_widths:
                if ("verify", w) not in self._programs:
                    args = (self._pvals_t, self._caches,
                            np.zeros((self.slots, w), np.int32),
                            np.zeros(self.slots, np.int32),
                            np.ones(self.slots, np.int32),
                            np.zeros(self.slots, bool))
                    new_caches, _ = self._run(
                        ("verify", w), "verify", w, self._verify_jit,
                        args)
                    self._caches = tuple(new_caches)
        return self.retraces

    # -- measured-gate surfaces ----------------------------------------------
    def kv_cache_bytes(self):
        """ACTUAL cache footprint (sum of live buffer nbytes); equals
        ``spec.kv_cache_bytes(slots, kv_dtype)`` — tests pin both
        against the memory_report() row (~0.31× f32 under int8 at the
        default head_dim 16)."""
        return int(sum(int(c.nbytes) for c in self._caches))

    def program_cost(self, kind, bucket=None):
        """XLA cost dict of one acquired program ({} before warmup)."""
        pkey_id = (kind, bucket) if bucket is not None else (kind,)
        return dict(self._program_costs.get(pkey_id) or {})

    def program_memory(self, kind, bucket=None):
        pkey_id = (kind, bucket) if bucket is not None else (kind,)
        return dict(self._program_memory.get(pkey_id) or {})

    def decode_bytes_per_token(self):
        """XLA cost-analysis bytes of ONE decode step divided by the
        lanes it advances — the per-token cost of cached decode."""
        cost = self.program_cost("decode")
        b = float(cost.get("bytes accessed", 0.0))
        return b / self.slots if b else None

    def reprefill_bytes_per_token(self, bucket=None):
        """Bytes of the CACHELESS re-prefill baseline at a seq bucket:
        what one generated token costs a server that recomputes the
        whole prompt instead of reading the cache. Compiled lazily (it
        is a measurement baseline, not a serving program — excluded
        from warmup and from the zero-fresh-compiles pin)."""
        b = self.buckets[-1] if bucket is None else bucket
        pkey_id = ("reprefill", b)
        with self._lock:
            if pkey_id not in self._programs:
                args = (self._pvals_t, np.zeros((1, b), np.int32),
                        np.int32(b))
                self._run(pkey_id, "reprefill", b,
                          self._reprefill_jit, args)
        cost = self.program_cost("reprefill", b)
        v = float(cost.get("bytes accessed", 0.0))
        return v or None

    # -- observability --------------------------------------------------------
    def report(self, reset=False):
        with self._lock:
            out = {
                "id": self.telemetry_id,
                "slots": self.slots,
                "seq_buckets": list(self.buckets),
                "max_seq": self.spec.max_seq,
                "free_slots": len(self._free),
                "retraces": self._materialized,
                "compile_cache_loads": self._cache_loads,
                "prefills": self._prefills,
                "decode_steps": self._decode_steps,
                "verify_steps": self._verify_steps,
                "tokens": self._tokens,
                "kv_dtype": self.kv_dtype,
                "kv_cache_bytes": self.kv_cache_bytes(),
                "kv_cache_accounted_bytes":
                    self.spec.kv_cache_bytes(self.slots,
                                             kv_dtype=self.kv_dtype),
                "kv_cache_f32_bytes":
                    self.spec.kv_cache_bytes(self.slots),
                "decode_bytes_per_token": self.decode_bytes_per_token(),
                "donate": self._donate,
            }
            if reset:
                self._prefills = 0
                self._decode_steps = 0
                self._verify_steps = 0
                self._tokens = 0
        return out
