"""Continuous batching: requests join/leave the decode batch per TOKEN.

The DynamicBatcher's unit of scheduling is a whole request; for
iterative decode that wastes lanes — a 4-token generation admitted next
to a 40-token one would hold its slot for 36 idle steps. Here the
batcher generalizes to per-token granularity (the vLLM-style continuous
batching discipline): every loop iteration advances ALL in-flight
generations one token through the engine's single decode program, and
any slot freed by a finished generation is backfilled from the queue
MID-FLIGHT — a prefill for the newcomer, then it rides the next decode
step with everyone else.

The existing machinery generalizes rather than forks (this class IS a
DynamicBatcher): admission control sheds past ``max_queue`` queued
requests with ``Overloaded``; deadlines bound QUEUE time (a generation
that started always streams to completion); every request carries a
trace id through its prefill span, token pushes, and completion event;
``max_wait_us`` becomes the FIRST-FILL window — when nothing is in
flight, the first queued prompt lingers for company so a cold burst
prefills together, while joins next to running generations are
immediate (lingering would stall live streams).

Round 21 grows two orthogonal axes on the same loop. SPECULATIVE
stepping: with a ``SpecDecodePredictor`` the per-iteration advance is
``spec_step`` — up to k+1 bit-identical tokens per lane per launch —
and ``submit(..., speculative=False)`` pins individual lanes to plain
semantics (they ride the same verify launch width-1). ROLES
(disaggregated prefill/decode): a ``role="prefill"`` batcher fills a
lane, streams token #1, then hands the KV lane to a decode replica
(``set_handoff`` / ``adopt``) so a long prompt's prefill never sits
between another stream's tokens; the ``kv_handoff`` fault site loses
the transfer mid-flight, in which case the adopting replica
RE-PREFILLS from the prompt — deterministic prefill makes the
recovery invisible (zero dropped, zero duplicated tokens). A declined
handoff decodes locally: role is policy, capability stays full.

Streaming: ``submit`` returns a :class:`StreamFuture` — iterate it for
tokens as they decode; ``result()`` blocks for the whole stream.
``stop(drain=True)`` runs every in-flight generation to completion;
``stop(drain=False)`` completes them with ``serving.Cancelled`` after
the tokens already streamed (the satellite fix: a future is ALWAYS
completed — the loop's finally block guarantees it even on a crashed
loop). SLO metrics are per-token: ``serving::<pid>::ttft_ms`` and
``::inter_token_ms`` histograms feed ``serving_report()`` and the
loadgen token closed loop.
"""
from __future__ import annotations

import threading
import time

import numpy as np

from ... import config
from ...base import MXNetError
from ...telemetry import trace as _trace
from .. import Cancelled, DeadlineExceeded, Overloaded
from ..batcher import DynamicBatcher, _DEADLINE_SLACK_S

__all__ = ["DecodeBatcher", "StreamFuture"]


class StreamFuture:
    """Completion handle for one generation that STREAMS.

    Iterate to receive tokens as they decode::

        for tok in batcher.submit(prompt):
            ...

    ``result(timeout)`` blocks for the full token list. A failed or
    cancelled generation delivers its already-streamed tokens, then the
    iterator (and ``result``) raises the error — ``Cancelled`` on
    ``stop(drain=False)``, never a hang."""

    __slots__ = ("_cond", "_tokens", "_done", "_error", "trace_id",
                 "_callbacks")

    def __init__(self):
        self._cond = threading.Condition()
        self._tokens = []
        self._done = False
        self._error = None
        self.trace_id = None
        self._callbacks = []

    # producer side (batcher loop)
    def _push(self, tok):
        with self._cond:
            self._tokens.append(tok)
            self._cond.notify_all()

    def _finish(self, error=None):
        with self._cond:
            if self._done:
                return
            self._done = True
            self._error = error
            cbs, self._callbacks = self._callbacks, []
            self._cond.notify_all()
        from ..batcher import _run_callback
        for cb in cbs:
            _run_callback(cb, self)

    def add_done_callback(self, fn):
        """Run ``fn(self)`` when the stream terminates (immediately when
        it already has) — same contract as ``ServingFuture``; the fleet
        router's replica-health accounting hangs off this hook."""
        with self._cond:
            if not self._done:
                self._callbacks.append(fn)
                return
        from ..batcher import _run_callback
        _run_callback(fn, self)

    def _complete(self, result=None, error=None):
        """Base-class completion contract (DynamicBatcher.stop shedding
        paths call this on queued futures)."""
        self._finish(error=error)

    # consumer side
    def done(self):
        with self._cond:
            return self._done

    def tokens_so_far(self):
        with self._cond:
            return list(self._tokens)

    def __iter__(self):
        idx = 0
        while True:
            with self._cond:
                while len(self._tokens) <= idx and not self._done:
                    self._cond.wait(0.1)
                if len(self._tokens) > idx:
                    tok = self._tokens[idx]
                    idx += 1
                else:
                    if self._error is not None:
                        raise self._error
                    return
            yield tok

    def result(self, timeout=None):
        deadline = time.perf_counter() + timeout \
            if timeout is not None else None
        with self._cond:
            while not self._done:
                remaining = None if deadline is None \
                    else deadline - time.perf_counter()
                if remaining is not None and remaining <= 0:
                    raise TimeoutError("generation still streaming")
                self._cond.wait(remaining if remaining is not None
                                else 0.1)
            if self._error is not None:
                raise self._error
            return list(self._tokens)


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "stop_token", "future",
                 "deadline", "t_submit", "trace_id", "span_id", "rows",
                 "speculative")

    def __init__(self, prompt, max_new_tokens, stop_token, future,
                 deadline, speculative=True):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.stop_token = stop_token
        self.future = future
        self.deadline = deadline
        self.rows = 1                      # base-class shed-event contract
        self.speculative = bool(speculative)
        self.trace_id = future.trace_id = _trace.new_trace_id()
        self.span_id = _trace.new_span_id()
        self.t_submit = time.perf_counter()


class _Adoption:
    """One KV-lane arriving from a prefill replica (disaggregated
    serving): the request plus its already-streamed progress and the
    exported lane — or ``lane=None`` when the transfer was lost
    mid-handoff, in which case the adopting side re-prefills."""

    __slots__ = ("req", "last", "produced", "lane", "t0")

    def __init__(self, req, last, produced, lane, t0):
        self.req = req
        self.last = last
        self.produced = produced
        self.lane = lane
        self.t0 = t0


class _Gen:
    """One in-flight generation: a claimed slot plus stream state."""

    __slots__ = ("req", "slot", "bucket", "last", "produced", "limit",
                 "t_first", "t_last")

    def __init__(self, req, slot, bucket, limit):
        self.req = req
        self.slot = slot
        self.bucket = bucket
        self.limit = limit
        self.last = None
        self.produced = 0
        self.t_first = None
        self.t_last = None

    def finished(self):
        return self.produced >= self.limit or \
            (self.req.stop_token is not None and
             self.last == self.req.stop_token)


class DecodeBatcher(DynamicBatcher):
    """Continuous-batching server over a :class:`DecodePredictor`.

    Parameters
    ----------
    predictor : DecodePredictor
    max_wait_us : int, optional
        First-fill window (default MXTPU_DECODE_MAX_WAIT_US).
    max_queue : int, optional
        Queued-REQUEST bound for admission (default
        MXTPU_DECODE_MAX_QUEUE).
    name : str
    role : str
        ``"unified"`` (default — prefill AND decode locally),
        ``"prefill"`` (fill KV lanes, then hand each one to a decode
        replica through ``set_handoff``; falls back to local decode
        when no decode replica takes it — never a dropped stream), or
        ``"decode"`` (adopts handed-off lanes via :meth:`adopt`;
        direct ``submit`` still works). Role is routing POLICY — every
        role retains full capability.
    speculative : bool, optional
        Advance in-flight lanes through the predictor's speculative
        ``spec_step`` (draft + multi-token verify) instead of one
        plain decode per step. Defaults to True exactly when the
        predictor exposes ``spec_step`` (a ``SpecDecodePredictor``).
        Streams stay bit-identical either way; per-request
        ``submit(..., speculative=False)`` opts a single lane out
        (mixed lanes ride the same verify launch with width-1 feeds).
    """

    def __init__(self, predictor, max_wait_us=None, max_queue=None,
                 name="decode", role="unified", speculative=None):
        if max_wait_us is None:
            max_wait_us = int(config.get("MXTPU_DECODE_MAX_WAIT_US",
                                         2000))
        if max_queue is None:
            max_queue = int(config.get("MXTPU_DECODE_MAX_QUEUE", 256))
        if role not in ("unified", "prefill", "decode"):
            raise MXNetError(
                f"role={role!r} must be unified|prefill|decode")
        super().__init__(predictor, max_batch=predictor.slots,
                         max_wait_us=max_wait_us, max_queue=max_queue,
                         name=name)
        self.role = role
        if speculative is None:
            speculative = hasattr(predictor, "spec_step")
        elif speculative and not hasattr(predictor, "spec_step"):
            raise MXNetError(
                "speculative=True needs a SpecDecodePredictor "
                "(predictor has no spec_step)")
        self.speculative = bool(speculative)
        self._decode_task = self._domain.new_task(f"{name}::decode")
        from ...telemetry import registry as treg
        pid = predictor.telemetry_id
        self._ttft_hist = treg.histogram(f"serving::{pid}::ttft_ms")
        self._itl_hist = treg.histogram(
            f"serving::{pid}::inter_token_ms")
        self._gens_c = treg.counter(f"serving::{pid}::generations")
        self._handoff_hist = treg.histogram(
            f"serving::{pid}::handoff_ms")
        self._inflight = {}                # slot -> _Gen (under _lock)
        self._adopt_q = []                 # _Adoption list (under _cond)
        self._handoff_fn = None
        self._handoffs = 0
        self._handoff_failures = 0
        self._adopted = 0
        self._cancel_requested = False
        self._cancelled = 0
        self._streamed_tokens = 0

    # -- client surface -------------------------------------------------------
    def submit(self, prompt, max_new_tokens=None, stop_token=None,
               deadline_ms=None, speculative=True):
        """Enqueue one generation; returns a :class:`StreamFuture`.

        ``prompt``: 1-D int token sequence (<= the spec's max_seq).
        ``max_new_tokens`` counts the whole stream including token #1
        and is clamped to the cache capacity
        (``DecodePredictor.gen_limit``); ``stop_token`` ends the stream
        after being yielded. ``deadline_ms`` bounds QUEUE time only —
        a generation that started always streams to completion.
        ``speculative=False`` pins this lane to plain-decode semantics
        even on a speculative batcher (it rides the same verify launch
        with a width-1 feed — the output is identical regardless; this
        is a latency/bytes policy knob, not a correctness one)."""
        prompt = self.predictor.check_prompt(prompt)
        self.predictor.bucket_for(prompt.shape[0])  # validates length
        future = StreamFuture()
        deadline = time.perf_counter() + deadline_ms / 1e3 \
            if deadline_ms is not None else None
        req = _GenRequest(prompt, max_new_tokens, stop_token, future,
                          deadline, speculative=speculative)
        with self._cond:
            if not self._running:
                raise MXNetError(
                    f"DecodeBatcher '{self.name}' is not started")
            if self._queued_rows + 1 > self.max_queue:
                self._shed += 1
                shed_depth = self._queued_rows
            else:
                shed_depth = None
                self._queue.append(req)
                self._queued_rows += 1
                self._cond.notify_all()
        if shed_depth is not None:
            self._shed_event(req, shed_depth)
            raise Overloaded(
                f"decode queue at bound ({shed_depth} requests queued, "
                f"max_queue={self.max_queue}); shedding load — retry "
                "with backoff")
        return future

    def generate(self, prompt, max_new_tokens=None, stop_token=None,
                 deadline_ms=None):
        """Streaming convenience: submit and iterate tokens."""
        return iter(self.submit(prompt, max_new_tokens=max_new_tokens,
                                stop_token=stop_token,
                                deadline_ms=deadline_ms))

    # -- disaggregated prefill/decode (round 21) ------------------------------
    def set_handoff(self, fn):
        """Install the prefill-role handoff sink:
        ``fn(req, last, produced, lane, t0) -> bool`` — the FleetRouter
        wires this to a decode replica's :meth:`adopt`. ``lane`` is
        ``export_lane``'s dict, or None when the transfer was lost
        (``kv_handoff`` fault) — the sink must still place the request
        so the decode side re-prefills. Returning False (or raising)
        keeps the generation HERE: local decode is always the
        fallback, zero dropped streams."""
        self._handoff_fn = fn

    def adopt(self, req, last, produced, lane, t0=None):
        """Take over a generation whose KV lane a prefill replica just
        filled (the decode side of the handoff). The lane lands in a
        free local slot at the next poll; ``lane=None`` re-prefills
        from the request's prompt (prefill is deterministic, so the
        recomputed token #1 equals the one already streamed — it is
        suppressed, not re-pushed)."""
        with self._cond:
            if not self._running:
                raise MXNetError(
                    f"DecodeBatcher '{self.name}' is not started")
            self._adopt_q.append(_Adoption(req, last, produced, lane,
                                           t0))
            self._cond.notify_all()
        return req.future

    def _handoff_gen(self, g):
        """Prefill-role epilogue for one freshly filled lane: export it
        and offer it to the handoff sink. Consults the ``kv_handoff``
        fault site — a fire loses the exported rows mid-transfer (the
        sink receives ``lane=None`` and the decode side re-prefills);
        ``action=kill`` dies outright. A sink that declines leaves the
        generation decoding locally."""
        from ... import faultinject
        t0 = time.perf_counter()
        lane = None
        try:
            if faultinject.fire("kv_handoff", slot=g.slot):
                raise faultinject.FaultInjected("kv_handoff",
                                                slot=g.slot)
            lane = self.predictor.export_lane(g.slot)
        except Exception:                    # noqa: BLE001
            lane = None
        ok = False
        try:
            ok = bool(self._handoff_fn(g.req, g.last, g.produced, lane,
                                       t0))
        except Exception:                    # noqa: BLE001
            ok = False
        if ok:
            self.predictor.release(g.slot)
            with self._lock:
                self._handoffs += 1
        else:
            with self._lock:
                self._handoff_failures += 1
                self._inflight[g.slot] = g

    def _start_adopted(self, a, slot):
        """Land one adopted lane (outside the queue lock): import the
        exported rows, or RE-PREFILL from the prompt when the handoff
        was lost — bit-identity makes the recovery invisible."""
        req = a.req
        plen = req.prompt.shape[0]
        bucket = self.predictor.bucket_for(plen)
        limit = self.predictor.gen_limit(plen, req.max_new_tokens)
        landed = False
        if a.lane is not None:
            try:
                with _trace.span(
                        "decode:lane_import", cat="serving",
                        trace=req.trace_id,
                        args={"batcher": self.telemetry_id,
                              "bytes": a.lane.get("bytes")}):
                    self.predictor.import_lane(slot, a.lane,
                                               prompt=req.prompt)
                landed = True
            except Exception:                # noqa: BLE001
                landed = False
        if not landed:
            try:
                with _trace.span(
                        "decode:reprefill", cat="serving",
                        trace=req.trace_id,
                        args={"batcher": self.telemetry_id,
                              "bucket": bucket}), \
                        self._tasks[bucket]:
                    self.predictor.prefill(slot, req.prompt)
            except Exception as e:           # noqa: BLE001
                self.predictor.release(slot)
                req.future._finish(error=e)
                return
        now = time.perf_counter()
        if a.t0 is not None:
            self._handoff_hist.observe((now - a.t0) * 1e3)
        g = _Gen(req, slot, bucket, limit)
        g.last = a.last
        g.produced = a.produced
        g.t_first = g.t_last = now
        with self._lock:
            self._adopted += 1
        if g.finished():
            self._complete_gen(g)
        else:
            with self._lock:
                self._inflight[slot] = g

    # -- stop() contract ------------------------------------------------------
    def _cancel_inflight(self):
        # called under the queue lock by stop(drain=False): mark the
        # in-flight generations; the LOOP completes their futures with
        # Cancelled (completing here would race the decode step that is
        # about to push tokens into them)
        self._cancel_requested = True
        self._cond.notify_all()

    # -- the continuous-batching loop ----------------------------------------
    def _take_cancelled(self):
        with self._cond:
            if not self._cancel_requested:
                return None
            self._cancel_requested = False
            victims = list(self._inflight.values())
            self._inflight.clear()
        return victims

    def _poll(self):
        """Admission decisions under the queue lock. Returns
        ``(admitted, expired, adopted)`` — ``admitted`` as ``(req,
        slot)`` pairs with lanes pre-claimed, ``adopted`` as
        ``(_Adoption, slot)`` pairs (handed-off lanes claim slots
        FIRST: they already hold a live stream) — or ``None`` at clean
        exit."""
        max_wait_s = self.max_wait_us / 1e6
        with self._cond:
            while self._running and not self._queue and \
                    not self._inflight and not self._adopt_q and \
                    not self._cancel_requested:
                self._cond.wait(timeout=0.1)
            if self._cancel_requested:
                return [], [], []
            if not self._queue and not self._inflight and \
                    not self._adopt_q:
                return None                         # stopped + drained
            adopted = []
            while self._adopt_q:
                slot = self.predictor.alloc_slot()
                if slot is None:
                    break                           # lanes saturated
                adopted.append((self._adopt_q.pop(0), slot))
            if self._queue and not self._inflight and not adopted \
                    and self._running:
                # first-fill linger: a cold burst is worth batching the
                # prefills; deadlines cap the linger exactly like the
                # whole-request batcher's window
                t_first = self._queue[0].t_submit
                while self._running and not self._adopt_q and \
                        len(self._queue) < self.predictor.slots:
                    launch_at = t_first + max_wait_s
                    for r in self._queue:
                        if r.deadline is not None and \
                                r.deadline - _DEADLINE_SLACK_S \
                                < launch_at:
                            launch_at = r.deadline - _DEADLINE_SLACK_S
                    remaining = launch_at - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(timeout=remaining)
            admitted, expired = [], []
            now = time.perf_counter()
            while self._queue:
                r = self._queue[0]
                if r.deadline is not None and r.deadline < now:
                    self._queue.pop(0)
                    self._queued_rows -= 1
                    self._deadline_missed += 1
                    waited_ms = (now - r.t_submit) * 1e3
                    r.future._finish(error=DeadlineExceeded(
                        f"deadline expired after {waited_ms:.1f} ms "
                        "in queue"))
                    expired.append((r, waited_ms))
                    continue
                slot = self.predictor.alloc_slot()
                if slot is None:
                    break                            # lanes saturated
                self._queue.pop(0)
                self._queued_rows -= 1
                admitted.append((r, slot))
        return admitted, expired, adopted

    def _emit_expired(self, expired):
        from ...telemetry import export as _texp
        for r, waited_ms in expired:
            if _texp.enabled():
                _texp.emit_event(
                    "serving_deadline", batcher=self.telemetry_id,
                    predictor=self.predictor.telemetry_id,
                    trace_id=r.trace_id, rows=1,
                    waited_ms=round(waited_ms, 3))
            if _trace.enabled():
                _trace.record_span(
                    "serving:request", "serving", r.t_submit,
                    waited_ms / 1e3, trace_id=r.trace_id,
                    span_id=r.span_id,
                    args={"error": "DeadlineExceeded"})

    def _start_gen(self, req, slot):
        """Prefill a newly admitted request into its lane (outside the
        queue lock — a compile/program run must never block submit) and
        stream token #1."""
        plen = req.prompt.shape[0]
        bucket = self.predictor.bucket_for(plen)
        limit = self.predictor.gen_limit(plen, req.max_new_tokens)
        try:
            with _trace.span(
                    "decode:prefill", cat="serving", trace=req.trace_id,
                    args={"batcher": self.telemetry_id,
                          "bucket": bucket, "prompt_len": plen}), \
                    self._tasks[bucket]:
                tok = self.predictor.prefill(slot, req.prompt)
        except Exception as e:                       # noqa: BLE001
            self.predictor.release(slot)
            req.future._finish(error=e)
            return
        now = time.perf_counter()
        self._ttft_hist.observe((now - req.t_submit) * 1e3)
        g = _Gen(req, slot, bucket, limit)
        g.last = tok
        g.produced = 1
        g.t_first = g.t_last = now
        req.future._push(tok)
        with self._lock:
            self._streamed_tokens += 1
        if g.finished():
            self._complete_gen(g)
        elif self.role == "prefill" and self._handoff_fn is not None:
            self._handoff_gen(g)
        else:
            with self._lock:
                self._inflight[slot] = g

    def _step(self):
        """Advance every in-flight generation — ONE token via the plain
        decode program, or up to k+1 via the speculative round
        (``spec_step``: identical tokens, fewer launches); retire
        finished lanes (their slots backfill on the next poll). A
        failed program fails the generations that were in it — the
        serving loop itself survives."""
        with self._lock:
            active = dict(self._inflight)
        if not active:
            return
        try:
            with _trace.span(
                    "decode:step", cat="serving",
                    args={"batcher": self.telemetry_id,
                          "lanes": len(active),
                          "speculative": self.speculative,
                          "trace_ids": [g.req.trace_id
                                        for g in active.values()]}), \
                    self._decode_task:
                if self.speculative:
                    out = self.predictor.spec_step(
                        {slot: (g.last, g.limit - g.produced,
                                g.req.speculative)
                         for slot, g in active.items()})
                else:
                    out = {slot: [tok] for slot, tok in
                           self.predictor.decode(
                               {slot: g.last
                                for slot, g in active.items()}
                           ).items()}
        except Exception as e:                       # noqa: BLE001
            with self._lock:
                for slot in active:
                    self._inflight.pop(slot, None)
            for slot, g in active.items():
                self.predictor.release(slot)
                g.req.future._finish(error=e)
            return
        now = time.perf_counter()
        finished = []
        pushes = []
        with self._lock:
            for slot, g in active.items():
                # a speculative round may overshoot a stop_token:
                # consume committed tokens only up to the finish (the
                # stream must end exactly where solo greedy ends)
                for tok in out[slot]:
                    g.last = tok
                    g.produced += 1
                    self._itl_hist.observe((now - g.t_last) * 1e3)
                    g.t_last = now
                    self._streamed_tokens += 1
                    pushes.append((g.req.future, tok))
                    if g.finished():
                        break
                if g.finished():
                    self._inflight.pop(slot, None)
                    finished.append(g)
        for fut, tok in pushes:
            fut._push(tok)
        for g in finished:
            self._complete_gen(g)

    def _complete_gen(self, g, error=None):
        self.predictor.release(g.slot)
        now = time.perf_counter()
        with self._lock:
            self._served += 1
        self._lat_hist[g.bucket].observe((now - g.req.t_submit) * 1e3)
        self._gens_c.inc()
        g.req.future._finish(error=error)
        if _trace.enabled():
            _trace.record_span(
                "serving:request", "serving", g.req.t_submit,
                now - g.req.t_submit, trace_id=g.req.trace_id,
                span_id=g.req.span_id,
                args={"tokens": g.produced,
                      "prompt_len": int(g.req.prompt.shape[0])})
        from ...telemetry import export as _texp
        if _texp.enabled():
            _texp.emit_event(
                "serving_generation", batcher=self.telemetry_id,
                predictor=self.predictor.telemetry_id,
                trace_id=g.req.trace_id, tokens=g.produced,
                prompt_len=int(g.req.prompt.shape[0]),
                ttft_ms=round((g.t_first - g.req.t_submit) * 1e3, 3),
                total_ms=round((now - g.req.t_submit) * 1e3, 3))

    def _loop(self):
        try:
            while True:
                victims = self._take_cancelled()
                if victims is not None:
                    for g in victims:
                        self.predictor.release(g.slot)
                        with self._lock:
                            self._cancelled += 1
                        g.req.future._finish(error=Cancelled(
                            f"server stopped after {g.produced} of "
                            f"{g.limit} tokens"))
                    continue
                work = self._poll()
                if work is None:
                    return
                admitted, expired, adopted = work
                self._emit_expired(expired)
                for a, slot in adopted:
                    self._start_adopted(a, slot)
                for r, slot in admitted:
                    self._start_gen(r, slot)
                self._step()
        finally:
            # the never-a-hung-future backstop: whatever the exit path
            # (clean drain, cancellation, or a crashed loop body),
            # every remaining future completes
            with self._cond:
                victims = list(self._inflight.values())
                self._inflight.clear()
                queued = list(self._queue)
                self._queue.clear()
                self._queued_rows = 0
                orphaned = list(self._adopt_q)
                self._adopt_q.clear()
                self._cancel_requested = False
            for a in orphaned:
                a.req.future._finish(error=Cancelled(
                    f"serving loop exited with the adopted lane "
                    f"unlanded after {a.produced} tokens"))
            for g in victims:
                self.predictor.release(g.slot)
                with self._lock:
                    self._cancelled += 1
                g.req.future._finish(error=Cancelled(
                    f"serving loop exited after {g.produced} of "
                    f"{g.limit} tokens"))
            for r in queued:
                r.future._finish(error=Cancelled(
                    "serving loop exited before this generation "
                    "started"))

    # -- observability --------------------------------------------------------
    @property
    def inflight(self):
        with self._lock:
            return len(self._inflight)

    def report(self, reset=False):
        from ...telemetry import registry as treg

        def _snap(h):
            return treg.snapshot(reset=reset,
                                 prefix=h.name).get(h.name, {})

        ttft = _snap(self._ttft_hist)
        itl = _snap(self._itl_hist)
        handoff = _snap(self._handoff_hist)
        with self._lock:
            per_bucket = {}
            for b in self.predictor.buckets:
                h = self._lat_hist[b]
                hsnap = treg.snapshot(reset=reset,
                                      prefix=h.name).get(h.name, {})
                per_bucket[b] = {"generations": hsnap.get("count", 0),
                                 "p50_ms": hsnap.get("p50"),
                                 "p99_ms": hsnap.get("p99")}
            out = {
                "id": self.telemetry_id,
                "name": self.name,
                "predictor_id": self.predictor.telemetry_id,
                "slots": self.predictor.slots,
                "max_wait_us": self.max_wait_us,
                "max_queue": self.max_queue,
                "queue_depth": self._queued_rows,
                "inflight": len(self._inflight),
                "served_generations": self._served,
                "streamed_tokens": self._streamed_tokens,
                "cancelled": self._cancelled,
                "shed_requests": self._shed,
                "deadline_missed": self._deadline_missed,
                "retraces": self.predictor.retraces,
                "ttft_p50_ms": ttft.get("p50"),
                "ttft_p99_ms": ttft.get("p99"),
                "inter_token_p50_ms": itl.get("p50"),
                "inter_token_p99_ms": itl.get("p99"),
                "per_bucket": per_bucket,
                "role": self.role,
                "speculative": self.speculative,
                "handoffs": self._handoffs,
                "handoff_failures": self._handoff_failures,
                "adopted": self._adopted,
                "handoff_p50_ms": handoff.get("p50"),
                "handoff_p99_ms": handoff.get("p99"),
            }
            if reset:
                self._served = 0
                self._shed = 0
                self._deadline_missed = 0
                self._cancelled = 0
                self._streamed_tokens = 0
                self._handoffs = 0
                self._handoff_failures = 0
                self._adopted = 0
        return out
