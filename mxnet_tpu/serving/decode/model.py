"""Transformer LM: one weight set, three program shapes.

The same named parameters drive (1) the TRAINING symbol built from
registry ops (``build_symbol`` — Embedding, LayerNorm, FullyConnected,
``CausalSelfAttention``, SoftmaxOutput; fits with the ordinary Module
path), (2) the PREFILL function (process a whole padded prompt, write
every position's K/V into the cache, emit the first generated token),
and (3) the single-token DECODE function (one new token per active
slot against the cached K/V). Prefill and decode are pure jnp — the
serving engine (engine.py) jits them with the KV-cache as donated
device state; the symbol is what ``fit()`` trains. Param-name parity is
the contract: ``Module.get_params()`` output feeds ``DecodePredictor``
directly (examples/transformer/tiny_lm.py goes end-to-end on it).

KV-cache layout: per layer one K and one V buffer of shape
``(slots, max_seq, num_heads, head_dim)`` float32 — slot-major so a
prefill writes one contiguous ``dynamic_update_slice`` row-block and a
decode step scatters ``slots`` rows at their per-slot positions.
Inactive slots scatter at index ``max_seq`` with ``mode="drop"``: a
NONNEGATIVE out-of-bounds sentinel, because negative indices wrap even
under drop semantics (the r13 sparse-embedding lesson). Stale rows
beyond a slot's position are masked with the ring-attention ``-1e30``
convention, whose contribution underflows to an exact 0.0 — stale
bytes can never perturb the stream, which is what makes continuous
batching bit-identical to solo decode.

Quantized cache (round 19, ``kv_dtype="int8"``): each K/V buffer
splits into an int8 value buffer plus a float32 scale buffer of shape
``(slots, max_seq, num_heads)`` — one symmetric absmax scale PER CACHE
ROW (slot, position, head), so the cache costs ``head_dim + 4`` bytes
per row instead of ``4·head_dim`` (0.25 + 1/head_dim of f32; 0.3125×
at the default head_dim 16). Rows quantize on write and the whole
cache dequantizes at f32 compute on read — XLA fuses the
convert-and-scale into the attention einsum's cache read, so the
decode step also MOVES fewer bytes, not just resides in fewer.
Per-row scales keep slot lanes fully independent (a lane's scales
never depend on other lanes' rows), so quantized continuous batching
stays bit-identical to quantized solo decode: the r16 pin holds under
int8. Stale-row scale entries are garbage like stale values — both
are masked to an exact 0.0 contribution by the same ``-1e30``
convention.
"""
from __future__ import annotations

import numpy as np

from ...base import MXNetError

__all__ = ["TransformerLMSpec", "build_symbol", "init_params",
           "init_caches", "KV_DTYPES"]

_NEG = -1e30
_LN_EPS = 1e-5
_KV_SCALE_FLOOR = 1e-12
KV_DTYPES = ("float32", "int8")


def check_kv_dtype(kv_dtype):
    kd = str(kv_dtype).strip().lower()
    if kd not in KV_DTYPES:
        raise MXNetError(
            f"kv_dtype={kv_dtype!r} not supported (one of {KV_DTYPES}; "
            "set via MXTPU_DECODE_KV_DTYPE)")
    return kd


class TransformerLMSpec:
    """Static architecture of the decode-servable transformer LM.

    Everything here is compile-key material: two engines with different
    specs must never share a cached program.
    """

    def __init__(self, vocab_size, num_embed=64, num_heads=4,
                 num_layers=2, max_seq=64, ffn_hidden=None, name="lm"):
        if num_embed % num_heads:
            raise MXNetError(
                f"num_embed={num_embed} not divisible by "
                f"num_heads={num_heads}")
        self.vocab_size = int(vocab_size)
        self.num_embed = int(num_embed)
        self.num_heads = int(num_heads)
        self.num_layers = int(num_layers)
        self.max_seq = int(max_seq)
        self.ffn_hidden = int(ffn_hidden or 4 * num_embed)
        self.head_dim = self.num_embed // self.num_heads
        self.name = name

    def param_shapes(self):
        """Ordered ``{name: shape}`` — the single naming contract shared
        by the training symbol and the serving programs."""
        d, f, v = self.num_embed, self.ffn_hidden, self.vocab_size
        out = {
            "tok_emb_weight": (v, d),
            "pos_emb_weight": (self.max_seq, d),
        }
        for i in range(self.num_layers):
            out[f"l{i}_ln1_gamma"] = (d,)
            out[f"l{i}_ln1_beta"] = (d,)
            out[f"l{i}_qkv_weight"] = (3 * d, d)
            out[f"l{i}_proj_weight"] = (d, d)
            out[f"l{i}_ln2_gamma"] = (d,)
            out[f"l{i}_ln2_beta"] = (d,)
            out[f"l{i}_ffn1_weight"] = (f, d)
            out[f"l{i}_ffn2_weight"] = (d, f)
        out["lnf_gamma"] = (d,)
        out["lnf_beta"] = (d,)
        out["head_weight"] = (v, d)
        return out

    def param_names(self):
        return list(self.param_shapes())

    def key_material(self):
        """Spec fingerprint for ``compile.program_key`` extras."""
        return {
            "vocab": self.vocab_size, "embed": self.num_embed,
            "heads": self.num_heads, "layers": self.num_layers,
            "max_seq": self.max_seq, "ffn": self.ffn_hidden,
        }

    def kv_cache_bytes(self, slots, kv_dtype="float32"):
        """Accounted KV-cache footprint for ``slots`` generation slots.
        f32: layers × {K,V} × slots × max_seq × heads × head_dim × 4.
        int8: each row costs ``head_dim`` int8 bytes plus one f32
        per-row scale — ``head_dim + 4`` per row, 0.25 + 1/head_dim of
        the f32 cache. Tests pin this against the live buffers' actual
        nbytes and ``memory_report()`` shows it next to per-program
        peaks."""
        rows = (self.num_layers * 2 * int(slots) * self.max_seq
                * self.num_heads)
        if check_kv_dtype(kv_dtype) == "int8":
            return rows * (self.head_dim + 4)
        return rows * self.head_dim * 4


def build_symbol(spec, seq_len, name="softmax"):
    """Training/scoring symbol at a fixed ``seq_len``: data is a
    ``(batch, seq_len)`` int token matrix, output the per-position
    next-token distribution; ``softmax_label`` binds as
    ``(batch, seq_len)`` shifted targets."""
    from ... import symbol as sym

    if seq_len > spec.max_seq:
        raise MXNetError(
            f"seq_len={seq_len} exceeds spec.max_seq={spec.max_seq}")
    data = sym.Variable("data")
    x = sym.Embedding(data=data, weight=sym.Variable("tok_emb_weight"),
                      input_dim=spec.vocab_size,
                      output_dim=spec.num_embed, name="tok_emb")
    pos = sym.Variable("pos_emb_weight",
                       shape=(spec.max_seq, spec.num_embed))
    x = sym.broadcast_add(x, pos.slice_axis(0, 0, seq_len),
                          name="pos_add")
    for i in range(spec.num_layers):
        h = sym.LayerNorm(x, gamma=sym.Variable(f"l{i}_ln1_gamma"),
                          beta=sym.Variable(f"l{i}_ln1_beta"),
                          axis=-1, eps=_LN_EPS, name=f"l{i}_ln1")
        qkv = sym.FullyConnected(
            h, weight=sym.Variable(f"l{i}_qkv_weight"),
            num_hidden=3 * spec.num_embed, no_bias=True, flatten=False,
            name=f"l{i}_qkv")
        attn = sym.CausalSelfAttention(qkv, num_heads=spec.num_heads,
                                       name=f"l{i}_attn")
        proj = sym.FullyConnected(
            attn, weight=sym.Variable(f"l{i}_proj_weight"),
            num_hidden=spec.num_embed, no_bias=True, flatten=False,
            name=f"l{i}_proj")
        x = sym.elemwise_add(x, proj, name=f"l{i}_res1")
        h2 = sym.LayerNorm(x, gamma=sym.Variable(f"l{i}_ln2_gamma"),
                           beta=sym.Variable(f"l{i}_ln2_beta"),
                           axis=-1, eps=_LN_EPS, name=f"l{i}_ln2")
        f1 = sym.FullyConnected(
            h2, weight=sym.Variable(f"l{i}_ffn1_weight"),
            num_hidden=spec.ffn_hidden, no_bias=True, flatten=False,
            name=f"l{i}_ffn1")
        f1 = sym.Activation(f1, act_type="relu", name=f"l{i}_relu")
        f2 = sym.FullyConnected(
            f1, weight=sym.Variable(f"l{i}_ffn2_weight"),
            num_hidden=spec.num_embed, no_bias=True, flatten=False,
            name=f"l{i}_ffn2")
        x = sym.elemwise_add(x, f2, name=f"l{i}_res2")
    xf = sym.LayerNorm(x, gamma=sym.Variable("lnf_gamma"),
                       beta=sym.Variable("lnf_beta"),
                       axis=-1, eps=_LN_EPS, name="lnf")
    logits = sym.FullyConnected(
        xf, weight=sym.Variable("head_weight"),
        num_hidden=spec.vocab_size, no_bias=True, flatten=False,
        name="head")
    return sym.SoftmaxOutput(logits, name=name)


def init_params(spec, seed=0, scale=0.02):
    """Deterministic random parameters (numpy, float32) — serving tests
    and the chaos worker need a real weight set without a training run;
    LN affines initialize to identity."""
    rs = np.random.RandomState(seed)
    out = {}
    for n, s in spec.param_shapes().items():
        if n.endswith("_gamma"):
            out[n] = np.ones(s, np.float32)
        elif n.endswith("_beta"):
            out[n] = np.zeros(s, np.float32)
        else:
            out[n] = rs.normal(0.0, scale, s).astype(np.float32)
    return out


def init_caches(spec, slots, kv_dtype="float32"):
    """Fresh zeroed cache buffers for ``slots`` lanes. f32: per layer
    ``[K, V]`` of (slots, max_seq, H, D) float32. int8: per layer
    ``[Kq, Kscale, Vq, Vscale]`` — int8 values plus (slots, max_seq, H)
    float32 per-row scales. The flat tuple is the donated device state
    threaded through prefill/decode."""
    import jax.numpy as jnp
    kd = check_kv_dtype(kv_dtype)
    vshape = (int(slots), spec.max_seq, spec.num_heads, spec.head_dim)
    out = []
    for _ in range(spec.num_layers):
        for _kv in range(2):
            if kd == "int8":
                out.append(jnp.zeros(vshape, jnp.int8))
                out.append(jnp.zeros(vshape[:3], jnp.float32))
            else:
                out.append(jnp.zeros(vshape, jnp.float32))
    return tuple(out)


# ---------------------------------------------------------------------------
# pure-jnp serving math (jitted by engine.py)
# ---------------------------------------------------------------------------

def _kv_quant_rows(rows):
    """Quantize fresh K/V rows ``(..., H, D)`` → (int8 rows, f32
    per-row scales ``(..., H)``): symmetric absmax over head_dim. The
    floor keeps an all-zero row's scale finite; with ``scale ≥
    absmax/127`` the rounded values can never exceed ±127, the clip is
    belt-and-braces."""
    import jax.numpy as jnp
    amax = jnp.max(jnp.abs(rows), axis=-1)
    scale = jnp.maximum(amax * (1.0 / 127.0), _KV_SCALE_FLOOR)
    q = jnp.clip(jnp.round(rows / scale[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def _kv_dequant(q, scale):
    """f32 view of a quantized cache buffer; XLA fuses the convert and
    the broadcast multiply into the consuming einsum's cache read."""
    import jax.numpy as jnp
    return q.astype(jnp.float32) * scale[..., None]


def _ln(x, gamma, beta):
    import jax
    import jax.numpy as jnp
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) * jax.lax.rsqrt(var + _LN_EPS) * gamma + beta


def _split_qkv(qkv, heads, head_dim):
    """(..., 3*H*D) -> three (..., H, D)."""
    shp = qkv.shape[:-1] + (3, heads, head_dim)
    q = qkv.reshape(shp)
    return q[..., 0, :, :], q[..., 1, :, :], q[..., 2, :, :]


def _block_tail(spec, p, i, x, attn_out):
    """proj + residual + FFN shared by prefill/decode/re-prefill."""
    import jax.numpy as jnp
    x = x + attn_out @ p[f"l{i}_proj_weight"].T
    h2 = _ln(x, p[f"l{i}_ln2_gamma"], p[f"l{i}_ln2_beta"])
    f = jnp.maximum(h2 @ p[f"l{i}_ffn1_weight"].T, 0.0)
    return x + f @ p[f"l{i}_ffn2_weight"].T


def _head(spec, p, x_last):
    """Final LN + tied head on the LAST position only — the serving
    programs never materialize the full (seq, vocab) logit block."""
    import jax.numpy as jnp
    xl = _ln(x_last, p["lnf_gamma"], p["lnf_beta"])
    logits = xl @ p["head_weight"].T
    return jnp.argmax(logits, axis=-1).astype(jnp.int32), logits


def prefill_step(spec, p, caches, tokens, length, slot,
                 kv_dtype="float32"):
    """Fill one slot's KV rows from a padded prompt; emit token #1.

    tokens: (1, Sb) int32 padded prompt (Sb = static seq bucket);
    length: () int32 true prompt length; slot: () int32. caches: tuple
    of 2*layers buffers (slots, max_seq, H, D) — 4*layers
    value+scale buffers under ``kv_dtype="int8"`` (``init_caches``).
    Returns ``(caches', next_token)``. Rows [length, Sb) hold pad K/V —
    decode overwrites position ``length`` first and masks beyond its
    position, so they are unreachable (see module docstring). Prefill
    attention runs on the EXACT f32 k/v of this prompt; only the rows
    WRITTEN are quantized — identically on the solo and batched paths,
    so bit-identity is unaffected.
    """
    import jax.numpy as jnp
    from jax import lax

    int8_kv = check_kv_dtype(kv_dtype) == "int8"
    sb = tokens.shape[1]
    scale = 1.0 / (spec.head_dim ** 0.5)
    x = p["tok_emb_weight"][tokens[0]] + p["pos_emb_weight"][:sb]
    causal = jnp.arange(sb)[:, None] >= jnp.arange(sb)[None, :]
    new_caches = []
    for i in range(spec.num_layers):
        h = _ln(x, p[f"l{i}_ln1_gamma"], p[f"l{i}_ln1_beta"])
        qkv = h @ p[f"l{i}_qkv_weight"].T
        q, k, v = _split_qkv(qkv, spec.num_heads, spec.head_dim)
        if int8_kv:
            kq, ks, vq, vs = caches[4 * i: 4 * i + 4]
            kqi, ksc = _kv_quant_rows(k)
            vqi, vsc = _kv_quant_rows(v)
            new_caches += [
                lax.dynamic_update_slice(kq, kqi[None], (slot, 0, 0, 0)),
                lax.dynamic_update_slice(ks, ksc[None], (slot, 0, 0)),
                lax.dynamic_update_slice(vq, vqi[None], (slot, 0, 0, 0)),
                lax.dynamic_update_slice(vs, vsc[None], (slot, 0, 0))]
        else:
            kc = lax.dynamic_update_slice(
                caches[2 * i], k[None].astype(caches[2 * i].dtype),
                (slot, 0, 0, 0))
            vc = lax.dynamic_update_slice(
                caches[2 * i + 1],
                v[None].astype(caches[2 * i + 1].dtype),
                (slot, 0, 0, 0))
            new_caches += [kc, vc]
        s = jnp.einsum("qhd,khd->hqk", q, k) * scale
        s = jnp.where(causal[None], s, _NEG)
        m = jnp.max(s, axis=-1, keepdims=True)
        w = jnp.exp(s - m)
        o = jnp.einsum("hqk,khd->qhd", w, v)
        o = o / jnp.swapaxes(jnp.sum(w, axis=-1, keepdims=True), 0, 1)
        x = _block_tail(spec, p, i, x, o.reshape(sb, -1))
    x_last = x[length - 1]
    nxt, _ = _head(spec, p, x_last)
    return tuple(new_caches), nxt


def decode_step(spec, p, caches, tokens, positions, active,
                kv_dtype="float32"):
    """Advance every active slot by ONE token against the cache.

    tokens: (slots,) int32 each slot's previous token; positions:
    (slots,) int32 the position that token occupies (== generated-so-far
    write index); active: (slots,) bool. Inactive slots compute garbage
    that writes nowhere (drop-mode scatter at the ``max_seq`` sentinel)
    and is discarded by the caller. Each slot's lane is independent —
    batched output rows equal solo output rows bit-for-bit; under
    ``kv_dtype="int8"`` the new row quantizes before the scatter and
    attention reads the dequantized cache, both per-lane, so the
    identity survives quantization. Returns ``(caches', next_tokens
    (slots,) int32)``.
    """
    import jax.numpy as jnp

    int8_kv = check_kv_dtype(kv_dtype) == "int8"
    n = tokens.shape[0]
    scale = 1.0 / (spec.head_dim ** 0.5)
    sidx = jnp.arange(n)
    safe_pos = jnp.where(active, positions, 0)
    wpos = jnp.where(active, positions, spec.max_seq)  # OOB => dropped
    x = p["tok_emb_weight"][tokens] + p["pos_emb_weight"][safe_pos]
    visible = jnp.arange(spec.max_seq)[None, :] <= positions[:, None]
    new_caches = []
    for i in range(spec.num_layers):
        h = _ln(x, p[f"l{i}_ln1_gamma"], p[f"l{i}_ln1_beta"])
        qkv = h @ p[f"l{i}_qkv_weight"].T
        q, k, v = _split_qkv(qkv, spec.num_heads, spec.head_dim)
        if int8_kv:
            kq, ks, vq, vs = caches[4 * i: 4 * i + 4]
            kqi, ksc = _kv_quant_rows(k)
            vqi, vsc = _kv_quant_rows(v)
            kq = kq.at[sidx, wpos].set(kqi, mode="drop")
            ks = ks.at[sidx, wpos].set(ksc, mode="drop")
            vq = vq.at[sidx, wpos].set(vqi, mode="drop")
            vs = vs.at[sidx, wpos].set(vsc, mode="drop")
            new_caches += [kq, ks, vq, vs]
            kc = _kv_dequant(kq, ks)
            vc = _kv_dequant(vq, vs)
        else:
            kc = caches[2 * i].at[sidx, wpos].set(
                k.astype(caches[2 * i].dtype), mode="drop")
            vc = caches[2 * i + 1].at[sidx, wpos].set(
                v.astype(caches[2 * i + 1].dtype), mode="drop")
            new_caches += [kc, vc]
        s = jnp.einsum("nhd,nmhd->nhm", q, kc) * scale
        s = jnp.where(visible[:, None, :], s, _NEG)
        m = jnp.max(s, axis=-1, keepdims=True)
        w = jnp.exp(s - m)
        o = jnp.einsum("nhm,nmhd->nhd", w, vc)
        o = o / jnp.sum(w, axis=-1)[..., None]
        x = _block_tail(spec, p, i, x, o.reshape(n, -1))
    nxt, _ = _head(spec, p, x)
    return tuple(new_caches), nxt


def verify_step(spec, p, caches, tokens, positions, n_tokens, active,
                kv_dtype="float32"):
    """Advance every active slot by UP TO K tokens in ONE program — the
    speculative-decoding verify step (round 21).

    tokens: (slots, K) int32 — token j of slot s feeds at position
    ``positions[s] + j``; token 0 is the slot's last COMMITTED token,
    tokens 1.. are draft proposals. positions: (slots,) int32 base
    position (== the committed write index, exactly what a plain decode
    step would feed). n_tokens: (slots,) int32 in [1, K] — tokens this
    slot actually feeds; the tail is padding that writes nowhere (the
    same ``max_seq`` drop-sentinel as inactive decode lanes). active:
    (slots,) bool. Returns ``(caches', out (slots, K) int32)`` where
    ``out[s, j]`` is the target's greedy argmax for the position AFTER
    fed token j — ``out[s, 0]`` is bit-for-bit what ``decode_step``
    would have emitted, and ``out[s, j]`` is the continuation GIVEN the
    fed prefix, which is why accept-prefix semantics (engine/spec.py)
    keep the stream identical to solo greedy decode.

    Token j's attention sees cache rows ``<= positions[s] + j``: the
    rows this step just scattered for tokens 0..j (the in-step causal
    prefix) plus every committed row, stale rows beyond masked to an
    exact-0 contribution by the same ``-1e30`` convention as decode.
    Rows written for REJECTED drafts are stale the moment the caller
    commits a shorter prefix — the next feed overwrites the first of
    them and masks the rest, so no rollback pass is ever needed. Lanes
    stay data-independent (per-lane rows, per-lane scales under int8):
    mixed speculative/plain batches cannot perturb each other, which is
    what lets plain lanes ride the same verify program at n_tokens=1.
    """
    import jax.numpy as jnp

    int8_kv = check_kv_dtype(kv_dtype) == "int8"
    n, kk = tokens.shape
    scale = 1.0 / (spec.head_dim ** 0.5)
    sidx = jnp.arange(n)
    j = jnp.arange(kk)
    fed = active[:, None] & (j[None, :] < n_tokens[:, None])   # (n, K)
    pos = positions[:, None] + j[None, :]                      # (n, K)
    safe_pos = jnp.where(fed, pos, 0)
    wpos = jnp.where(fed, pos, spec.max_seq)       # OOB => dropped
    x = p["tok_emb_weight"][tokens] + p["pos_emb_weight"][safe_pos]
    visible = jnp.arange(spec.max_seq)[None, None, :] <= pos[:, :, None]
    new_caches = []
    for i in range(spec.num_layers):
        h = _ln(x, p[f"l{i}_ln1_gamma"], p[f"l{i}_ln1_beta"])
        qkv = h @ p[f"l{i}_qkv_weight"].T
        q, k, v = _split_qkv(qkv, spec.num_heads, spec.head_dim)
        if int8_kv:
            kq, ks, vq, vs = caches[4 * i: 4 * i + 4]
            kqi, ksc = _kv_quant_rows(k)
            vqi, vsc = _kv_quant_rows(v)
            kq = kq.at[sidx[:, None], wpos].set(kqi, mode="drop")
            ks = ks.at[sidx[:, None], wpos].set(ksc, mode="drop")
            vq = vq.at[sidx[:, None], wpos].set(vqi, mode="drop")
            vs = vs.at[sidx[:, None], wpos].set(vsc, mode="drop")
            new_caches += [kq, ks, vq, vs]
            kc = _kv_dequant(kq, ks)
            vc = _kv_dequant(vq, vs)
        else:
            kc = caches[2 * i].at[sidx[:, None], wpos].set(
                k.astype(caches[2 * i].dtype), mode="drop")
            vc = caches[2 * i + 1].at[sidx[:, None], wpos].set(
                v.astype(caches[2 * i + 1].dtype), mode="drop")
            new_caches += [kc, vc]
        s = jnp.einsum("nkhd,nmhd->nkhm", q, kc) * scale
        s = jnp.where(visible[:, :, None, :], s, _NEG)
        m = jnp.max(s, axis=-1, keepdims=True)
        w = jnp.exp(s - m)
        o = jnp.einsum("nkhm,nmhd->nkhd", w, vc)
        o = o / jnp.sum(w, axis=-1)[..., None]
        x = _block_tail(spec, p, i, x, o.reshape(n, kk, -1))
    nxt, _ = _head(spec, p, x)
    return tuple(new_caches), nxt


def reprefill_step(spec, p, tokens, length):
    """The CACHELESS baseline: recompute the whole prompt forward and
    emit the next token, touching no KV state — what a server without a
    cache runs per generated token. Exists so the decode-vs-re-prefill
    bytes-accessed comparison (ISSUE 13's measured gate) compares real
    compiled programs, not an estimate."""
    import jax.numpy as jnp

    sb = tokens.shape[1]
    scale = 1.0 / (spec.head_dim ** 0.5)
    x = p["tok_emb_weight"][tokens[0]] + p["pos_emb_weight"][:sb]
    causal = jnp.arange(sb)[:, None] >= jnp.arange(sb)[None, :]
    for i in range(spec.num_layers):
        h = _ln(x, p[f"l{i}_ln1_gamma"], p[f"l{i}_ln1_beta"])
        qkv = h @ p[f"l{i}_qkv_weight"].T
        q, k, v = _split_qkv(qkv, spec.num_heads, spec.head_dim)
        s = jnp.einsum("qhd,khd->hqk", q, k) * scale
        s = jnp.where(causal[None], s, _NEG)
        m = jnp.max(s, axis=-1, keepdims=True)
        w = jnp.exp(s - m)
        o = jnp.einsum("hqk,khd->qhd", w, v)
        o = o / jnp.swapaxes(jnp.sum(w, axis=-1, keepdims=True), 0, 1)
        x = _block_tail(spec, p, i, x, o.reshape(sb, -1))
    x_last = x[length - 1]
    nxt, _ = _head(spec, p, x_last)
    return nxt
